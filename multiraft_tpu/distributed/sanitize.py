"""Runtime sanitizer: the dynamic twin of graftlint's serving-path
rules (``MRT_SANITIZE=1``).

The static rules (analysis/dataflow.py) prove properties about the
AST; this module asserts the same three properties about the *running*
process while the existing chaos/nemesis tests drive real traffic:

* **lock-order acyclicity** — every named lock in the transport stack
  is wrapped in :class:`~multiraft_tpu.analysis.lockorder.RecordingLock`
  and the observed acquisition graph is checked for a cycle on every
  NEW edge, not just at test teardown.  A cycle is a latent ABBA
  deadlock even if no run has hung yet.
* **queue bounds** — the serving queues the ``unbounded-queue`` rule
  audits (per-connection reply backlog today) assert their cap at
  every growth site via :meth:`Sanitizer.guard_queue`.
* **callback-duration budget** — every scheduler timer/IO callback is
  timed; one exceeding ``MRT_SANITIZE_CB_BUDGET_MS`` (default 250)
  stalls every reply riding the loop thread, which is exactly what the
  ``blocking-in-callback`` rule flags statically.

A violation is never silent: it is appended to the in-process log,
written to the flight recorder (``SANITIZE`` records — the postmortem
doctor surfaces them as ``sanitizer_violation`` anomalies), printed to
stderr, and counted on every registered node's metrics
(``sanitize.violations``).  ``MRT_SANITIZE_STRICT=1`` additionally
raises :class:`SanitizerViolation` at the detection site (unit tests;
the serving loops catch-and-log so a violating process keeps serving
while still leaving evidence).

Env vars:

* ``MRT_SANITIZE=1`` — master switch; off means zero hot-path cost
  (one ``is None`` check per callback).
* ``MRT_SANITIZE_CB_BUDGET_MS`` — callback budget (float, ms).
* ``MRT_SANITIZE_STRICT=1`` — raise on violation instead of only
  recording it.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.knobs import knob_bool, knob_float
from . import flightrec

__all__ = [
    "Sanitizer",
    "SanitizerViolation",
    "enabled",
    "get_sanitizer",
]

# Retained violation details (the full stream still reaches stderr,
# metrics, and the flight recorder) — the in-process log itself must
# not become the unbounded queue it polices.
_MAX_VIOLATIONS = 256


class SanitizerViolation(AssertionError):
    """Raised at the detection site under ``MRT_SANITIZE_STRICT=1``."""


class Sanitizer:
    """Process-wide runtime checker; see the module docstring.

    Constructed directly in unit tests; production code goes through
    :func:`get_sanitizer` so one instance watches the whole process."""

    def __init__(
        self, strict: bool = False, budget_ms: float = 250.0
    ) -> None:
        from ..analysis.lockorder import LockOrderRecorder

        self.strict = strict
        self.budget_s = budget_ms / 1000.0
        self.recorder = LockOrderRecorder(on_edge=self._on_lock_edge)
        self.violations: List[Dict[str, Any]] = []
        self._mu = threading.Lock()
        # Metrics sinks (Observability.metrics-shaped: .inc(name)) of
        # every node that installed us, so violations show up on the
        # scrape plane too.
        self._metrics: List[Any] = []

    # -- wiring ------------------------------------------------------------

    def install_locks(self, obj: Any, attrs: Dict[str, str]) -> None:
        """Wrap ``obj.<attr>`` locks in recording proxies;
        ``attrs`` maps attribute name → graph label."""
        for attr, label in attrs.items():
            self.recorder.wrap(obj, attr, label)

    def register_metrics(self, metrics: Any) -> None:
        with self._mu:
            if metrics not in self._metrics:
                self._metrics.append(metrics)
        metrics.inc("sanitize.active")

    # -- checks ------------------------------------------------------------

    def guard_queue(self, name: str, length: int, cap: int) -> None:
        """Assert a serving queue honors its cap (called after growth:
        a shed-oldest queue is exactly at cap, never past it)."""
        if length > cap:
            self._violate(
                "queue_bound",
                f"queue '{name}' at {length} entries, cap {cap}",
                a=length,
                b=cap,
                tag=name,
            )

    def run_callback(self, fn: Callable[..., Any], *args: Any) -> Any:
        """Run a scheduler callback under the duration budget."""
        t0 = time.perf_counter()
        try:
            return fn(*args)
        finally:
            dur = time.perf_counter() - t0
            if dur > self.budget_s:
                label = getattr(fn, "__qualname__", None) or getattr(
                    fn, "__name__", "?"
                )
                self._violate(
                    "callback_budget",
                    f"callback {label} ran {dur * 1e3:.1f} ms on the "
                    f"loop thread (budget {self.budget_s * 1e3:.0f} ms)",
                    a=int(dur * 1e6),
                    b=int(self.budget_s * 1e6),
                    tag=label,
                )

    def _on_lock_edge(self, held: str, acquired: str, thread: str) -> None:
        cyc = self.recorder.cycle()
        if cyc is not None:
            self._violate(
                "lock_order",
                f"acquisition-order cycle {' -> '.join(cyc)} "
                f"(edge {held} -> {acquired} on thread {thread})",
                tag=acquired,
            )

    # -- reporting ---------------------------------------------------------

    def _violate(
        self, kind: str, detail: str, a: int = 0, b: int = 0, tag: str = ""
    ) -> None:
        v = {"kind": kind, "detail": detail}
        with self._mu:
            if len(self.violations) < _MAX_VIOLATIONS:
                self.violations.append(v)
            metrics = list(self._metrics)
        for m in metrics:
            try:
                m.inc("sanitize.violations")
            except Exception:  # pragma: no cover - scrape plane is best-effort
                pass
        rec = flightrec.get_recorder()
        if rec is not None:
            rec.record(
                flightrec.SANITIZE,
                code=flightrec.SANITIZE_KIND_CODES.get(kind, 0),
                a=a,
                b=b,
                tag=tag,
            )
        print(f"MRT_SANITIZE violation [{kind}]: {detail}", file=sys.stderr)
        if self.strict:
            raise SanitizerViolation(f"[{kind}] {detail}")


# Process-wide singleton, created lazily on first use when
# MRT_SANITIZE=1 (same enablement pattern as flightrec.get_recorder).
_san: Optional[Sanitizer] = None
_san_lock = threading.Lock()


def enabled() -> bool:
    return knob_bool("MRT_SANITIZE")


def get_sanitizer() -> Optional[Sanitizer]:
    """The process-wide sanitizer, or ``None`` when disabled."""
    global _san
    if not enabled():
        return None
    with _san_lock:
        if _san is None:
            _san = Sanitizer(
                strict=knob_bool("MRT_SANITIZE_STRICT"),
                budget_ms=knob_float("MRT_SANITIZE_CB_BUDGET_MS"),
            )
    return _san
