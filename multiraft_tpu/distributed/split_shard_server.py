"""Serving layer for the SHARDED stack over split replica groups
(engine/split_shard.py) — real sockets, real processes.

Each process runs ``serve_split_shardkv``: one engine hosting the
config RSM (engine group 0) and every replica group, owning only its
``owners`` peer slots; per-tick boundary slabs ride ``SplitEngine.slab``
RPCs between processes (same exchange as split_server.py).  Killing a
process loses only its owned slots: groups whose survivors hold a
quorum keep electing, serving, and MIGRATING — the cross-process pull /
Challenge-1 GC handshake is state-driven (see engine/split_shard.py),
so whichever process next owns a leader re-derives any step the dead
one never took.

Client surface: ``SplitShardKV.command`` routes key→shard→gid
server-side from the latest applied config and answers ErrWrongLeader
when the owning group's leader lives at a peer process (the clerk
rotates, reference: shardkv/client.go:68-129); ``admin`` drives
join/leave/move at whichever process owns the ctrler leader, keyed on
the CLERK's unique client_id — the ctrler log is replicated to every
process, so a retry landing at a different leader owner dedups
exactly-once against the committed op, and two clerks' independent
command numbering can never collide (a per-process session would
silently swallow one clerk's op as another's duplicate).
"""

from __future__ import annotations

import itertools
from typing import Dict, Optional, Sequence, Tuple

from ..engine.core import EngineConfig
from ..engine.host import EngineDriver
from ..engine.split import SplitPeering, SplitSpec
from ..engine.split_shard import SplitShardKV
from ..engine.shardkv import ERR_WRONG_GROUP, OK
from ..services.shardkv import SERVING, key2shard
from ..sim.scheduler import TIMEOUT, Future
from ..utils.ids import unique_client_id
from .engine_server import ERR_TIMEOUT, EngineCmdArgs, EngineCmdReply
from .realtime import PumpCadence, service_busy
from .realtime import RealtimeScheduler
from .split_server import ERR_WRONG_LEADER
from .tcp import RpcNode

__all__ = [
    "SplitShardKVService",
    "SplitShardNetClerk",
    "serve_split_shardkv",
]


class SplitShardKVService:
    """``SplitShardKV.*`` + ``SplitEngine.slab`` on one process."""

    RESUBMIT_S = 0.25
    DEADLINE_S = 3.0
    ADMIN_OPS = ("join", "leave", "move")

    def __init__(
        self,
        sched: RealtimeScheduler,
        skv: SplitShardKV,
        peering: SplitPeering,
        peer_ends: Dict[int, object],
        pump_interval: float = 0.002,
        persistence=None,  # SplitPersistence (durable peer identity)
    ) -> None:
        self.sched = sched
        self.skv = skv
        self.peering = peering
        self.peer_ends = dict(peer_ends)
        self._cadence = PumpCadence(pump_interval)
        self._stopped = False
        self._persist = persistence
        sched.call_soon(self._pump_loop)

    def stop(self) -> None:
        self._stopped = True

    def _pump_loop(self) -> None:
        if self._stopped:
            return
        self.skv.pump(1)
        if self._persist is not None:
            # THE persistence invariant: the pump's raft slice is
            # fsynced before any of its slabs leave the process
            # (split_server.SplitPersistence).
            self._persist.after_pump()
        for proc, slab in self.peering.extract().items():
            end = self.peer_ends.get(proc)
            if end is not None:
                # Fire-and-forget: a lost slab is a dropped message and
                # Raft retries; the timeout just reclaims the future.
                self.sched.with_timeout(
                    end.call("SplitEngine.slab", slab), 1.0
                )
        self.sched.call_after(
            self._cadence.next_delay(service_busy(self.skv)),
            self._pump_loop,
        )

    # -- peer-facing -------------------------------------------------------

    def slab(self, blob: dict):
        self.peering.inject(blob)
        return True

    # -- probes (tests/operators) ------------------------------------------

    def status(self, args=None):
        """(latest config num, shard→gid list, any-slot-migrating,
        gids whose leader this process owns) — lets a test time a kill
        to land mid-migration and watch completion from outside."""
        cfg = self.skv.query_latest()
        migrating = any(
            sl.state != SERVING
            for rep in self.skv.reps.values()
            for sl in rep.shards.values()
        )
        led = [g for g in self.skv.gids
               if self.skv.local_leader(g) is not None]
        return (cfg.num, list(cfg.shards), migrating, led)

    # -- client-facing -----------------------------------------------------

    def command(self, args: EngineCmdArgs):
        def run():
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                cfg = self.skv.query_latest()
                gid = cfg.shards[key2shard(args.key)]
                if gid == 0 or gid not in self.skv.reps:
                    return EngineCmdReply(err=ERR_WRONG_GROUP)
                t = self.skv.submit_local(
                    gid, args.op, args.key, args.value,
                    client_id=args.client_id, command_id=args.command_id,
                )
                if t is None:
                    # The owning group's leader lives at a peer process.
                    return EngineCmdReply(err=ERR_WRONG_LEADER)
                sub_deadline = min(
                    self.sched.now + self.RESUBMIT_S, deadline
                )
                while not t.done and self.sched.now < sub_deadline:
                    yield 0.002
                if t.done and not t.failed:
                    if t.err == ERR_WRONG_GROUP:
                        # Config moved between submit and apply: re-route
                        # from the (now newer) applied config.
                        yield 0.002
                        continue
                    return EngineCmdReply(err=t.err, value=t.value)
                # failed (lost slot/leadership) or wedged: resubmit —
                # same (client_id, command_id), dedup-safe.
            return EngineCmdReply(err=ERR_TIMEOUT)

        return run()

    def admin(self, args):
        """args = (kind, payload, command_id, client_id); kind ∈
        ADMIN_OPS (a network-supplied string must never getattr into
        arbitrary methods).  ErrWrongLeader when the ctrler leader
        lives at a peer process — the clerk rotates.  The clerk's OWN
        client_id keys the dedup: admin ops land at whichever process
        owns the ctrler leader, so keying on a per-process session
        would let two clerks' independent command numbering collide
        and silently swallow an op as a duplicate."""
        kind, payload = args[0], args[1]
        cmd = args[2] if len(args) > 2 else None
        cid = args[3] if len(args) > 3 else None
        if kind not in self.ADMIN_OPS:
            return EngineCmdReply(err=f"ErrBadAdminOp:{kind}")

        def run():
            if kind == "join":
                arg = {int(g): list(s) for g, s in dict(payload).items()}
            elif kind == "move":
                arg = (int(payload[0]), int(payload[1]))
            else:
                arg = [int(g) for g in payload]
            t = self.skv.ctrl_local(kind, arg, command_id=cmd,
                                    client_id=cid)
            if t is None:
                return EngineCmdReply(err=ERR_WRONG_LEADER)
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                if t.done:
                    if t.failed:
                        return EngineCmdReply(err=ERR_TIMEOUT)
                    return EngineCmdReply(err=OK)
                yield 0.005
            return EngineCmdReply(err=ERR_TIMEOUT)

        return run()


class SplitShardNetClerk:
    """Clerk over the split-shard processes: session dedup + rotation
    on ErrWrongLeader / ErrWrongGroup / timeout (reference clerk loop,
    shardkv/client.go:68-129 — rotation covers both 'leader elsewhere'
    and 'shard mid-migration')."""

    _next = itertools.count(1)

    def __init__(self, sched, ends: Sequence) -> None:
        self.sched = sched
        self.ends = list(ends)
        self.client_id = unique_client_id(next(SplitShardNetClerk._next))
        self.command_id = 0
        self._admin_cmd = 0

    def _command(self, op: str, key: str, value: str = ""):
        if op != "Get":
            self.command_id += 1
        args = EngineCmdArgs(
            op=op, key=key, value=value,
            client_id=self.client_id, command_id=self.command_id,
        )
        i = 0
        while True:
            end = self.ends[i % len(self.ends)]
            fut: Future = end.call("SplitShardKV.command", args)
            reply = yield self.sched.with_timeout(fut, 3.5)
            if reply is None or reply is TIMEOUT or reply.err not in (
                OK, "ErrNoKey"
            ):
                i += 1  # dropped / wrong leader / mid-migration / timeout
                yield self.sched.sleep(0.02)
                continue
            return reply.value if reply.err == OK else ""

    def get(self, key: str):
        return self._command("Get", key)

    def put(self, key: str, value: str):
        return self._command("Put", key, value)

    def append(self, key: str, value: str):
        return self._command("Append", key, value)

    def admin(self, kind: str, payload):
        """join/leave/move with rotation.  The clerk's unique client_id
        + one command id per logical op make retries exactly-once
        through the ctrler dedup table — across processes too (every
        process applies the same replicated ctrler log, so a retry at
        a different leader owner dedups against the committed op)."""
        self._admin_cmd += 1
        args = (kind, payload, self._admin_cmd, self.client_id)
        i = 0
        while True:
            end = self.ends[i % len(self.ends)]
            fut: Future = end.call("SplitShardKV.admin", args)
            reply = yield self.sched.with_timeout(fut, 4.0)
            if reply is None or reply is TIMEOUT or reply.err != OK:
                i += 1
                yield self.sched.sleep(0.05)
                continue
            return True

    def status(self, proc: int):
        fut: Future = self.ends[proc].call("SplitShardKV.status", ())
        reply = yield self.sched.with_timeout(fut, 3.0)
        return None if reply is TIMEOUT else reply


def serve_split_shardkv(
    port: int,
    me: int,
    owners: Dict[int, Sequence[int]],
    peer_addrs: Dict[int, Tuple[str, int]],
    G: int = 3,
    host: str = "127.0.0.1",
    seed: int = 0,
    delay_elections: int = 0,
    data_dir: Optional[str] = None,
    snapshot_every_s: float = 30.0,
) -> RpcNode:
    """Bring up one split-shard process: engine group 0 = config RSM,
    groups ``1..G-1`` = gids ``1..G-1``, peer slots placed per
    ``owners`` (every process passes the SAME map).

    With ``data_dir`` the process is DURABLE under its peer identity
    (split_server.SplitPersistence, via the shared service-adapter
    trio): a kill -9'd process may be restarted on the same dir and
    REJOINS safely — persisted term/vote/log make double-votes and
    acked-entry loss impossible, and the service redo log re-applies
    shard/config state through the live apply gates.  Without it, a
    killed process must stay dead; the surviving quorums carry every
    acked write — replication is the durability."""
    node = RpcNode(listen=True, host=host, port=port)
    sched = node.sched

    def build():
        from .split_server import SplitPersistence

        cfg = EngineConfig(G=G, P=3, L=64, E=8, INGEST=8,
                           host_paced_compaction=True)
        driver = EngineDriver(cfg, seed=seed)
        skv = SplitShardKV(driver)
        peering = SplitPeering(
            driver, skv, SplitSpec(me=me, owners={
                int(g): list(o) for g, o in owners.items()
            })
        )
        persist = None
        if data_dir is not None:
            persist = SplitPersistence(
                data_dir, skv, peering, snapshot_every_s=snapshot_every_s
            )
            # BEFORE any tick: pre-restore state must never act.
            persist.load_and_install()
        if delay_elections:
            driver.state = driver.state._replace(
                elect_dl=driver.state.elect_dl + int(delay_elections)
            )
        # Warm the tick before the readiness line (first jit compile
        # would otherwise starve RPC dispatch under the first client).
        skv.pump(4)
        ends = {
            int(p): node.client_end(h, int(pt))
            for p, (h, pt) in peer_addrs.items()
            if int(p) != me
        }
        return SplitShardKVService(sched, skv, peering, ends,
                                   persistence=persist)

    svc = sched.run_call(build, timeout=600.0)
    node.add_service("SplitShardKV", svc)
    node.add_service("SplitEngine", svc)
    node.engine_service = svc
    return node
