"""Wedge detection: a per-group no-progress watchdog.

Gray failures wedge groups without killing anything: a leader severed
from its quorum (but not from its clients) keeps accepting proposals
that can never commit; a one-way partition leaves heartbeats flowing
in the direction that placates followers while append replies die in
the other.  Every liveness signal built on "is the process up" stays
green.  The only honest symptom is *no progress*: the group's commit
frontier stops advancing while proposals are pending.

This watch turns that symptom into evidence while the wedge is live.
Every ``interval`` seconds it scrapes the per-group commit frontier
(``ObsControl.groups``) and the driver's per-group ``Start()`` backlog,
and counts consecutive scrapes in which a group had work pending but
its commit index did not move.  At ``stall_ticks`` consecutive
no-progress scrapes the group is declared WEDGED:

* a ``WEDGE`` flight record (flightrec.py) names the group, its stall
  length, the stalled commit index, the pending backlog, and — in the
  tag — the stuck leader and its term (``"p<peer>@t<term>"``, ``p-1``
  when the group has no leader at all);
* ``gauge.wedged_groups`` (ObsControl.gauges) carries the live count,
  so a fleet scrape sees the wedge mid-run;
* ``wedge.trips`` counts wedge onsets, ``wedge.active`` mirrors the
  gauge in the metrics registry.

Recovery is detected the same way: one commit advance (or an emptied
backlog) clears the group's stall count, drops it from the wedged set,
and the gauge falls.  The postmortem doctor pairs the WEDGE records
with the chaos fault windows to name the partition that caused the
wedge (analysis/postmortem.py, "wedged leadership").

Knobs (env-tunable):

* ``MRT_WEDGE_INTERVAL``  watch period, seconds (default 0.25)
* ``MRT_WEDGE_TICKS``     consecutive stalled scrapes before a group
                          is declared wedged (default 8 — i.e. two
                          seconds of no progress at the default period,
                          comfortably past an election round-trip)
* ``MRT_WEDGE_WATCH=0``   disable the watch entirely

Like the overload watch it runs on the node's scheduler loop (same
thread as dispatch), so the loop-thread-only driver state is safe to
read, and a watch tick must never take the serving loop down.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set

from ..utils.knobs import knob_bool, knob_float, knob_int
from . import flightrec
from .observe import ObsControl

__all__ = ["WedgeWatch", "install_wedge_watch"]


class WedgeWatch:
    """Periodic commit-frontier-vs-backlog progress check on one node."""

    def __init__(self, node: Any, interval: Optional[float] = None,
                 stall_ticks: Optional[int] = None) -> None:
        self.node = node
        self.interval = (
            interval if interval is not None
            else knob_float("MRT_WEDGE_INTERVAL")
        )
        self.stall_ticks = max(1, int(
            stall_ticks if stall_ticks is not None
            else knob_int("MRT_WEDGE_TICKS")
        ))
        self._ctl = ObsControl(node)
        self._prev_commit: Optional[List[int]] = None
        self._stall: Dict[int, int] = {}  # group -> consecutive stalls
        self.wedged: Set[int] = set()     # groups currently wedged
        self._stopped = False
        node.sched.call_after(self.interval, self._tick)

    def stop(self) -> None:
        self._stopped = True

    # -- one watch tick ---------------------------------------------------

    def _tick(self) -> None:
        if self._stopped or getattr(self.node, "_closed", False):
            return
        try:
            self.check()
        except Exception:
            # The watch must never take the serving loop down.
            self.node.obs.metrics.inc("wedge.watch_errors")
        self.node.sched.call_after(self.interval, self._tick)

    def check(self) -> int:
        """Run one progress check; returns the wedged-group count."""
        m = self.node.obs.metrics
        groups = self._ctl.groups()
        if groups is None:  # no engine service on this node
            return 0
        driver = getattr(self._ctl._engine_kv(), "driver", None)
        backlog = getattr(driver, "backlog", None)
        commit: List[int] = groups["commit"]
        prev, self._prev_commit = self._prev_commit, list(commit)
        frec = getattr(self.node, "_frec", None)
        # Groups intentionally paused are NOT wedges: a sealed group is
        # mid-migration (its frontier freezes by design until the
        # destination adopts), and a reconfiguring group's commit may
        # legitimately stall while the joint phase waits on BOTH
        # quorums.  Counting either would fire a false "wedged
        # leadership" anomaly exactly when self-healing is working.
        sealed = groups.get("sealed") or []
        reconfig = groups.get("reconfig") or []
        for g in range(len(commit)):
            pend = int(backlog[g]) if backlog is not None else 0
            moved = prev is None or g >= len(prev) or commit[g] > prev[g]
            exempt = bool(
                (g < len(sealed) and sealed[g])
                or (g < len(reconfig) and reconfig[g])
            )
            if exempt:
                self._stall[g] = 0
                if g in self.wedged:
                    self.wedged.discard(g)
                m.inc("wedge.reconfig_exempt")
                continue
            if moved or pend <= 0:
                # Progress, or nothing owed: not a wedge.  (An idle
                # group with a severed leader is invisible here by
                # design — no client is being harmed.)
                self._stall[g] = 0
                self.wedged.discard(g)
                continue
            self._stall[g] = self._stall.get(g, 0) + 1
            if self._stall[g] < self.stall_ticks:
                continue
            if g not in self.wedged:
                self.wedged.add(g)
                m.inc("wedge.trips")
            # Re-recorded every stalled scrape while wedged: the ring
            # then shows the wedge's full extent, not just its onset,
            # and the doctor reads duration straight off the records.
            if frec is not None:
                frec.record(
                    flightrec.WEDGE,
                    code=g,
                    a=self._stall[g],
                    b=int(commit[g]),
                    c=pend,
                    tag=f"p{groups['leader'][g]}@t{groups['term'][g]}",
                )
        m.set("wedge.active", float(len(self.wedged)))
        return len(self.wedged)


def install_wedge_watch(
    node: Any, interval: Optional[float] = None
) -> Optional[WedgeWatch]:
    """Attach the watch to a serving node (no-op when
    ``MRT_WEDGE_WATCH=0``).  Returns the watch, kept reachable on
    ``node.wedge_watch`` (ObsControl.gauges reads it for
    ``gauge.wedged_groups``)."""
    if not knob_bool("MRT_WEDGE_WATCH"):
        return None
    watch = WedgeWatch(node, interval=interval)
    node.wedge_watch = watch
    return watch
