"""Engine-backed multi-process clusters (split out of cluster.py,
round 4): one process per CHIP-OWNING engine rather than one per
replica server.  Four deployment shapes:

* :class:`EngineProcessCluster` — one engine process serving G groups
  (plain KV), optionally durable (checkpoint + WAL) and mesh-sharded;
* :class:`EngineFleetCluster` — several engine shard processes
  splitting one global gid space, migration riding
  pull_shard/delete_shard RPCs between them;
* :class:`SplitProcessCluster` — processes SHARING each replica
  group's peer slots (engine/split.py): a process death loses single
  peers, surviving quorums keep serving;
* :class:`SplitShardProcessCluster` — the sharded stack with split
  peer slots (engine/split_shard.py): per-process failure domains
  WHILE shard migration continues.

Launch/readiness plumbing and the sim-backend clusters stay in
cluster.py; the blocking clerk facades here wrap the engine clerks.
"""

from __future__ import annotations

import os
import subprocess
import time
from typing import Any, Dict, List, Optional, Sequence

from ..sim.scheduler import TIMEOUT
from ..utils.knobs import knob_str
from .launch import (
    BlockingClerkBase as _BlockingClerkBase,
    check_ready as _check_ready,
    launch_server as _launch_server,
    reserve_ports as _reserve_ports,
)
from .tcp import RpcNode

__all__ = [
    "EngineProcessCluster",
    "EngineFleetCluster",
    "SplitProcessCluster",
    "SplitShardProcessCluster",
    "BlockingEngineClerk",
    "BlockingFleetClerk",
    "BlockingSplitClerk",
    "BlockingSplitShardClerk",
]


class EngineProcessCluster:
    """One chip-owning engine server process (kind ``engine_kv`` or
    ``engine_shardkv``) + blocking clerks — the engine-backed network
    cluster (SURVEY §2.2's sidecar story, step 1: a single front door
    coalescing clerk RPCs into device ticks).  Unlike the per-replica
    ``KVProcessCluster``, consensus replication happens ON CHIP across
    the engine's (G, P) lanes; the network carries client traffic only.
    """

    def __init__(
        self,
        kind: str = "engine_kv",
        groups: int = 64,
        host: str = "127.0.0.1",
        seed: int = 0,
        join_gids: Optional[List[int]] = None,
        data_dir: Optional[str] = None,
        checkpoint_every_s: float = 30.0,
        mesh_devices: int = 0,
        chaos_seed: Optional[int] = None,
    ) -> None:
        assert kind in ("engine_kv", "engine_shardkv")
        self.kind = kind
        self.host = host
        self.spec = {
            "kind": kind,
            "ports": _reserve_ports(1, host),
            "groups": groups,
            "seed": seed,
            "platform": knob_str("MRT_ENGINE_PLATFORM"),
        }
        if chaos_seed is not None:
            # Fault-injection mode: the server installs chaos hooks +
            # the "Chaos" control RPC (harness/nemesis.py drives it).
            self.spec["chaos_seed"] = int(chaos_seed)
        if join_gids is not None:
            self.spec["join_gids"] = list(join_gids)
        if data_dir is not None:
            # Durable mode: checkpoint + WAL under data_dir; kill() +
            # start() then recovers every acknowledged op.
            self.spec["data_dir"] = data_dir
            self.spec["checkpoint_every_s"] = checkpoint_every_s
        if mesh_devices:
            # Multi-chip mode: the server runs the shard_map tick over
            # this many local devices (G must divide evenly).
            self.spec["mesh_devices"] = mesh_devices
        self.proc: Optional[subprocess.Popen] = None

    @property
    def port(self) -> int:
        return self.spec["ports"][0]

    def start(self) -> None:
        assert self.proc is None or self.proc.poll() is not None
        self.proc = _launch_server(self.spec, "engine")
        _check_ready(self.proc, "engine", timeout=300.0)

    def kill(self) -> None:
        """SIGKILL the server process (literal crash; restart with
        :meth:`start` — durable mode recovers from data_dir)."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()

    def clerk(self, lane: str = "") -> "BlockingEngineClerk":
        """``lane="verify"`` marks the clerk's traffic for the server's
        admission exemption (porcupine samplers must keep sampling
        while user traffic sheds)."""
        return BlockingEngineClerk(
            self.port, host=self.host,
            service="EngineKV" if self.kind == "engine_kv"
            else "EngineShardKV",
            lane=lane,
        )

    def shutdown(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self.proc = None


class _SplitClusterBase:
    """Shared driver for the split deployments (plain KV and sharded):
    spec construction, the durable-vs-stay-dead crash discipline, and
    process lifecycle live exactly once here; subclasses pin the server
    kind, the label, and the clerk.

    ``owners[g][p]`` = process index owning peer slot ``p`` of group
    ``g`` (same map for every process).  ``delay_elections[i]`` biases
    process ``i``'s first election deadlines later — tests use it to
    park initial leadership on a chosen process.  Without ``data_dir``,
    replication across surviving quorums IS the durability and a
    killed member must stay dead (a fresh-state restart under an old
    peer identity can double-vote, engine/split.py's crash-model
    note); with it, each process is durable under its peer identity
    (SplitPersistence) and ``kill(i)`` + ``start(i)`` REJOINS from the
    persisted term/vote/log + service redo log."""

    KIND: str
    LABEL: str

    def __init__(
        self,
        owners: Dict[int, Sequence[int]],
        n_procs: int,
        groups: int,
        host: str = "127.0.0.1",
        seed: int = 0,
        delay_elections: Optional[Sequence[int]] = None,
        data_dir: Optional[str] = None,
        snapshot_every_s: float = 30.0,
        chaos_seed: Optional[int] = None,
    ) -> None:
        from . import engine_server  # noqa: F401  (codec registration)
        from . import split_server  # noqa: F401
        from . import split_shard_server  # noqa: F401

        self.host = host
        self.ports = _reserve_ports(n_procs, host)
        self.specs = []
        for i in range(n_procs):
            spec = {
                "kind": self.KIND,
                "me": i,
                "host": host,
                "ports": self.ports,
                "owners": {str(g): list(o) for g, o in owners.items()},
                "groups": groups,
                "seed": seed + i,
                "delay_elections": (
                    int(delay_elections[i]) if delay_elections else 0
                ),
                "platform": knob_str("MRT_ENGINE_PLATFORM"),
            }
            if data_dir is not None:
                spec["data_dir"] = os.path.join(data_dir, f"proc-{i}")
                spec["snapshot_every_s"] = snapshot_every_s
            if chaos_seed is not None:
                # Distinct per-process streams from one harness seed.
                spec["chaos_seed"] = int(chaos_seed) + i
            self.specs.append(spec)
        self.durable = data_dir is not None
        self._killed: set = set()
        self.procs: List[Optional[subprocess.Popen]] = [None] * n_procs

    def start(self, i: int) -> None:
        assert self.procs[i] is None or self.procs[i].poll() is not None
        # Restarting a previously-killed member is only safe in durable
        # mode (the double-vote hazard — see the class docstring).
        assert self.durable or i not in self._killed, (
            f"process {i} was killed; a non-durable split peer must "
            "stay dead (pass data_dir= for safe rejoin)"
        )
        self.procs[i] = _launch_server(self.specs[i], f"{self.LABEL}-{i}")
        _check_ready(self.procs[i], f"{self.LABEL}-{i}", timeout=300.0)

    def start_all(self) -> None:
        assert self.durable or not self._killed, (
            f"processes {sorted(self._killed)} were killed; a "
            "non-durable split peer must stay dead (pass data_dir= "
            "for safe rejoin)"
        )
        for i, spec in enumerate(self.specs):
            self.procs[i] = _launch_server(spec, f"{self.LABEL}-{i}")
        for i, p in enumerate(self.procs):
            _check_ready(p, f"{self.LABEL}-{i}", timeout=300.0)

    def kill(self, i: int) -> None:
        """SIGKILL process ``i``.  Durable mode: :meth:`start` rejoins
        it from its data_dir; non-durable: it must stay dead."""
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()
        self.procs[i] = None
        self._killed.add(i)

    def shutdown(self) -> None:
        for i in range(len(self.procs)):
            self.kill(i)


class SplitProcessCluster(_SplitClusterBase):
    """Several engine processes SHARING each replica group's peer slots
    (engine/split.py + distributed/split_server.py) — one process's
    death loses only its owned peer slots; any group whose surviving
    slots hold a quorum keeps serving with every acknowledged write
    intact.  Contrast :class:`EngineFleetCluster`, which partitions
    whole gids per process.  Crash/durability discipline:
    :class:`_SplitClusterBase`."""

    KIND = "split_kv"
    LABEL = "split"

    def __init__(self, owners, n_procs, groups: int = 8, **kw) -> None:
        super().__init__(owners, n_procs, groups, **kw)

    def clerk(self) -> "BlockingSplitClerk":
        return BlockingSplitClerk(self.ports, host=self.host)


class BlockingSplitClerk(_BlockingClerkBase):
    """Blocking client of a :class:`SplitProcessCluster`."""

    def __init__(
        self, ports: Sequence[int], host: str = "127.0.0.1"
    ) -> None:
        from .split_server import SplitNetClerk

        self.node = RpcNode()
        self.sched = self.node.sched
        ends = [self.node.client_end(host, p) for p in ports]
        self._clerk = SplitNetClerk(self.sched, ends)


class SplitShardProcessCluster(_SplitClusterBase):
    """Several engine processes SHARING the sharded stack's peer slots
    (engine/split_shard.py + distributed/split_shard_server.py): the
    config RSM and every replica group survive any minority-owner
    process death — including mid-migration (the reference shardkv
    failure model, shardkv/config.go:204-262, at the process level).
    Crash/durability discipline: :class:`_SplitClusterBase`."""

    KIND = "split_shardkv"
    LABEL = "splitshard"

    def __init__(self, owners, n_procs, groups: int = 3, **kw) -> None:
        super().__init__(owners, n_procs, groups, **kw)

    def clerk(self) -> "BlockingSplitShardClerk":
        return BlockingSplitShardClerk(self.ports, host=self.host)


class BlockingSplitShardClerk(_BlockingClerkBase):
    """Blocking client of a :class:`SplitShardProcessCluster`, with
    the admin (join/leave/move) and status probes exposed."""

    def __init__(
        self, ports: Sequence[int], host: str = "127.0.0.1"
    ) -> None:
        from .split_shard_server import SplitShardNetClerk

        self.node = RpcNode()
        self.sched = self.node.sched
        ends = [self.node.client_end(host, p) for p in ports]
        self._clerk = SplitShardNetClerk(self.sched, ends)

    def admin(self, kind: str, payload, timeout: float = 60.0) -> None:
        self._run(self._clerk.admin(kind, payload), timeout)

    def status(self, proc: int, timeout: float = 10.0):
        return self._run(self._clerk.status(proc), timeout)


class EngineFleetCluster:
    """Several chip-owning engine shard processes splitting one global
    gid space — SURVEY §2.2's end state at the process level: clerk
    traffic and shard migration ride the real network BETWEEN engines,
    consensus stays on each process's device.

    ``assignment[i]`` is the gid list process ``i`` hosts.  Admin ops
    are mirrored to every process in issue order with an explicit
    command id, so retries cannot fork the fleet's config histories.
    """

    def __init__(
        self,
        assignment: Sequence[Sequence[int]],
        host: str = "127.0.0.1",
        seed: int = 0,
        data_dir: Optional[str] = None,
        checkpoint_every_s: float = 30.0,
        mesh_devices: int = 0,
        chaos_seed: Optional[int] = None,
        spare_slots: int = 0,
        replicas: int = 3,
        voters: Optional[Sequence[int]] = None,
        shipping: bool = False,
        ship_sync: Optional[bool] = None,
        ship_window_s: Optional[float] = None,
    ) -> None:
        # Registers the wire dataclasses (EngineCmdArgs/Reply) with the
        # codec — admin replies are refused as unregistered otherwise.
        from . import engine_server  # noqa: F401

        self.host = host
        self.assignment = [list(g) for g in assignment]
        self.ports = _reserve_ports(len(self.assignment), host)
        self.owner_addrs = {}
        for i, gl in enumerate(self.assignment):
            for g in gl:
                self.owner_addrs[g] = (host, self.ports[i])
        self.specs = []
        for i, gl in enumerate(self.assignment):
            spec = {
                "kind": "engine_fleet",
                "ports": [self.ports[i]],
                "gids": gl,
                "peer_addrs": {
                    str(g): list(a) for g, a in self.owner_addrs.items()
                    if g not in gl
                },
                "seed": seed + i,
                "platform": knob_str("MRT_ENGINE_PLATFORM"),
            }
            if spare_slots:
                # Idle engine groups the placement controller adopts
                # migrated gids into (harness/fleet.py).
                spec["spare_slots"] = int(spare_slots)
            if replicas != 3 or voters is not None:
                # Spare engine REPLICA slots (self-healing replica
                # sets): P=replicas rows per group, only ``voters``
                # vote; the controller replaces a permanently dead
                # voter by seating a learner in a spare row.
                spec["replicas"] = int(replicas)
                if voters is not None:
                    spec["voters"] = [int(q) for q in voters]
            if data_dir is not None:
                spec["data_dir"] = os.path.join(data_dir, f"proc-{i}")
                spec["checkpoint_every_s"] = checkpoint_every_s
            if mesh_devices:
                # Each process runs its engine over a local mesh; its
                # len(gids)+1 engine groups must divide evenly over
                # mesh_devices (loud error from engine/mesh.py if not).
                spec["mesh_devices"] = mesh_devices
            if chaos_seed is not None:
                # Distinct per-process streams from one harness seed.
                spec["chaos_seed"] = int(chaos_seed) + i
            if shipping:
                # Durable state plane (distributed/stateplane.py): each
                # process ships hosted-group snapshots+tails to standby
                # processes, bounding failover data loss to the shipping
                # window (MRT_SHIP_WINDOW_S; ship_sync=True → zero
                # acknowledged-write loss).
                spec["fleet_addrs"] = {
                    str(j): [host, self.ports[j]]
                    for j in range(len(self.assignment))
                }
                spec["me"] = i
                if ship_sync is not None:
                    spec["ship_sync"] = bool(ship_sync)
                if ship_window_s is not None:
                    spec["ship_window_s"] = float(ship_window_s)
            self.specs.append(spec)
        self.procs: List[Optional[subprocess.Popen]] = [None] * len(self.specs)
        self._admin_node: Optional[RpcNode] = None
        self._admin_cmd = 0
        self._admin_inflight = None  # ((kind, repr(arg)), cmd) being retried

    def start_all(self) -> None:
        # Launch all processes first (jit warm-up dominates and runs in
        # parallel), then collect readiness lines.
        for i, spec in enumerate(self.specs):
            self.procs[i] = _launch_server(spec, f"fleet-{i}")
        for i, p in enumerate(self.procs):
            _check_ready(p, f"fleet-{i}", timeout=300.0)

    def kill(self, i: int) -> None:
        """SIGKILL fleet process ``i`` (its gids go dark until
        :meth:`start` revives it — from its data_dir in durable mode)."""
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()

    def start(self, i: int) -> None:
        """(Re)start fleet process ``i`` on its original spec/ports."""
        assert self.procs[i] is None or self.procs[i].poll() is not None
        self.procs[i] = _launch_server(self.specs[i], f"fleet-{i}")
        _check_ready(self.procs[i], f"fleet-{i}", timeout=300.0)

    def admin(self, kind: str, arg: Any, timeout: float = 60.0) -> None:
        """Mirror one config op to every process (same order, same
        command id → identical config histories; see the service's
        ``admin`` docstring for why the id is mandatory here).

        Retryable after a TimeoutError: re-issuing the SAME (kind, arg)
        reuses the interrupted attempt's command id, so processes that
        already applied it dedup instead of applying twice (a fresh id
        on retry would fork the fleet's config numbering)."""
        if self._admin_node is None:
            self._admin_node = RpcNode()
        op_key = (kind, repr(arg))
        if self._admin_inflight and self._admin_inflight[0] == op_key:
            cmd = self._admin_inflight[1]  # resume the interrupted op
        else:
            self._admin_cmd += 1
            cmd = self._admin_cmd
            self._admin_inflight = (op_key, cmd)
        sched = self._admin_node.sched
        deadline = time.monotonic() + timeout
        for i, port in enumerate(self.ports):
            # Skip processes that are not running: mirroring an admin op
            # to a killed process would spin until the deadline, and a
            # placed fleet keeps serving while the controller re-places
            # the dead process's gids.
            p = self.procs[i]
            if p is None or p.poll() is not None:
                continue
            end = self._admin_node.client_end(self.host, port)
            while True:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"fleet admin {kind} timed out")
                reply = sched.wait(
                    end.call("EngineShardKV.admin", (kind, arg, cmd)),
                    6.0,
                )
                if (
                    reply is not None
                    and reply is not TIMEOUT
                    and getattr(reply, "err", None) == "OK"
                ):
                    break  # committed on this process; next one
        self._admin_inflight = None

    def clerk(self) -> "BlockingFleetClerk":
        return BlockingFleetClerk(self.owner_addrs)

    def shutdown(self) -> None:
        if self._admin_node is not None:
            self._admin_node.close()
            self._admin_node = None
        for i, p in enumerate(self.procs):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
            self.procs[i] = None


class BlockingFleetClerk(_BlockingClerkBase):
    """Blocking client of an :class:`EngineFleetCluster`."""

    def __init__(self, owner_addrs: dict) -> None:
        from .engine_server import EngineFleetClerk

        self.node = RpcNode()
        self.sched = self.node.sched
        ends = {
            g: self.node.client_end(h, p)
            for g, (h, p) in owner_addrs.items()
        }
        # make_end: the clerk re-derives gid→end from the fleet's
        # placement view after ErrWrongGroup (a controller may have
        # MOVED the gid to another process).
        self._clerk = EngineFleetClerk(
            self.sched, ends, make_end=self.node.client_end
        )

    @property
    def client_id(self) -> int:
        return self._clerk.client_id


class BlockingEngineClerk(_BlockingClerkBase):
    """Blocking client of an :class:`EngineProcessCluster`."""

    def __init__(
        self, port: int, host: str = "127.0.0.1",
        service: str = "EngineKV", lane: str = "",
    ) -> None:
        from .engine_server import EngineClerk

        self.node = RpcNode()
        self.sched = self.node.sched
        end = self.node.client_end(host, port)
        self._clerk = EngineClerk(self.sched, end, service=service, lane=lane)

    @property
    def client_id(self) -> int:
        return self._clerk.client_id


