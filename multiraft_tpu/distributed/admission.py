"""Admission control: the layer that turns overload signals into
overload *behavior* (round 8).

PR 7's telemetry plane and the OverloadWatch can *see* queueing
collapse — LOADCURVE_r01 shows wire-stage p99 exploding to seconds past
a ~2000 ops/s knee while the engine itself stays at ~18 ms — but until
now nothing shed, bounded, or pushed back, so overload degraded every
client instead of a controlled few.  This module is the front door's
bouncer, consulted by ``tcp.py``'s dispatch before any handler runs:

* **Token-bucket admission per client session.**  Each connection is a
  client session (every clerk owns its own RpcNode/connection); each
  session gets a refill-on-read token bucket, plus one node-wide bucket
  bounding aggregate intake.  Buckets refill at ``rate × factor`` where
  ``factor`` comes from the brownout state machine (overload.py) —
  HEALTHY admits at the configured rate, SHEDDING and BROWNOUT tighten
  it, so the OverloadWatch's stage-p99/gauge trips translate directly
  into fewer admitted requests.
* **Bounded per-connection dispatch queue.**  A cap on
  dispatched-but-unreplied requests per connection.  The open-loop
  generator can offer load the server cannot refuse; this bound is what
  refuses it — past the cap the request is shed instead of joining the
  collapse queue.
* **Shed with an explicit retry hint.**  Refused requests get a
  ``("busy", req_id, retry_after_s)`` frame when the peer negotiated the
  ``busy`` hello capability, so the clerk resolves immediately with
  :data:`~.engine_wire.ERR_BUSY` and backs off for a *jittered*
  ``retry_after_s`` instead of burning its full timeout.  Legacy peers
  (no hello, or ``MRT_WIRE_LEGACY=1``) never see the frame — the shed
  degrades to a silent drop and the clerk's ordinary timeout+backoff,
  exactly the pre-round-8 overload behavior.
* **Priority lanes.**  Control-plane (``Chaos.*``/``Obs.*``), system
  traffic (placement/config/admin verbs, anything that is not the KV
  data plane), and the porcupine verifier's clerks (rids prefixed
  ``verify.``) are exempt from shedding, so the fleet stays observable,
  steerable, and verifiable while user traffic sheds.

Kill switch: ``MRT_ADMISSION=0`` skips the install entirely.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

from ..utils.knobs import knob_bool, knob_float, knob_int, knob_str
from .engine_wire import busy_reply  # noqa: F401  (re-export for tcp.py)
from .observe import is_control

__all__ = [
    "TokenBucket",
    "AdmissionController",
    "install_admission",
    "lane_of",
    "LANE_USER",
    "LANE_VERIFY",
    "LANE_SYSTEM",
    "LANE_CONTROL",
]


# -- lanes ------------------------------------------------------------------

LANE_USER = "user"        # KV data plane: the only lane that sheds
LANE_VERIFY = "verify"    # porcupine sampler clerks (rid prefix "verify.")
LANE_SYSTEM = "system"    # placement/config/admin/firehose-admin verbs
LANE_CONTROL = "control"  # Chaos.* / Obs.* — never shed, never counted

# The KV data plane is a small closed set of verbs; everything else on
# an engine service (placement, config, admin, pull_shard, ...) is
# system traffic that must keep flowing while user traffic sheds.
_DATA_METHS = frozenset({"command", "batch", "firehose"})


def lane_of(svc_meth: str, rid: Any) -> str:
    """Classify one dispatch into its priority lane.  ``rid`` is the
    request's trace id (clerks send ``"<lane.>client.seq"`` strings;
    open-loop raw calls send ``(rid, t_send)`` tuples)."""
    if is_control(svc_meth):
        return LANE_CONTROL
    _, _, meth = svc_meth.partition(".")
    if meth not in _DATA_METHS:
        return LANE_SYSTEM
    tag = rid[0] if isinstance(rid, tuple) and rid else rid
    if isinstance(tag, str) and tag.startswith("verify."):
        return LANE_VERIFY
    return LANE_USER


# -- token bucket -----------------------------------------------------------

class TokenBucket:
    """Refill-on-read token bucket.  ``take`` returns 0.0 on admit or
    the seconds until one token exists at the *current* effective rate
    — the raw material for the retry_after_s hint.  ``factor`` scales
    the refill rate (brownout tightening) without resetting state."""

    __slots__ = ("rate", "burst", "tokens", "_t", "_now")

    def __init__(self, rate: float, burst: float, now=time.monotonic):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self._now = now
        self._t = now()

    def take(self, factor: float = 1.0) -> float:
        eff = self.rate * factor
        now = self._now()
        if eff > 0:
            self.tokens = min(self.burst, self.tokens + (now - self._t) * eff)
        self._t = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        if eff <= 0:
            return 1.0
        return (1.0 - self.tokens) / eff


# -- controller -------------------------------------------------------------

class AdmissionController:
    """Dispatch-layer admission: consulted by tcp.py before handler
    lookup, driven by overload.py's brownout machine via
    :meth:`set_level`.  All calls happen on the node's single loop
    thread (dispatch, reply, overload tick), so no locking."""

    def __init__(
        self,
        metrics: Any = None,
        rate: Optional[float] = None,
        burst: Optional[float] = None,
        session_rate: Optional[float] = None,
        inflight_cap: Optional[int] = None,
        now=time.monotonic,
    ):
        # Default: 0.8x the measured per-op serving knee (LOADCURVE_r01
        # put it at 2000 offered ops/s) — admit into the region where
        # accepted-request latency is bounded AND stable, shed the
        # rest.  0.9x was tried and sits too close to saturation: the
        # knee-step p99 swung 85->196ms between identical sweeps.
        # Deployments serving a faster path (firehose batching, a
        # beefier host) should raise MRT_ADMIT_RATE to ~0.8x THEIR knee.
        self.rate = rate if rate is not None else knob_float("MRT_ADMIT_RATE")
        # Bucket depth = 125ms of rate: deep enough to absorb Poisson
        # arrival clumps (sd ~ sqrt(rate) per second), shallow enough
        # that a sustained overload starts shedding within ~an RTT
        # instead of admitting seconds of excess into the queues first.
        self.burst = burst if burst is not None else knob_float(
            "MRT_ADMIT_BURST", self.rate / 8.0)
        self.session_rate = session_rate if session_rate is not None else knob_float(
            "MRT_ADMIT_SESSION_RATE", self.rate)
        self.inflight_cap = int(inflight_cap if inflight_cap is not None
                                else knob_int("MRT_ADMIT_INFLIGHT"))
        # Minimum retry hint per brownout level — bucket deficits at
        # high refill rates are sub-millisecond, which would invite an
        # immediate re-offer; the floor grows as the node browns out.
        self.base_hint_s = knob_float("MRT_ADMIT_RETRY_S")
        self._now = now
        self._m = metrics
        self._global = TokenBucket(self.rate, self.burst, now=now)
        self._sessions: Dict[Any, TokenBucket] = {}
        self._inflight: Dict[Any, int] = {}
        # Brownout level (overload.HEALTHY/SHEDDING/BROWNOUT) and the
        # admission factor it maps to.
        self.level = 0
        self._factors = self._parse_factors(
            knob_str("MRT_BROWNOUT_FACTORS") or "")

    @staticmethod
    def _parse_factors(raw: str) -> Tuple[float, float, float]:
        try:
            parts = tuple(float(x) for x in raw.split(",") if x.strip())
            if len(parts) == 3:
                return parts  # type: ignore[return-value]
        except ValueError:
            pass
        return (1.0, 0.5, 0.2)

    @property
    def factor(self) -> float:
        return self._factors[min(self.level, len(self._factors) - 1)]

    def set_level(self, level: int) -> None:
        """Brownout drive: 0=healthy, 1=shedding, 2=brownout."""
        self.level = max(0, int(level))

    def tokens(self) -> float:
        """Current node-wide bucket depth (refreshed) — the
        ``gauge.admit_tokens`` export."""
        b = self._global
        eff = b.rate * self.factor
        if eff > 0:
            now = b._now()
            b.tokens = min(b.burst, b.tokens + (now - b._t) * eff)
            b._t = now
        return b.tokens

    def inflight_total(self) -> int:
        return sum(self._inflight.values())

    # -- the hot path -------------------------------------------------------

    def admit(self, conn: Any, lane: str) -> Optional[float]:
        """``None`` = admitted; a float = shed, with that retry_after_s
        hint.  Only the user lane ever sheds — control/system/verify
        traffic must survive the very overload this layer manages."""
        m = self._m
        if m is not None:
            m.inc(f"admit.lane.{lane}")
        if lane != LANE_USER:
            return None
        factor = self.factor
        hint = 0.0
        inflight = self._inflight.get(conn, 0)
        if inflight >= max(1, int(self.inflight_cap * factor)):
            hint = self.base_hint_s * (1 + self.level)
        else:
            wait = self._global.take(factor)
            if wait <= 0.0 and self.session_rate > 0:
                sess = self._sessions.get(conn)
                if sess is None:
                    sess = self._sessions[conn] = TokenBucket(
                        self.session_rate, max(1.0, self.session_rate / 8.0),
                        now=self._now)
                wait = sess.take(factor)
            if wait > 0.0:
                hint = max(wait, self.base_hint_s * (1 + self.level))
        if hint > 0.0:
            hint = min(hint, 5.0)
            if m is not None:
                m.inc("admit.shed")
                m.observe("admit.retry_after_s", hint)
            return hint
        self._inflight[conn] = inflight + 1
        if m is not None:
            m.inc("admit.accepted")
        return None

    def release(self, conn: Any, lane: str) -> None:
        """One admitted user-lane dispatch replied (or its connection
        died) — pairs 1:1 with a ``None`` return from :meth:`admit`."""
        if lane != LANE_USER:
            return
        left = self._inflight.get(conn, 0) - 1
        if left > 0:
            self._inflight[conn] = left
        else:
            self._inflight.pop(conn, None)

    def conn_closed(self, conn: Any) -> None:
        self._sessions.pop(conn, None)
        self._inflight.pop(conn, None)


def install_admission(node: Any, **kw: Any) -> Optional[AdmissionController]:
    """Attach an AdmissionController to a serving node (the engine
    front doors call this next to install_overload_watch).  Gated on
    ``MRT_ADMISSION`` (default on)."""
    if not knob_bool("MRT_ADMISSION"):
        return None
    adm = AdmissionController(metrics=node.obs.metrics, **kw)
    node.admission = adm
    return adm
