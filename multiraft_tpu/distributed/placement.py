"""Fleet placement controller — elastic cross-host group placement.

ARCHITECTURE §7 ends with "cross-host group placement is the transport
layer's job, not the kernel's"; this module is that job.  Three layers:

* :class:`PlacementCtrler` — the placement MAP (raft group → mesh
  process) as its own Raft-replicated state machine, riding the same
  machinery as :class:`~multiraft_tpu.services.shardctrler.ShardCtrler`
  so the map survives its own leader dying.  Migrations are two-phase
  in the map (``Begin`` intent → ``Commit``): a controller that dies
  mid-migration is resumed by its successor from the replicated intent,
  which is what makes the idempotent migration legs safe to retry —
  without the intent, a restarted controller could pull the same sealed
  source a second time and adopt it at a DIFFERENT destination, forking
  the group.

* :func:`plan_moves` — the pure planner: weighted minimal-movement
  rebalance (:func:`~multiraft_tpu.services.shardctrler.
  rebalance_weighted`) over per-group commit rates scraped from
  ``Obs.groups``, wrapped in the anti-thrash policy (hysteresis on
  relative spread gain, per-group cooldown, bounded moves per round).
  Failover moves — groups on a process that stopped answering
  ``Obs.ping`` within the deadline — bypass hysteresis and the cap:
  healing is never rate-limited by politeness knobs.

* :class:`PlacementController` — the real-time loop: scrape → plan →
  execute → push.  Execution rides the group-migration RPCs on
  :class:`~.engine_shard_server.EngineShardKVService` (``pull_group``
  seal+export at the source, ``adopt_group`` into a spare engine slot
  at the destination, ``drop_group`` back at the source), then pushes
  the new placement map fleet-wide (``place``) so servers re-derive
  their peer maps and clerks re-route.

Every knob reads an ``MRT_PLACE_*`` env var (constructor args win):

=====================  =======  ==========================================
MRT_PLACE_SCRAPE_S     0.5      seconds between controller rounds
MRT_PLACE_DEAD_S       3.0      no Obs.ping for this long → process dead
MRT_PLACE_COOLDOWN_S   5.0      a moved group may not move again sooner
MRT_PLACE_MIN_GAIN     0.25     min relative load-spread reduction to act
MRT_PLACE_MAX_MOVES    1        voluntary moves per round (failover exempt)
=====================  =======  ==========================================

Failure-detection semantics: liveness is "answered ``Obs.ping`` within
``dead_s``", judged on the controller's monotonic clock from its LAST
successful ping of that process.  A dead process's groups are adopted
EMPTY at survivors (``blob=None``) and re-pull whatever shards live
owners still hold — in a non-durable fleet the dead process's own
shard data is gone (the documented fleet crash model; durable placed
fleets are future work, see BatchedShardKV.load_state_dict's gid
guard).  A process declared dead must STAY dead: this module never
restarts processes, and a zombie that answers pings again after its
groups were re-placed keeps answering ErrWrongGroup for them (its
placement view is version-gated forward by the next push).

Every decision emits a PLACE flight record (code=gid, a=src, b=dst,
c=version, tag=reason) and ``place.*`` tracer spans sharing the stage
vocabulary (``scripts/trace_summary.py --placements`` renders them).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..services.shardctrler import rebalance_weighted
from ..utils.knobs import knob_bool, knob_float, knob_int
from ..transport import codec

__all__ = [
    "PlaceArgs",
    "PlaceReply",
    "PlacementCtrler",
    "PlacementClerk",
    "LocalPlacementStore",
    "TcpFleetTransport",
    "PlacementController",
    "plan_moves",
    "place_knobs",
]

OK = "OK"
ERR_WRONG_LEADER = "ErrWrongLeader"
ERR_TIMEOUT = "ErrTimeout"

QUERY = "Query"
SET = "Set"
BEGIN = "Begin"
DISPATCH = "Dispatch"
COMMIT = "Commit"
ABORT = "Abort"
# Replica-replacement (reconfig) intents — same two-phase discipline as
# migrations, but phased: a controller that dies between legs is
# resumed by its successor AT the recorded phase, which is what keeps
# add-learner / begin-joint retries idempotent instead of forking the
# group's membership.
RBEGIN = "RcfgBegin"
RPHASE = "RcfgPhase"
RDONE = "RcfgDone"
RABORT = "RcfgAbort"

# Bounded decision history kept in the replicated state (enough for
# the doctor's thrash window without growing the snapshot unboundedly).
HISTORY_CAP = 256


def place_knobs() -> Dict[str, float]:
    """The MRT_PLACE_* knob set, env-resolved (docs in module header)."""
    return {
        "scrape_s": knob_float("MRT_PLACE_SCRAPE_S"),
        "dead_s": knob_float("MRT_PLACE_DEAD_S"),
        "cooldown_s": knob_float("MRT_PLACE_COOLDOWN_S"),
        "min_gain": knob_float("MRT_PLACE_MIN_GAIN"),
        "max_moves": knob_int("MRT_PLACE_MAX_MOVES"),
        "replace": knob_bool("MRT_PLACE_REPLACE"),
        "replace_deadline_s": knob_float("MRT_PLACE_REPLACE_DEADLINE_S"),
    }


# ---------------------------------------------------------------------------
# The replicated placement map (ShardCtrler pattern)
# ---------------------------------------------------------------------------


@codec.registered
@dataclasses.dataclass
class PlaceArgs:
    """Unified op args (mirrors CtrlerArgs)."""

    op: str = QUERY
    placement: Dict[int, int] = dataclasses.field(default_factory=dict)
    gid: int = 0
    dst: int = 0
    reason: str = ""
    # Reconfig-intent fields (RBEGIN/RPHASE): the dead voter being
    # replaced, the spare slot replacing it, and the recorded phase.
    peer: int = -1
    new_peer: int = -1
    phase: str = ""
    client_id: int = 0
    command_id: int = 0


@codec.registered
@dataclasses.dataclass
class PlaceReply:
    err: str = OK
    version: int = 0
    placement: Dict[int, int] = dataclasses.field(default_factory=dict)
    # gid → (dst, reason, dispatched): ``dispatched`` flips once an
    # adopt RPC may have been sent — from then on the source may only
    # be force-unsealed with the destination provably dead.
    pending: Dict[int, Tuple[int, str, bool]] = dataclasses.field(
        default_factory=dict
    )
    # Recent committed moves: (version, gid, src, dst, reason).
    history: List[Tuple[int, int, int, int, str]] = dataclasses.field(
        default_factory=list
    )
    # gid → (dead_peer, new_peer, phase): in-flight replica-replacement
    # reconfigs, resumable by a successor controller at the phase.
    reconfigs: Dict[int, Tuple[int, int, str]] = dataclasses.field(
        default_factory=dict
    )


class PlacementCtrler:
    """Placement-map RSM server (RPC surface ``Placement.command``) —
    the :class:`~multiraft_tpu.services.shardctrler.ShardCtrler`
    skeleton verbatim: dedup by (client_id, command_id), waiters keyed
    on log index, snapshot/restore through the codec.

    State machine ops:

    * ``Set``    — install a whole map (fleet bootstrap), bumps version;
    * ``Begin``    — record migration INTENT ``gid → dst`` (no version
      bump: the map still answers the old owner until commit);
    * ``Dispatch`` — mark a begun intent's adopt RPC as (possibly)
      sent; a successor controller reading the intent knows it may no
      longer plain-unseal the source;
    * ``Commit``   — apply a begun intent: version += 1, map updated,
      decision appended to the bounded history;
    * ``Abort``    — drop an intent (destination died before adoption);
    * ``Query``    — read version, map, pending intents, history.
    """

    def __init__(
        self,
        sched,
        ends,
        me: int,
        persister,
        maxraftstate: int = -1,
        seed: int = 0,
    ) -> None:
        from ..raft.node import RaftNode
        from ..sim.scheduler import Future
        from ..utils.config import settings as _settings

        self.sched = sched
        self.me = me
        self.maxraftstate = maxraftstate
        self._snapshot_threshold = _settings().service.snapshot_threshold
        self._server_wait = _settings().service.server_wait
        self.version = 0
        self.placement: Dict[int, int] = {}
        self.pending: Dict[int, Tuple[int, str]] = {}
        self.reconfigs: Dict[int, Tuple[int, int, str]] = {}
        self.history: List[Tuple[int, int, int, int, str]] = []
        self.latest: Dict[int, int] = {}
        self._waiters: Dict[tuple, Future] = {}
        self._Future = Future
        self._killed = False
        self.rf = RaftNode(sched, ends, me, persister, self._on_apply,
                           seed=seed)
        self._install_snapshot(persister.read_snapshot())

    # -- RPC ------------------------------------------------------------

    def command(self, args: PlaceArgs):
        from ..sim.scheduler import TIMEOUT

        if self._killed:
            return PlaceReply(err=ERR_WRONG_LEADER)
        if (
            args.op != QUERY
            and self.latest.get(args.client_id, -1) >= args.command_id
        ):
            return self._reply()
        index, term, is_leader = self.rf.start(args)
        if not is_leader:
            return PlaceReply(err=ERR_WRONG_LEADER)
        fut = self._Future()
        key = (args.client_id, args.command_id, index)
        self._waiters[key] = fut
        result = yield self.sched.with_timeout(fut, self._server_wait)
        self._waiters.pop(key, None)
        if result is TIMEOUT:
            return PlaceReply(err=ERR_TIMEOUT)
        return result

    def _reply(self) -> PlaceReply:
        return PlaceReply(
            err=OK,
            version=self.version,
            placement=dict(self.placement),
            pending=dict(self.pending),
            history=list(self.history),
            reconfigs=dict(self.reconfigs),
        )

    # -- apply ----------------------------------------------------------

    def _on_apply(self, msg) -> None:
        if self._killed:
            return
        if msg.snapshot_valid:
            self._install_snapshot(msg.snapshot)
            return
        if not msg.command_valid:
            return
        args: PlaceArgs = msg.command
        is_dup = self.latest.get(args.client_id, -1) >= args.command_id
        if args.op != QUERY and not is_dup:
            if args.op == SET:
                self.placement = {
                    int(g): int(p) for g, p in args.placement.items()
                }
                self.version += 1
            elif args.op == BEGIN:
                self.pending[args.gid] = (args.dst, args.reason, False)
            elif args.op == DISPATCH:
                intent = self.pending.get(args.gid)
                if intent is not None:
                    self.pending[args.gid] = (
                        intent[0], intent[1], True
                    )
            elif args.op == COMMIT:
                intent = self.pending.pop(args.gid, None)
                if intent is not None:
                    dst, reason = intent[0], intent[1]
                    src = self.placement.get(args.gid, -1)
                    self.version += 1
                    self.placement[args.gid] = dst
                    self.history.append(
                        (self.version, args.gid, src, dst, reason)
                    )
                    del self.history[:-HISTORY_CAP]
            elif args.op == ABORT:
                self.pending.pop(args.gid, None)
            elif args.op == RBEGIN:
                self.reconfigs[args.gid] = (
                    int(args.peer), int(args.new_peer), "learner"
                )
            elif args.op == RPHASE:
                intent = self.reconfigs.get(args.gid)
                if intent is not None:
                    self.reconfigs[args.gid] = (
                        intent[0], intent[1], args.phase
                    )
            elif args.op == RDONE:
                intent = self.reconfigs.pop(args.gid, None)
                if intent is not None:
                    # No version bump — the gid→proc map is unchanged;
                    # the decision still lands in the bounded history.
                    self.history.append((
                        self.version, args.gid, intent[0], intent[1],
                        "replace-replica",
                    ))
                    del self.history[:-HISTORY_CAP]
            elif args.op == RABORT:
                self.reconfigs.pop(args.gid, None)
        if not is_dup:
            self.latest[args.client_id] = args.command_id
        waiter = self._waiters.get(
            (args.client_id, args.command_id, msg.command_index)
        )
        if waiter is not None:
            term, is_leader = self.rf.get_state()
            if is_leader and term == msg.command_term:
                waiter.resolve(self._reply())
        self._maybe_snapshot(msg.command_index)

    # -- snapshots -------------------------------------------------------

    def _maybe_snapshot(self, index: int) -> None:
        if self.maxraftstate < 0:
            return
        if self.rf.raft_state_size() >= (
            self._snapshot_threshold * self.maxraftstate
        ):
            blob = codec.encode({
                "version": self.version,
                "placement": dict(self.placement),
                "pending": dict(self.pending),
                "reconfigs": dict(self.reconfigs),
                "history": list(self.history),
                "latest": dict(self.latest),
            })
            self.rf.snapshot(index, blob)

    def _install_snapshot(self, data: bytes) -> None:
        if not data:
            return
        blob = codec.decode(data)
        self.version = blob["version"]
        self.placement = dict(blob["placement"])
        # Snapshots from before dispatch tracking hold 2-tuples:
        # normalize to (dst, reason, dispatched=False).
        self.pending = {
            int(g): (v[0], v[1],
                     bool(v[2]) if len(v) > 2 else False)
            for g, v in blob["pending"].items()
        }
        # Snapshots from before replica replacement hold no reconfigs.
        self.reconfigs = {
            int(g): (int(v[0]), int(v[1]), str(v[2]))
            for g, v in (blob.get("reconfigs") or {}).items()
        }
        self.history = list(blob["history"])
        self.latest = dict(blob["latest"])

    def kill(self) -> None:
        self._killed = True
        self.rf.kill()


class PlacementClerk:
    """Sim-side clerk of the placement RSM (CtrlerClerk pattern:
    leader-cycling retries, nonce-qualified client id)."""

    _next_client_id = 1 << 21  # distinct from CtrlerClerk's range

    def __init__(self, sched, ends) -> None:
        from ..utils.ids import unique_client_id

        self.sched = sched
        self.ends = ends
        self.leader = 0
        PlacementClerk._next_client_id += 1
        self.client_id = unique_client_id(PlacementClerk._next_client_id)
        self.command_id = 0

    def _command(self, args: PlaceArgs):
        from ..sim.scheduler import TIMEOUT

        args.client_id = self.client_id
        self.command_id += 1
        args.command_id = self.command_id
        while True:
            fut = self.ends[self.leader].call("Placement.command", args)
            reply = yield self.sched.with_timeout(fut, 0.1)
            if (
                reply is TIMEOUT
                or reply is None
                or reply.err in (ERR_WRONG_LEADER, ERR_TIMEOUT)
            ):
                self.leader = (self.leader + 1) % len(self.ends)
                continue
            return reply

    def query(self):
        return (yield from self._command(PlaceArgs(op=QUERY)))

    def set_map(self, placement: Dict[int, int]):
        return (yield from self._command(
            PlaceArgs(op=SET, placement=dict(placement))
        ))

    def begin(self, gid: int, dst: int, reason: str):
        return (yield from self._command(
            PlaceArgs(op=BEGIN, gid=gid, dst=dst, reason=reason)
        ))

    def dispatch(self, gid: int):
        return (yield from self._command(
            PlaceArgs(op=DISPATCH, gid=gid)
        ))

    def commit(self, gid: int):
        return (yield from self._command(PlaceArgs(op=COMMIT, gid=gid)))

    def abort(self, gid: int):
        return (yield from self._command(PlaceArgs(op=ABORT, gid=gid)))

    def rbegin(self, gid: int, dead_peer: int, new_peer: int):
        return (yield from self._command(PlaceArgs(
            op=RBEGIN, gid=gid, peer=dead_peer, new_peer=new_peer,
        )))

    def rphase(self, gid: int, phase: str):
        return (yield from self._command(
            PlaceArgs(op=RPHASE, gid=gid, phase=phase)
        ))

    def rdone(self, gid: int):
        return (yield from self._command(PlaceArgs(op=RDONE, gid=gid)))

    def rabort(self, gid: int):
        return (yield from self._command(PlaceArgs(op=RABORT, gid=gid)))


class LocalPlacementStore:
    """Dict-backed stand-in for the replicated map — unit tests of the
    controller loop that don't need RSM fault tolerance.  Same verbs
    as the blocking RSM facade (harness/fleet.py)."""

    def __init__(self, placement: Optional[Dict[int, int]] = None) -> None:
        self.version = 1 if placement else 0
        self.placement = dict(placement or {})
        self.pending: Dict[int, Tuple[int, str, bool]] = {}
        self.reconfigs: Dict[int, Tuple[int, int, str]] = {}
        self.history: List[Tuple[int, int, int, int, str]] = []

    def query(self):
        return (
            self.version, dict(self.placement), dict(self.pending),
            list(self.history),
        )

    def set_map(self, placement: Dict[int, int]) -> int:
        self.placement = dict(placement)
        self.version += 1
        return self.version

    def begin(self, gid: int, dst: int, reason: str) -> None:
        self.pending[gid] = (dst, reason, False)

    def dispatch(self, gid: int) -> None:
        intent = self.pending.get(gid)
        if intent is not None:
            self.pending[gid] = (intent[0], intent[1], True)

    def commit(self, gid: int) -> int:
        intent = self.pending.pop(gid)
        dst, reason = intent[0], intent[1]
        src = self.placement.get(gid, -1)
        self.version += 1
        self.placement[gid] = dst
        self.history.append((self.version, gid, src, dst, reason))
        del self.history[:-HISTORY_CAP]
        return self.version

    def abort(self, gid: int) -> None:
        self.pending.pop(gid, None)

    # -- reconfig intents -----------------------------------------------

    def reconfig_intents(self) -> Dict[int, Tuple[int, int, str]]:
        return dict(self.reconfigs)

    def rbegin(self, gid: int, dead_peer: int, new_peer: int) -> None:
        self.reconfigs[gid] = (int(dead_peer), int(new_peer), "learner")

    def rphase(self, gid: int, phase: str) -> None:
        intent = self.reconfigs.get(gid)
        if intent is not None:
            self.reconfigs[gid] = (intent[0], intent[1], phase)

    def rdone(self, gid: int) -> None:
        intent = self.reconfigs.pop(gid, None)
        if intent is not None:
            self.history.append((
                self.version, gid, intent[0], intent[1],
                "replace-replica",
            ))
            del self.history[:-HISTORY_CAP]

    def rabort(self, gid: int) -> None:
        self.reconfigs.pop(gid, None)


# ---------------------------------------------------------------------------
# The pure planner
# ---------------------------------------------------------------------------


def plan_moves(
    placement: Dict[int, int],
    loads: Dict[int, float],
    alive: List[int],
    *,
    min_gain: float = 0.25,
    cooldown_s: float = 5.0,
    last_moved: Optional[Dict[int, float]] = None,
    now_s: float = 0.0,
    max_moves: int = 1,
    exclude: Optional[set] = None,
) -> List[Tuple[int, Optional[int], int, str]]:
    """Decide this round's migrations.  Returns
    ``[(gid, src_or_None, dst, reason), ...]`` — ``src None`` means the
    source process is dead (adopt empty).

    Policy, in order:

    1. **Failover first, unconditionally**: every group placed on a
       process not in ``alive`` is re-placed (weighted orphan
       assignment).  No hysteresis, no cooldown, no cap — a dark group
       serves nobody.
    2. **Hysteresis**: voluntary rebalance moves happen only if the
       planned assignment reduces the per-process load spread
       (max − min) by at least ``min_gain`` of the current spread.
    3. **Cooldown**: a group moved within ``cooldown_s`` stays put.
    4. **Cap**: at most ``max_moves`` voluntary moves per round —
       bounded concurrent migrations, by construction.

    ``exclude`` gids (migrations already in flight) are pinned where
    they are and planned around."""
    last_moved = last_moved or {}
    exclude = exclude or set()
    alive = sorted(set(alive))
    if not alive or not placement:
        return []
    # Weights: scraped commit rates; a group with no signal yet gets a
    # tiny epsilon so orphan assignment still spreads them out.
    eps = 1e-6
    weights = {g: max(loads.get(g, 0.0), eps) for g in placement}

    assign = {
        g: (p if p in set(alive) else None) for g, p in placement.items()
    }
    movable = {
        g: a for g, a in assign.items() if g not in exclude
    }
    pinned = {g: a for g, a in assign.items() if g in exclude}

    target, raw_moves = rebalance_weighted(movable, weights, alive)

    def spread(a: Dict[int, Optional[int]]) -> float:
        load = {p: 0.0 for p in alive}
        for g, p in a.items():
            if p in load:
                load[p] += weights[g]
        return max(load.values()) - min(load.values())

    failover = []
    voluntary = []
    for gid, src, dst in raw_moves:
        if src is None or src not in set(alive):
            failover.append((gid, None, dst, "failover"))
        else:
            voluntary.append((gid, src, dst, "rebalance"))

    # Hysteresis: judge the voluntary portion of the plan by the spread
    # it would actually achieve (failovers happen regardless).
    if voluntary:
        before = dict(assign)
        for gid, _, dst, _ in failover:
            before[gid] = dst  # failovers land either way
        after = dict(before)
        for gid, _, dst, _ in voluntary:
            after[gid] = dst
        s0, s1 = spread(before), spread(after)
        if s0 <= 0 or (s0 - s1) < min_gain * s0:
            voluntary = []

    # Cooldown + cap on the voluntary moves only.
    voluntary = [
        m for m in voluntary
        if now_s - last_moved.get(m[0], -1e18) >= cooldown_s
    ][:max(0, int(max_moves))]
    # Pinned gids stay pinned (sanity: planner never touches them).
    assert not any(m[0] in pinned for m in failover + voluntary)
    return failover + voluntary


# ---------------------------------------------------------------------------
# Transport (real sockets) + the controller loop
# ---------------------------------------------------------------------------


class TcpFleetTransport:
    """The controller's view of the fleet over real sockets: one
    client end per mesh process, Obs scrapes + group-migration RPCs.
    All calls are synchronous (``sched.wait`` from the controller
    thread) and timeout-bounded."""

    PING_S = 1.0
    SCRAPE_S = 2.0
    MIGRATE_S = 15.0
    PUSH_S = 5.0

    def __init__(self, node, addrs: List[Tuple[str, int]]) -> None:
        self.node = node
        self.sched = node.sched
        self.addrs = [(h, int(p)) for h, p in addrs]
        self._ends = [node.client_end(h, p) for h, p in self.addrs]

    @property
    def n_procs(self) -> int:
        return len(self.addrs)

    def addr(self, proc: int) -> Tuple[str, int]:
        return self.addrs[proc]

    def _call(self, proc: int, meth: str, args: Any, timeout: float):
        from ..sim.scheduler import TIMEOUT

        reply = self.sched.wait(self._ends[proc].call(meth, args), timeout)
        return None if reply is TIMEOUT else reply

    def ping(self, proc: int) -> bool:
        return self._call(proc, "Obs.ping", None, self.PING_S) == "pong"

    def groups(self, proc: int) -> Optional[Dict[str, Any]]:
        r = self._call(proc, "Obs.groups", None, self.SCRAPE_S)
        return r if isinstance(r, dict) else None

    def pull_group(self, proc: int, gid: int):
        r = self._call(
            proc, "EngineShardKV.pull_group", (gid,), self.MIGRATE_S
        )
        if isinstance(r, tuple) and r and r[0] == OK:
            return r[1]
        return None

    def unseal_group(self, proc: int, gid: int,
                     force: bool = False) -> None:
        self._call(
            proc, "EngineShardKV.unseal_group", (gid, force), self.PUSH_S
        )

    def standby_state(self, proc: int, gid: int) -> Optional[Dict]:
        """Freshness of ``proc``'s shipped standby state for ``gid``
        (stateplane.StandbyStore.freshness), None if it holds none."""
        r = self._call(
            proc, "EngineShardKV.standby_state", (gid,), self.SCRAPE_S
        )
        return r if isinstance(r, dict) else None

    def recover_group(self, proc: int, gid: int) -> Optional[str]:
        """Ask ``proc`` to adopt ``gid`` from its OWN standby store
        (snapshot fast-forward + exactly-once tail replay).  Returns
        ``"recovered"``, ``"empty"`` (no shipped state there — caller
        falls back to empty adoption), or None on RPC failure."""
        r = self._call(
            proc, "EngineShardKV.recover_group", (gid,), self.MIGRATE_S
        )
        if isinstance(r, tuple) and len(r) >= 2 and r[0] == OK:
            return r[1]
        return None

    def adopt_group(self, proc: int, gid: int, blob) -> bool:
        r = self._call(
            proc, "EngineShardKV.adopt_group", (gid, blob), self.MIGRATE_S
        )
        return isinstance(r, tuple) and bool(r) and r[0] == OK

    def drop_group(self, proc: int, gid: int) -> bool:
        r = self._call(
            proc, "EngineShardKV.drop_group", (gid,), self.MIGRATE_S
        )
        return isinstance(r, tuple) and bool(r) and r[0] == OK

    def push_placement(
        self, proc: int, version: int, addr_map: Dict[int, Tuple[str, int]]
    ) -> bool:
        r = self._call(
            proc, "EngineShardKV.place", (version, addr_map), self.PUSH_S
        )
        return isinstance(r, tuple) and bool(r) and r[0] == OK

    # -- membership-change verbs (self-healing replica sets) ------------

    def replica_config(self, proc: int, gid: int) -> Optional[Dict]:
        """Leader's config view for ``gid`` (voter sets, joint flag,
        epoch) or None when leaderless / RPC failure."""
        r = self._call(
            proc, "EngineShardKV.replica_config", (gid,), self.SCRAPE_S
        )
        if isinstance(r, tuple) and len(r) >= 2 and r[0] == OK:
            return r[1]
        return None

    def add_learner(self, proc: int, gid: int, peer: int) -> bool:
        r = self._call(
            proc, "EngineShardKV.add_learner", (gid, peer), self.PUSH_S
        )
        return (isinstance(r, tuple) and len(r) >= 2 and r[0] == OK
                and bool(r[1]))

    def learner_match(self, proc: int, gid: int,
                      peer: int) -> Optional[Tuple[int, int]]:
        r = self._call(
            proc, "EngineShardKV.learner_match", (gid, peer), self.SCRAPE_S
        )
        if isinstance(r, tuple) and len(r) >= 2 and r[0] == OK:
            return r[1]
        return None

    def begin_joint(self, proc: int, gid: int, voters) -> bool:
        r = self._call(
            proc, "EngineShardKV.begin_joint", (gid, list(voters)),
            self.PUSH_S,
        )
        return (isinstance(r, tuple) and len(r) >= 2 and r[0] == OK
                and bool(r[1]))

    def kill_replica(self, proc: int, gid: int, peer: int) -> bool:
        r = self._call(
            proc, "EngineShardKV.kill_replica", (gid, peer), self.PUSH_S
        )
        return isinstance(r, tuple) and bool(r) and r[0] == OK


class PlacementController:
    """The scrape → plan → migrate loop (module docstring).  ``store``
    is the replicated map facade (``query/set_map/begin/commit/abort``
    — harness/fleet.py's blocking RSM clerk, or
    :class:`LocalPlacementStore` in unit tests); ``transport`` the
    fleet view (:class:`TcpFleetTransport` or an in-process fake)."""

    def __init__(
        self,
        transport,
        store,
        *,
        scrape_s: Optional[float] = None,
        dead_s: Optional[float] = None,
        cooldown_s: Optional[float] = None,
        min_gain: Optional[float] = None,
        max_moves: Optional[int] = None,
        replace: Optional[bool] = None,
        replace_deadline_s: Optional[float] = None,
        obs=None,
        recorder=None,
        clock=time.monotonic,
    ) -> None:
        k = place_knobs()
        self.transport = transport
        self.store = store
        self.scrape_s = k["scrape_s"] if scrape_s is None else scrape_s
        self.dead_s = k["dead_s"] if dead_s is None else dead_s
        self.cooldown_s = (
            k["cooldown_s"] if cooldown_s is None else cooldown_s
        )
        self.min_gain = k["min_gain"] if min_gain is None else min_gain
        self.max_moves = (
            k["max_moves"] if max_moves is None else int(max_moves)
        )
        self.replace = (
            bool(k["replace"]) if replace is None else bool(replace)
        )
        self.replace_deadline_s = (
            k["replace_deadline_s"] if replace_deadline_s is None
            else float(replace_deadline_s)
        )
        self._clock = clock
        self._obs = obs
        if recorder is None:
            from .flightrec import get_recorder

            recorder = get_recorder("placer")
        self._rec = recorder
        t0 = clock()
        self.last_pong = {p: t0 for p in range(transport.n_procs)}
        self.last_moved: Dict[int, float] = {}
        self.loads: Dict[int, float] = {}
        self.dead: set = set()
        # gid -> latest per-replica view scraped from Obs.groups
        # (proc, alive[], voters[], joint, sealed)
        self._replica_view: Dict[int, Dict[str, Any]] = {}
        # (gid, peer) -> clock when the replica was first seen dead
        self._replica_dead_since: Dict[Tuple[int, int], float] = {}
        # gid -> clock when the heal intent was begun (this controller;
        # a crash-resume successor drives the intent but skips stats)
        self._reconfig_t0: Dict[int, float] = {}
        # gid -> timing of the last COMPLETED replacement (benches)
        self.replace_stats: Dict[int, Dict[str, float]] = {}
        self.rounds = 0
        self.moves_done = 0
        self._pushed_version = -1
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        assert self._thread is None
        self._thread = threading.Thread(
            target=self._loop, name="placement-controller", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception:
                # The controller must outlive transient scrape/RPC
                # failures — the fleet heals by retrying, not by the
                # healer dying.
                if self._obs is not None:
                    self._obs.metrics.inc("place.step_errors")
            self._stop.wait(self.scrape_s)

    # -- observability helpers ------------------------------------------

    def _record(self, gid: int, src: Optional[int], dst: int,
                version: int, reason: str) -> None:
        if self._rec is not None:
            from .flightrec import PLACE

            self._rec.record(
                PLACE, code=gid, a=-1 if src is None else src, b=dst,
                c=version, tag=reason,
            )
        if self._obs is not None:
            self._obs.metrics.inc("place.moves")
            self._obs.metrics.inc(f"place.moves_{reason}")

    # -- one controller round -------------------------------------------

    def scrape(self) -> None:
        """Liveness + load: ping every process; fold per-gid commit
        rates from ``Obs.groups`` of the live ones."""
        now = self._clock()
        for p in range(self.transport.n_procs):
            if p in self.dead:
                continue  # declared dead: stays dead (module docstring)
            if not self.transport.ping(p):
                continue
            self.last_pong[p] = now
            g = self.transport.groups(p)
            if not g or "gids" not in g:
                continue
            rates = g.get("commit_rate") or [0.0] * g["G"]
            r_alive = g.get("replica_alive") or []
            voters = g.get("voters") or []
            joint = g.get("joint") or []
            sealed = g.get("sealed") or []
            for slot, gid in enumerate(g["gids"]):
                if gid <= 0:
                    continue
                self.loads[gid] = float(rates[slot])
                if slot < len(r_alive) and slot < len(voters):
                    self._replica_view[gid] = {
                        "proc": p,
                        "alive": list(r_alive[slot]),
                        "voters": list(voters[slot]),
                        "joint": bool(joint[slot])
                        if slot < len(joint) else False,
                        "sealed": bool(sealed[slot])
                        if slot < len(sealed) else False,
                    }
        # Per-REPLICA death ledger (distinct from per-PROCESS self.dead:
        # here the process serving the group is fine, one engine replica
        # row inside it is permanently down).  First-seen-dead timestamps
        # feed the replace-dead-replica policy (_heal_replicas).
        for gid, view in self._replica_view.items():
            for q, ok in enumerate(view["alive"]):
                if ok:
                    self._replica_dead_since.pop((gid, q), None)
                else:
                    self._replica_dead_since.setdefault((gid, q), now)
        self.dead |= {
            p for p in range(self.transport.n_procs)
            if now - self.last_pong[p] > self.dead_s
        }

    def step(self) -> int:
        """One scrape→plan→migrate round; returns moves executed."""
        self.rounds += 1
        self.scrape()
        now = self._clock()
        alive = [
            p for p in range(self.transport.n_procs) if p not in self.dead
        ]
        if not alive:
            return 0
        version, placement, pending, _ = self.store.query()
        if not placement:
            return 0  # not bootstrapped yet (harness seeds the map)
        executed = 0
        # Resume replicated intents first — a predecessor controller
        # may have died mid-migration (module docstring).
        for gid, intent in sorted(pending.items()):
            dst, reason = int(intent[0]), intent[1]
            src = placement.get(gid)
            if dst in self.dead:
                # Destination died before the group committed there.
                # The adopt may or may not have landed — either way that
                # copy is gone with the process, so unsealing the source
                # (if it lives) cannot fork the group.  force=True: the
                # engine refuses a plain unseal once the intent was
                # dispatched, and the dead destination is exactly the
                # proof that makes forcing safe.
                if src is not None and src in set(alive):
                    self.transport.unseal_group(src, gid, force=True)
                self.store.abort(gid)
                continue
            if self._execute(gid, src, dst, reason, alive):
                executed += 1
        # Self-healing replica sets: resume/begin joint-consensus
        # replacements of dead engine replicas before planning any
        # voluntary group moves — a group under reconfig must not also
        # be migrated mid-joint.
        executed += self._heal_replicas(alive)
        version, placement, pending, _ = self.store.query()
        reconfigs = self._reconfig_intents()
        moves = plan_moves(
            placement,
            self.loads,
            alive,
            min_gain=self.min_gain,
            cooldown_s=self.cooldown_s,
            last_moved=self.last_moved,
            now_s=now,
            max_moves=self.max_moves,
            exclude=set(pending) | set(reconfigs),
        )
        for gid, src, dst, reason in moves:
            if src is None and reason == "failover":
                # Stateful failover: re-target to the standby holding
                # the freshest shipped (snapshot, tail) pair BEFORE the
                # intent is begun, so the replicated intent records the
                # recovery destination.  No shipped state anywhere →
                # the planner's load-balanced pick stands (empty adopt).
                dst = self._freshest_dst(gid, alive, dst)
            self.store.begin(gid, dst, reason)
            if self._execute(gid, src, dst, reason, alive):
                executed += 1
        self._push(alive)
        return executed

    def _freshest_dst(self, gid: int, alive: List[int],
                      default: int) -> int:
        probe = getattr(self.transport, "standby_state", None)
        if probe is None:
            return default
        from .stateplane import pick_freshest

        states = []
        for p in alive:
            try:
                states.append((p, probe(p, gid)))
            except Exception:
                states.append((p, None))
        order = pick_freshest(states)
        return order[0] if order else default

    # -- replace-dead-replica policy (joint-consensus healing) ----------

    def _reconfig_intents(self) -> Dict[int, Tuple[int, int, str]]:
        fn = getattr(self.store, "reconfig_intents", None)
        if fn is None:
            return {}
        try:
            r = fn()
        except Exception:
            return {}
        return r if isinstance(r, dict) else {}

    def _config_record(self, gid: int, dead_p: int, new_p: int,
                       epoch: int, phase: str) -> None:
        if self._rec is not None:
            from .flightrec import CONFIG

            self._rec.record(
                CONFIG, code=gid, a=dead_p, b=new_p, c=epoch, tag=phase,
            )
        if self._obs is not None:
            metric = {
                "learner": "reconfig.begun",
                "joint": "reconfig.joint_entered",
                "done": "reconfig.completed",
                "abort": "reconfig.aborted",
            }.get(phase)
            if metric:
                self._obs.metrics.inc(metric)

    def _heal_replicas(self, alive: List[int]) -> int:
        """Replace dead engine replicas via joint consensus.  Every
        intent is a replicated two-phase record on the placement RSM
        (``rbegin``/``rphase``/``rdone``), and every leg is idempotent,
        so a controller crash mid-reconfig RESUMES at the recorded
        phase — it never forks membership.  Returns completed
        replacements this round."""
        if not self.replace:
            return 0
        if getattr(self.transport, "add_learner", None) is None:
            return 0  # transport predates membership verbs
        rbegin = getattr(self.store, "rbegin", None)
        if rbegin is None:
            return 0  # store predates reconfig intents
        now = self._clock()
        alive_set = set(alive)
        done = 0
        # 1. Resume replicated intents (ours or a dead predecessor's).
        for gid, intent in sorted(self._reconfig_intents().items()):
            view = self._replica_view.get(gid)
            if view is None or view["proc"] not in alive_set:
                continue  # group unreachable this round: retry later
            done += self._drive_reconfig(
                gid, view, int(intent[0]), int(intent[1]), str(intent[2]),
                now,
            )
        # 2. Begin new intents for voters dead past the grace period.
        intents = self._reconfig_intents()
        for (gid, q), t0 in sorted(self._replica_dead_since.items()):
            if gid in intents or now - t0 < self.dead_s:
                continue
            view = self._replica_view.get(gid)
            if view is None or view["proc"] not in alive_set:
                continue
            if view.get("sealed"):
                continue  # mid-migration: heal after the move settles
            if q not in view["voters"]:
                # Dead NON-voter (parked spare / demoted casualty):
                # nothing to heal — quorum does not depend on it.
                continue
            new_p = self._pick_spare(view, q)
            if new_p is None:
                if self._obs is not None:
                    self._obs.metrics.inc("reconfig.no_spare")
                continue
            self.store.rbegin(gid, q, new_p)
            self._reconfig_t0[gid] = t0
            self._config_record(gid, q, new_p, 0, "learner")
            done += self._drive_reconfig(
                gid, view, q, new_p, "learner", now
            )
            intents = self._reconfig_intents()
        return done

    def _pick_spare(self, view: Dict[str, Any],
                    dead_q: int) -> Optional[int]:
        """Lowest engine slot that is neither a voter nor the dead
        slot itself — the seat the new incarnation takes.  Voter sets
        are static-slot subsets, so replacement is always a SWAP into a
        spare row; no spare → no heal (reconfig.no_spare)."""
        voters = set(view["voters"])
        for q in range(len(view["alive"])):
            if q != dead_q and q not in voters:
                return q
        return None

    def _drive_reconfig(
        self, gid: int, view: Dict[str, Any], dead_p: int, new_p: int,
        phase: str, now: float,
    ) -> int:
        """Advance one replacement as far as this round allows:
        learner → catchup → joint → done.  Every leg re-checks engine
        state first, so re-running any prefix after a crash is a no-op
        (add_learner_gid answers True for a live learner, begin_joint
        for an already-entered or already-settled target)."""
        tr = self.transport
        store = self.store
        if phase == "learner":
            if not tr.add_learner(view["proc"], gid, new_p):
                return 0  # leaderless or slot still a voter: retry
            # The seated learner is a FRESH incarnation: any death
            # timestamp recorded for the (previously parked) slot
            # belongs to the old tenant, not this one.
            self._replica_dead_since.pop((gid, new_p), None)
            store.rphase(gid, "catchup")
            self._config_record(gid, dead_p, new_p, 0, "catchup")
            phase = "catchup"
        if phase == "catchup":
            # A learner that dies mid-catch-up can never close the gap:
            # abort and let the next round pick a different spare.
            t_dead = self._replica_dead_since.get((gid, new_p))
            if t_dead is not None and now - t_dead >= self.dead_s:
                store.rabort(gid)
                self._config_record(gid, dead_p, new_p, 0, "abort")
                self._reconfig_t0.pop(gid, None)
                return 0
            lm = tr.learner_match(view["proc"], gid, new_p)
            if lm is None:
                return 0
            match, last = int(lm[0]), int(lm[1])
            if match < last:
                return 0  # still catching up: promote next round
            cfg = tr.replica_config(view["proc"], gid)
            if cfg is None:
                return 0
            target = sorted(
                (set(cfg["voters_old"]) - {dead_p}) | {new_p}
            )
            if not tr.begin_joint(view["proc"], gid, target):
                return 0
            store.rphase(gid, "joint")
            self._config_record(
                gid, dead_p, new_p, int(cfg["epoch"]) + 1, "joint"
            )
            phase = "joint"
        if phase == "joint":
            cfg = tr.replica_config(view["proc"], gid)
            if cfg is None:
                return 0
            if cfg["joint"]:
                return 0  # both quorums still settling: engine exits
            if dead_p in cfg["voters_old"]:
                # Not joint AND the dead peer still votes: the leader
                # died after the intent recorded "joint" but before the
                # C_old,new entry replicated — the entry is LOST, not
                # pending.  Re-issue it (begin_joint is idempotent
                # against the already-settled target).
                target = sorted(
                    (set(cfg["voters_old"]) - {dead_p}) | {new_p}
                )
                tr.begin_joint(view["proc"], gid, target)
                return 0
            store.rdone(gid)
            self._config_record(
                gid, dead_p, new_p, int(cfg["epoch"]), "done"
            )
            self._replica_dead_since.pop((gid, dead_p), None)
            t0 = self._reconfig_t0.pop(gid, None)
            if t0 is not None:
                self.replace_stats[gid] = {
                    "replace_replica_s": max(0.0, now - (t0 + self.dead_s)),
                    "degraded_quorum_window_s": now - t0,
                }
            return 1
        return 0

    def _execute(
        self, gid: int, src: Optional[int], dst: int, reason: str,
        alive: List[int],
    ) -> bool:
        """Run one begun migration end-to-end.  Every leg is idempotent
        (seal/export returns the same frozen blob, adopt/drop answer OK
        on retry), so a False return simply leaves the intent pending
        for the next round."""
        from .observe import now_us

        rid = f"mig-{gid}-{self.rounds}"
        t_all = now_us()
        src_live = src is not None and src in set(alive)
        blob = None
        recovered = False
        if src_live:
            t0 = now_us()
            blob = self.transport.pull_group(src, gid)
            self._trace_span("place.pull", t0, rid, gid)
            if blob is None:
                return False  # source not sealable yet: retry next round
        # Mark the intent dispatched BEFORE any adopt/recover RPC can
        # fly: a successor controller reading the replicated intent
        # then knows a plain unseal of the source is no longer safe.
        disp = getattr(self.store, "dispatch", None)
        if disp is not None:
            disp(gid)
        if not src_live:
            # Dead source: durable recovery first — the destination
            # adopts from its own standby store (snapshot+tail replay).
            recover = getattr(self.transport, "recover_group", None)
            if recover is not None:
                t0 = now_us()
                r = recover(dst, gid)
                self._trace_span("place.recover", t0, rid, gid)
                if r == "recovered":
                    recovered = True
                    if self._obs is not None:
                        self._obs.metrics.inc("place.recoveries")
                elif r is None:
                    return False  # transient RPC failure: retry round
                # r == "empty": no shipped state at dst — fall through
                # to the explicit empty-adoption fallback below.
        if not recovered:
            t0 = now_us()
            adopted = self.transport.adopt_group(dst, gid, blob)
            self._trace_span("place.adopt", t0, rid, gid)
            if not adopted:
                # The adopt RPC may have landed despite the lost reply —
                # NEVER unseal the source now.  The intent stays pending
                # and the next round retries the (idempotent) adopt.
                return False
        reply_version = self.store.commit(gid)
        version = (
            reply_version if isinstance(reply_version, int)
            else self.store.query()[0]
        )
        if src_live:
            t0 = now_us()
            self.transport.drop_group(src, gid)  # idempotent; best effort
            self._trace_span("place.drop", t0, rid, gid)
        self.last_moved[gid] = self._clock()
        self.moves_done += 1
        self._record(gid, src if src_live else None, dst, version, reason)
        self._trace_span("place.total", t_all, rid, gid)
        if self._obs is not None:
            self._obs.tracer.instant(
                "place", now_us(), track="place", req=rid, group=gid,
                src=-1 if not src_live else src, dst=dst, reason=reason,
            )
        return True

    def _trace_span(self, name: str, t0_us: float, rid: str,
                    gid: int) -> None:
        if self._obs is None:
            return
        from .observe import now_us

        self._obs.tracer.span(
            name, t0_us, now_us() - t0_us, track="place", req=rid,
            group=gid,
        )

    def _push(self, alive: List[int]) -> None:
        """Push the committed placement view to every live process so
        servers re-derive peer maps and clerks can re-route.  Repushed
        after membership changes even when the version didn't move —
        a process that missed the last push needs it."""
        version, placement, _, _ = self.store.query()
        if version <= 0:
            return
        addr_map = {
            g: self.transport.addr(p) for g, p in placement.items()
        }
        for p in alive:
            self.transport.push_placement(p, version, addr_map)
        self._pushed_version = version
