"""The batched engine served over the real network — one process owns
the chip; clerk RPCs come in over TCP and are coalesced into engine
ticks (the first step of SURVEY §2.2's sidecar story: "clients talk to
a thin RPC front; commands coalesce into the device firehose").

Architecture (vs the per-replica sim/process stack in ``cluster.py``):

* ``EngineKVService`` wraps a :class:`BatchedKV` on an
  :class:`EngineDriver`.  A pump timer on the process's
  ``RealtimeScheduler`` advances the device tick loop every couple of
  milliseconds; every RPC that arrived since the last pump has already
  queued its command into the per-group backlog, so one device step
  carries *all* concurrent client traffic — the batching that makes a
  single chip serve thousands of groups.
* Writes ride the log with kvraft session dedup (``KVOp.client_id`` /
  ``command_id``) so the at-least-once transport (client retries on
  timeout) stays exactly-once.  Reads use the ReadIndex fast path
  (zero device work, linearizable at the applied frontier).
* ``EngineShardKVService`` is the sharded form: a
  :class:`BatchedShardKV` behind the same front door, with server-side
  key→shard routing against its replicated config and the clerk retry
  semantics of the reference (ErrWrongGroup → re-route).

Wire protocol: ``EngineKV.command`` / ``EngineShardKV.command`` over
:class:`~multiraft_tpu.distributed.tcp.RpcNode` frames.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Optional, Sequence

from ..engine.core import EngineConfig
from ..engine.firehose import MAX_FIREHOSE_ROWS
from ..engine.host import EngineDriver
from ..engine.kv import BatchedKV, KVOp
from ..porcupine.kv import OP_GET
from .engine_durability import (
    EngineDurability,
    await_frame_synced,
    demote_unsynced_rows,
    replay_kv_wal,
)
from . import flightrec
from .engine_wire import (
    _OPCODE,
    _OPNAME,
    ERR_TIMEOUT,
    OK,
    EngineCmdArgs,
    EngineCmdReply,
    make_mesh,
    route_group,
)
from ..utils.knobs import knob_bool, knob_float, knob_int
from .admission import install_admission
from .engine_pump import PUMP_THREAD_PREFIX, EnginePump, LoopOccupancy
from .overload import install_overload_watch
from .wedge import install_wedge_watch
from .realtime import (
    PumpCadence,
    RealtimeScheduler,
    service_busy,
)
from .tcp import RpcNode

__all__ = [
    "EngineCmdArgs",
    "EngineCmdReply",
    "EngineKVService",
    "EngineShardKVService",
    "EngineClerk",
    "FirehoseClerk",
    "ShardFirehoseClerk",
    "PipelinedClerk",
    "PipelinedFleetClerk",
    "EngineShardNetClerk",
    "EngineFleetClerk",
    "serve_engine_kv",
    "serve_engine_shardkv",
]


class EngineKVService:
    """``EngineKV.command`` RPC front for a :class:`BatchedKV`.

    All device work happens on the scheduler loop: the pump timer and
    the RPC handlers interleave there, so commands queued by handlers
    between pumps coalesce into the next device step."""

    # Handler-side patience before giving up on one submission and
    # resubmitting (dedup makes the duplicate harmless) — covers
    # tickets lost to leader changes.
    RESUBMIT_S = 0.25
    # Total per-RPC budget; the client retries after its own timeout.
    DEADLINE_S = 3.0

    def __init__(
        self,
        sched: RealtimeScheduler,
        kv: BatchedKV,
        pump_interval: float = 0.002,
        ticks_per_pump: int = 2,
        durability: Optional[EngineDurability] = None,
        obs=None,
    ) -> None:
        self.sched = sched
        self.kv = kv
        self.G = kv.driver.cfg.G
        self._cadence = PumpCadence(
            knob_float("MRT_PUMP_IDLE_S", default=pump_interval)
        )
        self._ticks = ticks_per_pump
        self._stopped = False
        self._dur = durability
        # The owning node's observability plane (tick/pump latency,
        # frame sizes, commit instants tagged with the caller's request
        # id).  Lazily defaulted via the `obs` property, so partially
        # constructed stubs (tests build handlers via __new__) work too.
        self._obs = obs
        # (client_id, command_id) -> WAL seq of the op's apply-time
        # record; handlers gate their ack on it being fsynced.  Pruned
        # once synced (absence = already durable).
        self._write_seqs: dict = {}
        # Black box: tick boundaries + consensus frontier transitions
        # land in the crash-surviving ring (flightrec.py).  The
        # frontier triple is only recorded when it CHANGES — a quiet
        # pump loop writes one TICK record per pump and nothing else.
        self._frec = flightrec.get_recorder()
        self._pumps = 0
        self._pump_t_dispatch = 0.0
        self._last_frontier = (-1, -1, -1)
        # Asynchronous engine pipeline (engine_pump.py): the loop
        # dispatches fused tick batches and completes them when the
        # dedicated pump thread has fetched the stacked metrics; the
        # legacy synchronous pump stays selectable per pump (kill
        # switch, mesh drivers, reorder chaos).  Durable servers pin
        # the depth to 1 so each checkpoint sees a drained pipeline
        # (EngineDriver.save refuses otherwise).
        self._pipe = None
        self._depth = 1
        self._pump_timer = None
        self._occ = LoopOccupancy(self.m)
        if knob_bool("MRT_ENGINE_PIPELINE"):
            loop_name = getattr(getattr(sched, "_thread", None), "name", "")
            suffix = (
                loop_name[len("multiraft-loop"):]
                if loop_name.startswith("multiraft-loop") else ""
            )
            self._pipe = EnginePump(sched, name=PUMP_THREAD_PREFIX + suffix)
            self._depth = (
                1 if durability is not None
                else max(1, knob_int("MRT_PIPELINE_DEPTH"))
            )
            pump_ticks = knob_int("MRT_PUMP_TICKS")
            if pump_ticks > 0:
                self._ticks = pump_ticks
        if durability is not None:
            # WAL at APPLY time (commit order): evict-and-resubmit can
            # commit ops in a different order than submission, and
            # replay must reproduce the order reads actually saw.
            kv.on_write = lambda g, op: self._write_seqs.__setitem__(
                (op.client_id, op.command_id),
                durability.log(("kv", _OPNAME[op.op], op.key, op.value,
                                op.client_id, op.command_id)),
            )
        if self._pipe is not None and kv.driver.fused_eligible():
            # Warm the fused n-tick program NOW, before the first
            # client byte: its first invocation pays the jit compile on
            # this (loop) thread, and paying it mid-serving stalls the
            # first rate step's tail (measured ~100 ms on the r04
            # sweep's opening step).  The backlog is empty at
            # construction, so this is two liveness ticks.
            self.kv.pump(self._ticks)
        sched.call_soon(self._pump_loop)

    @property
    def obs(self):
        o = getattr(self, "_obs", None)
        if o is None:
            from .observe import Observability

            o = self._obs = Observability()
        return o

    @property
    def m(self):
        return self.obs.metrics

    def stop(self) -> None:
        self._stopped = True
        pipe = getattr(self, "_pipe", None)
        if pipe is not None:
            pipe.stop()

    def final_checkpoint(self) -> bool:
        """Graceful-shutdown hook (CLI SIGTERM): fold everything into
        one last checkpoint so the next start skips WAL replay.  False
        when the server is not durable."""
        if self._dur is None:
            return False
        self._drain_pipeline()  # driver.save refuses in-flight batches
        self._dur.checkpoint()
        return True

    def _arm_pump(self, delay: float) -> None:
        """Single-timer discipline: exactly one pending _pump_loop
        timer, re-armed earlier when a completion says there is work."""
        t = self._pump_timer
        if t is not None:
            t.cancel()
        self._pump_timer = self.sched.call_after(delay, self._pump_loop)

    def _pump_loop(self) -> None:
        self._pump_timer = None
        if self._stopped:
            return
        d = self.kv.driver
        if self._pipe is not None and d.fused_eligible():
            # Pipelined path: dispatch a fused batch WITHOUT waiting —
            # the engine-pump thread blocks on the readback and posts
            # _pump_done back here.  The loop is free for wire work
            # while the device computes.
            if len(d._inflight) < self._depth:
                # Push queued replies first (see the sync path below).
                flush = getattr(self.sched, "flush_io", None)
                if flush is not None:
                    flush()
                cp0 = time.thread_time()
                pending = d.dispatch_ticks(self._ticks)
                pending.t_loop_cpu = time.thread_time() - cp0
                self._occ.add(time.perf_counter() - pending.t_dispatch)
                self._pipe.submit(
                    pending.fetch,
                    functools.partial(self._pump_done, pending),
                )
            self._arm_pump(self._cadence.next_delay(service_busy(self.kv)))
            return
        self._pump_sync()

    def _pump_sync(self) -> None:
        """Legacy synchronous pump (MRT_ENGINE_PIPELINE=0, mesh
        drivers, reorder chaos in flight): the whole device step runs
        on the loop thread."""
        # About to grind for up to several milliseconds: push any
        # queued replies onto the wire first, or a client whose op
        # resolved last tick waits out this whole one before it can
        # pipeline its next frame.  (No-op off the IoScheduler: sim
        # tests drive handlers with the virtual-time Scheduler.)
        flush = getattr(self.sched, "flush_io", None)
        if flush is not None:
            flush()
        t0 = time.perf_counter()
        cp0 = time.thread_time()
        self.kv.pump(self._ticks)
        dt = time.perf_counter() - t0
        cdt = time.thread_time() - cp0
        self._occ.add(dt)
        self._record_pump(dt, cdt)
        self._after_pump_durability()
        self._arm_pump(self._cadence.next_delay(service_busy(self.kv)))

    def _pump_done(self, pending, rec) -> None:
        """Loop-side completion of a dispatched batch (posted by the
        engine-pump thread with the fetched stacked metrics): fold the
        bookkeeping, sweep the frontier, observe, re-arm."""
        if isinstance(rec, BaseException):
            raise rec  # device failure: surface on the owning loop
        d = self.kv.driver
        if pending not in d._inflight:
            return  # already drained (final_checkpoint) or torn down
        t0 = time.perf_counter()
        cp0 = time.thread_time()
        d.complete_ticks(pending, rec)
        self.kv.after_step(pending.n)
        now = time.perf_counter()
        # Wall covers dispatch→completion (the client-visible pump
        # latency); CPU counts only the LOOP-side share — the split the
        # profiler uses to show the loop is no longer device-blocked.
        dt = now - pending.t_dispatch
        cdt = (time.thread_time() - cp0) + pending.t_loop_cpu
        self._occ.add(now - t0)
        self._record_pump(dt, cdt)
        self._after_pump_durability()
        if self._stopped:
            return
        self._arm_pump(self._cadence.next_delay(service_busy(self.kv)))

    def _record_pump(self, dt: float, cdt: float) -> None:
        self.m.inc("pump.count")
        self.m.observe("pump.wall_s", dt)
        # Wall-vs-CPU split: a tick whose wall ≫ CPU is device-bound
        # (the host blocked on the accelerator); wall ≈ CPU is
        # host-bound (binding/resolution burning the loop).  The CPU
        # side doubles as the engine stage's cost-accounting counter —
        # the pump IS the engine stage's CPU (observe.py vocabulary).
        self.m.observe("pump.cpu_s", cdt)
        self.m.observe("cpu.engine_s", cdt)
        # Pump sequencing for the tail plane: tick id + dispatch stamp
        # (now − wall) let a committing request attribute its parked
        # time to the fused tick that carried it.  Unconditional — the
        # flight-ring gate below must not decide whether requests know
        # their tick.
        self._pumps += 1
        self._pump_t_dispatch = time.perf_counter() - dt
        fr = self._frec
        if fr is not None:
            # Tick boundary + (on change only) the consensus frontier.
            # Everything here is host-side bookkeeping the pump already
            # computed — no device readback is added.
            d = self.kv.driver
            commits = int(d.commits_total)
            fr.record(
                flightrec.TICK, a=self._pumps, b=int(dt * 1e6), c=commits
            )
            lm = getattr(d, "last_metrics", None) or {}
            frontier = (
                commits,
                int(lm.get("leaders", -1)),
                int(lm.get("max_term", -1)),
            )
            if frontier != self._last_frontier:
                self._last_frontier = frontier
                fr.record(
                    flightrec.STATE, a=frontier[0], b=frontier[1],
                    c=frontier[2],
                )

    def _after_pump_durability(self) -> None:
        if self._dur is not None:
            self._dur.after_pump()  # group fsync + periodic checkpoint
            if self._write_seqs:
                self._write_seqs = {
                    k: v for k, v in self._write_seqs.items()
                    if not self._dur.synced(v)
                }

    def _drain_pipeline(self) -> None:
        """Complete every in-flight batch synchronously (checkpoint /
        shutdown path): blocks the loop, which is the point — nothing
        else may observe a half-accounted engine."""
        d = self.kv.driver
        while d._inflight:
            p = d._inflight[0]
            d.complete_ticks(p, p.fetch())
            self.kv.after_step(p.n)

    def replay_wal(self) -> int:
        """Recovery replay — delegated to
        :func:`~.engine_durability.replay_kv_wal` (strictly one record
        in flight per group; see its docstring for the full
        contract)."""
        n = replay_kv_wal(self.kv, self._dur, self.G)
        self.m.inc("wal.replays")
        self.m.inc("wal.replayed_records", n)
        return n

    # Largest multi-op frame one RPC may carry (bounds the per-pump
    # submit burst a single frame can impose).
    MAX_BATCH = 1024

    def batch(self, args_list):
        """Multi-op frame: one codec envelope carries a clerk's whole
        pipelined batch, applied in one pump (BENCHMARKS' named fix for
        the per-op RPC overhead dominating the serving path).  Writes
        are all submitted up front — they coalesce into the next device
        step together; Gets answer from the applied frontier after the
        frame's writes resolve, so a pipelined read sees its own
        frame's preceding writes.  Per-client order within the frame is
        preserved on resubmit (failures retry as an order-preserving
        subset; sessions are per group, so cross-group interleaving
        cannot trip dedup)."""
        if len(args_list) > self.MAX_BATCH:
            return [
                EngineCmdReply(err=f"ErrBatchTooLarge:{self.MAX_BATCH}")
            ] * len(args_list)
        self.m.inc("batch.frames")
        self.m.observe("batch.ops", float(len(args_list)))

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            replies = [None] * len(args_list)
            # Chains: a client's writes to ONE group must apply in
            # order (same-client dedup + same-key cross-op order).
            # FIFO backlog makes the whole chain safe to pipeline AT
            # ONCE: bindings land in submission order, and a leader-
            # change truncation can only fail a contiguous SUFFIX of
            # the chain.  The one hazard is resubmitting a failed
            # member while later members are still in flight (an
            # orphan sweep can fail out of order, and an inverted
            # rebinding lets the session table swallow the earlier
            # cmd) — so a chain with failures WAITS until every member
            # resolves, then resubmits from the first failure onward,
            # in order.  Chains to different groups pipeline freely.
            chains: dict = {}
            for i, a in enumerate(args_list):
                if a.op != "Get":
                    key = (a.client_id, route_group(a.key, self.G))
                    chains.setdefault(key, []).append((i, a))

            def submit(a):
                return self.kv.submit(
                    route_group(a.key, self.G),
                    KVOp(op=_OPCODE[a.op], key=a.key, value=a.value,
                         client_id=a.client_id, command_id=a.command_id),
                )

            tickets: dict = {}  # frame index -> latest ticket
            for members in chains.values():
                for i, a in members:
                    tickets[i] = submit(a)
            pending = set(chains)
            while pending and self.sched.now < deadline:
                progressed = False
                for qk in list(pending):
                    members = chains[qk]
                    if not all(tickets[i].done for i, _ in members):
                        continue
                    first_bad = next(
                        (k for k, (i, _) in enumerate(members)
                         if tickets[i].failed),
                        None,
                    )
                    if first_bad is None:
                        pending.discard(qk)
                        progressed = True
                        continue
                    # Resubmit the suffix in order (dedup makes any
                    # already-applied member a no-op resolve).
                    for i, a in members[first_bad:]:
                        tickets[i] = submit(a)
                if pending and not progressed:
                    yield 0.002
            tickets = {
                i: t for i, t in tickets.items()
                if t.done and not t.failed
            }
            # Durable mode: one group fsync covers the whole frame
            # (shared gate — see _await_frame_synced).
            synced_ok = set(tickets)
            yield from await_frame_synced(
                self.sched, self._dur, self._write_seqs, synced_ok,
                args_list, deadline,
            )
            for i, a in enumerate(args_list):
                if a.op == "Get":
                    replies[i] = EngineCmdReply(
                        err=OK,
                        value=self.kv.get(
                            route_group(a.key, self.G), a.key
                        ).value,
                    )
                else:
                    ok = i in synced_ok
                    replies[i] = EngineCmdReply(
                        err=OK if ok else ERR_TIMEOUT,
                        value=tickets[i].value if ok else "",
                    )
            return replies

        return run()

    # Largest columnar frame one firehose RPC may carry (the shared
    # wire-level limit — clerks split on the same constant).
    MAX_FIREHOSE = MAX_FIREHOSE_ROWS

    def info(self, _args=None) -> dict:
        """Topology the columnar clerks need for client-side routing."""
        return {"G": self.G}

    def firehose(self, blob):
        """Columnar frame (engine/firehose.py): ONE bytes blob in, one
        out — no per-op objects anywhere on the server path.  Rows that
        lose their log slot to a leader change come back as per-row
        RETRY errors; the CLIENT retries them under the same command
        ids (dedup keeps that exactly-once), which takes retry
        bookkeeping off this hot loop entirely."""
        import numpy as np

        from ..engine.firehose import FH_RETRY, pack_reply

        def run():
            # Buffer payloads pass straight through: the OOB codec
            # delivers blobs as bytes-likes and every consumer below
            # (np.frombuffer, memoryview slicing) speaks the buffer
            # protocol, so only exotic types pay a copy.
            raw = (
                blob if isinstance(blob, (bytes, bytearray, memoryview))
                else bytes(blob)
            )
            if len(raw) < 4:
                return ("err", "ErrMalformedFrame")
            n = int(np.frombuffer(raw, np.dtype("<u4"), 1, 0)[0])
            if n > self.MAX_FIREHOSE:
                return ("err", f"ErrFrameTooLarge:{self.MAX_FIREHOSE}")
            try:
                f = self.kv.submit_frame(raw)
            except ValueError as e:
                return ("err", str(e))
            self.m.inc("firehose.frames")
            self.m.inc("firehose.rows", n)
            t0 = self.sched.now
            deadline = t0 + self.DEADLINE_S
            while not f.done and self.sched.now < deadline:
                yield 0.002
            # Firehose lag: submit → frame resolution (device-side wait).
            self.m.observe("firehose.lag_s", self.sched.now - t0)
            err = f.err.copy()
            # Durable mode FIRST: the shared firehose ack gate (never
            # a false durable ack; unsynced rows demote to RETRY).
            # Must run before the Get gate below — a write that
            # applied but missed its fsync deadline is RETRY, and a
            # Get answering past it would observe state a crash could
            # still un-happen (the sharded handler orders it the same
            # way).
            if self._dur is not None:
                yield from demote_unsynced_rows(
                    self.sched, self._dur, self._write_seqs, f, err,
                    deadline,
                )
            if not f.done or (err[f.write_rows] != 0).any():
                # Writes unresolved, failed, OR demoted: Gets must NOT
                # answer (they would read before the frame's own
                # durable writes) — fail them so the client's retry
                # frame carries the gets together with the retried
                # writes.
                err[f.ops == 0] = FH_RETRY
            # Gets answer at frame completion from the applied state
            # (read-after-own-frame-writes, like the batch path).
            values = [b""] * len(f)
            for r in np.nonzero(f.ops == 0)[0].tolist():
                if err[r] == 0:
                    t = self.kv.get(int(f.groups[r]), f.keys[r])
                    values[r] = t.value.encode()
            return pack_reply(err, values)

        return run()

    def command(self, args: EngineCmdArgs):
        g = route_group(args.key, self.G)
        if args.op == "Get":
            # ReadIndex fast read: linearizable at the applied
            # frontier, no log entry, immediate reply.
            self.m.inc("kv.gets")
            t = self.kv.get(g, args.key)
            return EngineCmdReply(err=OK, value=t.value)

        # The caller's request id + stage clock, captured NOW (handler
        # entry runs on the dispatch breadcrumb; the generator body
        # runs later, when _cur_trace belongs to someone else).
        rid = self.obs.current_trace()
        stages = self.obs.current_stages()
        self.m.inc("kv.writes")

        # Write path: generator handler — yields let the pump advance.
        def run():
            t_start = self.sched.now
            deadline = t_start + self.DEADLINE_S
            t_parked = 0.0
            while self.sched.now < deadline:
                cs0 = time.thread_time() if stages is not None else 0.0
                t = self.kv.submit(
                    g,
                    KVOp(
                        op=_OPCODE[args.op],
                        key=args.key,
                        value=args.value,
                        client_id=args.client_id,
                        command_id=args.command_id,
                    ),
                )
                if stages is not None:
                    # The submit's binding cost runs in a coroutine
                    # step the dispatcher's synchronous cpu.handler_s
                    # segment can't see — fold it here (segment
                    # accounting: this CPU lands nowhere else).
                    self.m.observe(
                        "cpu.handler_s", time.thread_time() - cs0
                    )
                    # Parked from here until a pump carries the
                    # proposal (re-stamped per resubmit — churn waits
                    # are engine latency, not pump-queue latency).
                    t_parked = time.perf_counter()
                if stages is not None and not stages.engine:
                    # First submit closes the handler leg; resubmits
                    # stay inside the engine leg (they ARE the engine's
                    # latency under leader churn).
                    stages.engine = True
                    stages.fold(self.m, "handler")
                sub_deadline = min(
                    self.sched.now + self.RESUBMIT_S, deadline
                )
                while not t.done and self.sched.now < sub_deadline:
                    yield 0.002
                if t.done and not t.failed:
                    if stages is not None:
                        # Commit observed: submit → raft quorum +
                        # apply.  The durability gate below lands in
                        # the ack leg (folded at dispatch completion).
                        stages.fold(self.m, "engine")
                        # Tail attribution: which fused tick carried
                        # the commit, and how long the proposal sat
                        # parked before that tick was dispatched (the
                        # rest of the engine leg is device work).
                        # getattr: stub handlers built via __new__
                        # (tests) carry no pump state.
                        stages.tick = getattr(self, "_pumps", -1)
                        stages.pump_wait_s = max(
                            0.0,
                            getattr(self, "_pump_t_dispatch", 0.0)
                            - t_parked,
                        )
                    # Ack only once the apply-time WAL record is
                    # fsynced (absent = pruned = already durable, or
                    # a duplicate applied before this incarnation).
                    while self._dur is not None:
                        seq = self._write_seqs.get(
                            (args.client_id, args.command_id)
                        )
                        if seq is None or self._dur.synced(seq):
                            break
                        yield 0.002
                    self.m.observe(
                        "kv.command_s", self.sched.now - t_start
                    )
                    # getattr: stub handlers built via __new__ (tests)
                    # carry no recorder.
                    _fr = getattr(self, "_frec", None)
                    if _fr is not None:
                        # Last-committed evidence for the postmortem:
                        # survives a SIGKILL that the tracer's commit
                        # instant (below) would die with.
                        _fr.record(
                            flightrec.COMMIT, code=g,
                            a=args.client_id, b=args.command_id,
                            tag=rid or "",
                        )
                    if rid is not None:
                        # The engine-side leg of the request's journey:
                        # commit instant under the same id the clerk
                        # and RPC spans carry.
                        self.obs.tracer.instant(
                            "commit",
                            time.perf_counter() * 1e6,
                            track="engine",
                            req=rid,
                            group=g,
                        )
                    return EngineCmdReply(err=OK, value=t.value)
                # failed (evicted/orphaned) or wedged: resubmit under
                # the same (client_id, command_id) — dedup-safe.
                self.m.inc("kv.resubmits")
            return EngineCmdReply(err=ERR_TIMEOUT)

        return run()


def serve_engine_kv(
    port: int,
    G: int = 64,
    host: str = "127.0.0.1",
    seed: int = 0,
    record_groups: Optional[Sequence[int]] = None,
    data_dir: Optional[str] = None,
    checkpoint_every_s: float = 30.0,
    mesh_devices: int = 0,
) -> RpcNode:
    """Bring up the chip-owning engine KV server process: one
    EngineDriver (G groups), a BatchedKV, the pump loop, and a
    listening RpcNode.  Returns the node (caller keeps the process
    alive).

    With ``data_dir``, the server is DURABLE: periodic atomic
    checkpoints + a write-ahead log of acked ops (see EngineDurability)
    — a kill -9'd process restarted on the same dir recovers every
    acknowledged write.

    With ``mesh_devices`` > 0, the engine runs the shard_map tick over
    that many local chips (G must divide evenly) — the multi-chip
    production path; checkpoints restore back onto the same-size
    mesh."""
    node = RpcNode(listen=True, host=host, port=port)
    sched = node.sched

    def build():
        mesh = make_mesh(mesh_devices) if mesh_devices else None
        driver = None
        if data_dir:
            ckpt = os.path.join(data_dir, "engine.ckpt")
            if os.path.exists(ckpt):
                driver = EngineDriver.restore(ckpt, mesh=mesh)
        if driver is not None:
            node.obs.metrics.inc("engine.restores")
            kv = BatchedKV(driver, record_groups=list(record_groups or []))
            blob = driver.restored_extra.get("service")
            if blob:
                kv.load_state_dict(blob)
        else:
            # Shape knobs for throughput deployments (the firehose
            # bench serves G=256 at INGEST=24; defaults match the
            # round-2 serving shape).
            cfg = EngineConfig(
                G=G, P=3,
                L=int(os.environ.get("MULTIRAFT_SERVE_L", "64")),
                E=int(os.environ.get("MULTIRAFT_SERVE_E", "8")),
                INGEST=int(os.environ.get("MULTIRAFT_SERVE_INGEST", "8")),
            )
            driver = EngineDriver(cfg, seed=seed, mesh=mesh)
            kv = BatchedKV(driver, record_groups=list(record_groups or []))
            driver.run_until_quiet_leaders(2000)
        # Warm-up BEFORE the readiness line: elect leaders and compile
        # both tick variants (quiet + loaded).  The first jit compile
        # takes tens of seconds and runs on the scheduler loop — doing
        # it lazily would starve RPC dispatch and time out every early
        # client (observed: all first ops stall ~10s on CPU).  A
        # restored process recompiles too (fresh interpreter).
        driver.start(0, (KVOp(op=OP_GET, key=""), None))
        for _ in range(8):
            kv.pump(1)
        # This service routes by key hash; reject firehose frames
        # whose group column disagrees with it, server-side.
        kv.route_check = route_group
        dur = (
            EngineDurability(data_dir, driver, kv,
                             checkpoint_every_s=checkpoint_every_s,
                             metrics=node.obs.metrics)
            if data_dir else None
        )
        # Fold the driver's tick counter into the scrapeable registry
        # (tick SPANS stay gated on the diagnostic tracer below — they
        # force a device sync per tick).
        driver.metrics = node.obs.metrics
        if node.tracer is not None:
            driver.tracer = node.tracer  # ticks + RPCs on one timeline
        svc = EngineKVService(
            sched, kv, durability=dur, obs=node.obs,
            ticks_per_pump=int(
                os.environ.get("MULTIRAFT_SERVE_TICKS_PER_PUMP", "2")
            ),
        )
        if dur is not None:
            svc.replay_wal()  # recovery completes before readiness
            # Fold the replayed state into a fresh checkpoint and
            # rotate: bounds the next recovery, and discards the
            # duplicate records the replay's own apply hooks appended.
            dur.checkpoint()
        return svc

    svc = sched.run_call(build, timeout=600.0)
    node.add_service("EngineKV", svc)
    node.engine_service = svc  # keep reachable for introspection
    # Overload watch (overload.py): windowed stage-p99 + queue-gauge
    # bounds → OVERLOAD flight records, while the collapse is live.
    # Admission (admission.py): the watch's brownout state drives it,
    # turning those signals into shed/bounded behavior at dispatch.
    install_admission(node)
    install_overload_watch(node)
    # Wedge watchdog (wedge.py): per-group commit-frontier stall with
    # proposals pending -> WEDGE flight records + gauge.wedged_groups,
    # the gray-failure signal the up/down detectors above cannot see.
    install_wedge_watch(node)
    return node

# Backwards-compatible re-exports: engine_server was the single module
# for the whole serving stack before the round-4 decomposition, and
# in-repo callers/tests import these names from here.
from .engine_clerks import (  # noqa: E402,F401
    EngineClerk,
    EngineFleetClerk,
    FirehoseClerk,
    ShardFirehoseClerk,
    EngineShardNetClerk,
    PipelinedClerk,
    PipelinedFleetClerk,
)
from .engine_shard_server import (  # noqa: E402,F401
    EngineShardKVService,
    serve_engine_shardkv,
)
