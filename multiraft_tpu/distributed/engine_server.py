"""The batched engine served over the real network — one process owns
the chip; clerk RPCs come in over TCP and are coalesced into engine
ticks (the first step of SURVEY §2.2's sidecar story: "clients talk to
a thin RPC front; commands coalesce into the device firehose").

Architecture (vs the per-replica sim/process stack in ``cluster.py``):

* ``EngineKVService`` wraps a :class:`BatchedKV` on an
  :class:`EngineDriver`.  A pump timer on the process's
  ``RealtimeScheduler`` advances the device tick loop every couple of
  milliseconds; every RPC that arrived since the last pump has already
  queued its command into the per-group backlog, so one device step
  carries *all* concurrent client traffic — the batching that makes a
  single chip serve thousands of groups.
* Writes ride the log with kvraft session dedup (``KVOp.client_id`` /
  ``command_id``) so the at-least-once transport (client retries on
  timeout) stays exactly-once.  Reads use the ReadIndex fast path
  (zero device work, linearizable at the applied frontier).
* ``EngineShardKVService`` is the sharded form: a
  :class:`BatchedShardKV` behind the same front door, with server-side
  key→shard routing against its replicated config and the clerk retry
  semantics of the reference (ErrWrongGroup → re-route).

Wire protocol: ``EngineKV.command`` / ``EngineShardKV.command`` over
:class:`~multiraft_tpu.distributed.tcp.RpcNode` frames.
"""

from __future__ import annotations

import dataclasses
import itertools
import zlib
from typing import Any, Optional, Sequence

from ..engine.core import EngineConfig
from ..engine.host import EngineDriver
from ..engine.kv import BatchedKV, KVOp
from ..porcupine.kv import OP_APPEND, OP_GET, OP_PUT
from ..sim.scheduler import TIMEOUT, Future
from ..transport import codec
from ..utils.ids import unique_client_id
from .realtime import RealtimeScheduler
from .tcp import RpcNode

__all__ = [
    "EngineCmdArgs",
    "EngineCmdReply",
    "EngineKVService",
    "EngineShardKVService",
    "EngineClerk",
    "EngineShardNetClerk",
    "EngineFleetClerk",
    "serve_engine_kv",
    "serve_engine_shardkv",
]

OK = "OK"
ERR_TIMEOUT = "ErrTimeout"

_OPCODE = {"Get": OP_GET, "Put": OP_PUT, "Append": OP_APPEND}


@codec.registered
@dataclasses.dataclass
class EngineCmdArgs:
    op: str = "Get"
    key: str = ""
    value: str = ""
    client_id: int = 0
    command_id: int = 0


@codec.registered
@dataclasses.dataclass
class EngineCmdReply:
    err: str = OK
    value: str = ""


def route_group(key: str, G: int) -> int:
    """Deterministic key→group routing shared by every process (a
    stable hash — Python's builtin is salted per process)."""
    return zlib.crc32(key.encode()) % G


class EngineKVService:
    """``EngineKV.command`` RPC front for a :class:`BatchedKV`.

    All device work happens on the scheduler loop: the pump timer and
    the RPC handlers interleave there, so commands queued by handlers
    between pumps coalesce into the next device step."""

    # Handler-side patience before giving up on one submission and
    # resubmitting (dedup makes the duplicate harmless) — covers
    # tickets lost to leader changes.
    RESUBMIT_S = 0.25
    # Total per-RPC budget; the client retries after its own timeout.
    DEADLINE_S = 3.0

    def __init__(
        self,
        sched: RealtimeScheduler,
        kv: BatchedKV,
        pump_interval: float = 0.002,
        ticks_per_pump: int = 2,
    ) -> None:
        self.sched = sched
        self.kv = kv
        self.G = kv.driver.cfg.G
        self._interval = pump_interval
        self._ticks = ticks_per_pump
        self._stopped = False
        sched.call_soon(self._pump_loop)

    def stop(self) -> None:
        self._stopped = True

    def _pump_loop(self) -> None:
        if self._stopped:
            return
        self.kv.pump(self._ticks)
        self.sched.call_after(self._interval, self._pump_loop)

    def command(self, args: EngineCmdArgs):
        g = route_group(args.key, self.G)
        if args.op == "Get":
            # ReadIndex fast read: linearizable at the applied
            # frontier, no log entry, immediate reply.
            t = self.kv.get(g, args.key)
            return EngineCmdReply(err=OK, value=t.value)

        # Write path: generator handler — yields let the pump advance.
        def run():
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                t = self.kv.submit(
                    g,
                    KVOp(
                        op=_OPCODE[args.op],
                        key=args.key,
                        value=args.value,
                        client_id=args.client_id,
                        command_id=args.command_id,
                    ),
                )
                sub_deadline = min(
                    self.sched.now + self.RESUBMIT_S, deadline
                )
                while not t.done and self.sched.now < sub_deadline:
                    yield 0.002
                if t.done and not t.failed:
                    return EngineCmdReply(err=OK, value=t.value)
                # failed (evicted/orphaned) or wedged: resubmit under
                # the same (client_id, command_id) — dedup-safe.
            return EngineCmdReply(err=ERR_TIMEOUT)

        return run()


class EngineShardKVService:
    """``EngineShardKV.command``: the sharded engine service behind the
    same TCP front door.  Key→shard routing happens server-side against
    the replicated config; WRONG_GROUP during migration re-routes like
    the reference clerk (shardkv/client.go:68-129).

    **Fleet mode** (``peers`` given): this process hosts a subset of
    the global gid space and its ``BatchedShardKV`` migrates shards
    to/from peer processes over the network — ``remote_fetch`` becomes
    a ``pull_shard`` RPC to the owning peer, ``remote_delete`` a
    ``delete_shard`` RPC riding the peer's log (Challenge 1 across
    processes).  Ops for a gid hosted elsewhere answer ErrWrongGroup so
    the fleet clerk re-routes, exactly like a reference group answering
    for a shard it no longer owns."""

    RESUBMIT_S = 0.25
    DEADLINE_S = 5.0
    # Per-RPC bound on one migration fetch/delete attempt; the
    # orchestration sweep re-issues after a timeout.
    MIGRATE_RPC_S = 2.0

    def __init__(
        self,
        sched: RealtimeScheduler,
        skv,  # BatchedShardKV
        pump_interval: float = 0.002,
        ticks_per_pump: int = 2,
        peers: Optional[dict] = None,  # gid -> TcpClientEnd (remote owners)
    ) -> None:
        self.sched = sched
        self.skv = skv
        self._interval = pump_interval
        self._ticks = ticks_per_pump
        self._stopped = False
        self.peers = dict(peers or {})
        self._fleet = bool(self.peers)
        if self._fleet:
            self._fetches: dict = {}  # (gid, shard, num) -> Future
            self._deletes: dict = {}
            skv.remote_fetch = self._remote_fetch
            skv.remote_delete = self._remote_delete
        sched.call_soon(self._pump_loop)

    # -- fleet migration hooks (run on the loop thread, inside pump) ------

    def _remote_fetch(self, src_gid: int, shard: int, num: int):
        from ..engine.shardkv import OK as SK_OK

        key = (src_gid, shard, num)
        fut = self._fetches.get(key)
        if fut is None:
            end = self.peers.get(src_gid)
            if end is None:
                return None  # unroutable: keep retrying (config may fix)
            self._fetches[key] = self.sched.with_timeout(
                end.call("EngineShardKV.pull_shard", (src_gid, shard, num)),
                self.MIGRATE_RPC_S,
            )
            return None
        if not fut.done:
            return None
        del self._fetches[key]  # resolved: consume or retry next sweep
        reply = fut.value
        if (
            reply is None or reply is TIMEOUT
            or not isinstance(reply, tuple) or reply[0] != SK_OK
        ):
            return None  # dropped / not ready: the sweep re-issues
        return reply[1], reply[2]

    def _remote_delete(self, src_gid: int, shard: int, num: int):
        from ..engine.shardkv import OK as SK_OK

        key = (src_gid, shard, num)
        fut = self._deletes.get(key)
        if fut is None:
            end = self.peers.get(src_gid)
            if end is None:
                return True  # owner unknown everywhere: nothing to delete
            self._deletes[key] = self.sched.with_timeout(
                end.call("EngineShardKV.delete_shard", (src_gid, shard, num)),
                self.MIGRATE_RPC_S,
            )
            return None
        if not fut.done:
            return None
        del self._deletes[key]
        reply = fut.value
        if reply is None or reply is TIMEOUT or not isinstance(reply, tuple):
            return None  # dropped: re-issue next sweep
        return reply[0] == SK_OK  # False = ErrNotReady, re-asked later

    # -- fleet migration RPC handlers (the serving side of the hooks) -----

    def pull_shard(self, args):
        """Return ``(OK, data, latest)`` for a shard this process's old
        owner holds, once it has applied the puller's config number —
        the cross-process form of the in-process applied-state read
        (engine/shardkv.py _orchestrate step (b))."""
        from ..engine.shardkv import ERR_NOT_READY, ERR_WRONG_GROUP
        from ..engine.shardkv import OK as SK_OK

        src_gid, shard, num = args
        if src_gid not in self.skv.reps:
            return (ERR_WRONG_GROUP,)

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                rep = self.skv.reps[src_gid]
                if rep.cur.num >= num:
                    sh = rep.shards[shard]
                    return (SK_OK, dict(sh.data), dict(sh.latest))
                yield 0.01  # config catching up (the ErrNotReady gate)
            return (ERR_NOT_READY,)

        return run()

    def delete_shard(self, args):
        """Challenge-1 deletion on behalf of a remote puller: ride the
        local old owner's log (BatchedShardKV.delete_shard) and report
        the outcome."""
        from ..engine.shardkv import ERR_WRONG_GROUP
        from ..engine.shardkv import OK as SK_OK

        src_gid, shard, num = args
        if src_gid not in self.skv.reps:
            return (ERR_WRONG_GROUP,)

        def run():
            t = self.skv.delete_shard(src_gid, shard, num)
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                if t.done:
                    if t.failed:
                        return (ERR_TIMEOUT,)
                    return (SK_OK,) if t.err == SK_OK else (t.err,)
                yield 0.005
            return (ERR_TIMEOUT,)

        return run()

    def config(self, args):
        """Latest committed config as ``(num, shards, groups)`` — the
        fleet clerk's routing source (shardctrler Query analog)."""
        cfg = self.skv.query_latest()
        return (
            cfg.num,
            list(cfg.shards),
            {g: list(v) for g, v in cfg.groups.items()},
        )

    def stop(self) -> None:
        self._stopped = True

    def _pump_loop(self) -> None:
        if self._stopped:
            return
        self.skv.pump(self._ticks)
        self.sched.call_after(self._interval, self._pump_loop)

    def command(self, args: EngineCmdArgs):
        from ..engine.shardkv import ERR_WRONG_GROUP
        from ..services.shardkv import key2shard

        if args.op == "Get":
            # ReadIndex fast read (BatchedShardKV.get_fast): no log
            # entry, gated on serving-shard ownership exactly like the
            # logged path; ErrWrongGroup during migration pumps and
            # retries like any clerk op.
            def run_get():
                deadline = self.sched.now + self.DEADLINE_S
                while self.sched.now < deadline:
                    t = self.skv.get_fast(args.key)
                    if t.err == ERR_WRONG_GROUP:
                        # Fleet: the owner is (probably) another
                        # process — answer so the clerk re-routes.
                        if self._fleet:
                            return EngineCmdReply(err=ERR_WRONG_GROUP)
                        yield 0.01  # config moving; shard not serving here
                        continue
                    value = t.value if t.err == OK else ""
                    return EngineCmdReply(err=OK, value=value)
                return EngineCmdReply(err=ERR_TIMEOUT)

            return run_get()

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                cfg = self.skv.query_latest()
                gid = cfg.shards[key2shard(args.key)]
                if gid not in self.skv.reps:
                    if self._fleet:
                        # Hosted by a peer process: tell the clerk.
                        return EngineCmdReply(err=ERR_WRONG_GROUP)
                    yield 0.01  # shard unassigned; config still moving
                    continue
                t = self.skv.submit(
                    gid, args.op, args.key, args.value,
                    client_id=args.client_id, command_id=args.command_id,
                )
                sub_deadline = min(
                    self.sched.now + self.RESUBMIT_S, deadline
                )
                while not t.done and self.sched.now < sub_deadline:
                    yield 0.002
                if not t.done or t.failed or t.err == ERR_WRONG_GROUP:
                    continue  # resubmit / re-route; dedup-safe
                return EngineCmdReply(err=OK, value=t.value)
            return EngineCmdReply(err=ERR_TIMEOUT)

        return run()

    ADMIN_OPS = ("join", "leave", "move")

    def admin(self, args):
        """Config administration: args = (kind, payload[, command_id])
        with kind in ADMIN_OPS — a network-supplied string must never
        getattr into arbitrary methods.  The optional command_id makes
        retries exactly-once through the ctrler dedup table; a FLEET
        admin MUST pass one (a duplicate apply would fork the config
        histories' numbering across processes and wedge migration)."""
        kind, payload = args[0], args[1]
        cmd = args[2] if len(args) > 2 else None
        if kind not in self.ADMIN_OPS:
            return EngineCmdReply(err=f"ErrBadAdminOp:{kind}")

        def run():
            # join/leave take their payload whole (a gid list / mapping);
            # move takes (shard, gid) as two positionals.
            if kind == "move":
                t = self.skv.move(*payload, command_id=cmd)
            else:
                t = getattr(self.skv, kind)(payload, command_id=cmd)
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                if t.done:
                    return EngineCmdReply(err=OK if not t.failed else ERR_TIMEOUT)
                yield 0.005
            return EngineCmdReply(err=ERR_TIMEOUT)

        return run()


class EngineClerk:
    """Generator-coroutine client of an engine KV/shard server —
    retry-until-answer with session dedup, mirroring the reference
    clerk loop (kvraft/client.go:47-71) against the single front door."""

    # Clerks are created from concurrent threads (one per blocking
    # client); the counter allocation must be atomic or two clerks
    # share a client_id and dedup silently drops one's writes.
    _next = itertools.count(1)

    def __init__(self, sched, end, service: str = "EngineKV") -> None:
        self.sched = sched
        self.end = end
        self.service = service
        self.client_id = unique_client_id(next(EngineClerk._next))
        self.command_id = 0

    def _command(self, op: str, key: str, value: str = ""):
        if op != "Get":
            self.command_id += 1
        args = EngineCmdArgs(
            op=op, key=key, value=value,
            client_id=self.client_id, command_id=self.command_id,
        )
        while True:
            fut: Future = self.end.call(f"{self.service}.command", args)
            reply = yield self.sched.with_timeout(fut, 3.5)
            if (
                reply is None
                or reply is TIMEOUT
                or reply.err != OK
            ):
                continue  # lost/timed out/old leader: retry (dedup-safe)
            return reply.value

    def get(self, key: str):
        return self._command("Get", key)

    def put(self, key: str, value: str):
        return self._command("Put", key, value)

    def append(self, key: str, value: str):
        return self._command("Append", key, value)


class EngineShardNetClerk(EngineClerk):
    def __init__(self, sched, end) -> None:
        super().__init__(sched, end, service="EngineShardKV")


class EngineFleetClerk:
    """Clerk for a fleet of engine shard servers: route key→shard→gid→
    process from the replicated config, re-query and re-route on
    ErrWrongGroup — the reference clerk loop (shardkv/client.go:68-129)
    where each "group" is a chip-owning process."""

    def __init__(self, sched, ends_by_gid: dict) -> None:
        self.sched = sched
        self.ends = dict(ends_by_gid)  # gid -> TcpClientEnd
        self._all = list(dict.fromkeys(self.ends.values()))
        self.client_id = unique_client_id(next(EngineClerk._next))
        self.command_id = 0
        self._cfg = None  # cached (num, shards, groups)

    def _refresh_config(self):
        while True:
            for end in self._all:
                fut = end.call("EngineShardKV.config", ())
                reply = yield self.sched.with_timeout(fut, 2.0)
                if reply is not None and reply is not TIMEOUT:
                    self._cfg = reply
                    return reply
            yield self.sched.sleep(0.05)

    def _command(self, op: str, key: str, value: str = ""):
        from ..engine.shardkv import ERR_WRONG_GROUP
        from ..services.shardkv import key2shard

        if op != "Get":
            self.command_id += 1
        args = EngineCmdArgs(
            op=op, key=key, value=value,
            client_id=self.client_id, command_id=self.command_id,
        )
        while True:
            cfg = self._cfg
            if cfg is None:
                cfg = yield from self._refresh_config()
            gid = cfg[1][key2shard(key)]
            end = self.ends.get(gid)
            if end is None:  # unassigned shard / unknown gid: re-query
                yield self.sched.sleep(0.05)
                self._cfg = None
                continue
            fut = end.call("EngineShardKV.command", args)
            reply = yield self.sched.with_timeout(fut, 3.5)
            if reply is None or reply is TIMEOUT:
                self._cfg = None
                continue  # dropped / wedged: re-route and retry
            if reply.err == OK:
                return reply.value
            if reply.err == ERR_WRONG_GROUP:
                self._cfg = None  # stale routing: re-query the config
            yield self.sched.sleep(0.02)

    def get(self, key: str):
        return self._command("Get", key)

    def put(self, key: str, value: str):
        return self._command("Put", key, value)

    def append(self, key: str, value: str):
        return self._command("Append", key, value)


def serve_engine_kv(
    port: int,
    G: int = 64,
    host: str = "127.0.0.1",
    seed: int = 0,
    record_groups: Optional[Sequence[int]] = None,
) -> RpcNode:
    """Bring up the chip-owning engine KV server process: one
    EngineDriver (G groups), a BatchedKV, the pump loop, and a
    listening RpcNode.  Returns the node (caller keeps the process
    alive)."""
    node = RpcNode(listen=True, host=host, port=port)
    sched = node.sched

    def build():
        cfg = EngineConfig(G=G, P=3, L=64, E=8, INGEST=8)
        driver = EngineDriver(cfg, seed=seed)
        kv = BatchedKV(driver, record_groups=list(record_groups or []))
        # Warm-up BEFORE the readiness line: elect leaders and compile
        # both tick variants (quiet + loaded).  The first jit compile
        # takes tens of seconds and runs on the scheduler loop — doing
        # it lazily would starve RPC dispatch and time out every early
        # client (observed: all first ops stall ~10s on CPU).
        driver.run_until_quiet_leaders(2000)
        driver.start(0, (KVOp(op=OP_GET, key=""), None))
        for _ in range(8):
            kv.pump(1)
        return EngineKVService(sched, kv)

    svc = sched.run_call(build, timeout=600.0)
    node.add_service("EngineKV", svc)
    node.engine_service = svc  # keep reachable for introspection
    return node


def serve_engine_shardkv(
    port: int,
    G: int = 4,
    host: str = "127.0.0.1",
    seed: int = 0,
    join_gids: Optional[Sequence[int]] = None,
    gids: Optional[Sequence[int]] = None,
    peer_addrs: Optional[dict] = None,  # gid -> (host, port) of the owner
) -> RpcNode:
    """The sharded engine behind TCP: BatchedShardKV (replicated config
    + per-shard migration pipeline) on one chip-owning process.

    Fleet mode: pass ``gids`` (the global gids THIS process hosts; the
    local engine is sized ``len(gids)+1``) and ``peer_addrs`` (owner
    address for every remotely hosted gid) — shard migration then rides
    ``pull_shard``/``delete_shard`` RPCs between processes."""
    from ..engine.shardkv import BatchedShardKV

    node = RpcNode(listen=True, host=host, port=port)
    sched = node.sched
    local_gids = list(gids) if gids is not None else None
    G_local = (len(local_gids) + 1) if local_gids is not None else G
    peers = {
        g: node.client_end(h, p)
        for g, (h, p) in (peer_addrs or {}).items()
        if local_gids is None or g not in local_gids
    }

    def build():
        cfg = EngineConfig(G=G_local, P=3, L=64, E=8, INGEST=8)
        driver = EngineDriver(cfg, seed=seed)
        # Warm-up before readiness (see serve_engine_kv): elections +
        # both tick compiles happen here, not under client traffic —
        # the admin_sync join exercises the loaded variant.
        ok = driver.run_until_quiet_leaders(2000)
        assert ok, "engine groups failed to elect"
        skv = BatchedShardKV(driver, gids=local_gids)
        # Warm the LOADED tick variant before the readiness line (the
        # jit compile takes tens of seconds on CPU and would otherwise
        # land under the first admin/client RPC and time it out).  A
        # None payload is the "binding lost" no-op: it exercises the
        # ingest path without touching config history — essential in
        # fleet mode, where every process's history must stay aligned.
        skv.driver.start(0, None)
        skv.pump(8)
        for gid in join_gids or []:
            skv.admin_sync("join", [gid])
        return EngineShardKVService(sched, skv, peers=peers)

    svc = sched.run_call(build, timeout=600.0)
    node.add_service("EngineShardKV", svc)
    node.engine_service = svc
    return node
