"""The batched engine served over the real network — one process owns
the chip; clerk RPCs come in over TCP and are coalesced into engine
ticks (the first step of SURVEY §2.2's sidecar story: "clients talk to
a thin RPC front; commands coalesce into the device firehose").

Architecture (vs the per-replica sim/process stack in ``cluster.py``):

* ``EngineKVService`` wraps a :class:`BatchedKV` on an
  :class:`EngineDriver`.  A pump timer on the process's
  ``RealtimeScheduler`` advances the device tick loop every couple of
  milliseconds; every RPC that arrived since the last pump has already
  queued its command into the per-group backlog, so one device step
  carries *all* concurrent client traffic — the batching that makes a
  single chip serve thousands of groups.
* Writes ride the log with kvraft session dedup (``KVOp.client_id`` /
  ``command_id``) so the at-least-once transport (client retries on
  timeout) stays exactly-once.  Reads use the ReadIndex fast path
  (zero device work, linearizable at the applied frontier).
* ``EngineShardKVService`` is the sharded form: a
  :class:`BatchedShardKV` behind the same front door, with server-side
  key→shard routing against its replicated config and the clerk retry
  semantics of the reference (ErrWrongGroup → re-route).

Wire protocol: ``EngineKV.command`` / ``EngineShardKV.command`` over
:class:`~multiraft_tpu.distributed.tcp.RpcNode` frames.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import time
import zlib
from typing import Any, Optional, Sequence

from ..engine.core import EngineConfig
from ..engine.host import EngineDriver
from ..engine.kv import BatchedKV, KVOp
from ..porcupine.kv import OP_APPEND, OP_GET, OP_PUT
from ..sim.scheduler import TIMEOUT, Future
from ..transport import codec
from ..utils.ids import unique_client_id
from .realtime import RealtimeScheduler
from .tcp import RpcNode

__all__ = [
    "EngineCmdArgs",
    "EngineCmdReply",
    "EngineKVService",
    "EngineShardKVService",
    "EngineClerk",
    "PipelinedClerk",
    "PipelinedFleetClerk",
    "EngineShardNetClerk",
    "EngineFleetClerk",
    "serve_engine_kv",
    "serve_engine_shardkv",
]

OK = "OK"
ERR_TIMEOUT = "ErrTimeout"

_OPCODE = {"Get": OP_GET, "Put": OP_PUT, "Append": OP_APPEND}
_OPNAME = {v: k for k, v in _OPCODE.items()}


class EngineDurability:
    """Checkpoint + WAL lifecycle for one engine server process.

    The engine's durability contract (see distributed/wal.py): periodic
    atomic whole-engine checkpoints + a WAL of ops since the last one;
    write acks gate on the WAL record being fsynced (group commit at
    pump cadence, so the fsync amortizes over every op in the ~2 ms
    window).  Recovery restores the checkpoint and re-submits WAL
    records through consensus — session dedup makes it exactly-once."""

    def __init__(
        self,
        data_dir: str,
        driver: EngineDriver,
        state_owner,  # has state_dict() (BatchedKV / BatchedShardKV)
        checkpoint_every_s: float = 30.0,
        fsync: bool = True,
    ) -> None:
        from .wal import WriteAheadLog

        os.makedirs(data_dir, exist_ok=True)
        self.ckpt_path = os.path.join(data_dir, "engine.ckpt")
        self.wal = WriteAheadLog(os.path.join(data_dir, "ops.wal"),
                                 fsync=fsync)
        self.driver = driver
        self.state_owner = state_owner
        self.every = checkpoint_every_s
        self._last_ckpt = time.monotonic()

    def log(self, record) -> int:
        """Append one op record; returns its ack-gate seq."""
        return self.wal.append(codec.encode(record))

    def synced(self, seq: int) -> bool:
        return self.wal.synced >= seq

    def replay_records(self):
        for body in self.wal.replay():
            yield codec.decode(body)

    def after_pump(self) -> None:
        """Group fsync + periodic checkpoint, called once per pump."""
        self.wal.sync()
        if self.every > 0 and (
            time.monotonic() - self._last_ckpt >= self.every
        ):
            self.checkpoint()

    def checkpoint(self) -> None:
        """Atomic engine+service snapshot, then WAL rotation.  A crash
        between the two merely makes the next replay redundant."""
        self.driver.save(
            self.ckpt_path,
            extra={"service": self.state_owner.state_dict()},
        )
        self.wal.rotate()
        self._last_ckpt = time.monotonic()


@codec.registered
@dataclasses.dataclass
class EngineCmdArgs:
    op: str = "Get"
    key: str = ""
    value: str = ""
    client_id: int = 0
    command_id: int = 0


@codec.registered
@dataclasses.dataclass
class EngineCmdReply:
    err: str = OK
    value: str = ""


def route_group(key: str, G: int) -> int:
    """Deterministic key→group routing shared by every process (a
    stable hash — Python's builtin is salted per process)."""
    return zlib.crc32(key.encode()) % G


def _await_frame_synced(sched, dur, write_seqs, ok, args_list, deadline):
    """Durable frame-ack gate shared by both services' ``batch``
    handlers (yield-from inside the handler generator): every write in
    ``ok`` must have its apply-time WAL record fsynced before it may
    ack OK; at the deadline, unsynced writes are DROPPED from ``ok``
    (they answer ErrTimeout — never a false durable ack)."""
    while dur is not None:
        pend = [
            i for i in ok
            if (s := write_seqs.get(
                (args_list[i].client_id, args_list[i].command_id)
            )) is not None and not dur.synced(s)
        ]
        if not pend:
            break
        if sched.now >= deadline:
            ok -= set(pend)
            break
        yield 0.002


def _make_mesh(n_devices: int):
    """A 1-D ``groups`` mesh over the first ``n_devices`` local devices
    — the production entry to the shard_map tick (engine/mesh.py): the
    server's state lives sharded across its chips, consensus stays
    zero-collective, and the same driver/pump/checkpoint path serves
    single- and multi-chip alike."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if n_devices <= 0:
        raise ValueError(f"mesh_devices must be positive, got {n_devices}")
    devs = jax.devices()
    if n_devices > len(devs):
        raise ValueError(
            f"mesh_devices={n_devices} > {len(devs)} visible devices"
        )
    return Mesh(np.array(devs[:n_devices]), ("groups",))


class EngineKVService:
    """``EngineKV.command`` RPC front for a :class:`BatchedKV`.

    All device work happens on the scheduler loop: the pump timer and
    the RPC handlers interleave there, so commands queued by handlers
    between pumps coalesce into the next device step."""

    # Handler-side patience before giving up on one submission and
    # resubmitting (dedup makes the duplicate harmless) — covers
    # tickets lost to leader changes.
    RESUBMIT_S = 0.25
    # Total per-RPC budget; the client retries after its own timeout.
    DEADLINE_S = 3.0

    def __init__(
        self,
        sched: RealtimeScheduler,
        kv: BatchedKV,
        pump_interval: float = 0.002,
        ticks_per_pump: int = 2,
        durability: Optional[EngineDurability] = None,
    ) -> None:
        self.sched = sched
        self.kv = kv
        self.G = kv.driver.cfg.G
        self._interval = pump_interval
        self._ticks = ticks_per_pump
        self._stopped = False
        self._dur = durability
        # (client_id, command_id) -> WAL seq of the op's apply-time
        # record; handlers gate their ack on it being fsynced.  Pruned
        # once synced (absence = already durable).
        self._write_seqs: dict = {}
        if durability is not None:
            # WAL at APPLY time (commit order): evict-and-resubmit can
            # commit ops in a different order than submission, and
            # replay must reproduce the order reads actually saw.
            kv.on_write = lambda g, op: self._write_seqs.__setitem__(
                (op.client_id, op.command_id),
                durability.log(("kv", _OPNAME[op.op], op.key, op.value,
                                op.client_id, op.command_id)),
            )
        sched.call_soon(self._pump_loop)

    def stop(self) -> None:
        self._stopped = True

    def final_checkpoint(self) -> bool:
        """Graceful-shutdown hook (CLI SIGTERM): fold everything into
        one last checkpoint so the next start skips WAL replay.  False
        when the server is not durable."""
        if self._dur is None:
            return False
        self._dur.checkpoint()
        return True

    def _pump_loop(self) -> None:
        if self._stopped:
            return
        self.kv.pump(self._ticks)
        if self._dur is not None:
            self._dur.after_pump()  # group fsync + periodic checkpoint
            if self._write_seqs:
                self._write_seqs = {
                    k: v for k, v in self._write_seqs.items()
                    if not self._dur.synced(v)
                }
        self.sched.call_after(self._interval, self._pump_loop)

    def replay_wal(self) -> int:
        """Re-submit every WAL record through consensus (recovery path;
        runs to completion before the server starts answering).  Dedup
        tables make records already in the checkpoint no-ops.

        STRICTLY one record at a time PER GROUP: the WAL is
        commit-ordered, and both order guarantees that replay must
        reproduce are group-local — a client's cmd N vs N+1 (an
        eviction committing N+1 first would dedup-swallow the
        resubmitted N) and cross-client order on a shared key (an
        acked A-then-B pair replayed B-then-A would recover the wrong
        value).  A key routes to exactly one group, so serial-per-group
        preserves both while groups pipeline through each pump wave:
        recovery wall-clock scales with the deepest single-group
        backlog, not the WAL length.  With the default 30 s checkpoint
        interval the WAL bounds to ~30 s of acked writes, so expected
        RTO ≈ that backlog's longest per-group chain at one commit per
        ~2 pump rounds."""
        if self._dur is None:
            return 0
        recs = [rec for rec in self._dur.replay_records() if rec[0] == "kv"]
        queues: dict = {}
        for rec in recs:
            queues.setdefault(route_group(rec[2], self.G), []).append(rec)

        def submit(rec):
            _, op, key, value, cid, cmd = rec
            return self.kv.submit(
                route_group(key, self.G),
                KVOp(op=_OPCODE[op], key=key, value=value,
                     client_id=cid, command_id=cmd),
            )

        depth = max((len(q) for q in queues.values()), default=0)
        max_rounds = 4000 + 200 * depth
        pending: dict = {}  # group -> [ticket, attempts_left, submit_round]
        rounds = 0
        while queues:
            for g in queues:
                if g not in pending:
                    pending[g] = [submit(queues[g][0]), 50, rounds]
            self.kv.pump(2)
            rounds += 1
            for g, (t, left, since) in list(pending.items()):
                resubmit = False
                if t.done and not t.failed:
                    queues[g].pop(0)
                    del pending[g]
                    if not queues[g]:
                        del queues[g]
                elif t.done and t.failed:
                    resubmit = True  # evicted: same ids, dedup-safe
                elif rounds - since >= 600:
                    resubmit = True  # wedged ticket (binding lost)
                if resubmit:
                    if left <= 1:
                        rec = queues[g][0]
                        raise RuntimeError(
                            f"WAL replay of {rec[1]}({rec[2]!r}) did not "
                            "converge"
                        )
                    pending[g] = [submit(queues[g][0]), left - 1, rounds]
            if rounds > max_rounds:
                raise RuntimeError("WAL replay did not converge")
        return len(recs)

    # Largest multi-op frame one RPC may carry (bounds the per-pump
    # submit burst a single frame can impose).
    MAX_BATCH = 1024

    def batch(self, args_list):
        """Multi-op frame: one codec envelope carries a clerk's whole
        pipelined batch, applied in one pump (BENCHMARKS' named fix for
        the per-op RPC overhead dominating the serving path).  Writes
        are all submitted up front — they coalesce into the next device
        step together; Gets answer from the applied frontier after the
        frame's writes resolve, so a pipelined read sees its own
        frame's preceding writes.  Per-client order within the frame is
        preserved on resubmit (failures retry as an order-preserving
        subset; sessions are per group, so cross-group interleaving
        cannot trip dedup)."""
        if len(args_list) > self.MAX_BATCH:
            return [
                EngineCmdReply(err=f"ErrBatchTooLarge:{self.MAX_BATCH}")
            ] * len(args_list)

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            replies = [None] * len(args_list)
            # Chains: a client's writes to ONE group must apply in
            # order (same-client dedup + same-key cross-op order).
            # FIFO backlog makes the whole chain safe to pipeline AT
            # ONCE: bindings land in submission order, and a leader-
            # change truncation can only fail a contiguous SUFFIX of
            # the chain.  The one hazard is resubmitting a failed
            # member while later members are still in flight (an
            # orphan sweep can fail out of order, and an inverted
            # rebinding lets the session table swallow the earlier
            # cmd) — so a chain with failures WAITS until every member
            # resolves, then resubmits from the first failure onward,
            # in order.  Chains to different groups pipeline freely.
            chains: dict = {}
            for i, a in enumerate(args_list):
                if a.op != "Get":
                    key = (a.client_id, route_group(a.key, self.G))
                    chains.setdefault(key, []).append((i, a))

            def submit(a):
                return self.kv.submit(
                    route_group(a.key, self.G),
                    KVOp(op=_OPCODE[a.op], key=a.key, value=a.value,
                         client_id=a.client_id, command_id=a.command_id),
                )

            tickets: dict = {}  # frame index -> latest ticket
            for members in chains.values():
                for i, a in members:
                    tickets[i] = submit(a)
            pending = set(chains)
            while pending and self.sched.now < deadline:
                progressed = False
                for qk in list(pending):
                    members = chains[qk]
                    if not all(tickets[i].done for i, _ in members):
                        continue
                    first_bad = next(
                        (k for k, (i, _) in enumerate(members)
                         if tickets[i].failed),
                        None,
                    )
                    if first_bad is None:
                        pending.discard(qk)
                        progressed = True
                        continue
                    # Resubmit the suffix in order (dedup makes any
                    # already-applied member a no-op resolve).
                    for i, a in members[first_bad:]:
                        tickets[i] = submit(a)
                if pending and not progressed:
                    yield 0.002
            tickets = {
                i: t for i, t in tickets.items()
                if t.done and not t.failed
            }
            # Durable mode: one group fsync covers the whole frame
            # (shared gate — see _await_frame_synced).
            synced_ok = set(tickets)
            yield from _await_frame_synced(
                self.sched, self._dur, self._write_seqs, synced_ok,
                args_list, deadline,
            )
            for i, a in enumerate(args_list):
                if a.op == "Get":
                    replies[i] = EngineCmdReply(
                        err=OK,
                        value=self.kv.get(
                            route_group(a.key, self.G), a.key
                        ).value,
                    )
                else:
                    ok = i in synced_ok
                    replies[i] = EngineCmdReply(
                        err=OK if ok else ERR_TIMEOUT,
                        value=tickets[i].value if ok else "",
                    )
            return replies

        return run()

    def command(self, args: EngineCmdArgs):
        g = route_group(args.key, self.G)
        if args.op == "Get":
            # ReadIndex fast read: linearizable at the applied
            # frontier, no log entry, immediate reply.
            t = self.kv.get(g, args.key)
            return EngineCmdReply(err=OK, value=t.value)

        # Write path: generator handler — yields let the pump advance.
        def run():
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                t = self.kv.submit(
                    g,
                    KVOp(
                        op=_OPCODE[args.op],
                        key=args.key,
                        value=args.value,
                        client_id=args.client_id,
                        command_id=args.command_id,
                    ),
                )
                sub_deadline = min(
                    self.sched.now + self.RESUBMIT_S, deadline
                )
                while not t.done and self.sched.now < sub_deadline:
                    yield 0.002
                if t.done and not t.failed:
                    # Ack only once the apply-time WAL record is
                    # fsynced (absent = pruned = already durable, or
                    # a duplicate applied before this incarnation).
                    while self._dur is not None:
                        seq = self._write_seqs.get(
                            (args.client_id, args.command_id)
                        )
                        if seq is None or self._dur.synced(seq):
                            break
                        yield 0.002
                    return EngineCmdReply(err=OK, value=t.value)
                # failed (evicted/orphaned) or wedged: resubmit under
                # the same (client_id, command_id) — dedup-safe.
            return EngineCmdReply(err=ERR_TIMEOUT)

        return run()


class EngineShardKVService:
    """``EngineShardKV.command``: the sharded engine service behind the
    same TCP front door.  Key→shard routing happens server-side against
    the replicated config; WRONG_GROUP during migration re-routes like
    the reference clerk (shardkv/client.go:68-129).

    **Fleet mode** (``peers`` given): this process hosts a subset of
    the global gid space and its ``BatchedShardKV`` migrates shards
    to/from peer processes over the network — ``remote_fetch`` becomes
    a ``pull_shard`` RPC to the owning peer, ``remote_delete`` a
    ``delete_shard`` RPC riding the peer's log (Challenge 1 across
    processes).  Ops for a gid hosted elsewhere answer ErrWrongGroup so
    the fleet clerk re-routes, exactly like a reference group answering
    for a shard it no longer owns."""

    RESUBMIT_S = 0.25
    DEADLINE_S = 5.0
    # Per-RPC bound on one migration fetch/delete attempt; the
    # orchestration sweep re-issues after a timeout.
    MIGRATE_RPC_S = 2.0

    def __init__(
        self,
        sched: RealtimeScheduler,
        skv,  # BatchedShardKV
        pump_interval: float = 0.002,
        ticks_per_pump: int = 2,
        peers: Optional[dict] = None,  # gid -> TcpClientEnd (remote owners)
        durability: Optional[EngineDurability] = None,
    ) -> None:
        self.sched = sched
        self.skv = skv
        self._interval = pump_interval
        self._ticks = ticks_per_pump
        self._stopped = False
        self.peers = dict(peers or {})
        self._fleet = bool(self.peers)
        self._dur = durability
        # seq of the WAL record covering each applied insert — the GC
        # gate below refuses to ask the old owner to delete until the
        # inserted blob (possibly the last copy) is fsynced here.
        self._insert_seqs: dict = {}
        # (client_id, command_id) -> WAL seq, apply-time (commit order)
        # — see EngineKVService; pruned once synced.
        self._write_seqs: dict = {}
        self._admin_seqs: dict = {}  # command_id -> WAL seq
        # seq of the WAL record covering each applied delete — the
        # delete_shard RPC reply gates on it being fsynced: the puller
        # confirms (and never re-asks) the moment we answer OK, so an
        # OK that could be lost to a crash would leave a BEPULLING slot
        # here that nothing ever clears, wedging config advance.
        self._delete_seqs: dict = {}
        if self._dur is not None:
            skv.on_insert = self._on_insert_applied
            skv.on_delete = self._on_delete_applied
            skv.on_confirm = self._on_confirm_applied
            # The committing gid travels in the record: recovery REDOES
            # the write into that gid's slot directly (see
            # _redo_client_op) — re-routing by the latest config would
            # drop a write acked at an old owner just before a config
            # change, and a peer that never pulled pre-crash would then
            # pull an empty slot.
            skv.on_write = lambda gid, op: self._write_seqs.__setitem__(
                (op.client_id, op.command_id),
                durability.log(("skv", gid, op.op, op.key, op.value,
                                op.client_id, op.command_id)),
            )
            skv.on_ctrl = lambda op: self._admin_seqs.__setitem__(
                op.command_id,
                durability.log(("admin", op.kind, op.arg, op.command_id)),
            )
        if self._fleet:
            self._fetches: dict = {}  # (gid, shard, num) -> Future
            self._deletes: dict = {}
            skv.remote_fetch = self._remote_fetch
            skv.remote_delete = self._remote_delete
        sched.call_soon(self._pump_loop)

    # -- durability hooks (apply-time, loop thread) -----------------------

    def _on_insert_applied(self, gid, shard, num, data, latest):
        self._insert_seqs[(gid, shard, num)] = self._dur.log(
            ("insert", gid, shard, num, dict(data), dict(latest))
        )

    def _on_delete_applied(self, gid, shard, num):
        # Replayed on restore so a stale BEPULLING slot can't survive an
        # older checkpoint and wedge config advance.
        self._delete_seqs[(gid, shard, num)] = self._dur.log(
            ("delete", gid, shard, num)
        )

    def _on_confirm_applied(self, gid, shard, num):
        # Replayed on restore so recovery re-applies GCING→SERVING
        # locally instead of re-running the GC handshake — during
        # replay the loop thread is busy replaying, so an RPC to a
        # remote old owner could never resolve and recovery would
        # wedge (the confirm only ever committed because the delete
        # leg already succeeded pre-crash).
        self._dur.log(("confirm", gid, shard, num))

    # -- fleet migration hooks (run on the loop thread, inside pump) ------

    def _remote_fetch(self, src_gid: int, shard: int, num: int):
        from ..engine.shardkv import OK as SK_OK

        key = (src_gid, shard, num)
        fut = self._fetches.get(key)
        if fut is None:
            end = self.peers.get(src_gid)
            if end is None:
                return None  # unroutable: keep retrying (config may fix)
            self._fetches[key] = self.sched.with_timeout(
                end.call("EngineShardKV.pull_shard", (src_gid, shard, num)),
                self.MIGRATE_RPC_S,
            )
            return None
        if not fut.done:
            return None
        del self._fetches[key]  # resolved: consume or retry next sweep
        reply = fut.value
        if (
            reply is None or reply is TIMEOUT
            or not isinstance(reply, tuple) or reply[0] != SK_OK
        ):
            return None  # dropped / not ready: the sweep re-issues
        return reply[1], reply[2]

    def _remote_delete(self, src_gid: int, shard: int, num: int):
        from ..engine.shardkv import OK as SK_OK

        # Durability gate: never tell the old owner to delete a shard
        # whose inserted copy isn't fsynced locally yet — between its
        # delete and our next checkpoint/WAL-sync, a crash would lose
        # the only copy.  One pump's group fsync clears this.
        if self._dur is not None:
            for (g, s, n), seq in self._insert_seqs.items():
                if s == shard and n == num and not self._dur.synced(seq):
                    return None
        key = (src_gid, shard, num)
        fut = self._deletes.get(key)
        if fut is None:
            end = self.peers.get(src_gid)
            if end is None:
                return True  # owner unknown everywhere: nothing to delete
            self._deletes[key] = self.sched.with_timeout(
                end.call("EngineShardKV.delete_shard", (src_gid, shard, num)),
                self.MIGRATE_RPC_S,
            )
            return None
        if not fut.done:
            return None
        del self._deletes[key]
        reply = fut.value
        if reply is None or reply is TIMEOUT or not isinstance(reply, tuple):
            return None  # dropped: re-issue next sweep
        return reply[0] == SK_OK  # False = ErrNotReady, re-asked later

    # -- fleet migration RPC handlers (the serving side of the hooks) -----

    def pull_shard(self, args):
        """Return ``(OK, data, latest)`` for a shard this process's old
        owner holds, once it has applied the puller's config number —
        the cross-process form of the in-process applied-state read
        (engine/shardkv.py _orchestrate step (b))."""
        from ..engine.shardkv import ERR_NOT_READY, ERR_WRONG_GROUP
        from ..engine.shardkv import OK as SK_OK

        src_gid, shard, num = args
        if src_gid not in self.skv.reps:
            return (ERR_WRONG_GROUP,)

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                rep = self.skv.reps[src_gid]
                if rep.cur.num >= num:
                    sh = rep.shards[shard]
                    return (SK_OK, dict(sh.data), dict(sh.latest))
                yield 0.01  # config catching up (the ErrNotReady gate)
            return (ERR_NOT_READY,)

        return run()

    def delete_shard(self, args):
        """Challenge-1 deletion on behalf of a remote puller: ride the
        local old owner's log (BatchedShardKV.delete_shard) and report
        the outcome."""
        from ..engine.shardkv import ERR_WRONG_GROUP
        from ..engine.shardkv import OK as SK_OK

        src_gid, shard, num = args
        if src_gid not in self.skv.reps:
            return (ERR_WRONG_GROUP,)

        def run():
            t = self.skv.delete_shard(src_gid, shard, num)
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                if t.done:
                    if t.failed:
                        return (ERR_TIMEOUT,)
                    if t.err != SK_OK:
                        return (t.err,)
                    # Gate the OK on the delete's WAL record being
                    # fsynced: the puller confirms on our OK and never
                    # re-asks, so losing the record to a crash would
                    # strand a BEPULLING slot here forever.  (Absent =
                    # pruned = already durable, or the slot was already
                    # clear and no record was written — also durable.)
                    # Deadline-bounded: a stalled fsync must surface as
                    # a timeout the puller retries, not a pinned
                    # generator.
                    while self._dur is not None:
                        seq = self._delete_seqs.get((src_gid, shard, num))
                        if seq is None or self._dur.synced(seq):
                            break
                        if self.sched.now >= deadline:
                            return (ERR_TIMEOUT,)
                        yield 0.002
                    return (SK_OK,)
                yield 0.005
            return (ERR_TIMEOUT,)

        return run()

    def config(self, args):
        """Latest committed config as ``(num, shards, groups)`` — the
        fleet clerk's routing source (shardctrler Query analog)."""
        cfg = self.skv.query_latest()
        return (
            cfg.num,
            list(cfg.shards),
            {g: list(v) for g, v in cfg.groups.items()},
        )

    def stop(self) -> None:
        self._stopped = True

    def final_checkpoint(self) -> bool:
        """Graceful-shutdown hook — see EngineKVService."""
        if self._dur is None:
            return False
        self._dur.checkpoint()
        return True

    def _pump_loop(self) -> None:
        if self._stopped:
            return
        self.skv.pump(self._ticks)
        if self._dur is not None:
            self._dur.after_pump()  # group fsync + periodic checkpoint
            for attr in ("_insert_seqs", "_write_seqs", "_admin_seqs",
                         "_delete_seqs"):
                seqs = getattr(self, attr)
                if seqs:
                    setattr(self, attr, {
                        k: v for k, v in seqs.items()
                        if not self._dur.synced(v)
                    })
        self.sched.call_after(self._interval, self._pump_loop)

    def replay_wal(self) -> int:
        """Recovery replay in two passes over the (commit-ordered) WAL:

        1. admin records rebuild the config history, in order, each
           retried until it actually commits (an eviction during
           recovery must not silently skip a config — the fleet's
           histories would diverge);
        2. insert/delete/confirm/client records re-ride the local logs
           in WAL order, with their apply-time gates making anything
           already in the checkpoint a no-op.

        PULLS and the live GC/confirm handshake are paused for the
        duration via ``skv.migration_paused`` — a pull completing
        mid-replay would copy a slot before its redo records landed,
        and a GC handshake whose old owner is a REMOTE peer can never
        resolve here (this method runs synchronously on the scheduler
        loop, so peer RPC replies are not serviced until it returns).
        Committed GCING→SERVING transitions are instead re-applied from
        the WAL's "confirm" records — the pre-crash handshake already
        ran its delete leg, so replaying the confirm alone is sound —
        which keeps config advance (needs all-SERVING) purely local.
        A slot whose confirm had not committed pre-crash stays GCING
        through replay; the post-replay pump loop re-runs its handshake
        live (idempotent at the peer)."""
        if self._dur is None:
            return 0
        recs = list(self._dur.replay_records())
        self.skv.migration_paused = True
        try:
            for rec in recs:
                if rec[0] == "admin":
                    self._replay_admin(rec[1], rec[2], rec[3])
            for rec in recs:
                kind = rec[0]
                if kind == "insert":
                    self._replay_insert(*rec[1:])
                elif kind == "delete":
                    _, gid, shard, num = rec
                    if gid in self.skv.reps:
                        # The apply gate answers ErrNotReady while the
                        # source rep is behind `num` — wait like the
                        # insert replay does, or the record would
                        # "succeed" as a no-op and the stale BEPULLING
                        # slot would wedge config advance forever.
                        self._await_config(gid, num, "a delete record")
                        self._retry_until_ok(
                            lambda: self.skv.delete_shard(gid, shard, num)
                        )
                elif kind == "confirm":
                    _, gid, shard, num = rec
                    if gid in self.skv.reps:
                        # Re-apply the committed GCING→SERVING flip
                        # locally (never the cross-process handshake —
                        # see the docstring).  Gated on the rep having
                        # reached config `num` like insert/delete.
                        self._await_config(gid, num, "a confirm record")
                        self._retry_until_ok(
                            lambda: self.skv.confirm_shard(gid, shard, num)
                        )
                elif kind == "skv":
                    if len(rec) != 7:
                        # Records from the pre-gid WAL format cannot be
                        # routed safely — refuse loudly rather than
                        # misparse (shifted fields) or silently drop.
                        raise RuntimeError(
                            "WAL 'skv' record has legacy format "
                            f"({len(rec)} fields); cannot replay"
                        )
                    _, gid, op, key, value, cid, cmd = rec
                    self._redo_client_op(gid, op, key, value, cid, cmd)
            # Drain: let every replayed proposal commit before serving.
            self._pump_until(lambda: False, max_rounds=50)
        finally:
            self.skv.migration_paused = False
        return len(recs)

    def _pump_until(self, cond, max_rounds: int = 4000) -> bool:
        for _ in range(max_rounds):
            if cond():
                return True
            self.skv.pump(2)
        return cond()

    def _await_config(self, gid: int, num: int, what: str) -> None:
        """Pump until rep ``gid`` has applied config ``num`` (replay
        gate shared by insert and delete records); a timeout is a real
        recovery failure, raised loudly."""
        rep = self.skv.reps[gid]
        if not self._pump_until(lambda: rep.cur.num >= num):
            raise RuntimeError(
                f"replay: rep {gid} never reached config {num} for "
                f"{what} (stuck at {rep.cur.num})"
            )

    def _retry_until_ok(self, propose, attempts: int = 50):
        """Propose-and-wait with eviction retry (leader churn during
        recovery must not drop a record).  A resolved-but-not-OK ticket
        (e.g. ErrNotReady) retries too — callers gate config catch-up
        beforehand, so non-OK can only be transient."""
        from ..engine.shardkv import OK as SK_OK

        for _ in range(attempts):
            t = propose()
            self._pump_until(lambda: t.done)
            if t.done and not t.failed and t.err == SK_OK:
                return t
        raise RuntimeError("WAL replay proposal did not commit")

    def _replay_admin(self, kind, payload, cmd) -> None:
        def propose():
            if kind == "move":
                return self.skv.move(*payload, command_id=cmd)
            return getattr(self.skv, kind)(payload, command_id=cmd)

        self._retry_until_ok(propose)

    def _replay_insert(self, gid, shard, num, data, latest) -> None:
        if gid not in self.skv.reps:
            return
        from ..engine.shardkv import ShardTicket, _InsertOp
        from ..services.shardkv import PULLING

        rep = self.skv.reps[gid]
        # The apply gate needs the rep AT config `num` and PULLING —
        # wait for orchestration to advance it there (earlier inserts/
        # configs already replayed), else the insert would silently
        # no-op and a later remote re-fetch could find the peer's copy
        # already GC'd.
        self._await_config(gid, num, "an insert record")
        if rep.cur.num != num or rep.shards[shard].state != PULLING:
            return  # checkpoint already contains this insert's effects

        def propose():
            t = ShardTicket(group=gid)
            self.skv.driver.start(
                self.skv._g2l[gid],
                _InsertOp(config_num=num, shard=shard, data=dict(data),
                          latest=dict(latest), ticket=t),
            )
            return t

        self._retry_until_ok(propose)

    def _redo_client_op(self, gid, op, key, value, cid, cmd) -> None:
        """REDO one acknowledged write into the slot of the gid that
        committed it, directly on the host state — the standard
        redo-log discipline.  Routing/ownership gates don't apply to
        redo: the op already linearized pre-crash; in particular a
        write acked just before its shard went BEPULLING must land in
        that (now non-serving) slot so a peer's later pull sees it, and
        a subsequent WAL delete record clears it in order."""
        from ..services.shardkv import key2shard

        rep = self.skv.reps.get(gid)
        if rep is None:
            return  # record from a gid this process no longer hosts
        sh = rep.shards[key2shard(key)]
        if sh.latest.get(cid, -1) >= cmd:
            return  # already in the checkpoint / an earlier redo
        if op == "Put":
            sh.data[key] = value
        elif op == "Append":
            sh.data[key] = sh.data.get(key, "") + value
        sh.latest[cid] = cmd

    # Largest multi-op frame one RPC may carry (see EngineKVService).
    MAX_BATCH = 1024

    def batch(self, args_list):
        """Multi-op frame for the SHARDED service.  Chains key on
        (client, shard) — a shard's dedup table travels with it and
        same-key ops share a shard — and run STRICTLY one op in flight
        each, the reference clerk's serial discipline
        (shardkv/client.go:68-129): pipelining within a chain is
        unsafe here because an away-and-back shard migration can let a
        later op apply while an earlier one bounced ErrWrongGroup, and
        the earlier op's retry then dedup-swallows into a false OK.
        The frame's parallelism comes from chains to DIFFERENT shards
        pipelining freely.  In fleet mode, ops whose shard a peer
        process owns answer ErrWrongGroup per-op so the fleet clerk
        re-frames them to the owner."""
        from ..engine.shardkv import ERR_WRONG_GROUP
        from ..services.shardkv import key2shard

        if len(args_list) > self.MAX_BATCH:
            return [
                EngineCmdReply(err=f"ErrBatchTooLarge:{self.MAX_BATCH}")
            ] * len(args_list)

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            replies = [None] * len(args_list)
            chains: dict = {}
            for i, a in enumerate(args_list):
                if a.op == "Get":
                    continue
                chains.setdefault(
                    (a.client_id, key2shard(a.key)), []
                ).append(i)

            def submit(a):
                cfg = self.skv.query_latest()
                gid = cfg.shards[key2shard(a.key)]
                if gid not in self.skv.reps:
                    return None  # peer-owned (or unassigned) shard
                return self.skv.submit(
                    gid, a.op, a.key, a.value,
                    client_id=a.client_id, command_id=a.command_id,
                )

            tickets: dict = {}   # frame idx -> resolved-OK ticket
            wrong: set = set()   # frame idx -> answer ErrWrongGroup
            heads: dict = {}     # chain -> (frame idx, live ticket)
            cursor = {qk: 0 for qk in chains}
            pending = set(chains)
            while pending and self.sched.now < deadline:
                progressed = False
                for qk in list(pending):
                    members = chains[qk]
                    if qk not in heads:
                        i = members[cursor[qk]]
                        t = submit(args_list[i])
                        if t is None:
                            if self._fleet:
                                # Peer-owned: the whole remaining chain
                                # belongs to that peer — punt it.
                                for j in members[cursor[qk]:]:
                                    wrong.add(j)
                                pending.discard(qk)
                                progressed = True
                            continue  # non-fleet: config moving; wait
                        heads[qk] = (i, t)
                        continue
                    i, t = heads[qk]
                    if not t.done:
                        continue
                    del heads[qk]
                    if t.failed or t.err == ERR_WRONG_GROUP:
                        continue  # resubmit next round (dedup-safe)
                    tickets[i] = t
                    cursor[qk] += 1
                    progressed = True
                    if cursor[qk] >= len(members):
                        pending.discard(qk)
                if pending and not progressed:
                    yield 0.002
            # Durable frame ack (shared gate — see _await_frame_synced).
            ok = {
                i for i, t in tickets.items()
                if t.done and not t.failed and t.err == OK
            }
            yield from _await_frame_synced(
                self.sched, self._dur, self._write_seqs, ok,
                args_list, deadline,
            )
            for i, a in enumerate(args_list):
                if a.op == "Get":
                    t = self.skv.get_fast(a.key)
                    if t.err == ERR_WRONG_GROUP:
                        replies[i] = EngineCmdReply(err=ERR_WRONG_GROUP)
                    else:
                        replies[i] = EngineCmdReply(
                            err=OK, value=t.value if t.err == OK else ""
                        )
                elif i in wrong:
                    replies[i] = EngineCmdReply(err=ERR_WRONG_GROUP)
                elif i in ok:
                    replies[i] = EngineCmdReply(
                        err=OK, value=tickets[i].value
                    )
                else:
                    replies[i] = EngineCmdReply(err=ERR_TIMEOUT)
            return replies

        return run()

    def command(self, args: EngineCmdArgs):
        from ..engine.shardkv import ERR_WRONG_GROUP
        from ..services.shardkv import key2shard

        if args.op == "Get":
            # ReadIndex fast read (BatchedShardKV.get_fast): no log
            # entry, gated on serving-shard ownership exactly like the
            # logged path; ErrWrongGroup during migration pumps and
            # retries like any clerk op.
            def run_get():
                deadline = self.sched.now + self.DEADLINE_S
                while self.sched.now < deadline:
                    t = self.skv.get_fast(args.key)
                    if t.err == ERR_WRONG_GROUP:
                        # Fleet: the owner is (probably) another
                        # process — answer so the clerk re-routes.
                        if self._fleet:
                            return EngineCmdReply(err=ERR_WRONG_GROUP)
                        yield 0.01  # config moving; shard not serving here
                        continue
                    value = t.value if t.err == OK else ""
                    return EngineCmdReply(err=OK, value=value)
                return EngineCmdReply(err=ERR_TIMEOUT)

            return run_get()

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                cfg = self.skv.query_latest()
                gid = cfg.shards[key2shard(args.key)]
                if gid not in self.skv.reps:
                    if self._fleet:
                        # Hosted by a peer process: tell the clerk.
                        return EngineCmdReply(err=ERR_WRONG_GROUP)
                    yield 0.01  # shard unassigned; config still moving
                    continue
                t = self.skv.submit(
                    gid, args.op, args.key, args.value,
                    client_id=args.client_id, command_id=args.command_id,
                )
                sub_deadline = min(
                    self.sched.now + self.RESUBMIT_S, deadline
                )
                while not t.done and self.sched.now < sub_deadline:
                    yield 0.002
                if not t.done or t.failed or t.err == ERR_WRONG_GROUP:
                    continue  # resubmit / re-route; dedup-safe
                # Ack gates on the apply-time WAL record being fsynced
                # (absent = pruned/duplicate = already durable).
                while self._dur is not None:
                    seq = self._write_seqs.get(
                        (args.client_id, args.command_id)
                    )
                    if seq is None or self._dur.synced(seq):
                        break
                    yield 0.002
                return EngineCmdReply(err=OK, value=t.value)
            return EngineCmdReply(err=ERR_TIMEOUT)

        return run()

    ADMIN_OPS = ("join", "leave", "move")

    def admin(self, args):
        """Config administration: args = (kind, payload[, command_id])
        with kind in ADMIN_OPS — a network-supplied string must never
        getattr into arbitrary methods.  The optional command_id makes
        retries exactly-once through the ctrler dedup table; a FLEET
        admin MUST pass one (a duplicate apply would fork the config
        histories' numbering across processes and wedge migration)."""
        kind, payload = args[0], args[1]
        cmd = args[2] if len(args) > 2 else None
        if kind not in self.ADMIN_OPS:
            return EngineCmdReply(err=f"ErrBadAdminOp:{kind}")

        def run():
            # join/leave take their payload whole (a gid list / mapping);
            # move takes (shard, gid) as two positionals.
            if kind == "move":
                t = self.skv.move(*payload, command_id=cmd)
            else:
                t = getattr(self.skv, kind)(payload, command_id=cmd)
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                if t.done:
                    if t.failed:
                        return EngineCmdReply(err=ERR_TIMEOUT)
                    # Ack gates on the apply-time ("admin", ...) WAL
                    # record (logged by the on_ctrl hook in commit
                    # order) being fsynced.
                    while self._dur is not None:
                        seq = self._admin_seqs.get(t.command_id)
                        if seq is None or self._dur.synced(seq):
                            break
                        yield 0.002
                    return EngineCmdReply(err=OK)
                yield 0.005
            return EngineCmdReply(err=ERR_TIMEOUT)

        return run()


class EngineClerk:
    """Generator-coroutine client of an engine KV/shard server —
    retry-until-answer with session dedup, mirroring the reference
    clerk loop (kvraft/client.go:47-71) against the single front door."""

    # Clerks are created from concurrent threads (one per blocking
    # client); the counter allocation must be atomic or two clerks
    # share a client_id and dedup silently drops one's writes.
    _next = itertools.count(1)

    def __init__(self, sched, end, service: str = "EngineKV") -> None:
        self.sched = sched
        self.end = end
        self.service = service
        self.client_id = unique_client_id(next(EngineClerk._next))
        self.command_id = 0

    def _command(self, op: str, key: str, value: str = ""):
        if op != "Get":
            self.command_id += 1
        args = EngineCmdArgs(
            op=op, key=key, value=value,
            client_id=self.client_id, command_id=self.command_id,
        )
        while True:
            fut: Future = self.end.call(f"{self.service}.command", args)
            reply = yield self.sched.with_timeout(fut, 3.5)
            if (
                reply is None
                or reply is TIMEOUT
                or reply.err != OK
            ):
                continue  # lost/timed out/old leader: retry (dedup-safe)
            return reply.value

    def get(self, key: str):
        return self._command("Get", key)

    def put(self, key: str, value: str):
        return self._command("Put", key, value)

    def append(self, key: str, value: str):
        return self._command("Append", key, value)


class PipelinedClerk(EngineClerk):
    """Clerk that ships a whole batch of ops as ONE ``batch`` frame —
    the reference clerk's serial loop (kvraft/client.go:47-71) widened
    for the engine's coalescing front door: the server applies the
    frame in one pump, so per-op RPC overhead amortizes ~frame-fold.
    Whole-frame retry is dedup-safe (same client/command ids)."""

    # Mirror of EngineKVService.MAX_BATCH: oversized op lists split
    # into compliant frames client-side (the server's rejection is
    # permanent, so retrying an oversized frame would spin forever).
    MAX_FRAME = 1024

    def run_batch(self, ops):
        """ops = [(op, key, value), ...] → list of values (Gets) in
        order.  Generator (spawn on the scheduler)."""
        out = []
        for s in range(0, len(ops), self.MAX_FRAME):
            part = yield from self._one_frame(ops[s:s + self.MAX_FRAME])
            out.extend(part)
        return out

    def _one_frame(self, ops):
        frame = []
        for op, key, value in ops:
            if op != "Get":
                self.command_id += 1
            frame.append(
                EngineCmdArgs(
                    op=op, key=key, value=value,
                    client_id=self.client_id,
                    command_id=self.command_id,
                )
            )
        while True:
            fut: Future = self.end.call(f"{self.service}.batch", frame)
            reply = yield self.sched.with_timeout(fut, 10.0)
            if reply is not None and reply is not TIMEOUT and any(
                r.err.startswith("ErrBatchTooLarge") for r in reply
            ):
                # Permanent: the server's cap shrank below ours.
                raise ValueError(reply[0].err)
            if (
                reply is None
                or reply is TIMEOUT
                or any(r.err != OK for r in reply)
            ):
                continue  # lost/partial frame: retry whole (dedup-safe)
            return [r.value for r in reply]


class EngineShardNetClerk(EngineClerk):
    def __init__(self, sched, end) -> None:
        super().__init__(sched, end, service="EngineShardKV")


class EngineFleetClerk:
    """Clerk for a fleet of engine shard servers: route key→shard→gid→
    process from the replicated config, re-query and re-route on
    ErrWrongGroup — the reference clerk loop (shardkv/client.go:68-129)
    where each "group" is a chip-owning process."""

    def __init__(self, sched, ends_by_gid: dict) -> None:
        self.sched = sched
        self.ends = dict(ends_by_gid)  # gid -> TcpClientEnd
        self._all = list(dict.fromkeys(self.ends.values()))
        self.client_id = unique_client_id(next(EngineClerk._next))
        self.command_id = 0
        self._cfg = None  # cached (num, shards, groups)

    def _refresh_config(self):
        while True:
            for end in self._all:
                fut = end.call("EngineShardKV.config", ())
                reply = yield self.sched.with_timeout(fut, 2.0)
                if reply is not None and reply is not TIMEOUT:
                    self._cfg = reply
                    return reply
            yield self.sched.sleep(0.05)

    def _command(self, op: str, key: str, value: str = ""):
        from ..engine.shardkv import ERR_WRONG_GROUP
        from ..services.shardkv import key2shard

        if op != "Get":
            self.command_id += 1
        args = EngineCmdArgs(
            op=op, key=key, value=value,
            client_id=self.client_id, command_id=self.command_id,
        )
        while True:
            cfg = self._cfg
            if cfg is None:
                cfg = yield from self._refresh_config()
            gid = cfg[1][key2shard(key)]
            end = self.ends.get(gid)
            if end is None:  # unassigned shard / unknown gid: re-query
                yield self.sched.sleep(0.05)
                self._cfg = None
                continue
            fut = end.call("EngineShardKV.command", args)
            reply = yield self.sched.with_timeout(fut, 3.5)
            if reply is None or reply is TIMEOUT:
                self._cfg = None
                continue  # dropped / wedged: re-route and retry
            if reply.err == OK:
                return reply.value
            if reply.err == ERR_WRONG_GROUP:
                self._cfg = None  # stale routing: re-query the config
            yield self.sched.sleep(0.02)

    def get(self, key: str):
        return self._command("Get", key)

    def put(self, key: str, value: str):
        return self._command("Put", key, value)

    def append(self, key: str, value: str):
        return self._command("Append", key, value)


class PipelinedFleetClerk(EngineFleetClerk):
    """Multi-op frames over a sharded fleet: each round partitions the
    remaining ops by owning process (key→shard→gid→end from the
    replicated config) and ships one ``batch`` frame per process; ops
    answered ErrWrongGroup (shard mid-migration / stale routing)
    re-frame to the new owner next round.  Order safety: a frame's
    chains fully resolve server-side before it answers, so re-framed
    retries can never interleave with in-flight ops."""

    # Ops per sequential WINDOW.  An oversized batch must NOT split
    # into concurrently-in-flight frames: a (client, shard) chain
    # spanning two live frames breaks the serial-chain discipline the
    # server's dedup safety rests on (op N+1 applying while op N is
    # unresolved lets N's retry dedup-swallow into a false OK).  Each
    # window fully resolves before the next ships.
    MAX_FRAME = 1024

    def run_batch(self, ops):
        """ops = [(op, key, value), ...] → list of values in order."""
        out = []
        for s in range(0, len(ops), self.MAX_FRAME):
            part = yield from self._one_window(ops[s:s + self.MAX_FRAME])
            out.extend(part)
        return out

    def _one_window(self, ops):
        from ..services.shardkv import key2shard

        frame_args = []
        for op, key, value in ops:
            if op != "Get":
                self.command_id += 1
            frame_args.append(
                EngineCmdArgs(
                    op=op, key=key, value=value,
                    client_id=self.client_id,
                    command_id=self.command_id,
                )
            )
        results = [None] * len(ops)
        todo = list(range(len(ops)))
        while todo:
            cfg = self._cfg
            if cfg is None:
                cfg = yield from self._refresh_config()
            by_end: dict = {}
            unrouted = []
            for i in todo:
                gid = cfg[1][key2shard(frame_args[i].key)]
                end = self.ends.get(gid)
                if end is None:
                    unrouted.append(i)
                else:
                    by_end.setdefault(end, []).append(i)
            retry = list(unrouted)
            # Dispatch every process's frame FIRST, then collect:
            # wall-clock is the slowest frame, not the sum.  (Frames
            # are per-process partitions of one ≤MAX_FRAME window, so
            # none can exceed the server's cap.)
            flights = [
                (idxs, end.call(
                    "EngineShardKV.batch",
                    [frame_args[i] for i in idxs],
                ))
                for end, idxs in by_end.items()
            ]
            for part, fut in flights:
                reply = yield self.sched.with_timeout(fut, 10.0)
                if reply is None or reply is TIMEOUT:
                    retry.extend(part)
                    continue
                if any(
                    r.err.startswith("ErrBatchTooLarge") for r in reply
                ):
                    # Permanent: the server's cap shrank below ours.
                    raise ValueError(reply[0].err)
                for i, r in zip(part, reply):
                    if r.err == OK:
                        results[i] = r.value
                    else:
                        retry.append(i)
            todo = sorted(retry)
            if todo:
                self._cfg = None  # routing moved: re-query
                yield self.sched.sleep(0.02)
        return results


def serve_engine_kv(
    port: int,
    G: int = 64,
    host: str = "127.0.0.1",
    seed: int = 0,
    record_groups: Optional[Sequence[int]] = None,
    data_dir: Optional[str] = None,
    checkpoint_every_s: float = 30.0,
    mesh_devices: int = 0,
) -> RpcNode:
    """Bring up the chip-owning engine KV server process: one
    EngineDriver (G groups), a BatchedKV, the pump loop, and a
    listening RpcNode.  Returns the node (caller keeps the process
    alive).

    With ``data_dir``, the server is DURABLE: periodic atomic
    checkpoints + a write-ahead log of acked ops (see EngineDurability)
    — a kill -9'd process restarted on the same dir recovers every
    acknowledged write.

    With ``mesh_devices`` > 0, the engine runs the shard_map tick over
    that many local chips (G must divide evenly) — the multi-chip
    production path; checkpoints restore back onto the same-size
    mesh."""
    node = RpcNode(listen=True, host=host, port=port)
    sched = node.sched

    def build():
        mesh = _make_mesh(mesh_devices) if mesh_devices else None
        driver = None
        if data_dir:
            ckpt = os.path.join(data_dir, "engine.ckpt")
            if os.path.exists(ckpt):
                driver = EngineDriver.restore(ckpt, mesh=mesh)
        if driver is not None:
            kv = BatchedKV(driver, record_groups=list(record_groups or []))
            blob = driver.restored_extra.get("service")
            if blob:
                kv.load_state_dict(blob)
        else:
            cfg = EngineConfig(G=G, P=3, L=64, E=8, INGEST=8)
            driver = EngineDriver(cfg, seed=seed, mesh=mesh)
            kv = BatchedKV(driver, record_groups=list(record_groups or []))
            driver.run_until_quiet_leaders(2000)
        # Warm-up BEFORE the readiness line: elect leaders and compile
        # both tick variants (quiet + loaded).  The first jit compile
        # takes tens of seconds and runs on the scheduler loop — doing
        # it lazily would starve RPC dispatch and time out every early
        # client (observed: all first ops stall ~10s on CPU).  A
        # restored process recompiles too (fresh interpreter).
        driver.start(0, (KVOp(op=OP_GET, key=""), None))
        for _ in range(8):
            kv.pump(1)
        dur = (
            EngineDurability(data_dir, driver, kv,
                             checkpoint_every_s=checkpoint_every_s)
            if data_dir else None
        )
        if node.tracer is not None:
            driver.tracer = node.tracer  # ticks + RPCs on one timeline
        svc = EngineKVService(sched, kv, durability=dur)
        if dur is not None:
            svc.replay_wal()  # recovery completes before readiness
            # Fold the replayed state into a fresh checkpoint and
            # rotate: bounds the next recovery, and discards the
            # duplicate records the replay's own apply hooks appended.
            dur.checkpoint()
        return svc

    svc = sched.run_call(build, timeout=600.0)
    node.add_service("EngineKV", svc)
    node.engine_service = svc  # keep reachable for introspection
    return node


def serve_engine_shardkv(
    port: int,
    G: int = 4,
    host: str = "127.0.0.1",
    seed: int = 0,
    join_gids: Optional[Sequence[int]] = None,
    gids: Optional[Sequence[int]] = None,
    peer_addrs: Optional[dict] = None,  # gid -> (host, port) of the owner
    data_dir: Optional[str] = None,
    checkpoint_every_s: float = 30.0,
    mesh_devices: int = 0,
) -> RpcNode:
    """The sharded engine behind TCP: BatchedShardKV (replicated config
    + per-shard migration pipeline) on one chip-owning process.

    Fleet mode: pass ``gids`` (the global gids THIS process hosts; the
    local engine is sized ``len(gids)+1``) and ``peer_addrs`` (owner
    address for every remotely hosted gid) — shard migration then rides
    ``pull_shard``/``delete_shard`` RPCs between processes.

    With ``data_dir`` the process is DURABLE (checkpoint + WAL of
    client writes, admin ops, and migration inserts/deletes); a
    restarted process recovers every acknowledged op, and in a fleet
    the GC handshake is gated so a migrated-in blob is never the only
    un-fsynced copy."""
    from ..engine.shardkv import BatchedShardKV

    node = RpcNode(listen=True, host=host, port=port)
    sched = node.sched
    local_gids = list(gids) if gids is not None else None
    G_local = (len(local_gids) + 1) if local_gids is not None else G
    peers = {
        g: node.client_end(h, p)
        for g, (h, p) in (peer_addrs or {}).items()
        if local_gids is None or g not in local_gids
    }

    def build():
        mesh = _make_mesh(mesh_devices) if mesh_devices else None
        driver = None
        if data_dir:
            ckpt = os.path.join(data_dir, "engine.ckpt")
            if os.path.exists(ckpt):
                driver = EngineDriver.restore(ckpt, mesh=mesh)
        restored = driver is not None
        if not restored:
            cfg = EngineConfig(G=G_local, P=3, L=64, E=8, INGEST=8)
            driver = EngineDriver(cfg, seed=seed, mesh=mesh)
            # Warm-up before readiness (see serve_engine_kv):
            # elections + both tick compiles happen here, not under
            # client traffic.
            ok = driver.run_until_quiet_leaders(2000)
            assert ok, "engine groups failed to elect"
        skv = BatchedShardKV(driver, gids=local_gids)
        if restored:
            blob = driver.restored_extra.get("service")
            if blob:
                skv.load_state_dict(blob)
        # Warm the LOADED tick variant before the readiness line (the
        # jit compile takes tens of seconds on CPU and would otherwise
        # land under the first admin/client RPC and time it out).  A
        # None payload is the "binding lost" no-op: it exercises the
        # ingest path without touching config history — essential in
        # fleet mode, where every process's history must stay aligned.
        skv.driver.start(0, None)
        skv.pump(8)
        if not restored:
            # A restored process's config history lives in its
            # checkpoint + WAL — re-running the bootstrap joins would
            # allocate fresh ctrler ids the dedup table can't absorb
            # and append a spurious config per restart.
            for gid in join_gids or []:
                skv.admin_sync("join", [gid])
        dur = (
            EngineDurability(data_dir, driver, skv,
                             checkpoint_every_s=checkpoint_every_s)
            if data_dir else None
        )
        if node.tracer is not None:
            driver.tracer = node.tracer  # ticks + RPCs on one timeline
        svc = EngineShardKVService(sched, skv, peers=peers, durability=dur)
        if dur is not None:
            svc.replay_wal()  # recovery completes before readiness
            dur.checkpoint()  # fold replay into a fresh checkpoint
        return svc

    svc = sched.run_call(build, timeout=600.0)
    node.add_service("EngineShardKV", svc)
    node.engine_service = svc
    return node
