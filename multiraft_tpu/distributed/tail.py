"""Per-request lifecycle exemplars with tail-based sampling.

Every aggregate the observability plane keeps (stage histograms, CPU
segment clocks, flight records) answers "how is the fleet doing" —
none can answer "which requests make up the p99.9 and where did EACH
of them wait".  This module is the tail microscope ("The Tail at
Scale", Dean & Barroso; Dapper-style tail sampling): the serving path
accumulates a compact per-request lifecycle record (the StageClock's
stage vector split into queue WAITS vs work, plus admission outcome,
engine tick id, and ambient queue context at completion), and a
bounded per-process :class:`TailStore` retains:

* **guaranteed**: every request whose total exceeds ``MRT_TAIL_SLO_MS``
  (up to ``MRT_TAIL_SLO_CAP``; overflow is counted, never silently
  dropped from the books);
* **windowed top-k**: the ``MRT_TAIL_TOPK`` slowest since the last
  drain, even when under the SLO (the tail is interesting relative to
  its window, not only to a fixed bound);
* **reservoir**: a uniform ``MRT_TAIL_RESERVOIR``-sized sample of
  ALL completed requests — the baseline the outliers are read against.

Drain semantics mirror ``Obs.profile``: ``Obs.tail`` (chaos-exempt,
loop-thread) drains-on-read by default so fleet scrapes window
naturally; ``{"reset": false}`` peeks non-destructively (bundles use
this — evidence collection must not consume the evidence).

Crash path: retained over-SLO completions and every new window-slowest
are breadcrumbed as TAIL flight records (code=dominant-wait,
a=total_us, b=wait_us, c=tick_id, tag=rid), so a SIGKILL'd process's
ring still names its slowest request and the queue it died waiting in
(past the SLO cap only new-slowest rings — at saturation a record per
completion would just wrap the ring at flush-stage CPU cost).

The queue-wait vocabulary (``WAITS``) is shared verbatim with the
stage clocks: wire / dispatch / pump / flush are the parked states,
handler / engine / ack are work.  ``dominant_wait`` of an exemplar is
the largest of the four waits — the attribution loadcurve and the
postmortem doctor report.
"""

from __future__ import annotations

import heapq
import random
import threading
from typing import Any, Dict, List, Optional

from ..utils.knobs import knob_bool, knob_float, knob_int
from . import flightrec

__all__ = [
    "WAITS",
    "WORK",
    "TailStore",
    "tail_enabled",
    "dominant_wait",
    "exemplar_from_clock",
    "merge_drains",
]

# Queue-wait vs work split of the StageClock vocabulary (observe.py
# STAGES plus the pump wait the engine services contribute).  Codes
# come from flightrec.TAIL_WAIT_CODES so ring records and live drains
# agree.
WAITS = ("wire", "dispatch", "pump", "flush")
WORK = ("handler", "engine", "ack")

_TAIL = knob_bool("MRT_TAIL")


def tail_enabled() -> bool:
    """Process-wide kill switch, read once at import (the A/B lever
    for the overhead benchmark, like ``stageclock_enabled``)."""
    return _TAIL


def dominant_wait(ex: Dict[str, Any]) -> str:
    """The wait stage this exemplar parked longest in; ``"work"`` when
    every wait is zero (a purely CPU-bound request has no queue to
    blame)."""
    waits = ex.get("waits") or {}
    best, best_v = "work", 0.0
    for w in WAITS:
        v = waits.get(w, 0.0) or 0.0
        if v > best_v:
            best, best_v = w, v
    return best


def exemplar_from_clock(
    st: Any,
    outcome: str = "ok",
    ambient: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Finalize a completed StageClock (flush already folded) into a
    codec-safe exemplar dict.

    The engine stage straddles a queue: the proposal is parked until
    the next fused pump tick picks it up (``st.pump_wait_s``, stamped
    by the engine services), then does real device work.  The split
    here subtracts the pump wait from the engine stage so ``waits``
    and ``work`` partition the lifecycle instead of double counting.
    """
    vec = st.vec or {}
    engine = max(0.0, vec.get("engine", 0.0))
    pump = max(0.0, st.pump_wait_s)
    if engine:
        pump = min(pump, engine)
    waits = {
        "wire": max(0.0, vec.get("wire", 0.0)),
        "dispatch": max(0.0, vec.get("dispatch", 0.0)),
        "pump": pump,
        "flush": max(0.0, vec.get("flush", 0.0)),
    }
    ex: Dict[str, Any] = {
        "rid": st.rid,
        "outcome": outcome,
        "total_s": round(max(0.0, st.last - st.t0), 6),
        "tick": st.tick,
        "stages": {k: round(v, 6) for k, v in vec.items()},
        "waits": {k: round(v, 6) for k, v in waits.items()},
        "work": {
            "handler": round(max(0.0, vec.get("handler", 0.0)), 6),
            "engine": round(max(0.0, engine - pump), 6),
            "ack": round(max(0.0, vec.get("ack", 0.0)), 6),
        },
    }
    if ambient:
        ex["ambient"] = ambient
    return ex


class TailStore:
    """Bounded per-process exemplar store; ``offer`` runs on the
    node's loop thread, ``drain``/``snapshot`` via the Obs verb (also
    loop-thread) — the lock exists for direct test access and the
    blocking facades."""

    def __init__(
        self,
        slo_ms: Optional[float] = None,
        reservoir: Optional[int] = None,
        topk: Optional[int] = None,
        slo_cap: Optional[int] = None,
        frec: Optional[Any] = None,
        seed: int = 0x7A11,
    ) -> None:
        self.slo_s = (slo_ms if slo_ms is not None
                      else knob_float("MRT_TAIL_SLO_MS")) / 1e3
        self.reservoir_n = (reservoir if reservoir is not None
                            else knob_int("MRT_TAIL_RESERVOIR"))
        self.topk_n = topk if topk is not None else knob_int("MRT_TAIL_TOPK")
        self.slo_cap = (slo_cap if slo_cap is not None
                        else knob_int("MRT_TAIL_SLO_CAP"))
        self.frec = frec
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._slo: List[Dict[str, Any]] = []
        # Min-heap of (total_s, tiebreak, exemplar): the k slowest of
        # the window; the tiebreak keeps heapq off dict comparisons.
        self._topk: List[Any] = []
        self._res: List[Dict[str, Any]] = []
        self._seen = 0          # completions this window
        self._seen_total = 0    # completions since creation
        self._over_slo = 0      # over-SLO this window
        self._dropped_slo = 0   # over-SLO past the cap this window
        self._slowest_s = 0.0   # slowest total this window (breadcrumb)
        self._tie = 0

    # -- capture ------------------------------------------------------------

    def offer(self, ex: Dict[str, Any]) -> None:
        """Submit one completed request's lifecycle record.  ``ex`` is
        a plain dict (already codec-safe): at least ``rid``, ``total_s``
        and ``waits``; stage/ambient fields ride along untouched."""
        self.offer_deferred(float(ex.get("total_s") or 0.0), lambda: ex)

    def offer_deferred(self, total_s: float, build: Any) -> None:
        """Like :meth:`offer`, but the exemplar dict is only
        materialized (``build()``) when this completion will actually
        be retained or breadcrumbed — retention is decided from the
        total alone.  The serve path's flush loop uses this: at total
        saturation nearly every completion is over-SLO and past the
        cap, and it must cost one lock and three counter bumps, not a
        three-dict lifecycle record that is immediately dropped.

        Breadcrumb discipline (outside the lock — the ring has its
        own): retained over-SLO offers and every new window-slowest
        ring; a capped over-SLO offer that is not the new slowest does
        not, so saturation cannot turn the flush stage into a
        ring-writing loop while a SIGKILL'd process still names its
        slowest request."""
        total = float(total_s)
        ex = None
        stored_over = False
        with self._lock:
            self._seen += 1
            self._seen_total += 1
            over = total > self.slo_s
            if over:
                self._over_slo += 1
                if len(self._slo) < self.slo_cap:
                    ex = build()
                    self._slo.append(ex)
                    stored_over = True
                else:
                    self._dropped_slo += 1
            else:
                self._tie += 1
                want_topk = (len(self._topk) < self.topk_n
                             or (self._topk and total > self._topk[0][0]))
                if len(self._res) < self.reservoir_n:
                    res_j = len(self._res)
                elif self.reservoir_n > 0:
                    j = self._rng.randrange(self._seen)
                    res_j = j if j < self.reservoir_n else -1
                else:
                    res_j = -1
                if want_topk or res_j >= 0:
                    ex = build()
                if want_topk:
                    if len(self._topk) < self.topk_n:
                        heapq.heappush(self._topk, (total, self._tie, ex))
                    else:
                        heapq.heapreplace(self._topk,
                                          (total, self._tie, ex))
                if res_j >= 0:
                    if res_j == len(self._res):
                        self._res.append(ex)
                    else:
                        self._res[res_j] = ex
            new_slowest = total > self._slowest_s
            if new_slowest:
                self._slowest_s = total
        if self.frec is not None and (stored_over or new_slowest):
            if ex is None:
                ex = build()
            w = dominant_wait(ex)
            wait_s = (ex.get("waits") or {}).get(w, 0.0) or 0.0
            self.frec.record(
                flightrec.TAIL,
                code=flightrec.TAIL_WAIT_CODES.get(w, 0),
                a=int(total * 1e6), b=int(wait_s * 1e6),
                c=int(ex.get("tick") or 0),
                tag=str(ex.get("rid") or ""),
            )

    # -- read side ----------------------------------------------------------

    def _view(self) -> Dict[str, Any]:
        return {
            "slo_ms": round(self.slo_s * 1e3, 3),
            "seen": self._seen,
            "seen_total": self._seen_total,
            "over_slo": self._over_slo,
            "dropped_slo": self._dropped_slo,
            "slo": list(self._slo),
            "topk": [e for _, _, e in sorted(self._topk, reverse=True)],
            "reservoir": list(self._res),
        }

    def snapshot(self) -> Dict[str, Any]:
        """Non-destructive view (bundle collection peeks with this)."""
        with self._lock:
            return self._view()

    def drain(self) -> Dict[str, Any]:
        """Return the window's exemplars and reset the window — the
        fleet-scrape verb, mirroring the profiler's drain-on-read."""
        with self._lock:
            out = self._view()
            self._slo = []
            self._topk = []
            self._res = []
            self._seen = 0
            self._over_slo = 0
            self._dropped_slo = 0
            self._slowest_s = 0.0
            return out


def merge_drains(drains: List[Optional[Dict[str, Any]]]) -> Dict[str, Any]:
    """Fold per-process ``Obs.tail`` payloads (the ``"tail"`` member)
    into one fleet view: exemplar lists concatenated (slowest first),
    counters summed.  ``None``/missing entries (dead processes, tail
    plane off) are skipped."""
    out: Dict[str, Any] = {
        "seen": 0, "over_slo": 0, "dropped_slo": 0,
        "slo": [], "topk": [], "reservoir": [],
    }
    for d in drains:
        if not isinstance(d, dict):
            continue
        out["seen"] += int(d.get("seen") or 0)
        out["over_slo"] += int(d.get("over_slo") or 0)
        out["dropped_slo"] += int(d.get("dropped_slo") or 0)
        for k in ("slo", "topk", "reservoir"):
            out[k].extend(d.get(k) or [])
    for k in ("slo", "topk"):
        out[k].sort(key=lambda e: -(e.get("total_s") or 0.0))
    return out
