"""Per-process observability plane for the real-socket stack.

Every :class:`~multiraft_tpu.distributed.tcp.RpcNode` owns an
:class:`Observability` — one :class:`~multiraft_tpu.utils.metrics.Metrics`
registry plus one bounded :class:`~multiraft_tpu.utils.trace.Tracer` —
and auto-registers the ``"Obs"`` control service on it, mirroring the
``"Chaos"`` pattern (chaos.py).  Like chaos control frames, ``Obs.*``
frames are exempt from fault injection (see
:func:`is_control`): an observability plane that a nemesis can
partition away goes dark exactly when you need it.

The service verbs:

* ``Obs.ping``     — liveness probe.
* ``Obs.clock``    — this process's ``perf_counter`` in µs.  The
  scraper estimates per-process clock offset from the round trip
  (offset = remote_now − local_midpoint, taken at minimum RTT), which
  is what lets :mod:`multiraft_tpu.harness.observe` merge trace
  buffers from many processes onto one timeline.
* ``Obs.snapshot`` — metrics registry snapshot (+ chaos-rule hit
  counters when chaos is installed).
* ``Obs.trace``    — drain the trace buffer.  Drain, not read: repeated
  scrapes never duplicate events, and the server's memory stays bounded
  by ``max_events`` between scrapes (drops are counted and reported).
* ``Obs.profile``  — drain the process's continuous sampling profiler
  (profile.py): the folded-stack aggregate since the previous scrape.
  Drain-on-read like ``Obs.trace`` (pass ``{"reset": False}`` for a
  non-destructive peek); control-exempt like every Obs verb, so chaos
  cannot partition the profiler away.
* ``Obs.tail``     — drain the process's tail-exemplar store
  (tail.py): the per-request lifecycle records retained since the
  previous scrape (over-SLO guaranteed + windowed top-k + reservoir).
  Same drain-on-read / ``{"reset": False}`` contract as
  ``Obs.profile``, and chaos-exempt for the same reason — the tail
  microscope must stay readable during the overload it documents.

Timestamps everywhere are ``time.perf_counter() * 1e6`` — the same
clock the RPC spans and engine tick spans already use, so one process's
events need only a constant offset to land on the scraper's timeline.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

from ..utils.knobs import knob_bool, knob_int
from ..utils.metrics import Metrics
from ..utils.trace import Tracer

__all__ = [
    "Observability",
    "ObsControl",
    "StageClock",
    "install_obs",
    "is_control",
    "now_us",
    "stageclock_enabled",
    "stage_metric",
    "CONTROL_PREFIXES",
    "STAGES",
]

# Control-plane RPC prefixes exempt from fault injection everywhere
# (outbound decide, inbound decide, reply decide — see tcp.py).
CONTROL_PREFIXES = ("Chaos.", "Obs.")


def is_control(svc_meth: str) -> bool:
    return svc_meth.startswith(CONTROL_PREFIXES)


def now_us() -> float:
    """This process's trace clock (µs, arbitrary epoch, monotonic)."""
    return time.perf_counter() * 1e6


try:
    _PAGE_MB = os.sysconf("SC_PAGE_SIZE") / (1024.0 * 1024.0)
except (ValueError, OSError, AttributeError):  # non-POSIX
    _PAGE_MB = 4096.0 / (1024.0 * 1024.0)


def _rss_mb() -> Optional[float]:
    """Resident set size in MB via /proc/self/statm (one small read,
    no fork, no psutil); None where /proc is absent."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return float(int(f.read().split()[1])) * _PAGE_MB
    except (OSError, ValueError, IndexError):
        return None


# ---------------------------------------------------------------------------
# Per-stage latency decomposition (the stage clock)
# ---------------------------------------------------------------------------
#
# A tagged request is stamped at each hop of its life and the deltas
# fold into per-stage log-bucket histograms (Metrics.hists), named
# ``stage.<name>_s``:
#
#   wire     clerk ``call()`` → server socket read.  Both stamps are
#            CLOCK_MONOTONIC (machine-wide on Linux), so on one box the
#            delta is exact; across machines it absorbs the clock
#            offset and the fleet aggregator's min-RTT alignment is the
#            corrective lens.  Under overload this stage is where the
#            kernel socket backlog shows up — frames queue in the TCP
#            buffer while the loop thread is busy pumping.
#   dispatch socket read → handler dispatch (decode, chaos delay, the
#            loop's own event backlog).
#   handler  dispatch → engine submit (engine ops) or handler return
#            (plain RPCs).
#   engine   submit → raft commit observed (ticket resolution: tick
#            batches + quorum + apply).  Engine ops only.
#   ack      commit → reply enqueued (durability gate: fsync frontier /
#            checkpoint waits).  Engine ops only.
#   flush    reply enqueued → vectored write handed to the kernel (the
#            reply-coalescing wait).
#
# Clerk side, ``total`` (call → reply) folds into the CLIENT node's
# registry — the end-to-end number the load curve plots against the
# server-side decomposition.
#
# ``MRT_STAGECLOCK=0`` compiles the whole plane out (no send stamp, no
# StageClock allocation, no folds) — the A/B lever for the overhead
# budget in BENCHMARKS.
#
# CPU-SECONDS twins (``cpu.<stage>_s``, profiling plane): the same
# stage vocabulary carries explicit cost accounting — thread-CPU-clock
# deltas around each synchronous serve-path segment, observed into the
# same mergeable Hist machinery (so loadcurve windows them with
# Hist.sub exactly like the wall stages, and Hist.total is the
# window's CPU-seconds sum).  Segment accounting, not per-request:
# each loop-thread CPU second lands in exactly ONE stage, so the sums
# never double-count under pipelining —
#
#   cpu.wire_s      ingress frame decode (tcp._on_event)
#   cpu.dispatch_s  dispatch bookkeeping: admission, stage setup,
#                   handler lookup (tcp._dispatch entry → handler call)
#   cpu.handler_s   synchronous handler execution; engine write ops
#                   add their per-submit binding cost from the
#                   generator body (engine_server.command)
#   cpu.engine_s    pump tick CPU (engine_server._pump_loop) — the
#                   engine stage's CPU *is* the pump
#   cpu.ack_s       completion bookkeeping (tcp._dispatch._done)
#   cpu.flush_s     reply encode + vectored write (tcp._flush_replies)
#
# Coroutine-step scheduler overhead and generator bookkeeping outside
# the wrapped segments are not attributed (the sampling profiler is
# the exact lens); the counters answer "which stage burns the loop's
# CPU" at ~zero cost.  They ride the MRT_STAGECLOCK kill switch.

STAGES = ("wire", "dispatch", "handler", "engine", "ack", "flush", "total")

_STAGECLOCK = knob_bool("MRT_STAGECLOCK")


def stageclock_enabled() -> bool:
    """True unless MRT_STAGECLOCK=0 (read once at import)."""
    return _STAGECLOCK


def stage_metric(stage: str) -> str:
    """Histogram name for a stage (``wire`` → ``stage.wire_s``)."""
    return f"stage.{stage}_s"


class StageClock:
    """Mutable per-request stamp carrier (loop-thread only).

    Created at dispatch from the wire element's ``(rid, t_send)``; each
    ``fold`` observes now−last into the stage histogram and advances
    ``last``, so consecutive folds decompose the request's life into
    adjacent, non-overlapping intervals.  ``engine`` flags that the
    engine service folded handler/engine stages, so the dispatcher's
    completion fold knows whether it is closing ``ack`` (engine op) or
    ``handler`` (plain RPC).

    Lifecycle capture (the tail microscope, tail.py): when the node's
    tail plane is on, ``vec`` holds the request's own stage vector —
    every fold lands in it as well as the histogram — and the engine
    services deposit the pump-batch wait and engine tick id, so the
    completed request carries its full stage+wait decomposition to the
    tail store.  ``vec`` stays ``None`` with the tail plane off: the
    pure-StageClock path allocates nothing extra.
    """

    __slots__ = ("rid", "last", "engine", "t0", "vec", "tick",
                 "pump_wait_s", "ambient")

    def __init__(
        self, rid: str, last: float, vec: Optional[Dict[str, float]] = None
    ) -> None:
        self.rid = rid
        self.last = last
        self.engine = False
        self.t0 = last
        self.vec = vec
        self.tick = -1
        self.pump_wait_s = 0.0
        self.ambient: Optional[Dict[str, Any]] = None

    def fold(
        self, metrics: Metrics, stage: str, now: Optional[float] = None
    ) -> float:
        if now is None:
            now = time.perf_counter()
        dt = now - self.last
        if dt < 0.0:
            dt = 0.0
        metrics.observe(f"stage.{stage}_s", dt)
        if self.vec is not None:
            self.vec[stage] = self.vec.get(stage, 0.0) + dt
        self.last = now
        return dt


class Observability:
    """One process's metrics registry + trace buffer.

    ``max_events`` defaults from ``MRT_OBS_MAX_EVENTS`` (50k ≈ 10 MB
    worst case) — the buffer self-truncates under load and ``dropped``
    reports how much, so an unscrapped long run costs bounded memory.
    """

    def __init__(
        self, name: Optional[str] = None, max_events: Optional[int] = None
    ) -> None:
        if max_events is None:
            max_events = knob_int("MRT_OBS_MAX_EVENTS")
        self.name = name or f"pid{os.getpid()}"
        self.metrics = Metrics()
        self.tracer = Tracer(max_events=max_events)
        self.node: Any = None  # back-ref set by the owning RpcNode

    def current_trace(self) -> Optional[str]:
        """The request id of the RPC being dispatched right now, if any
        (loop-thread breadcrumb — lets service code deep in a handler
        tag its own spans/instants with the caller's id)."""
        n = self.node
        return getattr(n, "_cur_trace", None) if n is not None else None

    def current_stages(self) -> Optional[StageClock]:
        """The stage clock of the RPC being dispatched right now, if any
        (loop-thread breadcrumb, same discipline as current_trace) —
        lets the engine service fold handler/engine/ack stages onto the
        clock the dispatcher started."""
        n = self.node
        return getattr(n, "_cur_stages", None) if n is not None else None


class ObsControl:
    """The ``"Obs"`` service: scrape verbs over the node's own plane."""

    def __init__(self, node: Any) -> None:
        self._node = node
        # Commit-rate window state for groups(): (now_us, commit list)
        # of the previous scrape — rates are deltas BETWEEN scrapes, so
        # the placer reads load directly instead of diffing counters.
        self._g_prev: Optional[tuple] = None

    def _engine_kv(self):
        """The engine service's frontier service, whichever attribute
        it hangs off (``kv`` on EngineKVService, ``skv`` on the sharded
        services)."""
        svc = getattr(self._node, "engine_service", None)
        kv = getattr(svc, "kv", None)
        if kv is None:
            kv = getattr(svc, "skv", None)
        return kv

    def ping(self, args: Any = None) -> str:
        return "pong"

    def clock(self, args: Any = None) -> float:
        return now_us()

    def snapshot(self, args: Any = None) -> Dict[str, Any]:
        obs = self._node.obs
        out: Dict[str, Any] = {
            "name": obs.name,
            "pid": os.getpid(),
            "now_us": now_us(),
            "metrics": obs.metrics.snapshot(),
            "gauges": self.gauges(),
        }
        chaos = getattr(self._node, "chaos", None)
        if chaos is not None:
            out["chaos"] = chaos.snapshot()
        groups = self.groups()
        if groups is not None:
            out["groups"] = groups
        return out

    def gauges(self, args: Any = None) -> Dict[str, float]:
        """Live queue-depth / in-flight gauges — saturation visible in
        a scrape, not only in a postmortem.  Runs on the loop thread
        (all Obs verbs dispatch there), so reading the loop-thread-only
        reply queues is safe; engine attributes are getattr-guarded for
        nodes without an engine service."""
        node = self._node
        out: Dict[str, float] = {}
        outq = getattr(node, "_outq", None)
        if outq is not None:
            out["gauge.replyq"] = float(sum(len(v) for v in outq.values()))
        pending = getattr(node, "_pending", None)
        if pending is not None:
            out["gauge.inflight"] = float(len(pending))
        svc = getattr(node, "engine_service", None)
        if svc is not None:
            driver = getattr(self._engine_kv(), "driver", None)
            backlog = getattr(driver, "backlog", None)
            if backlog is not None:
                out["gauge.backlog"] = float(backlog.sum())
            ws = getattr(svc, "_write_seqs", None)
            if ws is not None:
                out["gauge.wal_unsynced"] = float(len(ws))
            wal = getattr(getattr(svc, "_dur", None), "wal", None)
            if wal is not None:
                out["gauge.wal_pending"] = float(
                    wal.appended - wal.synced
                )
        adm = getattr(node, "admission", None)
        if adm is not None:
            # Admission plane (admission.py): bucket depth plus the
            # bounded dispatched-unreplied count it enforces.
            out["gauge.admit_tokens"] = float(adm.tokens())
            out["gauge.admit_inflight"] = float(adm.inflight_total())
        ww = getattr(node, "wedge_watch", None)
        if ww is not None:
            # Wedge watchdog (wedge.py): groups whose commit frontier
            # is stalled with proposals pending — gray-failure liveness
            # visible in a scrape, before the postmortem.
            out["gauge.wedged_groups"] = float(len(ww.wedged))
        # Process resource gauges (stdlib only — no psutil): the CPU
        # clock is cumulative, so two scrapes diff into the window's
        # CPU-seconds; against the wall window that says whether the
        # process is CPU-pegged (the loadcurve records all three per
        # step).  rss via /proc/self/statm on Linux; absent elsewhere.
        out["gauge.cpu_s"] = time.process_time()
        out["gauge.threads"] = float(threading.active_count())
        rss = _rss_mb()
        if rss is not None:
            out["gauge.rss_mb"] = rss
        return out

    def hist(self, args: Any = None) -> Dict[str, Any]:
        """Cumulative log-bucket histogram dumps + live gauges — the
        fleet scraper's verb.  Cumulative by design: two scrapes diff
        into the window between them (Hist.sub), so repeated scrapes
        are idempotent reads, never destructive drains."""
        obs = self._node.obs
        return {
            "name": obs.name,
            "pid": os.getpid(),
            "now_us": now_us(),
            "hists": obs.metrics.hist_dumps(),
            "gauges": self.gauges(),
        }

    def groups(self, args: Any = None) -> Optional[Dict[str, Any]]:
        """Per-raft-group introspection (columnar, one entry per group):
        leader replica (−1 = none), max term, commit index, applied
        index, log length above the snapshot base, last snapshot index,
        the GLOBAL gid each local engine slot hosts (``gids``, −1 for
        the config RSM / spare slots), and a windowed per-group commit
        RATE (``commit_rate``, commits/s since the previous scrape of
        this verb — the placement controller's load signal).  ``None``
        on nodes without an engine service (pure clients, sim-backend
        servers).  The postmortem doctor uses the commit/applied columns
        to compute apply lag at time of death; folded into
        :meth:`snapshot` so every scrape carries it."""
        kv = self._engine_kv()
        driver = getattr(kv, "driver", None)
        state = getattr(driver, "state", None)
        if state is None:
            return None
        # numpy/engine imports stay local: pure-client nodes must not
        # pull the jax stack in just to serve Obs.ping.
        import numpy as np

        from ..engine.core import LEADER

        role = np.asarray(state.role)
        alive = np.asarray(state.alive).astype(bool)
        lead = (role == LEADER) & alive
        leader = np.where(lead.any(axis=1), lead.argmax(axis=1), -1)
        G = int(role.shape[0])
        commit = np.asarray(state.commit).max(axis=1).tolist()
        now = now_us()
        rate = [0.0] * G
        prev = self._g_prev
        if prev is not None and len(prev[1]) == G:
            dt_s = (now - prev[0]) / 1e6
            if dt_s > 0:
                rate = [
                    max(0.0, (c - p) / dt_s)
                    for c, p in zip(commit, prev[1])
                ]
        self._g_prev = (now, list(commit))
        # Local slot → global gid (fleet mode); −1 marks the config RSM
        # (slot 0) and idle spare slots.
        l2g = getattr(kv, "_l2g", None)
        gids = (
            [l2g.get(g, -1) for g in range(G)]
            if l2g is not None else list(range(G))
        )
        out = {
            "G": G,
            "gids": gids,
            "leader": leader.tolist(),
            "term": np.asarray(state.term).max(axis=1).tolist(),
            "commit": commit,
            "commit_rate": rate,
            "applied": np.asarray(state.applied).max(axis=1).tolist(),
            "log_len": np.asarray(state.log_len).max(axis=1).tolist(),
            "snap_index": np.asarray(state.base).max(axis=1).tolist(),
        }
        # Replica-membership health (engine/host.py joint consensus):
        # per-replica liveness, the voter set (leader's view; row with
        # the widest view when leaderless), joint flag, and whether a
        # reconfig is in flight — the placement controller's dead-voter
        # signal and the wedge watchdog's exemption column.  Guarded:
        # states restored from pre-membership checkpoints lack the
        # fields until their first tick.
        vo = getattr(state, "voters_old", None)
        if vo is not None:
            vo = np.asarray(vo)
            vn = np.asarray(state.voters_new)
            joint = np.asarray(state.joint)
            cfg_idx = np.asarray(state.cfg_idx)
            P = int(vo.shape[1])
            union = vo | vn
            row = np.where(
                lead.any(axis=1), lead.argmax(axis=1), union.argmax(axis=1)
            )
            bits = union[np.arange(G), row]
            out["replica_alive"] = alive.tolist()
            out["voters"] = [
                [q for q in range(P) if (int(b) >> q) & 1] for b in bits
            ]
            out["joint"] = joint.any(axis=1).tolist()
            out["reconfig"] = (
                joint.any(axis=1)
                | (cfg_idx.max(axis=1) > np.asarray(commit))
            ).tolist()
        is_sealed = getattr(kv, "is_sealed", None)
        if is_sealed is not None and l2g is not None:
            out["sealed"] = [
                bool(g in l2g and is_sealed(l2g[g])) for g in range(G)
            ]
        return out

    def trace(self, args: Any = None) -> Dict[str, Any]:
        obs = self._node.obs
        events, dropped = obs.tracer.drain()
        return {
            "name": obs.name,
            "pid": os.getpid(),
            "now_us": now_us(),
            "events": events,
            "dropped": dropped,
        }

    def profile(self, args: Any = None) -> Dict[str, Any]:
        """Drain the process's sampling profiler (profile.py) — the
        folded-stack aggregate since the previous scrape, plus the
        sampler's own health/overhead telemetry.  ``{"reset": False}``
        peeks without draining.  ``profile`` is None when the sampler
        is disabled (MRT_PROFILE=0) or never started in this process —
        an explicit marker, so a fleet merge can tell "no CPU burned"
        from "not profiling"."""
        from .profile import get_profiler

        reset = not (isinstance(args, dict) and args.get("reset") is False)
        prof = get_profiler()
        return {
            "name": self._node.obs.name,
            "pid": os.getpid(),
            "now_us": now_us(),
            "profile": (
                None if prof is None
                else (prof.drain() if reset else prof.snapshot())
            ),
        }

    def tail(self, args: Any = None) -> Dict[str, Any]:
        """Drain the process's tail-exemplar store (tail.py) — the
        per-request lifecycle records retained since the previous
        scrape.  ``{"reset": False}`` peeks without draining (bundle
        collection uses this: evidence gathering must not consume the
        evidence).  ``tail`` is None when the plane is off
        (MRT_TAIL=0 / MRT_STAGECLOCK=0) — an explicit marker, so a
        fleet merge can tell "no slow requests" from "not looking"."""
        reset = not (isinstance(args, dict) and args.get("reset") is False)
        store = getattr(self._node, "tail", None)
        return {
            "name": self._node.obs.name,
            "pid": os.getpid(),
            "now_us": now_us(),
            "tail": (
                None if store is None
                else (store.drain() if reset else store.snapshot())
            ),
        }


def install_obs(node: Any) -> ObsControl:
    """Register the ``"Obs"`` service on ``node`` (idempotent in effect;
    mirrors chaos.install_chaos)."""
    ctl = ObsControl(node)
    node.add_service("Obs", ctl)
    return ctl
