"""Per-process observability plane for the real-socket stack.

Every :class:`~multiraft_tpu.distributed.tcp.RpcNode` owns an
:class:`Observability` — one :class:`~multiraft_tpu.utils.metrics.Metrics`
registry plus one bounded :class:`~multiraft_tpu.utils.trace.Tracer` —
and auto-registers the ``"Obs"`` control service on it, mirroring the
``"Chaos"`` pattern (chaos.py).  Like chaos control frames, ``Obs.*``
frames are exempt from fault injection (see
:func:`is_control`): an observability plane that a nemesis can
partition away goes dark exactly when you need it.

The service verbs:

* ``Obs.ping``     — liveness probe.
* ``Obs.clock``    — this process's ``perf_counter`` in µs.  The
  scraper estimates per-process clock offset from the round trip
  (offset = remote_now − local_midpoint, taken at minimum RTT), which
  is what lets :mod:`multiraft_tpu.harness.observe` merge trace
  buffers from many processes onto one timeline.
* ``Obs.snapshot`` — metrics registry snapshot (+ chaos-rule hit
  counters when chaos is installed).
* ``Obs.trace``    — drain the trace buffer.  Drain, not read: repeated
  scrapes never duplicate events, and the server's memory stays bounded
  by ``max_events`` between scrapes (drops are counted and reported).

Timestamps everywhere are ``time.perf_counter() * 1e6`` — the same
clock the RPC spans and engine tick spans already use, so one process's
events need only a constant offset to land on the scraper's timeline.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

from ..utils.metrics import Metrics
from ..utils.trace import Tracer

__all__ = [
    "Observability",
    "ObsControl",
    "install_obs",
    "is_control",
    "now_us",
    "CONTROL_PREFIXES",
]

# Control-plane RPC prefixes exempt from fault injection everywhere
# (outbound decide, inbound decide, reply decide — see tcp.py).
CONTROL_PREFIXES = ("Chaos.", "Obs.")


def is_control(svc_meth: str) -> bool:
    return svc_meth.startswith(CONTROL_PREFIXES)


def now_us() -> float:
    """This process's trace clock (µs, arbitrary epoch, monotonic)."""
    return time.perf_counter() * 1e6


class Observability:
    """One process's metrics registry + trace buffer.

    ``max_events`` defaults from ``MRT_OBS_MAX_EVENTS`` (50k ≈ 10 MB
    worst case) — the buffer self-truncates under load and ``dropped``
    reports how much, so an unscrapped long run costs bounded memory.
    """

    def __init__(
        self, name: Optional[str] = None, max_events: Optional[int] = None
    ) -> None:
        if max_events is None:
            max_events = int(os.environ.get("MRT_OBS_MAX_EVENTS", "50000"))
        self.name = name or f"pid{os.getpid()}"
        self.metrics = Metrics()
        self.tracer = Tracer(max_events=max_events)
        self.node: Any = None  # back-ref set by the owning RpcNode

    def current_trace(self) -> Optional[str]:
        """The request id of the RPC being dispatched right now, if any
        (loop-thread breadcrumb — lets service code deep in a handler
        tag its own spans/instants with the caller's id)."""
        n = self.node
        return getattr(n, "_cur_trace", None) if n is not None else None


class ObsControl:
    """The ``"Obs"`` service: scrape verbs over the node's own plane."""

    def __init__(self, node: Any) -> None:
        self._node = node

    def ping(self, args: Any = None) -> str:
        return "pong"

    def clock(self, args: Any = None) -> float:
        return now_us()

    def snapshot(self, args: Any = None) -> Dict[str, Any]:
        obs = self._node.obs
        out: Dict[str, Any] = {
            "name": obs.name,
            "pid": os.getpid(),
            "now_us": now_us(),
            "metrics": obs.metrics.snapshot(),
        }
        chaos = getattr(self._node, "chaos", None)
        if chaos is not None:
            out["chaos"] = chaos.snapshot()
        groups = self.groups()
        if groups is not None:
            out["groups"] = groups
        return out

    def groups(self, args: Any = None) -> Optional[Dict[str, Any]]:
        """Per-raft-group introspection (columnar, one entry per group):
        leader replica (−1 = none), max term, commit index, applied
        index, log length above the snapshot base, and last snapshot
        index.  ``None`` on nodes without an engine service (pure
        clients, sim-backend servers).  The postmortem doctor uses the
        commit/applied columns to compute apply lag at time of death;
        folded into :meth:`snapshot` so every scrape carries it."""
        svc = getattr(self._node, "engine_service", None)
        driver = getattr(getattr(svc, "kv", None), "driver", None)
        state = getattr(driver, "state", None)
        if state is None:
            return None
        # numpy/engine imports stay local: pure-client nodes must not
        # pull the jax stack in just to serve Obs.ping.
        import numpy as np

        from ..engine.core import LEADER

        role = np.asarray(state.role)
        alive = np.asarray(state.alive).astype(bool)
        lead = (role == LEADER) & alive
        leader = np.where(lead.any(axis=1), lead.argmax(axis=1), -1)
        return {
            "G": int(role.shape[0]),
            "leader": leader.tolist(),
            "term": np.asarray(state.term).max(axis=1).tolist(),
            "commit": np.asarray(state.commit).max(axis=1).tolist(),
            "applied": np.asarray(state.applied).max(axis=1).tolist(),
            "log_len": np.asarray(state.log_len).max(axis=1).tolist(),
            "snap_index": np.asarray(state.base).max(axis=1).tolist(),
        }

    def trace(self, args: Any = None) -> Dict[str, Any]:
        obs = self._node.obs
        events, dropped = obs.tracer.drain()
        return {
            "name": obs.name,
            "pid": os.getpid(),
            "now_us": now_us(),
            "events": events,
            "dropped": dropped,
        }


def install_obs(node: Any) -> ObsControl:
    """Register the ``"Obs"`` service on ``node`` (idempotent in effect;
    mirrors chaos.install_chaos)."""
    ctl = ObsControl(node)
    node.add_service("Obs", ctl)
    return ctl
