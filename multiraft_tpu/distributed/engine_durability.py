"""Durability + recovery for the engine serving stack: the
checkpoint/WAL lifecycle (:class:`EngineDurability`), the shared
durable frame-ack gate, and both WAL replay paths (plain-KV re-submit
and the sharded two-pass redo, :class:`ShardWalReplay`).  Split out of
engine_server.py (round 4): the replay logic is the subtlest code in
the serving stack and deserves its own module boundary; the services
delegate to it unchanged.

See distributed/wal.py for the on-disk format and the torn-tail
contract; reference analog: the Persister carryover crash model
(raft/config.go:113-142) at engine granularity.
"""

from __future__ import annotations

import os
import time

from ..engine.kv import KVOp
from ..transport import codec
from .engine_wire import _OPCODE, route_group

__all__ = [
    "EngineDurability",
    "await_frame_synced",
    "replay_kv_wal",
    "ShardWalReplay",
]


class EngineDurability:
    """Checkpoint + WAL lifecycle for one engine server process.

    The engine's durability contract (see distributed/wal.py): periodic
    atomic whole-engine checkpoints + a WAL of ops since the last one;
    write acks gate on the WAL record being fsynced (group commit at
    pump cadence, so the fsync amortizes over every op in the ~2 ms
    window).  Recovery restores the checkpoint and re-submits WAL
    records through consensus — session dedup makes it exactly-once."""

    def __init__(
        self,
        data_dir: str,
        driver,
        state_owner,  # has state_dict() (BatchedKV / BatchedShardKV)
        checkpoint_every_s: float = 30.0,
        fsync: bool = True,
        metrics=None,
    ) -> None:
        from ..utils.metrics import Metrics
        from .wal import WriteAheadLog

        os.makedirs(data_dir, exist_ok=True)
        self.ckpt_path = os.path.join(data_dir, "engine.ckpt")
        # The server passes its per-process registry so WAL fsync
        # latency / rotate counts surface in Obs.snapshot scrapes.
        self.metrics = metrics if metrics is not None else Metrics()
        self.wal = WriteAheadLog(os.path.join(data_dir, "ops.wal"),
                                 fsync=fsync, metrics=self.metrics)
        self.driver = driver
        self.state_owner = state_owner
        self.every = checkpoint_every_s
        self._last_ckpt = time.monotonic()
        # Optional composite ack gate (stateplane.py sync shipping):
        # when set, an ack additionally waits for the record to be
        # covered remotely — ``synced`` becomes "fsynced locally AND
        # shipped to a standby", so a SIGKILL after the ack can never
        # lose the write even with the local disk gone.
        self.extra_sync_gate = None  # Callable[[int], bool] | None

    def log(self, record) -> int:
        """Append one op record; returns its ack-gate seq."""
        return self.wal.append(codec.encode(record))

    def synced(self, seq: int) -> bool:
        if self.wal.synced < seq:
            return False
        gate = self.extra_sync_gate
        return gate is None or gate(seq)

    def tail_records(self, from_seq: int):
        """Decoded ``(seq, record)`` pairs past ``from_seq`` from the
        WAL's bounded retention — the state plane's shipping tail."""
        return [
            (s, codec.decode(b)) for s, b in self.wal.tail(from_seq)
        ]

    def replay_records(self):
        for body in self.wal.replay():
            yield codec.decode(body)

    def after_pump(self) -> None:
        """Group fsync + periodic checkpoint, called once per pump."""
        self.wal.sync()
        if self.every > 0 and (
            time.monotonic() - self._last_ckpt >= self.every
        ):
            self.checkpoint()

    def checkpoint(self) -> None:
        """Atomic engine+service snapshot, then WAL rotation.  A crash
        between the two merely makes the next replay redundant."""
        t0 = time.perf_counter()
        self.driver.save(
            self.ckpt_path,
            extra={"service": self.state_owner.state_dict()},
        )
        self.wal.rotate()
        self.metrics.inc("ckpt.saves")
        self.metrics.observe("ckpt.save_s", time.perf_counter() - t0)
        self._last_ckpt = time.monotonic()


def await_frame_synced(sched, dur, write_seqs, ok, args_list, deadline):
    """Durable frame-ack gate shared by the services' ``batch``
    handlers (yield-from inside the handler generator): every write in
    ``ok`` must have its apply-time WAL record fsynced before it may
    ack OK; at the deadline, unsynced writes are DROPPED from ``ok``
    (they answer ErrTimeout — never a false durable ack)."""
    while dur is not None:
        pend = [
            i for i in ok
            if (s := write_seqs.get(
                (args_list[i].client_id, args_list[i].command_id)
            )) is not None and not dur.synced(s)
        ]
        if not pend:
            break
        if sched.now >= deadline:
            ok -= set(pend)
            break
        yield 0.002


def demote_unsynced_rows(sched, dur, write_seqs, frame, err, deadline):
    """Firehose form of the frame-ack gate (yield-from inside the
    handler generator): wait for every OK write ROW's apply-time WAL
    record to fsync; at the deadline, unsynced rows demote to RETRY in
    ``err`` — never a false durable ack.  Shared by the plain and
    sharded firehose handlers so the protocol lives once."""
    import types as _types

    ok_rows = {int(r) for r in frame.write_rows.tolist() if err[r] == 0}
    rows_view = [
        _types.SimpleNamespace(client_id=c, command_id=m)
        for c, m in zip(frame.clients_l, frame.commands_l)
    ]
    yield from await_frame_synced(
        sched, dur, write_seqs, ok_rows, rows_view, deadline
    )
    from ..engine.firehose import FH_RETRY

    for r in frame.write_rows.tolist():
        if err[r] == 0 and r not in ok_rows:
            err[r] = FH_RETRY


def replay_kv_wal(kv, dur, G: int) -> int:
    """Re-submit every plain-KV WAL record through consensus (recovery
    path; runs to completion before the server starts answering).
    Dedup tables make records already in the checkpoint no-ops.

    STRICTLY one record at a time PER GROUP: the WAL is commit-ordered,
    and both order guarantees that replay must reproduce are
    group-local — a client's cmd N vs N+1 (an eviction committing N+1
    first would dedup-swallow the resubmitted N) and cross-client order
    on a shared key (an acked A-then-B pair replayed B-then-A would
    recover the wrong value).  A key routes to exactly one group, so
    serial-per-group preserves both while groups pipeline through each
    pump wave: recovery wall-clock scales with the deepest single-group
    backlog, not the WAL length.  With the default 30 s checkpoint
    interval the WAL bounds to ~30 s of acked writes, so expected RTO ≈
    that backlog's longest per-group chain at one commit per ~2 pump
    rounds."""
    if dur is None:
        return 0
    recs = [rec for rec in dur.replay_records() if rec[0] == "kv"]
    queues: dict = {}
    for rec in recs:
        queues.setdefault(route_group(rec[2], G), []).append(rec)

    def submit(rec):
        _, op, key, value, cid, cmd = rec
        return kv.submit(
            route_group(key, G),
            KVOp(op=_OPCODE[op], key=key, value=value,
                 client_id=cid, command_id=cmd),
        )

    depth = max((len(q) for q in queues.values()), default=0)
    max_rounds = 4000 + 200 * depth
    pending: dict = {}  # group -> [ticket, attempts_left, submit_round]
    rounds = 0
    while queues:
        for g in queues:
            if g not in pending:
                pending[g] = [submit(queues[g][0]), 50, rounds]
        kv.pump(2)
        rounds += 1
        for g, (t, left, since) in list(pending.items()):
            resubmit = False
            if t.done and not t.failed:
                queues[g].pop(0)
                del pending[g]
                if not queues[g]:
                    del queues[g]
            elif t.done and t.failed:
                resubmit = True  # evicted: same ids, dedup-safe
            elif rounds - since >= 600:
                resubmit = True  # wedged ticket (binding lost)
            if resubmit:
                if left <= 1:
                    rec = queues[g][0]
                    raise RuntimeError(
                        f"WAL replay of {rec[1]}({rec[2]!r}) did not "
                        "converge"
                    )
                pending[g] = [submit(queues[g][0]), left - 1, rounds]
        if rounds > max_rounds:
            raise RuntimeError("WAL replay did not converge")
    return len(recs)


class ShardWalReplay:
    """Recovery replay for the SHARDED engine service, in two passes
    over the (commit-ordered) WAL:

    1. admin records rebuild the config history, in order, each retried
       until it actually commits (an eviction during recovery must not
       silently skip a config — the fleet's histories would diverge);
    2. insert/delete/confirm/client records re-ride the local logs in
       WAL order, with their apply-time gates making anything already
       in the checkpoint a no-op.

    PULLS and the live GC/confirm handshake are paused for the duration
    via ``skv.migration_paused`` — a pull completing mid-replay would
    copy a slot before its redo records landed, and a GC handshake
    whose old owner is a REMOTE peer can never resolve here (replay
    runs synchronously on the scheduler loop, so peer RPC replies are
    not serviced until it returns).  Committed GCING→SERVING
    transitions are instead re-applied from the WAL's "confirm" records
    — the pre-crash handshake already ran its delete leg, so replaying
    the confirm alone is sound — which keeps config advance (needs
    all-SERVING) purely local.  A slot whose confirm had not committed
    pre-crash stays GCING through replay; the post-replay pump loop
    re-runs its handshake live (idempotent at the peer)."""

    def __init__(self, skv, dur) -> None:
        self.skv = skv
        self.dur = dur

    def run(self) -> int:
        if self.dur is None:
            return 0
        recs = list(self.dur.replay_records())
        self.skv.migration_paused = True
        try:
            for rec in recs:
                if rec[0] == "admin":
                    self._replay_admin(rec[1], rec[2], rec[3])
            for rec in recs:
                kind = rec[0]
                if kind == "insert":
                    self._replay_insert(*rec[1:])
                elif kind == "delete":
                    _, gid, shard, num = rec
                    if gid in self.skv.reps:
                        # The apply gate answers ErrNotReady while the
                        # source rep is behind `num` — wait like the
                        # insert replay does, or the record would
                        # "succeed" as a no-op and the stale BEPULLING
                        # slot would wedge config advance forever.
                        self._await_config(gid, num, "a delete record")
                        self._retry_until_ok(
                            lambda: self.skv.delete_shard(gid, shard, num)
                        )
                elif kind == "confirm":
                    _, gid, shard, num = rec
                    if gid in self.skv.reps:
                        # Re-apply the committed GCING→SERVING flip
                        # locally (never the cross-process handshake —
                        # see the class docstring).  Gated on the rep
                        # having reached config `num` like
                        # insert/delete.
                        self._await_config(gid, num, "a confirm record")
                        self._retry_until_ok(
                            lambda: self.skv.confirm_shard(gid, shard, num)
                        )
                elif kind == "skv":
                    if len(rec) != 7:
                        # Records from the pre-gid WAL format cannot be
                        # routed safely — refuse loudly rather than
                        # misparse (shifted fields) or silently drop.
                        raise RuntimeError(
                            "WAL 'skv' record has legacy format "
                            f"({len(rec)} fields); cannot replay"
                        )
                    _, gid, op, key, value, cid, cmd = rec
                    self._redo_client_op(gid, op, key, value, cid, cmd)
            # Drain: let every replayed proposal commit before serving.
            self._pump_until(lambda: False, max_rounds=50)
        finally:
            self.skv.migration_paused = False
        return len(recs)

    def _pump_until(self, cond, max_rounds: int = 4000) -> bool:
        for _ in range(max_rounds):
            if cond():
                return True
            self.skv.pump(2)
        return cond()

    def _await_config(self, gid: int, num: int, what: str) -> None:
        """Pump until rep ``gid`` has applied config ``num`` (replay
        gate shared by insert and delete records); a timeout is a real
        recovery failure, raised loudly."""
        rep = self.skv.reps[gid]
        if not self._pump_until(lambda: rep.cur.num >= num):
            raise RuntimeError(
                f"replay: rep {gid} never reached config {num} for "
                f"{what} (stuck at {rep.cur.num})"
            )

    def _retry_until_ok(self, propose, attempts: int = 50):
        """Propose-and-wait with eviction retry (leader churn during
        recovery must not drop a record).  A resolved-but-not-OK ticket
        (e.g. ErrNotReady) retries too — callers gate config catch-up
        beforehand, so non-OK can only be transient."""
        from ..engine.shardkv import OK as SK_OK

        for _ in range(attempts):
            t = propose()
            self._pump_until(lambda: t.done)
            if t.done and not t.failed and t.err == SK_OK:
                return t
        raise RuntimeError("WAL replay proposal did not commit")

    def _replay_admin(self, kind, payload, cmd) -> None:
        def propose():
            if kind == "move":
                return self.skv.move(*payload, command_id=cmd)
            return getattr(self.skv, kind)(payload, command_id=cmd)

        self._retry_until_ok(propose)

    def _replay_insert(self, gid, shard, num, data, latest) -> None:
        if gid not in self.skv.reps:
            return
        from ..engine.shardkv import ShardTicket, _InsertOp
        from ..services.shardkv import PULLING

        rep = self.skv.reps[gid]
        # The apply gate needs the rep AT config `num` and PULLING —
        # wait for orchestration to advance it there (earlier inserts/
        # configs already replayed), else the insert would silently
        # no-op and a later remote re-fetch could find the peer's copy
        # already GC'd.
        self._await_config(gid, num, "an insert record")
        if rep.cur.num != num or rep.shards[shard].state != PULLING:
            return  # checkpoint already contains this insert's effects

        def propose():
            t = ShardTicket(group=gid)
            self.skv.driver.start(
                self.skv._g2l[gid],
                _InsertOp(config_num=num, shard=shard, data=dict(data),
                          latest=dict(latest), ticket=t),
            )
            return t

        self._retry_until_ok(propose)

    def _redo_client_op(self, gid, op, key, value, cid, cmd) -> None:
        """REDO one acknowledged write into the slot of the gid that
        committed it, directly on the host state — the standard
        redo-log discipline.  Routing/ownership gates don't apply to
        redo: the op already linearized pre-crash; in particular a
        write acked just before its shard went BEPULLING must land in
        that (now non-serving) slot so a peer's later pull sees it, and
        a subsequent WAL delete record clears it in order."""
        from ..services.shardkv import key2shard

        rep = self.skv.reps.get(gid)
        if rep is None:
            return  # record from a gid this process no longer hosts
        sh = rep.shards[key2shard(key)]
        if sh.latest.get(cid, -1) >= cmd:
            return  # already in the checkpoint / an earlier redo
        if op == "Put":
            sh.data[key] = value
        elif op == "Append":
            sh.data[key] = sh.data.get(key, "") + value
        sh.latest[cid] = cmd
