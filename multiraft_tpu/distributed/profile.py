"""Continuous sampling profiler: CPU attribution from socket to tick.

The telemetry plane measures *where requests wait* (stage clocks,
observe.py) and *what happened after death* (flight rings,
flightrec.py) — this module measures *where CPU time goes*.  Every
process runs one daemon sampler thread over ``sys._current_frames()``
at a configurable rate, folding each thread's stack into a bounded
``{folded_stack: count}`` aggregate keyed by THREAD NAME — which is
why every long-lived thread in the tree is named at its spawn site
(``multiraft-loop/<node>``, ``porcupine-sampler-<i>``, ...): the
profile is readable attribution, not ``Thread-7``.

Design points, in the order they matter:

* **Sampling, not tracing.**  ``sys._current_frames()`` is one C call
  returning every thread's current frame; walking ``f_back`` chains is
  pure pointer chasing, and per-code-object label memoization keeps a
  sample at ~40 µs.  The default rate adapts to the host (67 Hz with
  spare cores, 19 Hz on one CPU where every wakeup preempts the
  serving thread — see :func:`_default_hz`), keeping measured cost on
  the firehose-sockets bench <2% throughput (BENCHMARKS "Continuous
  profiling") — which is what lets ``MRT_PROFILE`` default ON: a
  profiler you must remember to enable is never running when the
  incident happens.  Both rates are prime: they avoid lockstep with
  10 ms scheduler timers and 100 Hz OS tick harmonics.
* **Folded stacks, bounded memory.**  Aggregation is a dict from
  ``"thread;mod.fn;mod.fn;..."`` (root first, the flamegraph collapsed
  format) to sample count, capped at ``MRT_PROFILE_MAX_STACKS``
  distinct keys; once full, new stacks fold into a per-thread
  ``(overflow)`` bucket and ``overflow`` counts them — stack churn
  (deep recursion over varying data) costs a counter, never unbounded
  memory.  Frame walks are depth-capped at ``MRT_PROFILE_DEPTH``
  (deepest frames kept — the leaf names the hot function; the root
  beyond the cap collapses into ``(...)``).
* **Drain-on-read fleet scrape.**  ``Obs.profile`` (observe.py) drains
  the aggregate — repeated scrapes never double-count a sample, and
  the windowed scrape discipline the loadcurve already uses for
  histograms applies unchanged: each rate step's profile is exactly
  the samples taken during that step.  Obs verbs are control-exempt
  (``CONTROL_PREFIXES``), so chaos cannot partition the profiler away.
* **Self-accounting.**  The sampler measures its own cost
  (``self_cpu_s`` via ``time.thread_time`` deltas on the sampler
  thread) and reports it in every snapshot, so the overhead budget is
  continuously observable, not a one-time benchmark claim.
* **Black-box breadcrumbs.**  Once a second the sampler drops a PROF
  flight record (samples, distinct stacks, hottest leaf function in
  the tag, and — in the code field — process CPU busy per-mille of
  wall over the breadcrumb window) so a SIGKILL'd process still
  leaves evidence of what it was burning CPU on — the postmortem
  doctor reads these next to the OVERLOAD records to split "CPU
  saturation" (busy ≈ 1000‰: the stage's CPU-seconds fill the wall
  window, the tag names the hot function) from "queueing collapse"
  (queues diverged while the CPU sat idle).

The pure helpers at the bottom (:func:`merge_folded`,
:func:`top_functions`, :func:`per_thread_totals`, :func:`to_collapsed`)
are shared by the fleet merger (harness/observe.py), the loadcurve
per-window attribution (harness/loadcurve.py), and the CLI
(scripts/profile_summary.py) — one vocabulary end to end.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..utils.knobs import knob_bool, knob_float, knob_int

__all__ = [
    "SERVING_THREAD_PREFIXES",
    "SamplingProfiler",
    "profiler_enabled",
    "get_profiler",
    "maybe_start_profiler",
    "fold_frame",
    "merge_folded",
    "top_functions",
    "per_thread_totals",
    "to_collapsed",
    "from_collapsed",
    "diff_folded",
]

_PROFILE = knob_bool("MRT_PROFILE")

# Thread-name prefixes counting as SERVING-side CPU in ranking cuts
# (loadcurve per-window attribution, openloop sweeps): the scheduler
# loops and, since the asynchronous pipeline, the engine-pump threads
# that block on device readbacks on the loops' behalf
# (distributed/engine_pump.py).  A new serving thread family must be
# added here or its CPU silently drops out of the serving headline.
SERVING_THREAD_PREFIXES: Tuple[str, ...] = (
    "multiraft-loop",
    "multiraft-pump",
)


def _default_hz() -> float:
    """Sampling rate: 67 Hz with spare cores, 19 Hz on a 1-CPU host.

    On multi-core the sampler runs BESIDE the workers and the budget is
    its own CPU (~40 µs/sample → ~0.3% of one core at 67 Hz).  On one
    CPU the budget is WAKEUPS, not sampler CPU: every sample forces a
    GIL handoff that preempts the serving thread mid-batch (smaller
    socket batches per epoll wake → more syscalls per op), measured at
    ~0.08% throughput per Hz on the firehose bench — 67 Hz would cost
    ~5%, 19 Hz stays under the 2% default-on budget (BENCHMARKS
    "Continuous profiling").  Both primes, off OS-tick harmonics.
    ``MRT_PROFILE_HZ`` overrides unconditionally."""
    try:
        ncpu = len(os.sched_getaffinity(0))  # type: ignore[attr-defined]
    except AttributeError:  # non-Linux
        ncpu = os.cpu_count() or 1
    return knob_float("MRT_PROFILE_HZ",
                      default=67.0 if ncpu > 1 else 19.0)


_DEF_HZ = _default_hz()
_DEF_DEPTH = knob_int("MRT_PROFILE_DEPTH")
_DEF_MAX_STACKS = knob_int("MRT_PROFILE_MAX_STACKS")

OVERFLOW_FRAME = "(overflow)"
TRUNC_FRAME = "(...)"


def profiler_enabled() -> bool:
    """True unless ``MRT_PROFILE=0`` (read once at import)."""
    return _PROFILE


def _mod_of(filename: str) -> str:
    """Compact module label from a code object's filename: the
    basename without ``.py`` (``.../distributed/tcp.py`` → ``tcp``).
    Package-qualified names would be prettier but cost a path walk per
    frame on the sampling hot path; the basename is unambiguous within
    this tree and short enough for 20-byte flight-record tags."""
    base = filename.rsplit("/", 1)[-1].rsplit("\\", 1)[-1]
    return base[:-3] if base.endswith(".py") else base


# Per-code-object label memo.  Code objects are module-lifetime, so
# keying on them directly turns the per-frame f-string + basename work
# into one dict hit after the first sample of each function — the
# difference between ~2.6% and <1% sampler overhead on a 1-CPU host.
# Capped so pathological codegen (exec'd one-shot code objects) cannot
# pin memory; past the cap labels are rebuilt per sample, never wrong.
_label_cache: Dict[Any, str] = {}
_LABEL_CACHE_MAX = 32768


def _frame_label(code: Any) -> str:
    lbl = _label_cache.get(code)
    if lbl is None:
        name = getattr(code, "co_qualname", None) or code.co_name
        lbl = f"{_mod_of(code.co_filename)}.{name}"
        if len(_label_cache) < _LABEL_CACHE_MAX:
            _label_cache[code] = lbl
    return lbl


def fold_frame(frame: Any, depth: int = _DEF_DEPTH) -> str:
    """Fold one thread's live frame chain into the collapsed-stack
    string, ROOT FIRST (``main;tcp._run;codec.decode``).  Deterministic
    for a given frame chain — the property the folded-stack tests pin.
    Deeper than ``depth`` keeps the LEAF side (the hot function) and
    collapses the excess root into ``(...)``."""
    parts: List[str] = []
    f = frame
    while f is not None and len(parts) < depth:
        parts.append(_frame_label(f.f_code))
        f = f.f_back
    if f is not None:
        parts.append(TRUNC_FRAME)
    parts.reverse()
    return ";".join(parts)


class SamplingProfiler:
    """One process's continuous sampler (start/stop idempotent).

    The aggregate maps ``"thread;frames..."`` → count; ``snapshot()``
    copies it, ``drain()`` hands it off and resets — the Obs scrape
    verb.  All mutation happens under ``_lock`` (sampler thread writes,
    scrape reads cross-thread)."""

    def __init__(
        self,
        hz: float = _DEF_HZ,
        depth: int = _DEF_DEPTH,
        max_stacks: int = _DEF_MAX_STACKS,
    ) -> None:
        self.hz = max(float(hz), 0.1)
        self.depth = int(depth)
        self.max_stacks = int(max_stacks)
        self.stacks: Dict[str, int] = {}
        self.samples = 0
        self.overflow = 0
        self.errors = 0
        self.self_cpu_s = 0.0
        self.started_at: Optional[float] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # ident → thread name, lazily rebuilt: only when a sampled
        # ident is unknown (a thread was spawned) or on the periodic
        # refresh in sample_once (drops names of dead threads).
        # threading.enumerate() per sample is the other avoidable
        # per-sample allocation on the hot path.
        self._names: Dict[int, str] = {}

    # -- lifecycle --------------------------------------------------------

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "SamplingProfiler":
        """Spawn the sampler thread (no-op if already running)."""
        if self.running:
            return self
        self._stop.clear()
        self.started_at = time.perf_counter()
        self._thread = threading.Thread(
            target=self._run, name="mrt-profiler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop and join the sampler (no-op if not running)."""
        t = self._thread
        if t is None:
            return
        self._stop.set()
        if t.is_alive() and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None

    # -- sampling ---------------------------------------------------------

    def _run(self) -> None:
        interval = 1.0 / self.hz
        rec = None
        try:  # local import: flightrec imports observe, not us — but
            from .flightrec import PROF, get_recorder  # keep lazy anyway
            rec = get_recorder()
        except Exception:
            PROF = 0
        last_wall = time.perf_counter()
        last_cpu = time.process_time()
        next_rec = last_wall + 1.0
        while not self._stop.wait(interval):
            t0 = time.thread_time()
            try:
                self.sample_once()
            except Exception:
                with self._lock:
                    self.errors += 1
            self.self_cpu_s += time.thread_time() - t0
            if rec is not None:
                now = time.perf_counter()
                if now >= next_rec:
                    next_rec = now + 1.0
                    # Busy per-mille: process CPU over wall since the
                    # last breadcrumb.  ~1000‰ means one thread pegged
                    # the window end to end (can exceed 1000 with
                    # several busy threads) — the doctor's CPU-
                    # saturation evidence.  Clamped to the record's
                    # u16 code field.
                    cpu = time.process_time()
                    dw = now - last_wall
                    busy = (
                        int(1000.0 * (cpu - last_cpu) / dw)
                        if dw > 0 else 0
                    )
                    last_wall, last_cpu = now, cpu
                    with self._lock:
                        hot = self._hottest_leaf()
                        rec.record(
                            PROF, max(0, min(busy, 64000)),
                            self.samples, len(self.stacks),
                            self.overflow, tag=hot,
                        )

    def sample_once(self) -> None:
        """Take exactly one sample of every thread but the sampler's
        own (callable directly — the deterministic test hook; the
        sampler thread calls it on its cadence)."""
        me = threading.get_ident()
        frames = sys._current_frames()
        names = self._names
        if (
            any(i != me and i not in names for i in frames)
            or self.samples % 256 == 0
        ):
            names = self._names = {
                t.ident: t.name
                for t in threading.enumerate()
                if t.ident is not None
            }
        with self._lock:
            self.samples += 1
            for ident, frame in frames.items():
                if ident == me:
                    continue
                tname = names.get(ident, f"tid-{ident}")
                key = f"{tname};{fold_frame(frame, self.depth)}"
                n = self.stacks.get(key)
                if n is not None:
                    self.stacks[key] = n + 1
                elif len(self.stacks) < self.max_stacks:
                    self.stacks[key] = 1
                else:
                    self.overflow += 1
                    okey = f"{tname};{OVERFLOW_FRAME}"
                    self.stacks[okey] = self.stacks.get(okey, 0) + 1

    def _hottest_leaf(self) -> str:
        """Leaf function of the highest-count stack (lock held)."""
        if not self.stacks:
            return ""
        key = max(self.stacks, key=self.stacks.__getitem__)
        return key.rsplit(";", 1)[-1][:20]

    # -- scrape -----------------------------------------------------------

    def _dump(self, reset: bool) -> Dict[str, Any]:
        with self._lock:
            out = {
                "hz": self.hz,
                "samples": self.samples,
                "overflow": self.overflow,
                "errors": self.errors,
                "self_cpu_s": round(self.self_cpu_s, 6),
                "stacks": dict(self.stacks),
            }
            if reset:
                self.stacks = {}
                self.samples = 0
                self.overflow = 0
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Read-only copy of the aggregate (counts keep accumulating)."""
        return self._dump(reset=False)

    def drain(self) -> Dict[str, Any]:
        """Hand off the aggregate and reset it — the scrape protocol:
        repeated drains never duplicate a sample.  ``self_cpu_s`` and
        ``errors`` stay cumulative (they are overhead/health telemetry,
        not window data)."""
        return self._dump(reset=True)


# -- process singleton ------------------------------------------------------

_proc_lock = threading.Lock()
_proc_profiler: Optional[SamplingProfiler] = None


def get_profiler() -> Optional[SamplingProfiler]:
    """The process's running profiler, if any (None when disabled or
    never started)."""
    return _proc_profiler


def maybe_start_profiler() -> Optional[SamplingProfiler]:
    """Start the per-process sampler if ``MRT_PROFILE`` allows it
    (idempotent; every RpcNode calls this at construction — first node
    in a process starts the sampler, the rest share it)."""
    global _proc_profiler
    if not _PROFILE:
        return None
    with _proc_lock:
        if _proc_profiler is None:
            _proc_profiler = SamplingProfiler()
            _proc_profiler.start()
        return _proc_profiler


# -- pure folded-stack algebra (shared by fleet merge / CLI / loadcurve) ----

def merge_folded(dumps: Iterable[Dict[str, int]]) -> Dict[str, int]:
    """Merge many ``{folded: count}`` aggregates into one (exact —
    sample counts add)."""
    out: Dict[str, int] = {}
    for d in dumps:
        for k, v in d.items():
            out[k] = out.get(k, 0) + int(v)
    return out


def diff_folded(
    after: Dict[str, int], before: Dict[str, int]
) -> Dict[str, int]:
    """``after − before`` per stack, clamped at 0 and 0-entries
    dropped — the window between two cumulative snapshots."""
    out: Dict[str, int] = {}
    for k, v in after.items():
        d = int(v) - int(before.get(k, 0))
        if d > 0:
            out[k] = d
    return out


def _split(key: str) -> Tuple[str, List[str]]:
    parts = key.split(";")
    return parts[0], parts[1:]


def per_thread_totals(folded: Dict[str, int]) -> Dict[str, int]:
    """Samples per thread name (first folded segment)."""
    out: Dict[str, int] = {}
    for k, v in folded.items():
        t, _ = _split(k)
        out[t] = out.get(t, 0) + int(v)
    return out


def top_functions(
    folded: Dict[str, int], n: int = 10
) -> List[Dict[str, Any]]:
    """Rank functions by SELF samples (leaf of the stack — where the
    CPU actually was), carrying cumulative (anywhere-on-stack) counts
    alongside: ``[{"func", "self", "cum"}, ...]``.  Cumulative counts
    a function once per stack it appears on (recursion doesn't double
    count).  Synthetic frames — ``(overflow)``, ``(...)`` — rank like
    any other so truncation is visible in the report."""
    self_c: Dict[str, int] = {}
    cum_c: Dict[str, int] = {}
    for k, v in folded.items():
        _, frames = _split(k)
        if not frames:
            continue
        v = int(v)
        leaf = frames[-1]
        self_c[leaf] = self_c.get(leaf, 0) + v
        for fn in set(frames):
            cum_c[fn] = cum_c.get(fn, 0) + v
    ranked = sorted(self_c.items(), key=lambda kv: (-kv[1], kv[0]))
    return [
        {"func": fn, "self": s, "cum": cum_c.get(fn, s)}
        for fn, s in ranked[:n]
    ]


def to_collapsed(folded: Dict[str, int]) -> str:
    """Render as flamegraph collapsed format: one ``stack count`` line
    per entry, sorted for determinism (feed to ``flamegraph.pl`` or
    speedscope directly)."""
    return "\n".join(
        f"{k} {int(v)}" for k, v in sorted(folded.items())
    )


def from_collapsed(text: str) -> Dict[str, int]:
    """Parse :func:`to_collapsed` output (tolerates blank lines)."""
    out: Dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, cnt = line.rpartition(" ")
        if not stack:
            continue
        try:
            out[stack] = out.get(stack, 0) + int(cnt)
        except ValueError:
            continue
    return out
