"""Subprocess launch + readiness plumbing and the blocking-clerk
base, shared by the cluster drivers in cluster.py and
engine_cluster.py (their own module so neither imports the other —
the round-4 decomposition must not create an import cycle)."""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Any, List

from ..sim.scheduler import TIMEOUT
from ..utils.knobs import knob_str
from .realtime import RealtimeScheduler
from .tcp import RpcNode

__all__ = [
    "launch_server",
    "check_ready",
    "reserve_ports",
    "BlockingClerkBase",
]


def launch_server(spec: dict, label: Any) -> subprocess.Popen:
    """Spawn one server subprocess (shared by both cluster drivers):
    env setup, optional MRT_SERVER_LOG_DIR stderr capture, Popen."""
    import json

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")  # server procs never need a chip
    # Server children never talk to the TPU tunnel: dropping the axon
    # activation env skips its sitecustomize entirely (measured 1.76 s
    # -> 0.05 s interpreter startup per child — across the suite's
    # ~50 children that was ~1.5 min of pure startup).
    env.pop("PALLAS_AXON_POOL_IPS", None)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    log_dir = knob_str("MRT_SERVER_LOG_DIR")
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        stderr = open(os.path.join(log_dir, f"server-{label}.err"), "a")
    else:
        stderr = subprocess.DEVNULL
    try:
        return subprocess.Popen(
            [sys.executable, "-m", "multiraft_tpu.distributed.cluster",
             json.dumps(spec)],
            stdout=subprocess.PIPE, stderr=stderr, env=env, text=True,
        )
    finally:
        if log_dir:
            stderr.close()


def check_ready(
    proc: subprocess.Popen, label: Any, timeout: float = 120.0
) -> None:
    """Block until the child prints its readiness line, bounded by
    ``timeout`` — a child that starts but hangs before printing (e.g.
    stuck in jax/native-build import) must not wedge the launcher
    forever.  On timeout the child is killed and the failure raised.
    Callers must register ``proc`` for reaping BEFORE calling this — a
    child that fails the check is still a live process."""
    import select
    import time as _time

    deadline = _time.monotonic() + timeout
    buf = ""
    while True:
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            proc.kill()
            proc.wait()  # reap — crash/restart loops must not pile zombies
            raise RuntimeError(
                f"server {label} produced no readiness line within "
                f"{timeout:.0f}s; killed"
            )
        ready, _, _ = select.select([proc.stdout], [], [], remaining)
        if not ready:
            continue
        chunk = os.read(proc.stdout.fileno(), 4096).decode(
            "utf-8", "replace"
        )
        if chunk == "":
            # EOF: the child is gone.  Reap and report HOW it died —
            # a negative returncode names the signal (a silent SIGKILL
            # reads very differently from a clean exit-1).
            rc = proc.wait()
            raise RuntimeError(
                f"server {label} failed to start (exit {rc}): {buf!r}"
            )
        buf += chunk
        if "\n" in buf:
            line = buf.split("\n", 1)[0]
            if not line.startswith("ready"):
                raise RuntimeError(
                    f"server {label} failed to start: {line!r}"
                )
            return

def reserve_ports(n: int, host: str) -> List[int]:
    import socket

    ports, socks = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind((host, 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class BlockingClerkBase:
    """Synchronous client facade: drives a generator-coroutine clerk on
    a RealtimeScheduler and blocks the calling thread for the result.
    Subclasses construct ``self._clerk`` (anything with get/put/append
    generator methods)."""

    sched: RealtimeScheduler
    node: RpcNode
    _clerk: Any

    def _run(self, gen, timeout: float) -> Any:
        fut = self.sched.spawn(gen)
        value = self.sched.wait(fut, timeout)
        if value is TIMEOUT:
            # Cancel the abandoned retry loop (resolving the spawn future
            # halts the coroutine at its next step) — otherwise it would
            # spin forever and race the caller's next command on this
            # single-outstanding-op clerk.
            self.sched.post(fut.resolve, TIMEOUT)
            raise TimeoutError("cluster did not answer in time")
        return value

    def get(self, key: str, timeout: float = 30.0) -> str:
        return self._run(self._clerk.get(key), timeout)

    def put(self, key: str, value: str, timeout: float = 30.0) -> None:
        self._run(self._clerk.put(key, value), timeout)

    def append(self, key: str, value: str, timeout: float = 30.0) -> None:
        self._run(self._clerk.append(key, value), timeout)

    def close(self) -> None:
        """Close the RPC node (its scheduler loop stops with it)."""
        self.node.close()

