"""Shared wire layer for the engine serving stack: command
dataclasses, deterministic key→group routing, opcode maps, and the
local-mesh helper.  Split out of engine_server.py (round 4) so the KV
service, the sharded service, the clerks, and the durability machinery
depend on one small module instead of each other.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Any

from ..porcupine.kv import OP_APPEND, OP_GET, OP_PUT
from ..transport import codec

__all__ = [
    "OK",
    "ERR_TIMEOUT",
    "ERR_BUSY",
    "EngineCmdArgs",
    "EngineCmdReply",
    "busy_reply",
    "retry_after_of",
    "route_group",
    "make_mesh",
]

OK = "OK"
ERR_TIMEOUT = "ErrTimeout"
# Admission-control shed: the dispatch layer refused the request before
# any handler saw it.  The reply carries a retry_after_s hint; clerks
# honor it with jitter (engine_clerks._busy_delay) instead of hammering.
ERR_BUSY = "ErrBusy"

_OPCODE = {"Get": OP_GET, "Put": OP_PUT, "Append": OP_APPEND}
_OPNAME = {v: k for k, v in _OPCODE.items()}


@codec.registered
@dataclasses.dataclass
class EngineCmdArgs:
    op: str = "Get"
    key: str = ""
    value: str = ""
    client_id: int = 0
    command_id: int = 0


@codec.registered
@dataclasses.dataclass
class EngineCmdReply:
    err: str = OK
    value: str = ""
    # Widened in round 8 (admission control).  Pickle bypasses
    # __init__, so a reply encoded by a pre-round-8 peer decodes
    # WITHOUT this attribute — always read it via retry_after_of(),
    # never reply.retry_after_s directly.
    retry_after_s: float = 0.0


def busy_reply(retry_after_s: float) -> EngineCmdReply:
    """The shed reply the dispatch layer sends in place of a handler
    result when admission refuses a request."""
    return EngineCmdReply(err=ERR_BUSY, retry_after_s=float(retry_after_s))


def retry_after_of(reply: Any) -> float:
    """Decode-compatible read of the retry hint: replies encoded by
    older peers lack the field entirely (pickle restores __dict__, not
    dataclass defaults)."""
    return float(getattr(reply, "retry_after_s", 0.0) or 0.0)


def route_group(key: str, G: int) -> int:
    """Deterministic key→group routing shared by every process (a
    stable hash — Python's builtin is salted per process)."""
    return zlib.crc32(key.encode()) % G


def make_mesh(n_devices: int) -> Any:  # jax.sharding.Mesh (jax imported lazily)
    """A 1-D ``groups`` mesh over the first ``n_devices`` local devices
    — the production entry to the shard_map tick (engine/mesh.py): the
    server's state lives sharded across its chips, consensus stays
    zero-collective, and the same driver/pump/checkpoint path serves
    single- and multi-chip alike."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if n_devices <= 0:
        raise ValueError(f"mesh_devices must be positive, got {n_devices}")
    devs = jax.devices()
    if n_devices > len(devs):
        raise ValueError(
            f"mesh_devices={n_devices} > {len(devs)} visible devices"
        )
    return Mesh(np.array(devs[:n_devices]), ("groups",))
