"""Shared wire layer for the engine serving stack: command
dataclasses, deterministic key→group routing, opcode maps, and the
local-mesh helper.  Split out of engine_server.py (round 4) so the KV
service, the sharded service, the clerks, and the durability machinery
depend on one small module instead of each other.
"""

from __future__ import annotations

import dataclasses
import zlib

from ..porcupine.kv import OP_APPEND, OP_GET, OP_PUT
from ..transport import codec
from ..utils.cpus import usable_cpus

__all__ = [
    "OK",
    "ERR_TIMEOUT",
    "EngineCmdArgs",
    "EngineCmdReply",
    "route_group",
    "make_mesh",
]

OK = "OK"
ERR_TIMEOUT = "ErrTimeout"

_OPCODE = {"Get": OP_GET, "Put": OP_PUT, "Append": OP_APPEND}
_OPNAME = {v: k for k, v in _OPCODE.items()}


@codec.registered
@dataclasses.dataclass
class EngineCmdArgs:
    op: str = "Get"
    key: str = ""
    value: str = ""
    client_id: int = 0
    command_id: int = 0


@codec.registered
@dataclasses.dataclass
class EngineCmdReply:
    err: str = OK
    value: str = ""


def route_group(key: str, G: int) -> int:
    """Deterministic key→group routing shared by every process (a
    stable hash — Python's builtin is salted per process)."""
    return zlib.crc32(key.encode()) % G


class PumpCadence:
    """Adaptive pump scheduling shared by the serving loops: pump HOT
    (a fraction of the idle interval) while client work is in flight,
    idle cadence otherwise.  The fixed-interval loop leaves the pump
    ~half idle under load (measured: the in-process framed ceiling
    rises 28k → 45k ops/s at a fixed hot cadence); the idle interval
    still bounds the steady-state CPU burn, and the hot interval keeps
    a real idle window each cycle so the socket reactor (the
    scheduler's idle wait) continues to run.

    GATED ON CORE COUNT, like the transport's adaptive busy-poll
    (tcp.py MRT_SPIN_US): on a single-CPU box the hot pump steals the
    co-located clients' cycles and the end-to-end number DROPS
    (measured −38% on the 1-core test VM), so single-core hosts keep
    the fixed cadence.  ``MRT_PUMP_HOT=1/0`` overrides."""

    HOT_DIV = 5     # hot interval = interval / HOT_DIV
    HOT_PUMPS = 3   # stay hot this many pumps past the last work

    def __init__(self, interval: float) -> None:
        import os

        self.interval = interval
        self.hot_interval = interval / self.HOT_DIV
        default = "1" if usable_cpus() > 1 else "0"
        self.enabled = os.environ.get("MRT_PUMP_HOT", default) == "1"
        self._hot = 0

    def next_delay(self, busy: bool) -> float:
        """``busy`` = the service observed in-flight work this pump
        (entries applied, or commands waiting in the backlog)."""
        if not self.enabled:
            return self.interval
        if busy:
            self._hot = self.HOT_PUMPS
        elif self._hot:
            self._hot -= 1
        return self.hot_interval if self._hot else self.interval


def service_busy(svc) -> bool:
    """The serving loops' shared work-pending signal: the last sweep
    applied entries, or submitted commands await ingestion."""
    return bool(svc.last_applied) or bool(svc.driver.backlog.any())


def make_mesh(n_devices: int):
    """A 1-D ``groups`` mesh over the first ``n_devices`` local devices
    — the production entry to the shard_map tick (engine/mesh.py): the
    server's state lives sharded across its chips, consensus stays
    zero-collective, and the same driver/pump/checkpoint path serves
    single- and multi-chip alike."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if n_devices <= 0:
        raise ValueError(f"mesh_devices must be positive, got {n_devices}")
    devs = jax.devices()
    if n_devices > len(devs):
        raise ValueError(
            f"mesh_devices={n_devices} > {len(devs)} visible devices"
        )
    return Mesh(np.array(devs[:n_devices]), ("groups",))
