"""Shared wire layer for the engine serving stack: command
dataclasses, deterministic key→group routing, opcode maps, and the
local-mesh helper.  Split out of engine_server.py (round 4) so the KV
service, the sharded service, the clerks, and the durability machinery
depend on one small module instead of each other.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Any

from ..porcupine.kv import OP_APPEND, OP_GET, OP_PUT
from ..transport import codec

__all__ = [
    "OK",
    "ERR_TIMEOUT",
    "EngineCmdArgs",
    "EngineCmdReply",
    "route_group",
    "make_mesh",
]

OK = "OK"
ERR_TIMEOUT = "ErrTimeout"

_OPCODE = {"Get": OP_GET, "Put": OP_PUT, "Append": OP_APPEND}
_OPNAME = {v: k for k, v in _OPCODE.items()}


@codec.registered
@dataclasses.dataclass
class EngineCmdArgs:
    op: str = "Get"
    key: str = ""
    value: str = ""
    client_id: int = 0
    command_id: int = 0


@codec.registered
@dataclasses.dataclass
class EngineCmdReply:
    err: str = OK
    value: str = ""


def route_group(key: str, G: int) -> int:
    """Deterministic key→group routing shared by every process (a
    stable hash — Python's builtin is salted per process)."""
    return zlib.crc32(key.encode()) % G


def make_mesh(n_devices: int) -> Any:  # jax.sharding.Mesh (jax imported lazily)
    """A 1-D ``groups`` mesh over the first ``n_devices`` local devices
    — the production entry to the shard_map tick (engine/mesh.py): the
    server's state lives sharded across its chips, consensus stays
    zero-collective, and the same driver/pump/checkpoint path serves
    single- and multi-chip alike."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if n_devices <= 0:
        raise ValueError(f"mesh_devices must be positive, got {n_devices}")
    devs = jax.devices()
    if n_devices > len(devs):
        raise ValueError(
            f"mesh_devices={n_devices} > {len(devs)} visible devices"
        )
    return Mesh(np.array(devs[:n_devices]), ("groups",))
