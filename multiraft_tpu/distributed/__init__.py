"""Real-deployment runtime: wall-clock event loop + TCP transport.

The simulated stack (``multiraft_tpu.sim`` + ``multiraft_tpu.transport``)
is the test fabric, exactly like the reference where "serving" means
constructing servers inside a simulated network (SURVEY §0).  This
package is the part the reference *doesn't* have: the same RaftNode /
KVServer / ShardCtrler objects deployed across real OS processes over
real sockets, with durable on-disk persistence — the runtime you point
actual clients at.

Components:

* :mod:`realtime`  — ``RealtimeScheduler``: the sim ``Scheduler`` API
  (call_after / futures / coroutine spawn) on a wall-clock event-loop
  thread, so every sim-tested component runs unmodified in real time.
* :mod:`native`    — C++ epoll framed-TCP transport (plain C ABI +
  ctypes; built on first use like the porcupine native checker).
* :mod:`tcp`       — RPC endpoints over that transport exposing the
  ``ClientEnd.call → Future`` contract.
* :mod:`disk`      — ``DiskPersister``: crash-atomic file-backed
  (state, snapshot) pair store.
* :mod:`cluster`   — multi-process cluster launcher for Raft/KV server
  groups, plus in-process client clerks.
"""

from .disk import DiskPersister
from .realtime import RealtimeScheduler

__all__ = ["DiskPersister", "RealtimeScheduler"]
