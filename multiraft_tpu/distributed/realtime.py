"""Wall-clock event loop with the sim Scheduler's exact surface.

Every framework component (RaftNode, KVServer, ShardCtrler, clerks) is
written against the virtual-time ``Scheduler`` API: timers via
``call_at/call_after/call_soon``, suspension via ``Future``, blocking
control flow via generator coroutines (``spawn``).  This class provides
the same contract on real time: one event-loop thread owns all callback
execution (so the single-threaded mutation model the sim guarantees by
construction still holds), a monotonic clock replaces virtual ``now``,
and a thread-safe ``post`` lets IO threads (the TCP transport) marshal
completions onto the loop.

This is the deployment analog of the reference's goroutine runtime
(reference: raft/raft.go:51-87) — except there is exactly one mutator
thread, so the reference's mutex discipline (raft/raft.go:22) has no
equivalent to get wrong.
"""

from __future__ import annotations

import heapq
import threading
import time
import types
from typing import Any, Callable, Generator, Optional

from ..sim.scheduler import TIMEOUT, Future, Timer
from ..utils.cpus import usable_cpus
from ..utils.knobs import knob_bool
from .sanitize import get_sanitizer

__all__ = [
    "RealtimeScheduler",
    "IoScheduler",
    "PumpCadence",
    "Backoff",
    "service_busy",
]


class Backoff:
    """Bounded exponential backoff with equal jitter, for clerk retry
    loops.  Without it, a fast-failing RPC (connection refused while a
    server restarts, a partitioned minority answering instantly) turns
    the reference retry loop into a hot spin — thousands of doomed
    calls per second hammering the exact process trying to recover.

    ``next_delay()`` draws uniformly from ``[cur/2, cur]`` (equal
    jitter: a floor keeps the loop off the CPU, the random half
    de-synchronizes clerks that failed together), then doubles ``cur``
    up to ``cap``.  ``reset()`` on success re-arms the fast first
    retry."""

    def __init__(
        self,
        base: float = 0.02,
        cap: float = 1.0,
        factor: float = 2.0,
        rng: Optional[Any] = None,
    ) -> None:
        import random

        self.base = base
        self.cap = cap
        self.factor = factor
        self._cur = base
        self._rng = rng if rng is not None else random.Random()

    def next_delay(self) -> float:
        cur = self._cur
        self._cur = min(self.cap, cur * self.factor)
        return cur / 2.0 + self._rng.random() * (cur / 2.0)

    def jittered(self, base: float) -> float:
        """Equal-jitter a caller-supplied delay — a server's
        ``retry_after_s`` hint, a fixed config-wait — WITHOUT advancing
        the doubling state.  The server hands the same hint to every
        clerk it sheds; a deterministic wait would re-synchronize them
        into the next thundering herd."""
        return base / 2.0 + self._rng.random() * (base / 2.0)

    def reset(self) -> None:
        self._cur = self.base


class RealtimeScheduler:
    """Drop-in wall-clock implementation of the sim ``Scheduler`` API.

    ``now`` is seconds on a monotonic clock (an absolute epoch is never
    exposed, matching the sim's relative-time semantics).  All callbacks
    — timer fires, future resolutions, coroutine steps — execute on the
    single loop thread.  External threads interact only through
    :meth:`post` and :meth:`wait`.
    """

    def __init__(self, name: str = "multiraft-loop") -> None:
        self._origin = time.monotonic()
        self._heap: list[tuple[float, int, Timer]] = []
        self._seq = 0
        self._lock = threading.Lock()
        self._wakeup = threading.Condition(self._lock)
        self._stopped = False
        self.fired_events = 0
        # Runtime sanitizer (MRT_SANITIZE=1): every callback the loop
        # runs goes through its duration-budget shim.  None = off =
        # one `is None` check per dispatch.
        self._san = get_sanitizer()
        # ``name`` is the loop thread's name — the profiler keys CPU
        # attribution by it (profile.py), so multi-node processes pass
        # a per-node suffix ("multiraft-loop/9001") to keep their
        # loops distinguishable in the fleet flame.
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    # -- clock ------------------------------------------------------------

    @property
    def now(self) -> float:
        return time.monotonic() - self._origin

    def on_loop_thread(self) -> bool:
        """True when called from the loop thread (the only thread that
        may touch loop-owned state like RpcNode's reply queue)."""
        return threading.current_thread() is self._thread

    def flush_io(self) -> None:
        """Force any pending IO flush now.  No-op here; IoScheduler
        overrides it.  Long-running timer callbacks (an engine pump
        about to grind for milliseconds) call this first so replies
        already queued don't wait them out."""

    # -- scheduling (sim-compatible) --------------------------------------

    def call_at(self, when: float, fn: Callable, *args: Any) -> Timer:
        timer = Timer(when, fn, args)
        with self._lock:
            self._seq += 1
            heapq.heappush(self._heap, (when, self._seq, timer))
            self._wakeup.notify()
        return timer

    def call_after(self, delay: float, fn: Callable, *args: Any) -> Timer:
        return self.call_at(self.now + delay, fn, *args)

    def call_soon(self, fn: Callable, *args: Any) -> Timer:
        return self.call_at(self.now, fn, *args)

    # ``post`` is the documented thread-safe entry point; internally
    # call_at already locks, so they share one implementation.
    post = call_soon

    # -- futures / coroutines (same semantics as sim Scheduler) -----------

    def sleep(self, delay: float) -> Future:
        fut = Future()
        self.call_after(delay, fut.resolve, None)
        return fut

    def with_timeout(self, fut: Future, timeout: float) -> Future:
        out = Future()
        timer = self.call_after(timeout, out.resolve, TIMEOUT)

        def _done(f: Future) -> None:
            timer.cancel()
            out.resolve(f.value)

        fut.add_done_callback(_done)
        return out

    def spawn(self, gen: Generator) -> Future:
        result = Future()
        if not isinstance(gen, types.GeneratorType):
            result.resolve(gen)
            return result

        def step(send_value: Any) -> None:
            if result.done:  # cancelled from outside (BlockingClerk timeout)
                gen.close()
                return
            try:
                waited = gen.send(send_value)
            except StopIteration as stop:
                result.resolve(stop.value)
                return
            if isinstance(waited, Future):
                # Step inline on resolution — the sim Scheduler's exact
                # semantics (sim/scheduler.py spawn).  Safe because every
                # resolve already runs on the loop thread; posting would
                # add a heap round trip per coroutine step.
                waited.add_done_callback(lambda f: step(f.value))
            elif isinstance(waited, (int, float)):
                self.call_after(float(waited), step, None)
            else:  # pragma: no cover - defensive
                raise TypeError(f"coroutine yielded {waited!r}")

        self.call_soon(step, None)
        return result

    # -- cross-thread waiting ---------------------------------------------

    def wait(self, fut: Future, timeout: Optional[float] = None) -> Any:
        """Block the *calling* (non-loop) thread until ``fut`` resolves.

        Returns the future's value, or :data:`TIMEOUT` on timeout.  The
        external-thread analog of the sim's ``run_until``.

        ``Future`` is not thread-safe (it never needs to be on the loop),
        so the callback is *attached on the loop thread* — the same
        thread every resolve runs on — making the done-check/append
        sequence race-free by construction.
        """
        done = threading.Event()
        box: list[Any] = []

        def _resolved(f: Future) -> None:
            box.append(f.value)
            done.set()

        self.post(lambda: fut.add_done_callback(_resolved))
        if not done.wait(timeout):
            return TIMEOUT
        return box[0]

    def run_call(self, fn: Callable, *args: Any, timeout: float = 30.0) -> Any:
        """Run ``fn(*args)`` on the loop thread and return its result to
        the calling thread; exceptions propagate to the caller instead of
        dying on the loop (construction-time errors must be loud)."""
        fut = Future()

        def _invoke() -> None:
            try:
                fut.resolve((True, fn(*args)))
            except BaseException as e:  # noqa: BLE001 - transported
                fut.resolve((False, e))

        self.post(_invoke)
        out = self.wait(fut, timeout)
        if out is TIMEOUT:
            raise TimeoutError(f"run_call timed out after {timeout}s")
        ok, value = out
        if not ok:
            raise value
        return value

    # -- lifecycle ---------------------------------------------------------

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            self._wakeup.notify()
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._stopped:
                    return
                while True:
                    if not self._heap:
                        self._wakeup.wait()
                        if self._stopped:
                            return
                        continue
                    when, _, timer = self._heap[0]
                    if timer.cancelled:
                        heapq.heappop(self._heap)
                        continue
                    delay = when - self.now
                    if delay <= 0:
                        heapq.heappop(self._heap)
                        break
                    self._wakeup.wait(delay)
                    if self._stopped:
                        return
                fn, args = timer._fn, timer._args
                timer._fn, timer._args = None, ()
            if fn is None:  # cancelled between pop and dispatch
                continue
            self.fired_events += 1
            try:
                if self._san is not None:
                    self._san.run_callback(fn, *args)
                else:
                    fn(*args)
            except Exception:  # pragma: no cover - keep the loop alive
                import traceback

                traceback.print_exc()


class IoScheduler(RealtimeScheduler):
    """A :class:`RealtimeScheduler` whose loop thread is ALSO the IO
    dispatcher: instead of sleeping on a condition variable between
    timers, it blocks in ``io_poll`` (the native transport's inline
    epoll reactor) and handles each event with ``io_handle`` right on
    the loop thread.

    This erases the sim-era thread topology's latency tax.  With a
    separate poller thread, every inbound frame costs two futex
    handoffs (transport → poller condvar, poller → loop ``post``);
    here a frame goes kernel → loop thread → handler inline, so a
    serial RPC round trip crosses exactly one wakeup per process.

    ``io_wake`` must interrupt a blocked ``io_poll`` (it returns
    ``None``); cross-thread ``call_at``/``post``/``stop`` use it in
    place of the condvar notify.  Wakes are level-triggered in the
    transport (an eventfd counter), so a wake that lands before the
    poll starts is not lost.

    ``io_flush`` (optional) runs on the loop thread at two points,
    distinguished by its ``force`` argument.  ``io_flush(True)`` runs
    immediately before every ``io_poll`` — nothing may sit queued while
    the loop blocks.  ``io_flush(False)`` runs after every timer
    callback, and the hook may decline it: under saturation the timer
    heap is never empty (pump ticks requeue faster than they run), so
    the before-poll flush can starve for many milliseconds — a convoy
    where every client waits on replies stuck behind engine compute.
    The soft flush bounds that starvation at one callback, while still
    letting the hook accumulate replies across back-to-back cheap
    callbacks into one vectored write per connection.
    """

    def __init__(
        self,
        io_poll: Callable[[float], Any],
        io_handle: Callable[[Any], None],
        io_wake: Callable[[], None],
        idle_max: float = 0.2,
        io_flush: Optional[Callable[[bool], None]] = None,
        name: str = "multiraft-loop",
    ) -> None:
        self._io_poll = io_poll
        self._io_handle = io_handle
        self._io_wake = io_wake
        self._io_flush = io_flush
        self._idle_max = idle_max
        super().__init__(name=name)

    def flush_io(self) -> None:
        """Run the io_flush hook forced, from the loop thread.  The
        entry point for callbacks that KNOW they are about to block the
        loop for a while (engine pump ticks): queued replies leave
        before the grind instead of aging through it."""
        if self._io_flush is not None and self.on_loop_thread():
            self._io_flush(True)

    def call_at(self, when: float, fn: Callable, *args: Any) -> Timer:
        timer = Timer(when, fn, args)
        with self._lock:
            self._seq += 1
            heapq.heappush(self._heap, (when, self._seq, timer))
        # The loop blocks in io_poll, not on the condvar — interrupt it
        # unless we ARE the loop (it re-checks the heap after every
        # callback and IO event anyway, so a self-wake is pure syscall
        # overhead on the hot path).
        if threading.current_thread() is not self._thread:
            self._io_wake()
        return timer

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
        self._io_wake()
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while True:
            fn = args = None
            popped = False
            with self._lock:
                if self._stopped:
                    return
                delay = self._idle_max
                while self._heap:
                    when, _, timer = self._heap[0]
                    if timer.cancelled:
                        heapq.heappop(self._heap)
                        continue
                    d = when - self.now
                    if d <= 0:
                        heapq.heappop(self._heap)
                        fn, args = timer._fn, timer._args
                        timer._fn, timer._args = None, ()
                        popped = True
                    else:
                        delay = min(d, self._idle_max)
                    break
            if popped:
                if fn is not None:  # else cancelled between push and pop
                    self.fired_events += 1
                    try:
                        if self._san is not None:
                            self._san.run_callback(fn, *args)
                        else:
                            fn(*args)
                    except Exception:  # pragma: no cover - keep loop alive
                        import traceback

                        traceback.print_exc()
                    # Soft flush after every timer callback: the hook
                    # flushes only replies old enough that waiting out
                    # another (potentially milliseconds-long) pump tick
                    # would hurt, and keeps batching fresh ones.
                    if self._io_flush is not None:
                        try:
                            self._io_flush(False)
                        except Exception:  # pragma: no cover
                            import traceback

                            traceback.print_exc()
                continue
            if self._io_flush is not None:
                try:
                    self._io_flush(True)
                except Exception:  # pragma: no cover - keep the loop alive
                    import traceback

                    traceback.print_exc()
            ev = self._io_poll(delay)
            if ev is not None:
                self.fired_events += 1
                try:
                    if self._san is not None:
                        self._san.run_callback(self._io_handle, ev)
                    else:
                        self._io_handle(ev)
                except Exception:  # pragma: no cover - keep the loop alive
                    import traceback

                    traceback.print_exc()


class PumpCadence:
    """Adaptive pump scheduling shared by the serving loops: pump HOT
    (a fraction of the idle interval) while client work is in flight,
    idle cadence otherwise.  The fixed-interval loop leaves the pump
    ~half idle under load (measured: the in-process framed ceiling
    rises 28k → 45k ops/s at a fixed hot cadence); the idle interval
    still bounds the steady-state CPU burn, and the hot interval keeps
    a real idle window each cycle so the socket reactor (the
    scheduler's idle wait) continues to run.

    GATED ON CORE COUNT, like the transport's adaptive busy-poll
    (tcp.py MRT_SPIN_US): on a single-CPU box the hot pump steals the
    co-located clients' cycles and the end-to-end number DROPS
    (measured −38% on the 1-core test VM), so single-core hosts keep
    the fixed cadence.  ``MRT_PUMP_HOT=1/0`` overrides."""

    HOT_DIV = 5     # hot interval = interval / HOT_DIV
    HOT_PUMPS = 3   # stay hot this many pumps past the last work

    def __init__(self, interval: float) -> None:
        self.interval = interval
        self.hot_interval = interval / self.HOT_DIV
        self.enabled = knob_bool("MRT_PUMP_HOT",
                                 default=usable_cpus() > 1)
        self._hot = 0

    def next_delay(self, busy: bool) -> float:
        """``busy`` = the service observed in-flight work this pump
        (entries applied, or commands waiting in the backlog)."""
        if not self.enabled:
            return self.interval
        if busy:
            self._hot = self.HOT_PUMPS
            return self.hot_interval
        if self._hot:
            self._hot -= 1
            return self.hot_interval
        return self.interval


def service_busy(svc) -> bool:
    """The serving loops' shared work-pending signal: the last sweep
    applied entries, or submitted commands await ingestion."""
    return bool(svc.last_applied) or bool(svc.driver.backlog.any())
