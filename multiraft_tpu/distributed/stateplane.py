"""Durable state plane: snapshot shipping, WAL tailing, and stateful
cross-host failover (ARCHITECTURE §15).

The placement controller (placement.py) heals a dead process by
re-adopting its groups — but until this module, it adopted them EMPTY
(`adopt_gid(blob=None)`, the non-durable crash model): acknowledged
writes died with the host.  The state plane closes that hole without
sealing anything:

* Each hosted group's applied state is exported on a cadence via the
  non-sealing :meth:`BatchedShardKV.snapshot_group` (the
  ``export_group`` blob shape) and **shipped** to one or more standby
  processes chosen by declarative placement rules — an ordered list of
  ``(regex, ShipSpec)`` pairs matched against ``gid-<n>`` (and an
  optional operator label), first match wins, SNIPPETS.md [2]'s
  ``match_partition_rules`` style.  Specs express pin (only these
  procs), anti-affinity (never these procs), and spread (N copies
  rotated across distinct candidates).
* Every client write the group applies after the snapshot is **tailed**
  to the same standbys as a per-group redo record carrying its original
  ``(client_id, command_id)``, so data loss is bounded to the shipping
  window (``MRT_SHIP_WINDOW_S``) rather than "everything since boot".
* On ``kill_mesh_process`` the controller consults the standbys, picks
  the one with the freshest ``(snapshot, tail)`` pair
  (:func:`pick_freshest`), and recovers through the EXISTING adopt
  path: ``adopt_gid(blob=recovery_blob(...))`` then re-submit the tail
  through the group's own log with the original session ids — the
  per-shard dedup tables travel inside the blob, so replay is
  exactly-once (the engine_durability.py recovery contract).  Empty
  adoption remains the explicit fallback only when no shipped state
  exists.

Shipped payloads reuse the WAL's ``magic ‖ crc32 ‖ len ‖ body``
torn-tail framing (:func:`frame_blob` / :func:`unframe_blob`, magic
``MRSP``): a half-received or bit-flipped shipment fails the CRC at the
standby and is discarded — never stored, never adopted.

Freshness ordering across ownership changes: every
:class:`StatePlane` incarnation mints a unique owner token, each
shipment carries it, and standbys adopt a new token by resetting the
group's shipped state.  At recovery time :func:`pick_freshest` first
picks the most recently fed token (the latest incarnation), then the
highest ``(tail_seq, snap_seq)`` within it — a standby holding a long
tail from a PREVIOUS owner never outranks a short tail from the owner
that actually died.
"""

from __future__ import annotations

import dataclasses
import heapq
import os
import re
import struct
import time
import zlib
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..services.shardkv import SERVING, key2shard
from ..utils.knobs import knob_bool, knob_float, knob_int
from ..transport import codec

__all__ = [
    "ShipSpec",
    "match_ship_rules",
    "choose_standbys",
    "frame_blob",
    "unframe_blob",
    "ship_knobs",
    "StatePlane",
    "StandbyStore",
    "pick_freshest",
    "recovery_blob",
    "redo_record",
    "replay_tail",
    "iter_replay_tail",
]


# ---------------------------------------------------------------------------
# Declarative shipping rules (SNIPPETS.md [2] match_partition_rules style)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ShipSpec:
    """Where a group's shipped state may live.

    * ``copies`` — how many distinct standbys receive it (spread).
    * ``pin`` — restrict standbys to these procs (empty = any).
    * ``avoid`` — anti-affinity: never these procs.
    """

    copies: int = 1
    pin: Tuple[int, ...] = ()
    avoid: Tuple[int, ...] = ()


#: The no-rule fallback: one copy, anywhere but the owner.
DEFAULT_SPEC = ShipSpec()


def match_ship_rules(
    rules: List[Tuple[str, ShipSpec]], name: str
) -> ShipSpec:
    """First ``re.search`` match wins; no match falls back to
    :data:`DEFAULT_SPEC` (shipping is on by default — an unmatched
    group still gets one standby, it is never silently unprotected)."""
    for rule, spec in rules or ():
        if re.search(rule, name) is not None:
            return spec
    return DEFAULT_SPEC


def choose_standbys(
    gid: int,
    owner: int,
    procs: List[int],
    rules: Optional[List[Tuple[str, ShipSpec]]] = None,
    label: str = "",
) -> List[int]:
    """Resolve ``gid``'s standby set: match the rules against
    ``gid-<n>`` (plus the operator label, so rules can target either),
    filter candidates by pin/anti-affinity, and rotate the starting
    candidate by gid so different groups spread across different
    standbys deterministically."""
    name = f"gid-{gid}" if not label else f"gid-{gid} {label}"
    spec = match_ship_rules(rules or [], name)
    cands = [p for p in procs if p != owner and p not in spec.avoid]
    if spec.pin:
        cands = [p for p in cands if p in spec.pin]
    if not cands:
        return []
    start = gid % len(cands)
    order = cands[start:] + cands[:start]
    return order[: max(1, spec.copies)]


# ---------------------------------------------------------------------------
# Shipment framing — the WAL's torn-tail contract (wal.py)
# ---------------------------------------------------------------------------

_MAGIC = b"MRSP"
_HEADER = struct.Struct("<4sIQ")  # magic, crc32, body-len (wal.py shape)
_LEN = struct.Struct("<Q")


def frame_blob(body: bytes) -> bytes:
    """``magic ‖ crc32 ‖ len ‖ body`` — crc covers len+body, so a
    truncated length field can never validate (wal.py's contract)."""
    crc = zlib.crc32(body, zlib.crc32(_LEN.pack(len(body))))
    return _HEADER.pack(_MAGIC, crc, len(body)) + body


def unframe_blob(buf: bytes) -> Optional[bytes]:
    """Inverse of :func:`frame_blob`; ``None`` on ANY damage — wrong
    magic, torn tail, truncation, bit flip.  Never raises: a corrupt
    shipment is discarded, not adopted."""
    if not isinstance(buf, (bytes, bytearray, memoryview)):
        return None
    buf = bytes(buf)
    if len(buf) < _HEADER.size:
        return None
    try:
        magic, crc, n = _HEADER.unpack_from(buf, 0)
    except struct.error:
        return None
    if magic != _MAGIC:
        return None
    if len(buf) != _HEADER.size + n:
        return None
    body = buf[_HEADER.size:]
    if zlib.crc32(body, zlib.crc32(_LEN.pack(n))) != crc:
        return None
    return body


# ---------------------------------------------------------------------------
# Knobs
# ---------------------------------------------------------------------------


def ship_knobs() -> Dict[str, float]:
    """Env-resolved shipping knobs (placement.py's place_knobs shape).

    * ``MRT_SHIP_WINDOW_S`` — snapshot cadence; the bound on data loss
      when async shipping races a death (default 5.0 s).
    * ``MRT_SHIP_TAIL_CAP`` — re-snapshot early once the unshipped tail
      exceeds this many records (bounds standby replay time).
    * ``MRT_SHIP_SYNC`` — 1 = acks gate on shipment (zero acknowledged-
      write loss; the durable chaos gate runs with this on).
    """
    return {
        "window_s": knob_float("MRT_SHIP_WINDOW_S"),
        "tail_cap": float(knob_int("MRT_SHIP_TAIL_CAP")),
        "sync": 1.0 if knob_bool("MRT_SHIP_SYNC") else 0.0,
    }


# ---------------------------------------------------------------------------
# Owner side: StatePlane
# ---------------------------------------------------------------------------


class StatePlane:
    """Per-process shipper: captures each hosted group's applied writes
    (chained onto ``skv.on_write``), snapshots on a cadence, and ships
    snapshot+tail to rule-chosen standbys through a caller-provided
    ``send(proc, payload_bytes) -> Optional[dict]`` delivery hook (a
    direct function call in the in-process fleet, an RPC in the socket
    fleet).

    The standby's reply reports its contiguous frontier
    (``{"ok": bool, "have": tail_seq}``); the shipper resends from
    ``have + 1``.  The ``have`` frontier is AUTHORITATIVE — the shipper
    believes it regardless of which payload the reply answered, so an
    async delivery hook (the socket server keeps one in-flight ship RPC
    per standby and hands back last round's reply) composes without any
    payload↔reply pairing.  The full tail since the last shipped
    snapshot is retained owner-side, so any standby can always be
    caught up or re-based on a fresh snapshot.
    """

    def __init__(
        self,
        skv,
        *,
        me: int,
        n_procs: int,
        send: Callable[[int, bytes], Optional[dict]],
        rules: Optional[List[Tuple[str, ShipSpec]]] = None,
        labels: Optional[Dict[int, str]] = None,
        window_s: Optional[float] = None,
        tail_cap: Optional[int] = None,
        sync: Optional[bool] = None,
        wal_seq_fn: Optional[Callable[[], int]] = None,
        obs=None,
        recorder=None,
        clock=time.monotonic,
    ) -> None:
        k = ship_knobs()
        self.skv = skv
        self.me = me
        self.n_procs = n_procs
        self.send = send
        self.rules = list(rules or [])
        self.labels = dict(labels or {})
        self.window_s = k["window_s"] if window_s is None else window_s
        self.tail_cap = int(
            k["tail_cap"] if tail_cap is None else tail_cap
        )
        self.sync = bool(k["sync"]) if sync is None else bool(sync)
        self._wal_seq_fn = wal_seq_fn
        self._obs = obs
        self._rec = recorder
        self._clock = clock
        # Unique per incarnation: standbys key freshness on it so a
        # previous owner's stale tail never outranks the live owner's.
        self.token = f"{me}.{os.urandom(6).hex()}"
        # Per-gid capture state.
        self._tail_seq: Dict[int, int] = {}       # last captured seq
        self._tail: Dict[int, deque] = {}         # (seq, record, wal_seq)
        self._snap: Dict[int, Dict[str, Any]] = {}  # framed-ready snapshot
        self._snap_seq: Dict[int, int] = {}       # tail seq at snapshot
        self._snap_ts: Dict[int, float] = {}
        # Per-(gid, standby) acked tail frontier — the standby's own
        # "have" reply, believed verbatim.
        self._acked_tail: Dict[Tuple[int, int], int] = {}
        # (tail_seq, wal_seq) pairs not yet covered by ANY standby —
        # drained by _apply_reply, the source of the sync gate and the
        # lag metric.  Bounded in async mode (coverage is advisory
        # there); unbounded in sync mode by necessity (dropping an
        # entry would ack a write that was never shipped).
        self._unacked: Dict[int, deque] = {}
        # Sync-ship gate: wal seqs of writes not yet acked by ANY
        # standby (lazy-deletion min-heap — see covered()).
        self._unshipped: List[int] = []
        self._shipped_wal: set = set()
        # now() of the last moment each gid was fully shipped — the
        # doctor's "data loss window" is measured against this.
        self._covered_ts: Dict[int, float] = {}
        self._t0 = self._clock()
        self._prev_on_write = None
        self.rounds = 0

    # -- capture ---------------------------------------------------------

    def attach(self) -> None:
        """Chain onto ``skv.on_write`` AFTER any existing hook (the
        durability hook logs to the WAL first, so ``wal_seq_fn`` read
        here names the record that covers this write)."""
        self._prev_on_write = self.skv.on_write
        prev = self._prev_on_write

        def hook(gid: int, op) -> None:
            if prev is not None:
                prev(gid, op)
            self.note_write(gid, op)

        self.skv.on_write = hook

    def detach(self) -> None:
        self.skv.on_write = self._prev_on_write
        self._prev_on_write = None

    def note_write(self, gid: int, op) -> None:
        """Capture one applied client write into ``gid``'s tail."""
        if op.op not in ("Put", "Append"):
            return
        seq = self._tail_seq.get(gid, 0) + 1
        self._tail_seq[gid] = seq
        wal_seq = self._wal_seq_fn() if self._wal_seq_fn else 0
        rec = (op.op, op.key, op.value, op.client_id, op.command_id)
        # Bounded by maybe_snapshot: a tail past MRT_SHIP_TAIL_CAP
        # forces an early re-snapshot that prunes seqs <= snap_seq (and
        # a snapshot refusal only happens mid-migration, when the
        # sealed group takes no writes).
        self._tail.setdefault(gid, deque()).append(  # graftlint: disable=unbounded-queue
            (seq, rec, wal_seq)
        )
        un = self._unacked.get(gid)
        if un is None:
            # Sync mode may never drop an entry (each is an unshipped
            # acked-write obligation); async coverage is advisory.
            un = self._unacked[gid] = deque(
                maxlen=None if self.sync else 65536
            )
        # Async: deque maxlen above bounds it.  Sync: each entry is an
        # unshipped acked-write obligation and the ack gate stalls
        # writers until standbys ack — backpressure, not growth.
        un.append((seq, wal_seq))  # graftlint: disable=unbounded-queue
        if self.sync and wal_seq:
            heapq.heappush(self._unshipped, wal_seq)

    def forget_group(self, gid: int) -> None:
        """Drop capture state after the group migrates away.  Its
        unshipped wal seqs are released (the sealed export blob carried
        the data) so they never wedge the global sync gate."""
        if self.sync:
            for _seq, w in self._unacked.get(gid, ()):
                if w:
                    self._shipped_wal.add(w)
        for d in (self._tail_seq, self._tail, self._snap,
                  self._snap_seq, self._snap_ts, self._unacked,
                  self._covered_ts):
            d.pop(gid, None)
        for key in [k for k in self._acked_tail if k[0] == gid]:
            self._acked_tail.pop(key, None)

    # -- sync-ship ack gate ---------------------------------------------

    def covered(self, wal_seq: int) -> bool:
        """True once the write logged at ``wal_seq`` has been shipped to
        (and acked by) at least one standby.  The EngineDurability
        composite sync gate (``extra_sync_gate``) calls this so acks
        wait for remote coverage, making acknowledged-write loss
        structurally impossible under SIGKILL."""
        if not self.sync:
            return True
        h = self._unshipped
        while h and h[0] in self._shipped_wal:
            self._shipped_wal.discard(heapq.heappop(h))
        return not h or h[0] > wal_seq

    # -- snapshots -------------------------------------------------------

    def maybe_snapshot(self, gid: int, now: Optional[float] = None) -> bool:
        """Refresh ``gid``'s snapshot when the cadence expires or the
        retained tail exceeds the cap.  A ``snapshot_group`` refusal
        (mid-migration) keeps the old snapshot and keeps tailing — the
        plane degrades to a longer replay, never to a gap."""
        now = self._clock() if now is None else now
        last = self._snap_ts.get(gid)
        tail_len = len(self._tail.get(gid, ()))
        due = (
            last is None
            or now - last >= self.window_s
            or tail_len > self.tail_cap
        )
        if not due:
            return False
        blob = self.skv.snapshot_group(gid)
        if blob is None:
            return False
        seq = self._tail_seq.get(gid, 0)
        self._snap[gid] = blob
        self._snap_seq[gid] = seq
        self._snap_ts[gid] = now
        # Tail records at or below the snapshot seq are inside the
        # snapshot; retain only the suffix.  (Coverage bookkeeping
        # lives in _unacked and is driven by standby acks, not by
        # snapshot folding — a standby acks these seqs either via the
        # snapshot or via tail batches it already holds.)
        tail = self._tail.get(gid)
        if tail:
            while tail and tail[0][0] <= seq:
                tail.popleft()
        if self._obs is not None:
            self._obs.metrics.inc("ship.snapshots")
        return True

    # -- shipping --------------------------------------------------------

    def hosted_gids(self) -> List[int]:
        return [g for g in self.skv.gids if g != 0]

    def ship_round(self, now: Optional[float] = None) -> int:
        """One shipping sweep over every hosted group; returns payloads
        delivered.  Safe to call every pump — per-standby frontiers make
        it a no-op when nothing changed."""
        now = self._clock() if now is None else now
        self.rounds += 1
        sent = 0
        procs = list(range(self.n_procs))
        for gid in list(self.hosted_gids()):
            self.maybe_snapshot(gid, now)
            standbys = choose_standbys(
                gid, self.me, procs, self.rules,
                self.labels.get(gid, ""),
            )
            for sb in standbys:
                sent += self._ship_to(gid, sb, now)
        if self._obs is not None:
            lag = self.max_lag_s(now)
            self._obs.metrics.set("ship.lag_s", lag)
        return sent

    def _ship_to(self, gid: int, sb: int, now: float) -> int:
        have = self._acked_tail.get((gid, sb), -1)
        snap_seq = self._snap_seq.get(gid)
        if snap_seq is not None and have < snap_seq:
            # The standby is behind the current snapshot epoch: records
            # at or below snap_seq were folded out of the retained
            # tail, so only the snapshot can bridge it forward.
            payload = self._frame(gid, "snap", snap_seq,
                                  snap=self._snap[gid], now=now)
            reply = self.send(sb, payload)
            return self._apply_reply(gid, sb, reply, "snap", 1,
                                     len(payload))
        # Tail leg: records past the standby's acked frontier (all
        # still retained — retention only drops seqs <= snap_seq).
        base = max(have, 0)
        batch = [
            (seq, rec) for seq, rec, _w in self._tail.get(gid, ())
            if seq > base
        ]
        if not batch:
            return 0
        payload = self._frame(gid, "tail", snap_seq or 0,
                              records=batch, now=now)
        reply = self.send(sb, payload)
        return self._apply_reply(gid, sb, reply, "tail", len(batch),
                                 len(payload))

    def _apply_reply(self, gid: int, sb: int, reply, kind: str,
                     n_records: int, n_bytes: int) -> int:
        """Fold one standby reply in.  ``have`` is authoritative (the
        standby's contiguous frontier under our token) even when the
        reply answered an earlier payload — see the class docstring."""
        if not isinstance(reply, dict):
            return 0
        rg = reply.get("gid")
        if rg is None or int(rg) != gid:
            # A framing-level reject (no gid) or a reply answering some
            # OTHER group's payload (the async hook hands back last
            # round's reply) — never fold a foreign frontier in.
            return 0
        h = int(reply.get("have", -1))
        cur = self._acked_tail.get((gid, sb), -1)
        if h > cur:
            self._acked_tail[(gid, sb)] = h
            self._prune_unacked(gid)
        elif not reply.get("ok") and h < cur:
            # The standby regressed (restarted, or rejects under a new
            # token) — believe it, so the next round re-bases: a
            # frontier below snap_seq routes back to the snapshot leg.
            self._acked_tail[(gid, sb)] = h
        if reply.get("ok"):
            self._record(gid, kind, n_records, n_bytes, h)
            return 1
        return 0

    def _prune_unacked(self, gid: int) -> None:
        best = max(
            (v for (g, _sb), v in self._acked_tail.items() if g == gid),
            default=-1,
        )
        un = self._unacked.get(gid)
        while un and un[0][0] <= best:
            _seq, w = un.popleft()
            if self.sync and w:
                # Lazy-deletion twin of the _unshipped heap: covered()
                # discards each entry as it pops the matching heap
                # element, so the set tracks only the in-flight window
                # (which sync backpressure bounds).
                self._shipped_wal.add(w)  # graftlint: disable=unbounded-queue
        if un is not None and not un:
            self._covered_ts[gid] = self._clock()

    def _frame(self, gid: int, kind: str, snap_seq: int, *,
               snap: Optional[Dict[str, Any]] = None,
               records: Optional[List[Tuple[int, tuple]]] = None,
               now: float = 0.0) -> bytes:
        msg = {
            "gid": gid,
            "token": self.token,
            "kind": kind,
            "snap_seq": snap_seq,
            "snap": snap,
            "records": records or [],
            "ts": now,
        }
        return frame_blob(codec.encode(msg))

    def max_lag_s(self, now: Optional[float] = None) -> float:
        """Worst-case shipping lag across hosted groups: how long the
        most-behind group has had captured writes unacked by every
        standby (0 when everything shipped).  This is the live estimate
        of the data-loss window a death right now would open."""
        now = self._clock() if now is None else now
        worst = 0.0
        for gid in self.hosted_gids():
            un = self._unacked.get(gid)
            if not un:
                self._covered_ts[gid] = now
                continue
            since = self._covered_ts.get(gid, self._t0)
            worst = max(worst, now - since)
        return worst

    def _record(self, gid: int, kind: str, n_records: int,
                n_bytes: int, frontier: int) -> None:
        if self._rec is not None:
            from .flightrec import SHIP

            self._rec.record(
                SHIP, code=gid, a=n_records, b=n_bytes, c=frontier,
                tag=kind,
            )
        if self._obs is not None:
            self._obs.metrics.inc("ship.bytes", n_bytes)
            if kind == "tail":
                self._obs.metrics.inc("ship.tail_records", n_records)


# ---------------------------------------------------------------------------
# Standby side: StandbyStore
# ---------------------------------------------------------------------------


class StandbyStore:
    """Per-process receiver: validated shipments keyed by gid.  All
    validation happens at receive time — framing CRC, codec decode,
    tail contiguity — so recovery never has to distrust stored state."""

    def __init__(self, obs=None) -> None:
        self._state: Dict[int, Dict[str, Any]] = {}
        self.rejects = 0
        self._obs = obs

    def receive(self, payload: bytes) -> dict:
        """Ingest one framed shipment.  Returns the shipper's ack
        (``{"ok": True, "have": frontier}``) or a rejection carrying
        the frontier we DO have so the shipper can resync."""
        body = unframe_blob(payload)
        if body is None:
            self.rejects += 1
            if self._obs is not None:
                self._obs.metrics.inc("ship.rejects")
            return {"ok": False, "have": -1}
        try:
            msg = codec.decode(body)
        except Exception:
            self.rejects += 1
            if self._obs is not None:
                self._obs.metrics.inc("ship.rejects")
            return {"ok": False, "have": -1}
        gid = int(msg["gid"])
        st = self._state.get(gid)
        token = msg["token"]
        if st is None or st["token"] != token:
            # New owner incarnation.  Only a SNAPSHOT (or a tail that
            # starts at seq 1, replayable onto an empty adopt) may
            # establish the new token — a mid-stream tail under an
            # unknown token is rejected WITHOUT clobbering the previous
            # incarnation's state, which is still the freshest
            # recoverable copy until the new owner ships a base.
            base_ok = msg["kind"] == "snap" or (
                msg["records"] and int(msg["records"][0][0]) == 1
            )
            if not base_ok:
                return {"ok": False, "have": -1, "gid": gid}
            st = self._state[gid] = {
                "token": token, "snap": None, "snap_seq": -1,
                "tail": [], "tail_seq": -1, "ts": 0.0,
            }
            if msg["kind"] != "snap":
                st["snap_seq"] = 0
                st["tail_seq"] = 0
        if msg["kind"] == "snap":
            st["snap"] = msg["snap"]
            st["snap_seq"] = int(msg["snap_seq"])
            st["tail"] = [
                (s, r) for s, r in st["tail"] if s > st["snap_seq"]
            ]
            st["tail_seq"] = max(st["snap_seq"], st["tail_seq"])
            st["ts"] = float(msg["ts"])
            return {"ok": True, "have": st["tail_seq"], "gid": gid}
        # Tail batch: accept only a contiguous extension of our
        # frontier; anything else asks the shipper to resync.
        frontier = st["tail_seq"]
        if frontier < 0:
            # Same token but no base yet (snap handler always sets one,
            # so this only guards a tail racing ahead of its snapshot).
            if msg["records"] and int(msg["records"][0][0]) == 1:
                st["snap_seq"] = 0
                st["tail_seq"] = frontier = 0
            else:
                return {"ok": False, "have": -1, "gid": gid}
        fresh = [
            (int(s), tuple(r)) for s, r in msg["records"]
            if int(s) > frontier
        ]
        expect = frontier + 1
        if fresh and fresh[0][0] != expect:
            return {"ok": False, "have": frontier, "gid": gid}
        for s, r in fresh:
            if s != expect:
                return {"ok": False, "have": st["tail_seq"], "gid": gid}
            st["tail"].append((s, r))
            st["tail_seq"] = s
            expect += 1
        st["ts"] = float(msg["ts"])
        return {"ok": True, "have": st["tail_seq"], "gid": gid}

    def freshness(self, gid: int) -> Optional[Dict[str, Any]]:
        st = self._state.get(gid)
        if st is None:
            return None
        return {
            "token": st["token"],
            "snap_seq": st["snap_seq"],
            "tail_seq": st["tail_seq"],
            "ts": st["ts"],
            "has_snap": st["snap"] is not None,
        }

    def get(self, gid: int) -> Optional[
        Tuple[Optional[Dict[str, Any]], List[tuple]]
    ]:
        """The recoverable state: ``(snapshot-or-None, tail records)``.
        Tail records are ``(op, key, value, client_id, command_id)``
        tuples in capture (= apply) order."""
        st = self._state.get(gid)
        if st is None:
            return None
        return st["snap"], [r for _s, r in st["tail"]]

    def drop(self, gid: int) -> None:
        self._state.pop(gid, None)

    def gids(self) -> List[int]:
        return sorted(self._state)


def pick_freshest(
    states: List[Tuple[int, Optional[Dict[str, Any]]]]
) -> List[int]:
    """Order candidate standbys, freshest first.

    ``states`` is ``[(proc, freshness-dict-or-None), ...]``.  The
    winning owner token is the one whose standbys were fed most
    recently (the latest incarnation of the group); within it, standbys
    rank by ``(tail_seq, snap_seq, ts)`` descending.  Standbys holding
    other tokens follow (stale incarnations — better than empty), and
    procs with nothing are excluded."""
    known = [(p, f) for p, f in states if f is not None]
    if not known:
        return []
    latest_by_token: Dict[str, float] = {}
    for _p, f in known:
        tok = f["token"]
        latest_by_token[tok] = max(
            latest_by_token.get(tok, 0.0), float(f["ts"])
        )
    winner = max(latest_by_token.items(), key=lambda kv: kv[1])[0]

    def rank(item):
        p, f = item
        primary = 1 if f["token"] == winner else 0
        return (primary, f["tail_seq"], f["snap_seq"], f["ts"], -p)

    return [p for p, _f in sorted(known, key=rank, reverse=True)]


# ---------------------------------------------------------------------------
# Recovery: snapshot fast-forward + exactly-once tail replay
# ---------------------------------------------------------------------------


def recovery_blob(
    snap: Optional[Dict[str, Any]], latest_cfg
) -> Optional[Dict[str, Any]]:
    """Prepare a shipped snapshot for ``adopt_gid``.

    If the snapshot's config matches the latest committed config it
    adopts as-is.  If the config moved on while the group was down, the
    blob is fast-forwarded: re-stamped at the LATEST config (shard data
    and dedup tables preserved, every slot SERVING) rather than
    replayed through config history — replaying would wedge leaving
    shards in BEPULLING forever, the exact hazard ``adopt_gid``'s
    docstring describes for empty adoption.  Shards the latest config
    assigns elsewhere sit inert (``can_serve`` is false for them), and
    the preserved dedup tables keep the subsequent tail replay
    exactly-once.  Caveat (documented in ARCHITECTURE §15): a shard
    handoff that completed inside the shipping window before the death
    is bounded by the same ``MRT_SHIP_WINDOW_S`` loss window."""
    if snap is None:
        return None
    cur = snap["cur"]
    if cur.num >= latest_cfg.num:
        return snap
    return {
        "gid": snap["gid"],
        "cur": latest_cfg.clone(),
        "prev": cur.clone(),
        "shards": {
            int(s): (SERVING, dict(data), dict(latest))
            for s, (_state, data, latest) in snap["shards"].items()
        },
    }


def redo_record(skv, gid: int, rec: tuple) -> None:
    """Direct host redo of one tail record — the fallback when the
    logged re-submit cannot serve (ownership moved mid-replay).
    Mirrors ``ShardWalReplay._redo_client_op``: dedup on the shard's
    session table, then mutate, so it composes with the logged path."""
    op, key, value, cid, cmd = rec
    rep = skv.reps.get(gid)
    if rep is None:
        return
    sh = rep.shards[key2shard(key)]
    if sh.latest.get(cid, -1) >= cmd:
        return
    if op == "Put":
        sh.data[key] = value
    elif op == "Append":
        sh.data[key] = sh.data.get(key, "") + value
    sh.latest[cid] = cmd


def iter_replay_tail(skv, gid: int, records: List[tuple]):
    """Generator form of tail replay for the scheduler-driven server:
    re-submit each record through ``gid``'s OWN log with its original
    ``(client_id, command_id)`` — the shard's dedup table (restored
    from the snapshot) drops any record the snapshot already covers,
    so replay is exactly-once.  Yields poll delays while a ticket is in
    flight; falls back to :func:`redo_record` when the log path cannot
    serve the record (e.g. the config moved the shard away — the data
    still has to land for a later handoff)."""
    for rec in records:
        op, key, value, cid, cmd = rec
        t = skv.submit(gid, op, key, value, client_id=cid,
                       command_id=cmd)
        waited = 0.0
        while not t.done and waited < 5.0:
            delay = yield 0.002
            waited += 0.002 if delay is None else 0.002
        if (not t.done) or t.failed or t.err:
            redo_record(skv, gid, rec)


def replay_tail(skv, gid: int, records: List[tuple],
                pump: Optional[Callable[[], None]] = None) -> int:
    """Blocking tail replay for in-process fleets: drive
    :func:`iter_replay_tail` with ``pump`` (defaults to
    ``skv.pump``).  Returns the number of records replayed."""
    if pump is None:
        pump = lambda: skv.pump(2)  # noqa: E731
    it = iter_replay_tail(skv, gid, records)
    try:
        next(it)
        while True:
            pump()
            it.send(None)
    except StopIteration:
        pass
    return len(records)
