"""Crash-atomic on-disk Persister.

The reference's Persister is in-memory byte slices with an atomic
(state, snapshot) pair save (reference: raft/persister.go:57-64); crash
realism comes from the test fixture copying it into the reborn server
(reference: raft/config.go:113-142).  A real deployment needs the same
contract from the filesystem.

Layout: two rename-atomic files, ``state.bin`` (term/vote/log) and
``snap.bin`` (service snapshot), each length-prefixed and checksummed
(the CRC covers the length too, so a corrupted header can't silently
mis-frame the blob).  Splitting them keeps the hot path cheap: raft
state is re-persisted on every vote/term/log mutation (reference quirk
#6, raft/raft.go:205-216), and must not drag a multi-megabyte snapshot
plus its fsync along each time.

Crash-ordering invariant: the snapshot is made durable *before* any
raft state whose log was compacted against it.  The dangerous crash is
(new state, old snapshot): the trimmed log no longer covers the gap
above the old snapshot, so entries are lost forever.  The reverse —
(old state, new snapshot) — is safe: on restart the service boots from
the newer snapshot and the re-applied older entries are filtered by the
per-client dup table (same at-most-once machinery that absorbs
duplicate RPCs).  ``save_state_and_snapshot`` therefore fsyncs
``snap.bin`` to disk before touching ``state.bin``.

A torn write can only lose the *new* blob, never corrupt the old one
(POSIX rename atomicity); a checksum mismatch falls back to empty state
(fresh server), which Raft's protocol tolerates by design.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib

__all__ = ["DiskPersister", "set_fsync_stall", "fsync_stall_point"]

# -- gray-disk fault injection ----------------------------------------------
#
# A slow-but-alive disk is the storage analog of a slow link: fsync
# still succeeds, just late — the fault class that wedges WAL-gated
# acks without tripping any liveness detector built on "is it up".
# ChaosControl.fsync_stall arms this process-wide stall; every sync
# point (DiskPersister._write below, WriteAheadLog.sync) runs through
# fsync_stall_point() so a single verb grays out ALL durable writes on
# the node.  Each applied stall is recorded in the arming ChaosState's
# hit ledger ("disk" path, kind "fsync_stall") so nemesis fault-window
# verification and the postmortem doctor see it.

_stall_lock = threading.Lock()
_stall_s = 0.0
_stall_chaos = None


def set_fsync_stall(seconds: float, chaos=None) -> None:
    """Arm (or, with 0, clear) the process-wide fsync stall."""
    global _stall_s, _stall_chaos
    with _stall_lock:
        _stall_s = max(0.0, float(seconds))
        _stall_chaos = chaos if _stall_s > 0 else None


def fsync_stall_point() -> None:
    """Run by every durable-write sync path before its os.fsync."""
    with _stall_lock:
        s, chaos = _stall_s, _stall_chaos
    if s <= 0.0:
        return
    time.sleep(s)
    if chaos is not None:
        chaos.note_fault("disk", "fsync_stall")

_MAGIC = b"MRF2"
_HEADER = struct.Struct("<4sIQ")  # magic, crc32(len ‖ body), len(body)
_LEN = struct.Struct("<Q")


class DiskPersister:
    """File-backed drop-in for :class:`multiraft_tpu.raft.persister.Persister`.

    One instance owns one directory.  Reads are served from an in-memory
    mirror; every save rewrites the corresponding file atomically.
    """

    def __init__(self, directory: str, fsync: bool = True) -> None:
        self.dir = directory
        self._state_path = os.path.join(directory, "state.bin")
        self._snap_path = os.path.join(directory, "snap.bin")
        self._fsync = fsync
        os.makedirs(directory, exist_ok=True)
        self._raft_state = self._load(self._state_path)
        self._snapshot = self._load(self._snap_path)

    # -- Persister API -----------------------------------------------------

    def copy(self) -> "DiskPersister":
        return DiskPersister(self.dir, fsync=self._fsync)

    def save_raft_state(self, state: bytes) -> None:
        self._write(self._state_path, state)
        self._raft_state = state

    def read_raft_state(self) -> bytes:
        return self._raft_state

    def raft_state_size(self) -> int:
        return len(self._raft_state)

    def save_state_and_snapshot(self, state: bytes, snapshot: bytes) -> None:
        # Snapshot first — see the crash-ordering invariant above.
        self._write(self._snap_path, snapshot)
        self._write(self._state_path, state)
        self._raft_state, self._snapshot = state, snapshot

    def read_snapshot(self) -> bytes:
        return self._snapshot

    def snapshot_size(self) -> int:
        return len(self._snapshot)

    # -- internals ---------------------------------------------------------

    def _write(self, path: str, body: bytes) -> None:
        # Running CRC over (length ‖ body) without concatenating — the
        # body can be a multi-megabyte snapshot on the hot persist path.
        crc = zlib.crc32(body, zlib.crc32(_LEN.pack(len(body))))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_HEADER.pack(_MAGIC, crc, len(body)))
            f.write(body)
            f.flush()
            if self._fsync:
                fsync_stall_point()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if self._fsync:
            # The rename itself is only durable once the directory entry
            # is — without this, a power cut can resurrect the *previous*
            # blob, un-persisting a vote/term and allowing two leaders in
            # one term.
            dfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)

    @staticmethod
    def _load(path: str) -> bytes:
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return b""
        if len(raw) < _HEADER.size:
            return b""
        magic, crc, n = _HEADER.unpack_from(raw)
        body = raw[_HEADER.size:]
        if (
            magic != _MAGIC
            or len(body) != n
            or zlib.crc32(body, zlib.crc32(_LEN.pack(n))) != crc
        ):
            return b""
        return body
