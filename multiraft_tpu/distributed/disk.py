"""Crash-atomic on-disk Persister.

The reference's Persister is in-memory byte slices with an atomic
(state, snapshot) pair save (reference: raft/persister.go:57-64); crash
realism comes from the test fixture copying it into the reborn server
(reference: raft/config.go:113-142).  A real deployment needs the same
contract from the filesystem: the pair must be visible atomically — the
service snapshot must never run ahead of the raft state it belongs to.

Implementation: both blobs are written to one temp file
(length-prefixed, checksummed) in the target directory, fsync'd, then
``rename``'d over ``current.bin`` — POSIX rename atomicity gives
all-or-nothing pair replacement.  A torn write can only lose the *new*
pair, never corrupt the old one; a checksum mismatch falls back to
empty state (fresh server), which Raft's protocol tolerates by design.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Tuple

__all__ = ["DiskPersister"]

_MAGIC = b"MRFT"
_HEADER = struct.Struct("<4sIQQ")  # magic, crc32(payload), len(state), len(snap)


class DiskPersister:
    """File-backed drop-in for :class:`multiraft_tpu.raft.persister.Persister`.

    One instance owns one directory.  Reads are served from an in-memory
    mirror; every save rewrites ``current.bin`` atomically.
    """

    def __init__(self, directory: str, fsync: bool = True) -> None:
        self.dir = directory
        self.path = os.path.join(directory, "current.bin")
        self._fsync = fsync
        os.makedirs(directory, exist_ok=True)
        self._raft_state, self._snapshot = self._load()

    # -- Persister API -----------------------------------------------------

    def copy(self) -> "DiskPersister":
        return DiskPersister(self.dir, fsync=self._fsync)

    def save_raft_state(self, state: bytes) -> None:
        self._write(state, self._snapshot)

    def read_raft_state(self) -> bytes:
        return self._raft_state

    def raft_state_size(self) -> int:
        return len(self._raft_state)

    def save_state_and_snapshot(self, state: bytes, snapshot: bytes) -> None:
        self._write(state, snapshot)

    def read_snapshot(self) -> bytes:
        return self._snapshot

    def snapshot_size(self) -> int:
        return len(self._snapshot)

    # -- internals ---------------------------------------------------------

    def _write(self, state: bytes, snapshot: bytes) -> None:
        payload = state + snapshot
        header = _HEADER.pack(
            _MAGIC, zlib.crc32(payload), len(state), len(snapshot)
        )
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(header)
            f.write(payload)
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        os.replace(tmp, self.path)
        if self._fsync:
            # The rename itself is only durable once the directory entry
            # is — without this, a power cut can resurrect the *previous*
            # pair, un-persisting a vote/term and allowing two leaders in
            # one term.
            dfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        self._raft_state, self._snapshot = state, snapshot

    def _load(self) -> Tuple[bytes, bytes]:
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return b"", b""
        if len(raw) < _HEADER.size:
            return b"", b""
        magic, crc, n_state, n_snap = _HEADER.unpack_from(raw)
        payload = raw[_HEADER.size:]
        if (
            magic != _MAGIC
            or len(payload) != n_state + n_snap
            or zlib.crc32(payload) != crc
        ):
            return b"", b""
        return payload[:n_state], payload[n_state:]
