"""ctypes loader for the native TCP transport (``transport.cpp``).

Built with g++ on first use (same pattern as the porcupine native
checker — no pybind11 in this image, plain C ABI).  Exposes
:class:`NativeTransport`, a thin Python veneer over the epoll loop:
connection ids, framed send, blocking event poll.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Tuple

from ...utils.native_build import build_and_load

__all__ = ["NativeTransport", "native_available", "EV_FRAME", "EV_ACCEPT", "EV_CLOSED"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "transport.cpp")
_SO = os.path.join(_HERE, "libmrtransport.so")

EV_FRAME, EV_ACCEPT, EV_CLOSED = 0, 1, 2

_lib = None
_build_failed = False
_build_lock = threading.Lock()


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _build_lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            lib = build_and_load(_SRC, _SO, extra_flags=["-pthread"])
            lib.mrt_create.restype = ctypes.c_void_p
            lib.mrt_destroy.argtypes = [ctypes.c_void_p]
            lib.mrt_listen.restype = ctypes.c_int
            lib.mrt_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
            lib.mrt_connect.restype = ctypes.c_int64
            lib.mrt_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
            lib.mrt_send.restype = ctypes.c_int
            # c_char_p: bytes pass their buffer pointer straight through
            # (no copy) — safe because mrt_send consumes synchronously.
            lib.mrt_send.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_char_p, ctypes.c_uint32,
            ]
            lib.mrt_sendv.restype = ctypes.c_int
            lib.mrt_sendv.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_uint32,
            ]
            lib.mrt_close.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.mrt_wake.argtypes = [ctypes.c_void_p]
            lib.mrt_set_spin.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.mrt_poll.restype = ctypes.c_int64
            lib.mrt_poll.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_uint32,
                ctypes.c_int,
            ]
            _lib = lib
            return lib
        except Exception:
            _build_failed = True
            return None


def native_available() -> bool:
    return _load() is not None


class NativeTransport:
    """One framed-TCP endpoint: listener + outbound connections.

    The calling thread of :meth:`poll` IS the read reactor — epoll_wait
    and frame parsing run inline, and idle-connection sends write
    inline on the sender's thread, so a serial RPC crosses zero futex
    handoffs inside the transport (see transport.cpp's header).

    Thread contract: ``send``/``connect``/``close_conn``/``wake`` are
    safe from any thread (serialized against ``close`` by a lock).
    ``poll`` is owned by one dispatcher thread, and the owner must stop
    polling before calling ``close``.  :meth:`wake` interrupts a
    blocked :meth:`poll` (it returns ``None`` early) — the hook that
    lets a scheduler loop double as the IO dispatcher.
    """

    def __init__(self, buf_size: int = 1 << 20) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native transport unavailable (g++ build failed)")
        self._lib = lib
        self._h = lib.mrt_create()
        self._lock = threading.Lock()
        self._buf = (ctypes.c_uint8 * buf_size)()
        self._cap = buf_size
        # poll() is single-threaded by contract — reuse the out-params.
        self._pconn = ctypes.c_int64()
        self._ptyp = ctypes.c_int()

    def listen(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Bind+listen; returns the bound port (ephemeral for port 0)."""
        with self._lock:
            if self._h is None:
                raise OSError("transport closed")
            got = self._lib.mrt_listen(self._h, host.encode(), port)
        if got < 0:
            raise OSError(f"listen on {host}:{port} failed")
        return got

    def connect(self, host: str, port: int) -> int:
        """Begin a non-blocking connect; returns the conn id immediately.
        A failed handshake later surfaces as an EV_CLOSED event."""
        with self._lock:
            if self._h is None:
                raise ConnectionError("transport closed")
            cid = self._lib.mrt_connect(self._h, host.encode(), port)
        if cid < 0:
            raise ConnectionError(f"connect to {host}:{port} failed")
        return cid

    def send(self, conn: int, data: bytes) -> bool:
        # Senders may be any thread, so close() must not free the C++
        # Transport under a concurrent send — the lock stays (it is an
        # uncontended ~0.1 µs on the hot path; the big costs were the
        # frame copy and the thread handoffs, both gone).
        with self._lock:
            if self._h is None:
                return False
            return self._lib.mrt_send(self._h, conn, data, len(data)) == 0

    # writev caps iovec counts at IOV_MAX (1024 on Linux); chunk below it.
    _SENDV_MAX = 512

    def send_parts(self, conn: int, parts: list) -> bool:
        """Vectored raw write: ``parts`` are PRE-FRAMED byte runs
        (length prefixes included by the caller) delivered in order as
        one ``writev`` per chunk — the one-syscall-per-flush half of
        the reply-coalescing fast path.  Accepts ``bytes`` and
        buffer-protocol objects (memoryview/bytearray/numpy views);
        writable buffers pass their pointer zero-copy."""
        n = len(parts)
        if n == 0:
            return True
        if n == 1 and isinstance(parts[0], bytes):
            return self.send(conn, parts[0])
        for lo in range(0, n, self._SENDV_MAX):
            chunk = parts[lo: lo + self._SENDV_MAX]
            k = len(chunk)
            ptrs = (ctypes.c_void_p * k)()
            lens = (ctypes.c_uint32 * k)()
            keep = []  # pins every pointer's backing object until the call
            for i, p in enumerate(chunk):
                if isinstance(p, bytes):
                    ptrs[i] = ctypes.cast(ctypes.c_char_p(p), ctypes.c_void_p)
                    lens[i] = len(p)
                    keep.append(p)
                    continue
                mv = p if isinstance(p, memoryview) else memoryview(p)
                if mv.readonly:
                    b = bytes(mv)  # rare: readonly view not backed by bytes
                    ptrs[i] = ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p)
                    lens[i] = len(b)
                    keep.append(b)
                else:
                    arr = (ctypes.c_char * mv.nbytes).from_buffer(mv)
                    ptrs[i] = ctypes.addressof(arr)
                    lens[i] = mv.nbytes
                    keep.append(arr)
            with self._lock:
                if self._h is None:
                    return False
                ok = self._lib.mrt_sendv(self._h, conn, ptrs, lens, k) == 0
            del keep
            if not ok:
                return False
        return True

    def set_spin(self, us: int) -> None:
        """Busy-poll budget (µs) before :meth:`poll` blocks — trades a
        sliver of CPU for removing both futex wakes from an active
        round trip.  0 disables (the default)."""
        with self._lock:
            if self._h is not None:
                self._lib.mrt_set_spin(self._h, int(us))

    def close_conn(self, conn: int) -> None:
        with self._lock:
            if self._h is not None:
                self._lib.mrt_close(self._h, conn)

    def wake(self) -> None:
        """Interrupt a blocked :meth:`poll` (it returns ``None``)."""
        with self._lock:
            if self._h is not None:
                self._lib.mrt_wake(self._h)

    def poll(self, timeout: float) -> Optional[Tuple[int, int, bytes]]:
        """Next event as ``(conn_id, type, payload)``, or None on
        timeout or :meth:`wake`."""
        h = self._h
        if h is None:
            return None
        conn, typ = self._pconn, self._ptyp
        n = self._lib.mrt_poll(
            h, ctypes.byref(conn), ctypes.byref(typ),
            self._buf, self._cap, int(timeout * 1000),
        )
        if n < 0:
            return None
        if n > self._cap:  # grow and re-poll (frame stayed queued)
            self._cap = int(n)
            self._buf = (ctypes.c_uint8 * self._cap)()
            return self.poll(timeout)
        return conn.value, typ.value, ctypes.string_at(self._buf, int(n))

    def close(self) -> None:
        with self._lock:
            h, self._h = self._h, None
        if h:
            self._lib.mrt_destroy(h)

    def __del__(self) -> None:  # pragma: no cover - GC path
        try:
            self.close()
        except Exception:
            pass
