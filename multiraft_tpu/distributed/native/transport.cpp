// Native framed-TCP transport for the distributed runtime.
//
// The reference's transport is labrpc: an in-process channel fabric
// (reference: labrpc/labrpc.go:128-165) — adequate because "serving"
// there means tests.  This is the real-deployment counterpart: real
// sockets speaking length-prefixed binary frames, exposed through a
// plain C ABI consumed via ctypes (no pybind11 in this image).
//
// Architecture — latency first.  A serial RPC's critical path must not
// cross threads inside the transport, so:
//
//   * mrt_send WRITES INLINE on the caller's thread (writev of
//     header+payload) whenever the connection is idle.  Only a partial
//     write (socket buffer full) or a still-connecting socket queues
//     the frame for the background writer.
//   * mrt_poll RUNS THE READ REACTOR INLINE on the calling thread:
//     epoll_wait → read → frame parse → return, no handoff queue, no
//     condvar.  The poller thread IS the read event loop.
//   * a background writer thread exists ONLY for the slow path: it
//     owns a second epoll set holding sockets with queued writes or
//     in-progress connects, flushing on EPOLLOUT.  Idle connections
//     never touch it, so the echo round trip costs exactly two kernel
//     socket wakeups and zero futex handoffs.
//
// Model:
//   * connections are integer ids; the listener auto-accepts and
//     surfaces EV_ACCEPT from mrt_poll
//   * completed inbound frames surface as EV_FRAME events
//   * EV_CLOSED reports peer disconnect/error; ids are never reused
//   * mrt_wake interrupts a blocked mrt_poll (it returns -1 like a
//     timeout) — the scheduler-integration hook, letting one thread be
//     both the IO dispatcher and the timer loop
//
// Thread contract: send/connect/close/wake from any thread; poll from
// exactly one thread, and the owner stops polling before mrt_destroy.
// Progress on queued writes needs no polling (the writer thread covers
// it); inbound frames and connect completions surface only via poll.
//
// Python owns message semantics (codec, request/reply matching); this
// layer owns bytes, liveness, and wakeups.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sched.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int EV_FRAME = 0;
constexpr int EV_ACCEPT = 1;
constexpr int EV_CLOSED = 2;

constexpr uint64_t TAG_WAKE = static_cast<uint64_t>(-1);
constexpr uint64_t TAG_LISTEN = static_cast<uint64_t>(-2);

struct Event {
  int64_t conn;
  int type;
  std::vector<uint8_t> data;
};

struct Conn {
  int fd = -1;
  std::vector<uint8_t> rbuf;            // accumulated inbound bytes
  std::deque<std::vector<uint8_t>> wq;  // pending outbound frames
  size_t woff = 0;                      // offset into wq.front()
  bool closed = false;
  bool err = false;         // closed by error → EV_CLOSED owed to the poller
  bool connecting = false;  // non-blocking connect still in progress
  bool in_wep = false;      // registered in the writer's epoll set
};

class Transport {
 public:
  Transport() {
    repfd_ = epoll_create1(EPOLL_CLOEXEC);
    wepfd_ = epoll_create1(EPOLL_CLOEXEC);
    wake_fd_ = eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
    wwake_fd_ = eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = TAG_WAKE;
    epoll_ctl(repfd_, EPOLL_CTL_ADD, wake_fd_, &ev);
    epoll_event wev{};
    wev.events = EPOLLIN;
    wev.data.u64 = TAG_WAKE;
    epoll_ctl(wepfd_, EPOLL_CTL_ADD, wwake_fd_, &wev);
    writer_ = std::thread([this] { WriterLoop(); });
  }

  ~Transport() {
    running_ = false;
    WakeWriter();
    writer_.join();
    {
      std::lock_guard<std::mutex> g(mu_);
      for (auto& [id, c] : conns_)
        if (c.fd >= 0) close(c.fd);
      conns_.clear();
    }
    if (listen_fd_ >= 0) close(listen_fd_);
    close(wake_fd_);
    close(wwake_fd_);
    close(repfd_);
    close(wepfd_);
  }

  // Returns bound port (listen on port 0 for ephemeral), or -1.
  int Listen(const char* host, int port) {
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      close(fd);
      return -1;
    }
    if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
        listen(fd, 128) < 0) {
      close(fd);
      return -1;
    }
    socklen_t len = sizeof(addr);
    getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
    SetNonblock(fd);
    listen_fd_ = fd;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = TAG_LISTEN;
    epoll_ctl(repfd_, EPOLL_CTL_ADD, fd, &ev);
    return ntohs(addr.sin_port);
  }

  // Non-blocking connect: returns a conn id immediately; frames sent
  // before the handshake completes are queued and flushed by the
  // writer when the socket turns writable.  A failed connect surfaces
  // as EV_CLOSED so callers' pending RPCs resolve to "dropped" rather
  // than stalling the caller's event loop on a SYN timeout.
  int64_t Connect(const char* host, int port) {
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (fd < 0) return -1;
    SetNonblock(fd);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      close(fd);
      return -1;
    }
    int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    bool in_progress = rc < 0 && errno == EINPROGRESS;
    if (rc < 0 && !in_progress) {
      close(fd);
      return -1;
    }
    return Register(fd, /*connecting=*/in_progress);
  }

  bool Send(int64_t id, const uint8_t* data, uint32_t len) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = conns_.find(id);
    if (it == conns_.end() || it->second.closed) return false;
    Conn& c = it->second;
    uint32_t be = htonl(len);
    size_t done = 0;
    if (!c.connecting && c.wq.empty()) {
      // Fast path: the connection is idle — write from this thread.
      iovec iov[2];
      iov[0].iov_base = &be;
      iov[0].iov_len = 4;
      iov[1].iov_base = const_cast<uint8_t*>(data);
      iov[1].iov_len = len;
      ssize_t n = writev(c.fd, iov, 2);
      if (n == static_cast<ssize_t>(4 + len)) return true;
      if (n < 0) {
        if (errno != EAGAIN && errno != EWOULDBLOCK) {
          // Dead socket: owe the poller an EV_CLOSED so pending RPCs
          // resolve to "dropped" (the frame is accepted-and-lost, the
          // same outcome the queued path would reach).
          c.closed = true;
          c.err = true;
          WakePoller();
          return true;
        }
        n = 0;
      }
      done = static_cast<size_t>(n);
    }
    std::vector<uint8_t> frame(4 + len);
    memcpy(frame.data(), &be, 4);
    memcpy(frame.data() + 4, data, len);
    c.wq.push_back(std::move(frame));
    if (c.wq.size() == 1) c.woff = done;
    if (!c.connecting) WatchWrites(id, c);
    return true;
  }

  // Vectored raw write: the caller supplies PRE-FRAMED byte parts
  // (length prefixes included) and they hit the socket as one writev —
  // the coalescing half of the zero-copy fast path (tcp.py queues a
  // scheduler-iteration's replies and flushes them here, so N replies
  // cost one syscall instead of N).  Parts need no frame alignment on
  // the slow path: the write queue carries raw byte runs (FlushWrites
  // is offset-based), so a partial writev's remainder becomes one
  // queued blob.
  bool SendV(int64_t id, const uint8_t* const* parts, const uint32_t* lens,
             uint32_t nparts) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = conns_.find(id);
    if (it == conns_.end() || it->second.closed) return false;
    Conn& c = it->second;
    size_t total = 0;
    for (uint32_t i = 0; i < nparts; ++i) total += lens[i];
    size_t done = 0;
    if (!c.connecting && c.wq.empty()) {
      std::vector<iovec> iov(nparts);
      for (uint32_t i = 0; i < nparts; ++i) {
        iov[i].iov_base = const_cast<uint8_t*>(parts[i]);
        iov[i].iov_len = lens[i];
      }
      ssize_t n = writev(c.fd, iov.data(), static_cast<int>(nparts));
      if (n == static_cast<ssize_t>(total)) return true;
      if (n < 0) {
        if (errno != EAGAIN && errno != EWOULDBLOCK) {
          // Dead socket: same accepted-and-lost contract as Send.
          c.closed = true;
          c.err = true;
          WakePoller();
          return true;
        }
        n = 0;
      }
      done = static_cast<size_t>(n);
    }
    std::vector<uint8_t> rest;
    rest.reserve(total - done);
    size_t skip = done;
    for (uint32_t i = 0; i < nparts; ++i) {
      if (skip >= lens[i]) {
        skip -= lens[i];
        continue;
      }
      rest.insert(rest.end(), parts[i] + skip, parts[i] + lens[i]);
      skip = 0;
    }
    c.wq.push_back(std::move(rest));
    if (c.wq.size() == 1) c.woff = 0;
    if (!c.connecting) WatchWrites(id, c);
    return true;
  }

  void CloseConn(int64_t id) {
    {
      std::lock_guard<std::mutex> g(mu_);
      auto it = conns_.find(id);
      if (it == conns_.end() || it->second.closed) return;
      it->second.closed = true;  // user close: torn down silently
    }
    WakePoller();
  }

  void Wake() { WakePoller(); }

  // Busy-poll budget before blocking in epoll_wait.  A serial RPC's
  // reply lands tens of µs after the request goes out; spinning that
  // long instead of sleeping removes the ~5-10 µs futex wake from
  // both sides of the round trip.  Cost when idle: one spin per Poll
  // call (the scheduler polls every idle_max=200 ms) — negligible.
  void SetSpin(int us) { spin_us_ = us; }

  // Blocks up to timeout_ms for an event, running the read reactor on
  // the calling thread.  Returns payload length and fills conn/type;
  // -1 on timeout OR external wake (callers loop).  cap==0 peeks size
  // only (the event stays queued).
  int64_t Poll(int64_t* conn, int* type, uint8_t* buf, uint32_t cap,
               int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    for (;;) {
      if (!ready_.empty()) {
        Event& e = ready_.front();
        *conn = e.conn;
        *type = e.type;
        int64_t n = static_cast<int64_t>(e.data.size());
        if (n > 0 && cap < e.data.size()) return n;  // caller re-polls bigger
        if (n > 0) memcpy(buf, e.data.data(), e.data.size());
        ready_.pop_front();
        return n;
      }
      auto now = std::chrono::steady_clock::now();
      epoll_event evs[64];
      int n = 0;
      if (spin_us_ > 0) {
        // Spin phase: non-blocking epoll probes until the budget (or
        // the caller's deadline) runs out.  No sched_yield: measured on
        // a single CPU, yielding spinners just starve each other (the
        // multi-thread echo went 25 → 44 µs); spin is only enabled on
        // multicore boxes where the probe loop runs undisturbed.
        auto spin_until =
            std::min(now + std::chrono::microseconds(spin_us_), deadline);
        while ((n = epoll_wait(repfd_, evs, 64, 0)) == 0 &&
               std::chrono::steady_clock::now() < spin_until) {
        }
        if (n == 0 && std::chrono::steady_clock::now() >= deadline)
          return -1;  // deadline consumed by the spin
        now = std::chrono::steady_clock::now();
      }
      if (n == 0) {
        int remaining =
            now >= deadline
                ? 0
                : static_cast<int>(
                      std::chrono::duration_cast<std::chrono::milliseconds>(
                          deadline - now)
                          .count()) +
                      1;
        n = epoll_wait(repfd_, evs, 64, remaining);
      }
      if (n < 0) {
        if (errno == EINTR) continue;
        return -1;
      }
      if (n == 0) return -1;  // timed out
      bool woken = false;
      for (int i = 0; i < n; ++i) {
        uint64_t tag = evs[i].data.u64;
        if (tag == TAG_WAKE) {
          uint64_t junk;
          while (read(wake_fd_, &junk, sizeof(junk)) > 0) {
          }
          woken = true;
          continue;
        }
        if (tag == TAG_LISTEN) {
          for (;;) {
            int fd = accept4(listen_fd_, nullptr, nullptr, SOCK_CLOEXEC);
            if (fd < 0) break;
            int64_t id = Register(fd);
            ready_.push_back(Event{id, EV_ACCEPT, {}});
          }
          continue;
        }
        HandleReadEvent(static_cast<int64_t>(tag), evs[i].events);
      }
      if (woken) {
        SweepClosed();
        if (ready_.empty()) return -1;  // spurious-wake contract
      }
      // Loop: ready_ may have filled; otherwise re-wait on remaining time.
    }
  }

 private:
  void SetNonblock(int fd) {
    fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }

  int64_t Register(int fd, bool connecting = false) {
    SetNonblock(fd);
    int64_t id = next_id_.fetch_add(1);
    {
      std::lock_guard<std::mutex> g(mu_);
      Conn& c = conns_[id];
      c.fd = fd;
      c.connecting = connecting;
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.u64 = static_cast<uint64_t>(id);
      epoll_ctl(repfd_, EPOLL_CTL_ADD, fd, &ev);
      // The writer completes the handshake (EPOLLOUT = connected).
      if (connecting) WatchWrites(id, c);
    }
    return id;
  }

  // Register in the writer's epoll set (idempotent).  Caller holds mu_.
  void WatchWrites(int64_t id, Conn& c) {
    if (c.in_wep || c.fd < 0) return;
    epoll_event ev{};
    ev.events = EPOLLOUT;
    ev.data.u64 = static_cast<uint64_t>(id);
    epoll_ctl(wepfd_, EPOLL_CTL_ADD, c.fd, &ev);
    c.in_wep = true;
  }

  void UnwatchWrites(Conn& c) {  // caller holds mu_
    if (!c.in_wep || c.fd < 0) return;
    epoll_ctl(wepfd_, EPOLL_CTL_DEL, c.fd, nullptr);
    c.in_wep = false;
  }

  void WakePoller() {
    uint64_t one = 1;
    [[maybe_unused]] ssize_t r = write(wake_fd_, &one, sizeof(one));
  }

  void WakeWriter() {
    uint64_t one = 1;
    [[maybe_unused]] ssize_t r = write(wwake_fd_, &one, sizeof(one));
  }

  // Poll-thread only: tear down one conn, emitting EV_CLOSED if owed.
  void TearDown(int64_t id, Conn& c, bool notify) {  // caller holds mu_
    UnwatchWrites(c);
    if (c.fd >= 0) {
      epoll_ctl(repfd_, EPOLL_CTL_DEL, c.fd, nullptr);
      close(c.fd);
      c.fd = -1;
    }
    if (notify) ready_.push_back(Event{id, EV_CLOSED, {}});
  }

  // Poll-thread only: collect conns closed by other threads (user
  // CloseConn → silent; Send/writer error → EV_CLOSED).
  void SweepClosed() {
    std::lock_guard<std::mutex> g(mu_);
    for (auto it = conns_.begin(); it != conns_.end();) {
      Conn& c = it->second;
      if (c.closed) {
        TearDown(it->first, c, /*notify=*/c.err);
        it = conns_.erase(it);
      } else {
        ++it;
      }
    }
  }

  // Poll-thread only: one epoll event on a data socket.
  void HandleReadEvent(int64_t id, uint32_t events) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = conns_.find(id);
    if (it == conns_.end()) return;
    Conn& c = it->second;
    if (c.closed) return;  // swept on the next wake
    // Drain readable bytes BEFORE honoring HUP/ERR: a peer that writes
    // a reply and dies delivers EPOLLIN|EPOLLHUP in one event, and the
    // final frame must not be discarded.
    if (!c.connecting && (events & EPOLLIN)) HandleReadable(id, c);
    if (events & (EPOLLHUP | EPOLLERR)) {
      c.closed = true;
      c.err = true;
    }
    if (c.closed) {
      TearDown(id, c, /*notify=*/true);
      conns_.erase(it);
    }
  }

  void HandleReadable(int64_t id, Conn& c) {  // caller holds mu_
    uint8_t chunk[65536];
    for (;;) {
      ssize_t n = read(c.fd, chunk, sizeof(chunk));
      if (n > 0) {
        c.rbuf.insert(c.rbuf.end(), chunk, chunk + n);
        // A short read means the socket buffer is drained — skip the
        // EAGAIN probe (halves read syscalls on small-frame traffic;
        // level-triggered epoll re-arms if more arrives between the
        // short read and the next epoll_wait).
        if (n < static_cast<ssize_t>(sizeof(chunk))) break;
        continue;
      }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
      c.closed = true;  // EOF or error
      c.err = true;
      break;
    }
    size_t off = 0;
    while (c.rbuf.size() - off >= 4) {
      uint32_t len;
      memcpy(&len, c.rbuf.data() + off, 4);
      len = ntohl(len);
      if (c.rbuf.size() - off - 4 < len) break;
      ready_.push_back(Event{
          id, EV_FRAME,
          std::vector<uint8_t>(c.rbuf.begin() + off + 4,
                               c.rbuf.begin() + off + 4 + len)});
      off += 4 + len;
    }
    if (off) c.rbuf.erase(c.rbuf.begin(), c.rbuf.begin() + off);
  }

  // Writer thread: returns false if the connection died mid-write.
  bool FlushWrites(Conn& c) {  // caller holds mu_
    while (!c.wq.empty()) {
      auto& front = c.wq.front();
      ssize_t n = write(c.fd, front.data() + c.woff, front.size() - c.woff);
      if (n < 0)
        return errno == EAGAIN || errno == EWOULDBLOCK;  // retry on EPOLLOUT
      c.woff += static_cast<size_t>(n);
      if (c.woff == front.size()) {
        c.wq.pop_front();
        c.woff = 0;
      }
    }
    UnwatchWrites(c);  // drained: back to fast-path sends
    return true;
  }

  // Background slow path: completes handshakes and drains queued
  // writes.  Idle connections are not in wepfd_, so a healthy serial
  // RPC workload never wakes this thread.
  void WriterLoop() {
    epoll_event evs[64];
    while (running_) {
      int n = epoll_wait(wepfd_, evs, 64, 200);
      if (!running_) return;
      for (int i = 0; i < n; ++i) {
        uint64_t tag = evs[i].data.u64;
        if (tag == TAG_WAKE) {
          uint64_t junk;
          while (read(wwake_fd_, &junk, sizeof(junk)) > 0) {
          }
          continue;
        }
        bool died = false;
        {
          std::lock_guard<std::mutex> g(mu_);
          auto it = conns_.find(static_cast<int64_t>(tag));
          if (it == conns_.end()) continue;
          Conn& c = it->second;
          if (c.closed) {
            // Level-triggered EPOLLOUT would re-fire every iteration
            // until the poll thread sweeps — drop the watch now or
            // this loop busy-spins while the poller is busy (e.g. a
            // long jit compile inside pump).
            UnwatchWrites(c);
            continue;
          }
          if (c.connecting) {
            int err = 0;
            socklen_t elen = sizeof(err);
            getsockopt(c.fd, SOL_SOCKET, SO_ERROR, &err, &elen);
            if (err != 0) {
              died = true;
            } else {
              c.connecting = false;
            }
          }
          if (!died && !c.connecting) {
            if (c.wq.empty())
              UnwatchWrites(c);
            else if (!FlushWrites(c))
              died = true;
          }
          if (died) {
            c.closed = true;
            c.err = true;
          }
        }
        if (died) WakePoller();  // poller sweeps → EV_CLOSED
      }
    }
  }

  std::atomic<int> spin_us_{0};
  int repfd_ = -1;   // read reactor, run inline by Poll()
  int wepfd_ = -1;   // write/backpressure set, run by the writer thread
  int wake_fd_ = -1;
  int wwake_fd_ = -1;
  int listen_fd_ = -1;
  std::atomic<bool> running_{true};
  std::atomic<int64_t> next_id_{1};
  std::thread writer_;

  std::mutex mu_;  // guards conns_ and every Conn's mutable state
  std::unordered_map<int64_t, Conn> conns_;

  std::deque<Event> ready_;  // poll-thread only: parsed, undelivered events
};

}  // namespace

extern "C" {

void* mrt_create() { return new Transport(); }

void mrt_destroy(void* t) { delete static_cast<Transport*>(t); }

int mrt_listen(void* t, const char* host, int port) {
  return static_cast<Transport*>(t)->Listen(host, port);
}

int64_t mrt_connect(void* t, const char* host, int port) {
  return static_cast<Transport*>(t)->Connect(host, port);
}

int mrt_send(void* t, int64_t conn, const uint8_t* data, uint32_t len) {
  return static_cast<Transport*>(t)->Send(conn, data, len) ? 0 : -1;
}

int mrt_sendv(void* t, int64_t conn, const uint8_t* const* parts,
              const uint32_t* lens, uint32_t nparts) {
  return static_cast<Transport*>(t)->SendV(conn, parts, lens, nparts) ? 0 : -1;
}

void mrt_close(void* t, int64_t conn) {
  static_cast<Transport*>(t)->CloseConn(conn);
}

void mrt_wake(void* t) { static_cast<Transport*>(t)->Wake(); }

void mrt_set_spin(void* t, int us) {
  static_cast<Transport*>(t)->SetSpin(us);
}

int64_t mrt_poll(void* t, int64_t* conn, int* type, uint8_t* buf,
                 uint32_t cap, int timeout_ms) {
  return static_cast<Transport*>(t)->Poll(conn, type, buf, cap, timeout_ms);
}

}  // extern "C"
