// Native framed-TCP transport for the distributed runtime.
//
// The reference's transport is labrpc: an in-process channel fabric
// (reference: labrpc/labrpc.go:128-165) — adequate because "serving"
// there means tests.  This is the real-deployment counterpart: an
// epoll event loop owning all sockets, speaking length-prefixed binary
// frames, exposed through a plain C ABI consumed via ctypes (no
// pybind11 in this image).
//
// Model:
//   * one background IO thread per Transport (epoll_wait loop)
//   * connections are integer ids; the listener auto-accepts and
//     surfaces EV_ACCEPT
//   * mrt_send enqueues a frame (u32 LE length + payload) on any thread
//   * completed inbound frames surface as EV_FRAME events drained by
//     mrt_poll (blocking with timeout, mutex+condvar queue)
//   * EV_CLOSED reports peer disconnect/error; ids are never reused
//
// Python owns message semantics (codec, request/reply matching); this
// layer owns bytes, liveness, and wakeups.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int EV_FRAME = 0;
constexpr int EV_ACCEPT = 1;
constexpr int EV_CLOSED = 2;

struct Event {
  int64_t conn;
  int type;
  std::vector<uint8_t> data;
};

struct Conn {
  int fd = -1;
  std::vector<uint8_t> rbuf;          // accumulated inbound bytes
  std::deque<std::vector<uint8_t>> wq;  // pending outbound frames
  size_t woff = 0;                    // offset into wq.front()
  bool closed = false;
  bool connecting = false;  // non-blocking connect still in progress
};

class Transport {
 public:
  Transport() {
    epfd_ = epoll_create1(EPOLL_CLOEXEC);
    wake_fd_ = eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = -1;  // wakeup marker
    epoll_ctl(epfd_, EPOLL_CTL_ADD, wake_fd_, &ev);
    thread_ = std::thread([this] { Loop(); });
  }

  ~Transport() {
    running_ = false;
    Wake();
    thread_.join();
    {
      std::lock_guard<std::mutex> g(mu_);
      for (auto& [id, c] : conns_)
        if (c.fd >= 0) close(c.fd);
      conns_.clear();
    }
    if (listen_fd_ >= 0) close(listen_fd_);
    close(wake_fd_);
    close(epfd_);
  }

  // Returns bound port (listen on port 0 for ephemeral), or -1.
  int Listen(const char* host, int port) {
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      close(fd);
      return -1;
    }
    if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
        listen(fd, 128) < 0) {
      close(fd);
      return -1;
    }
    socklen_t len = sizeof(addr);
    getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
    SetNonblock(fd);
    listen_fd_ = fd;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = -2;  // listener marker
    epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
    return ntohs(addr.sin_port);
  }

  // Non-blocking connect: returns a conn id immediately; frames sent
  // before the handshake completes are queued and flushed when the
  // socket turns writable.  A failed connect surfaces as EV_CLOSED so
  // callers' pending RPCs resolve to "dropped" rather than stalling
  // the caller's event loop on a SYN timeout.
  int64_t Connect(const char* host, int port) {
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (fd < 0) return -1;
    SetNonblock(fd);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      close(fd);
      return -1;
    }
    int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    bool in_progress = rc < 0 && errno == EINPROGRESS;
    if (rc < 0 && !in_progress) {
      close(fd);
      return -1;
    }
    return Register(fd, /*connecting=*/in_progress);
  }

  bool Send(int64_t id, const uint8_t* data, uint32_t len) {
    std::vector<uint8_t> frame(4 + len);
    uint32_t n = htonl(len);
    memcpy(frame.data(), &n, 4);
    memcpy(frame.data() + 4, data, len);
    {
      std::lock_guard<std::mutex> g(mu_);
      auto it = conns_.find(id);
      if (it == conns_.end() || it->second.closed) return false;
      it->second.wq.push_back(std::move(frame));
    }
    Wake();  // loop flushes; EPOLLOUT armed there if the write stalls
    return true;
  }

  void Close(int64_t id) {
    {
      std::lock_guard<std::mutex> g(mu_);
      auto it = conns_.find(id);
      if (it == conns_.end() || it->second.closed) return;
      it->second.closed = true;  // loop tears it down
    }
    Wake();
  }

  // Blocks up to timeout_ms for an event.  Returns payload length and
  // fills conn/type; -1 on timeout.  cap==0 peeks size only (frame
  // stays queued).
  int64_t Poll(int64_t* conn, int* type, uint8_t* buf, uint32_t cap,
               int timeout_ms) {
    std::unique_lock<std::mutex> g(qmu_);
    if (!qcv_.wait_for(g, std::chrono::milliseconds(timeout_ms),
                       [this] { return !events_.empty(); }))
      return -1;
    Event& e = events_.front();
    *conn = e.conn;
    *type = e.type;
    int64_t n = static_cast<int64_t>(e.data.size());
    if (n > 0 && cap < e.data.size()) return n;  // caller re-polls bigger
    if (n > 0) memcpy(buf, e.data.data(), e.data.size());
    events_.pop_front();
    return n;
  }

 private:
  void SetNonblock(int fd) {
    fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }

  int64_t Register(int fd, bool connecting = false) {
    SetNonblock(fd);
    int64_t id = next_id_.fetch_add(1);
    {
      std::lock_guard<std::mutex> g(mu_);
      Conn& c = conns_[id];
      c.fd = fd;
      c.connecting = connecting;
    }
    epoll_event ev{};
    // EPOLLOUT completes the handshake for in-progress connects.
    ev.events = EPOLLIN | (connecting ? EPOLLOUT : 0u);
    ev.data.u64 = id;
    epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
    return id;
  }

  void Wake() {
    uint64_t one = 1;
    [[maybe_unused]] ssize_t r = write(wake_fd_, &one, sizeof(one));
  }

  void Emit(int64_t conn, int type, std::vector<uint8_t> data = {}) {
    std::lock_guard<std::mutex> g(qmu_);
    events_.push_back(Event{conn, type, std::move(data)});
    qcv_.notify_one();
  }

  void TearDown(int64_t id, Conn& c, bool notify) {
    if (c.fd >= 0) {
      epoll_ctl(epfd_, EPOLL_CTL_DEL, c.fd, nullptr);
      close(c.fd);
      c.fd = -1;
    }
    if (notify) Emit(id, EV_CLOSED);
  }

  void HandleReadable(int64_t id, Conn& c) {
    uint8_t chunk[65536];
    for (;;) {
      ssize_t n = read(c.fd, chunk, sizeof(chunk));
      if (n > 0) {
        c.rbuf.insert(c.rbuf.end(), chunk, chunk + n);
        continue;
      }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
      c.closed = true;  // EOF or error
      break;
    }
    size_t off = 0;
    while (c.rbuf.size() - off >= 4) {
      uint32_t len;
      memcpy(&len, c.rbuf.data() + off, 4);
      len = ntohl(len);
      if (c.rbuf.size() - off - 4 < len) break;
      Emit(id, EV_FRAME,
           std::vector<uint8_t>(c.rbuf.begin() + off + 4,
                                c.rbuf.begin() + off + 4 + len));
      off += 4 + len;
    }
    if (off) c.rbuf.erase(c.rbuf.begin(), c.rbuf.begin() + off);
  }

  // Returns false if the connection died mid-write.
  bool FlushWrites(int64_t id, Conn& c) {
    while (!c.wq.empty()) {
      auto& front = c.wq.front();
      ssize_t n =
          write(c.fd, front.data() + c.woff, front.size() - c.woff);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          epoll_event ev{};
          ev.events = EPOLLIN | EPOLLOUT;
          ev.data.u64 = id;
          epoll_ctl(epfd_, EPOLL_CTL_MOD, c.fd, &ev);
          return true;
        }
        return false;
      }
      c.woff += static_cast<size_t>(n);
      if (c.woff == front.size()) {
        c.wq.pop_front();
        c.woff = 0;
      }
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = id;
    epoll_ctl(epfd_, EPOLL_CTL_MOD, c.fd, &ev);
    return true;
  }

  void Loop() {
    epoll_event evs[64];
    while (running_) {
      int n = epoll_wait(epfd_, evs, 64, 100);
      if (!running_) return;
      // Drain the wakeup counter and flush all pending writes.
      {
        uint64_t junk;
        while (read(wake_fd_, &junk, sizeof(junk)) > 0) {
        }
        std::lock_guard<std::mutex> g(mu_);
        for (auto it = conns_.begin(); it != conns_.end();) {
          Conn& c = it->second;
          if (c.closed) {
            TearDown(it->first, c, /*notify=*/false);
            it = conns_.erase(it);
            continue;
          }
          if (c.fd >= 0 && !c.connecting && !c.wq.empty() &&
              !FlushWrites(it->first, c)) {
            TearDown(it->first, c, /*notify=*/true);
            it = conns_.erase(it);
            continue;
          }
          ++it;
        }
      }
      for (int i = 0; i < n; ++i) {
        int64_t tag = static_cast<int64_t>(evs[i].data.u64);
        if (tag == -1) continue;  // wakeup fd, drained above
        if (tag == -2) {          // listener
          for (;;) {
            int fd = accept4(listen_fd_, nullptr, nullptr, SOCK_CLOEXEC);
            if (fd < 0) break;
            int64_t id = Register(fd);
            Emit(id, EV_ACCEPT);
          }
          continue;
        }
        std::lock_guard<std::mutex> g(mu_);
        auto it = conns_.find(tag);
        if (it == conns_.end()) continue;
        Conn& c = it->second;
        if (c.connecting && (evs[i].events & EPOLLOUT)) {
          int err = 0;
          socklen_t elen = sizeof(err);
          getsockopt(c.fd, SOL_SOCKET, SO_ERROR, &err, &elen);
          if (err != 0) {
            c.closed = true;
          } else {
            c.connecting = false;  // handshake done; flush below
          }
        }
        // Drain readable bytes BEFORE honoring HUP/ERR: a peer that
        // writes a reply and dies delivers EPOLLIN|EPOLLHUP in one
        // event, and the final frame must not be discarded.
        if (!c.closed && !c.connecting && (evs[i].events & EPOLLIN))
          HandleReadable(tag, c);
        if (evs[i].events & (EPOLLHUP | EPOLLERR)) c.closed = true;
        if (!c.closed && !c.connecting && (evs[i].events & EPOLLOUT)) {
          if (!FlushWrites(tag, c)) c.closed = true;
        }
        if (c.closed) {
          // Deliver any frames parsed before EOF first, then the close.
          TearDown(tag, c, /*notify=*/true);
          conns_.erase(it);
        }
      }
    }
  }

  int epfd_ = -1;
  int wake_fd_ = -1;
  int listen_fd_ = -1;
  std::atomic<bool> running_{true};
  std::atomic<int64_t> next_id_{1};
  std::thread thread_;

  std::mutex mu_;  // guards conns_
  std::unordered_map<int64_t, Conn> conns_;

  std::mutex qmu_;  // guards events_
  std::condition_variable qcv_;
  std::deque<Event> events_;
};

}  // namespace

extern "C" {

void* mrt_create() { return new Transport(); }

void mrt_destroy(void* t) { delete static_cast<Transport*>(t); }

int mrt_listen(void* t, const char* host, int port) {
  return static_cast<Transport*>(t)->Listen(host, port);
}

int64_t mrt_connect(void* t, const char* host, int port) {
  return static_cast<Transport*>(t)->Connect(host, port);
}

int mrt_send(void* t, int64_t conn, const uint8_t* data, uint32_t len) {
  return static_cast<Transport*>(t)->Send(conn, data, len) ? 0 : -1;
}

void mrt_close(void* t, int64_t conn) {
  static_cast<Transport*>(t)->Close(conn);
}

int64_t mrt_poll(void* t, int64_t* conn, int* type, uint8_t* buf,
                 uint32_t cap, int timeout_ms) {
  return static_cast<Transport*>(t)->Poll(conn, type, buf, cap, timeout_ms);
}

}  // extern "C"
