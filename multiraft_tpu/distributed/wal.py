"""Write-ahead log for the durable engine server.

The reference persists every raft-state mutation through its Persister
(reference quirk #6, raft/raft.go:205-216) — affordable when state is
one group's log.  The batched engine cannot re-serialize ``[G, P, L]``
tensors per op, so durability splits in two:

* periodic whole-engine checkpoints (:meth:`EngineDriver.save`, atomic
  at a tick boundary, service state in ``extra``), and
* this WAL of acknowledged client/admin ops since the last checkpoint.

Recovery = restore the checkpoint, then RE-SUBMIT every WAL record
through consensus with its original ``(client_id, command_id)`` — the
session dedup tables make replay exactly-once, the same machinery that
absorbs duplicate RPCs (reference: kvraft/server.go:66-69).  Records
already reflected in the checkpoint dedup to no-ops; records past it
commit now.  A crash between checkpoint and rotation only makes replay
redundant, never wrong.

Framing mirrors ``DiskPersister``: per record ``magic ‖ crc32(len ‖
body) ‖ len ‖ body``.  A torn tail record fails its checksum and is
dropped — safe because acks gate on :meth:`sync` having covered the
record (group fsync at pump cadence), so a torn record was never
acknowledged.
"""

from __future__ import annotations

import os
import struct
import time
import zlib
from typing import Iterator, Optional

from ..utils.metrics import Metrics

__all__ = ["WriteAheadLog"]

_MAGIC = b"MRWL"
_HEADER = struct.Struct("<4sIQ")  # magic, crc32(len ‖ body), len(body)
_LEN = struct.Struct("<Q")


class WriteAheadLog:
    """Append-only record log with group fsync and atomic rotation.

    Single-writer: the owning service appends/syncs from its loop
    thread only.  ``seq`` numbers are per-incarnation (they gate acks,
    they are not stored).
    """

    def __init__(
        self,
        path: str,
        fsync: bool = True,
        metrics: Optional[Metrics] = None,
    ) -> None:
        self.path = path
        self._fsync = fsync
        # Observability: appends/fsyncs/rotates as counters, fsync
        # latency as samples.  A private registry when the owner passes
        # none — the instrumentation below never branches on None.
        self.metrics = metrics if metrics is not None else Metrics()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # Truncate any torn tail BEFORE appending: records written
        # after leftover garbage would be unreachable to every future
        # replay (it stops at the first bad record) — silently losing
        # the next incarnation's acked writes.
        valid = self._valid_prefix_len()
        if valid is not None:
            os.truncate(path, valid)
        self._f = open(path, "ab")
        # Seqs are MONOTONIC for the whole incarnation — rotation must
        # not reset them, because ack gates and the fleet GC gate hold
        # seqs across it (a reset would turn synced(seq) false again
        # and wedge a quiet server's ack waits forever).
        self.appended = 0  # records appended by this incarnation
        self.synced = 0    # records known durable

    def _valid_prefix_len(self) -> Optional[int]:
        """Byte length of the intact record prefix, or None if the file
        is missing or already fully valid."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        off = 0
        while off + _HEADER.size <= len(raw):
            magic, crc, n = _HEADER.unpack_from(raw, off)
            body = raw[off + _HEADER.size: off + _HEADER.size + n]
            if (
                magic != _MAGIC
                or len(body) != n
                or zlib.crc32(body, zlib.crc32(_LEN.pack(n))) != crc
            ):
                return off
            off += _HEADER.size + n
        return off if off < len(raw) else None

    # -- recovery ---------------------------------------------------------

    def replay(self) -> Iterator[bytes]:
        """Yield every intact record body in append order, stopping at
        the first torn/corrupt record (an unacknowledged tail).  Call
        before appending."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return
        off = 0
        while off + _HEADER.size <= len(raw):
            magic, crc, n = _HEADER.unpack_from(raw, off)
            body = raw[off + _HEADER.size: off + _HEADER.size + n]
            if (
                magic != _MAGIC
                or len(body) != n
                or zlib.crc32(body, zlib.crc32(_LEN.pack(n))) != crc
            ):
                return  # torn tail: never acked, drop it and stop
            yield body
            off += _HEADER.size + n

    # -- append path ------------------------------------------------------

    def append(self, body: bytes) -> int:
        """Buffer one record; returns its seq (ack-gate with
        ``synced >= seq`` after a :meth:`sync`)."""
        crc = zlib.crc32(body, zlib.crc32(_LEN.pack(len(body))))
        self._f.write(_HEADER.pack(_MAGIC, crc, len(body)))
        self._f.write(body)
        self.appended += 1
        m = self.metrics
        m.inc("wal.appends")
        m.inc("wal.bytes", _HEADER.size + len(body))
        return self.appended

    def sync(self) -> None:
        """Group commit: make everything appended so far durable."""
        if self.synced >= self.appended:
            return
        t0 = time.perf_counter()
        self._f.flush()
        if self._fsync:
            os.fsync(self._f.fileno())
        m = self.metrics
        m.inc("wal.fsyncs")
        m.observe("wal.fsync_s", time.perf_counter() - t0)
        self.synced = self.appended

    # -- rotation (after a successful checkpoint) -------------------------

    def rotate(self) -> None:
        """Truncate to empty, atomically.  Call only after the covering
        checkpoint is durable — a crash in between merely makes the
        next replay redundant (dedup absorbs it)."""
        self._f.close()
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        os.replace(tmp, self.path)
        if self._fsync:
            dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        self._f = open(self.path, "ab")
        self.metrics.inc("wal.rotates")
        # appended/synced deliberately NOT reset — see __init__.

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass
