"""Write-ahead log for the durable engine server.

The reference persists every raft-state mutation through its Persister
(reference quirk #6, raft/raft.go:205-216) — affordable when state is
one group's log.  The batched engine cannot re-serialize ``[G, P, L]``
tensors per op, so durability splits in two:

* periodic whole-engine checkpoints (:meth:`EngineDriver.save`, atomic
  at a tick boundary, service state in ``extra``), and
* this WAL of acknowledged client/admin ops since the last checkpoint.

Recovery = restore the checkpoint, then RE-SUBMIT every WAL record
through consensus with its original ``(client_id, command_id)`` — the
session dedup tables make replay exactly-once, the same machinery that
absorbs duplicate RPCs (reference: kvraft/server.go:66-69).  Records
already reflected in the checkpoint dedup to no-ops; records past it
commit now.  A crash between checkpoint and rotation only makes replay
redundant, never wrong.

Framing mirrors ``DiskPersister``: per record ``magic ‖ crc32(len ‖
body) ‖ len ‖ body``.  A torn tail record fails its checksum and is
dropped — safe because acks gate on :meth:`sync` having covered the
record (group fsync at pump cadence), so a torn record was never
acknowledged.

Write batching: :meth:`append` only stages ``header ‖ body`` in memory;
:meth:`sync` lands the whole batch as ONE ``write()`` before the group
fsync.  Semantics are unchanged — acks already gate on :meth:`sync`, so
a record that never reached the file was never acknowledged, exactly
like a torn tail.  Startup reads the file ONCE: a single streamed scan
both finds the valid prefix (truncating any torn tail before this
incarnation appends) and retains the record bodies for :meth:`replay`.
"""

from __future__ import annotations

import os
import struct
import time
import zlib
from collections import deque
from typing import Iterator, List, Optional, Tuple

from ..utils.metrics import Metrics
from . import disk, flightrec

__all__ = ["WriteAheadLog"]

_MAGIC = b"MRWL"
_HEADER = struct.Struct("<4sIQ")  # magic, crc32(len ‖ body), len(body)
_LEN = struct.Struct("<Q")
_SCAN_CHUNK = 1 << 20


class WriteAheadLog:
    """Append-only record log with group fsync and atomic rotation.

    Single-writer: the owning service appends/syncs from its loop
    thread only.  ``seq`` numbers are per-incarnation (they gate acks,
    they are not stored).
    """

    #: Bounded retention for :meth:`tail` — the state plane's shipping
    #: window is seconds, so a few thousand recent records is plenty;
    #: older records are covered by shipped snapshots.
    TAIL_RETAIN = 4096

    def __init__(
        self,
        path: str,
        fsync: bool = True,
        metrics: Optional[Metrics] = None,
    ) -> None:
        self.path = path
        self._fsync = fsync
        # Observability: appends/fsyncs/rotates as counters, fsync
        # latency as samples.  A private registry when the owner passes
        # none — the instrumentation below never branches on None.
        self.metrics = metrics if metrics is not None else Metrics()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # ONE streamed pass over the file: find the valid prefix AND
        # keep the intact bodies for replay().  Truncate any torn tail
        # BEFORE appending: records written after leftover garbage
        # would be unreachable to every future replay (it stops at the
        # first bad record) — silently losing the next incarnation's
        # acked writes.
        valid, bodies = self._scan()
        if valid is not None:
            os.truncate(path, valid)
        self._startup: Optional[List[bytes]] = bodies
        self._f = open(path, "ab")
        self._pend: List[bytes] = []  # staged records since last sync
        # Seqs are MONOTONIC for the whole incarnation — rotation must
        # not reset them, because ack gates and the fleet GC gate hold
        # seqs across it (a reset would turn synced(seq) false again
        # and wedge a quiet server's ack waits forever).
        self.appended = 0  # records appended by this incarnation
        self.synced = 0    # records known durable
        # Recent (seq, body) pairs for the state plane's WAL tailing —
        # bounded, survives rotation (seqs are monotonic across it).
        self._tail: deque = deque(maxlen=self.TAIL_RETAIN)
        # Black-box evidence of durability progress: append seq and
        # fsync frontier land in the crash-surviving ring, so a
        # SIGKILL'd process still shows how far its acks were covered
        # (the doctor's fsync-gap analysis).  None when disabled.
        self._frec = flightrec.get_recorder()

    def _scan(self) -> Tuple[Optional[int], List[bytes]]:
        """One streamed pass: byte length of the intact record prefix
        (None if the file is missing or already fully valid) plus every
        intact body in append order."""
        bodies: List[bytes] = []
        try:
            f = open(self.path, "rb")
        except FileNotFoundError:
            return None, bodies
        hdr = _HEADER.size
        with f:
            window = bytearray()
            valid = 0   # bytes consumed as intact records
            torn = False
            while True:
                chunk = f.read(_SCAN_CHUNK)
                if chunk:
                    window.extend(chunk)
                at_eof = not chunk
                off = 0
                while len(window) - off >= hdr:
                    magic, crc, n = _HEADER.unpack_from(window, off)
                    if magic != _MAGIC:
                        torn = True
                        break
                    if len(window) - off - hdr < n:
                        if at_eof:
                            torn = True  # record torn mid-body
                        break  # need more bytes
                    body = bytes(window[off + hdr: off + hdr + n])
                    if zlib.crc32(body, zlib.crc32(_LEN.pack(n))) != crc:
                        torn = True
                        break
                    bodies.append(body)
                    off += hdr + n
                    valid += hdr + n
                del window[:off]
                if torn:
                    break
                if at_eof:
                    if window:  # trailing partial header
                        torn = True
                    break
        return (valid if torn else None), bodies

    # -- recovery ---------------------------------------------------------

    def replay(self) -> Iterator[bytes]:
        """Yield every intact record body in append order, stopping at
        the first torn/corrupt record (an unacknowledged tail).  Call
        before appending.  Served from the constructor's single scan;
        a second call (or a contract-breaking replay-after-append)
        falls back to re-scanning the file."""
        if self._startup is not None:
            bodies, self._startup = self._startup, None
            yield from bodies
            return
        try:  # make staged/buffered appends visible to the re-scan
            self._write_pending()
            self._f.flush()
        except Exception:
            pass
        _, bodies = self._scan()
        yield from bodies

    # -- append path ------------------------------------------------------

    def append(self, body: bytes) -> int:
        """Stage one record; returns its seq (ack-gate with
        ``synced >= seq`` after a :meth:`sync`)."""
        crc = zlib.crc32(body, zlib.crc32(_LEN.pack(len(body))))
        # Drained by _write_pending on every sync(): bounded by the
        # records staged within one pump (group-commit batching).
        self._pend.append(  # graftlint: disable=unbounded-queue
            _HEADER.pack(_MAGIC, crc, len(body)) + body
        )
        self.appended += 1
        # deque(maxlen=TAIL_RETAIN) from __init__ — old entries fall
        # off as new ones land.
        self._tail.append((self.appended, body))  # graftlint: disable=unbounded-queue
        m = self.metrics
        m.inc("wal.appends")
        m.inc("wal.bytes", _HEADER.size + len(body))
        if self._frec is not None:
            self._frec.record(
                flightrec.WAL_APPEND, a=self.appended, b=len(body)
            )
        return self.appended

    def _write_pending(self) -> None:
        """Land every staged record as one ``write()``."""
        if not self._pend:
            return
        nrec = len(self._pend)
        batch = self._pend[0] if nrec == 1 else b"".join(self._pend)
        self._pend.clear()
        self._f.write(batch)
        m = self.metrics
        m.inc("wal.write_batches")
        m.observe("wal.batch_records", float(nrec))

    def sync(self) -> None:
        """Group commit: make everything appended so far durable."""
        if self.synced >= self.appended:
            return
        t0 = time.perf_counter()
        self._write_pending()
        self._f.flush()
        if self._fsync:
            # Gray-disk chaos: slow-but-alive storage stalls here (the
            # stall lands inside wal.fsync_s, where the postmortem
            # doctor's fsync-gap scan will see it).
            disk.fsync_stall_point()
            os.fsync(self._f.fileno())
        dt = time.perf_counter() - t0
        m = self.metrics
        m.inc("wal.fsyncs")
        m.observe("wal.fsync_s", dt)
        self.synced = self.appended
        if self._frec is not None:
            self._frec.record(
                flightrec.WAL_FSYNC, a=self.synced, b=int(dt * 1e6)
            )

    # -- tailing (state-plane shipping) -----------------------------------

    def tail(self, from_seq: int) -> List[Tuple[int, bytes]]:
        """Retained ``(seq, body)`` records with ``seq > from_seq``, in
        append order — the per-incarnation segment iteration the state
        plane ships between snapshots.  Retention is bounded
        (:data:`TAIL_RETAIN`); a caller that has fallen behind the
        retained window gets a gap (the first returned seq is not
        ``from_seq + 1``) and must re-base on a snapshot."""
        return [(s, b) for s, b in self._tail if s > from_seq]

    # -- rotation (after a successful checkpoint) -------------------------

    def rotate(self) -> None:
        """Truncate to empty, atomically.  Call only after the covering
        checkpoint is durable — a crash in between merely makes the
        next replay redundant (dedup absorbs it)."""
        # Staged records are covered by the checkpoint being rotated
        # behind (rotate runs right after sync/checkpoint on the loop
        # thread) — discard them the same way the truncate discards
        # written-but-rotated bytes.
        self._pend.clear()
        self._startup = None
        self._f.close()
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        os.replace(tmp, self.path)
        if self._fsync:
            dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        self._f = open(self.path, "ab")
        self.metrics.inc("wal.rotates")
        # appended/synced deliberately NOT reset — see __init__.

    def close(self) -> None:
        try:
            self._write_pending()
        except Exception:
            pass
        try:
            self._f.close()
        except Exception:
            pass
