"""The SHARDED engine service behind the TCP front door — the
sharded half of the former engine_server.py (split round 4; the wire
layer lives in engine_wire.py, durability/replay in
engine_durability.py, clerks in engine_clerks.py).

``EngineShardKVService`` wraps a :class:`~multiraft_tpu.engine.shardkv.
BatchedShardKV`: server-side key→shard routing against the replicated
config, the reference clerk retry semantics (ErrWrongGroup →
re-route, shardkv/client.go:68-129), multi-op frames, fleet-mode
migration RPCs (pull_shard/delete_shard — Challenge 1 across
processes), and durable serving (checkpoint + WAL + recovery via
:class:`~.engine_durability.ShardWalReplay`).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Optional, Sequence

from ..engine.core import EngineConfig
from ..engine.firehose import MAX_FIREHOSE_ROWS
from ..engine.host import EngineDriver
from ..sim.scheduler import TIMEOUT
from .engine_durability import (
    EngineDurability,
    ShardWalReplay,
    await_frame_synced,
    demote_unsynced_rows,
)
from .engine_wire import (
    ERR_TIMEOUT,
    OK,
    EngineCmdArgs,
    EngineCmdReply,
    make_mesh,
)
from ..utils.knobs import knob_bool, knob_float, knob_int
from .engine_pump import PUMP_THREAD_PREFIX, EnginePump, LoopOccupancy
from .realtime import (
    PumpCadence,
    RealtimeScheduler,
    service_busy,
)
from .tcp import RpcNode

__all__ = ["EngineShardKVService", "serve_engine_shardkv"]


class EngineShardKVService:
    """``EngineShardKV.command``: the sharded engine service behind the
    same TCP front door.  Key→shard routing happens server-side against
    the replicated config; WRONG_GROUP during migration re-routes like
    the reference clerk (shardkv/client.go:68-129).

    **Fleet mode** (``peers`` given): this process hosts a subset of
    the global gid space and its ``BatchedShardKV`` migrates shards
    to/from peer processes over the network — ``remote_fetch`` becomes
    a ``pull_shard`` RPC to the owning peer, ``remote_delete`` a
    ``delete_shard`` RPC riding the peer's log (Challenge 1 across
    processes).  Ops for a gid hosted elsewhere answer ErrWrongGroup so
    the fleet clerk re-routes, exactly like a reference group answering
    for a shard it no longer owns."""

    RESUBMIT_S = 0.25
    DEADLINE_S = 5.0
    # Per-RPC bound on one migration fetch/delete attempt; the
    # orchestration sweep re-issues after a timeout.
    MIGRATE_RPC_S = 2.0

    def __init__(
        self,
        sched: RealtimeScheduler,
        skv,  # BatchedShardKV
        pump_interval: float = 0.002,
        ticks_per_pump: int = 2,
        peers: Optional[dict] = None,  # gid -> TcpClientEnd (remote owners)
        durability: Optional[EngineDurability] = None,
        obs=None,
        fleet: Optional[bool] = None,
        make_end=None,  # (host, port) -> TcpClientEnd, for placement pushes
        placement0: Optional[dict] = None,  # gid -> (host, port), version 0
        fleet_addrs: Optional[dict] = None,  # proc -> (host, port): the
        # whole fleet, state-plane ship targets (distributed/stateplane)
        me: Optional[int] = None,  # this process's index in fleet_addrs
        ship_rules=None,  # [(regex, ShipSpec)] declarative standby rules
        ship_sync: Optional[bool] = None,  # acks gate on shipment
        ship_window_s: Optional[float] = None,
    ) -> None:
        self.sched = sched
        self.skv = skv
        self._cadence = PumpCadence(
            knob_float("MRT_PUMP_IDLE_S", default=pump_interval)
        )
        self._ticks = ticks_per_pump
        self._stopped = False
        # Asynchronous engine pipeline — see EngineKVService; same
        # dispatch/complete split, same durable depth pin.
        self._pipe = None
        self._depth = 1
        self._pump_timer = None
        if knob_bool("MRT_ENGINE_PIPELINE"):
            loop_name = getattr(getattr(sched, "_thread", None), "name", "")
            suffix = (
                loop_name[len("multiraft-loop"):]
                if loop_name.startswith("multiraft-loop") else ""
            )
            self._pipe = EnginePump(sched, name=PUMP_THREAD_PREFIX + suffix)
            self._depth = (
                1 if durability is not None
                else max(1, knob_int("MRT_PIPELINE_DEPTH"))
            )
            pump_ticks = knob_int("MRT_PUMP_TICKS")
            if pump_ticks > 0:
                self._ticks = pump_ticks
        self.peers = dict(peers or {})
        # A fleet process whose peer map is momentarily empty (all gids
        # local, or rebuilt by a placement push) must KEEP answering
        # ErrWrongGroup for foreign gids — hence the explicit flag.
        self._fleet = bool(self.peers) if fleet is None else fleet
        self._make_end = make_end
        self._ends_by_addr: dict = {}
        # (version, {gid: (host, port)}) — advanced only by `place`
        # pushes with a strictly newer version (controller restarts and
        # reordered pushes are harmless).
        self._placement = (0, dict(placement0 or {}))
        self._dur = durability
        # Observability plane (see EngineKVService): the owning node's,
        # lazily defaulted via the `obs` property for stub construction.
        self._obs = obs
        self._occ = LoopOccupancy(self.m)
        # Pump sequencing for the tail plane (see _record_pump).
        self._pumps = 0
        self._pump_t_dispatch = 0.0
        # seq of the WAL record covering each applied insert — the GC
        # gate below refuses to ask the old owner to delete until the
        # inserted blob (possibly the last copy) is fsynced here.
        self._insert_seqs: dict = {}
        # (client_id, command_id) -> WAL seq, apply-time (commit order)
        # — see EngineKVService; pruned once synced.
        self._write_seqs: dict = {}
        self._admin_seqs: dict = {}  # command_id -> WAL seq
        # seq of the WAL record covering each applied delete — the
        # delete_shard RPC reply gates on it being fsynced: the puller
        # confirms (and never re-asks) the moment we answer OK, so an
        # OK that could be lost to a crash would leave a BEPULLING slot
        # here that nothing ever clears, wedging config advance.
        self._delete_seqs: dict = {}
        if self._dur is not None:
            skv.on_insert = self._on_insert_applied
            skv.on_delete = self._on_delete_applied
            skv.on_confirm = self._on_confirm_applied
            # The committing gid travels in the record: recovery REDOES
            # the write into that gid's slot directly (see
            # _redo_client_op) — re-routing by the latest config would
            # drop a write acked at an old owner just before a config
            # change, and a peer that never pulled pre-crash would then
            # pull an empty slot.
            skv.on_write = lambda gid, op: self._write_seqs.__setitem__(
                (op.client_id, op.command_id),
                durability.log(("skv", gid, op.op, op.key, op.value,
                                op.client_id, op.command_id)),
            )
            skv.on_ctrl = lambda op: self._admin_seqs.__setitem__(
                op.command_id,
                durability.log(("admin", op.kind, op.arg, op.command_id)),
            )
        if self._fleet:
            self._fetches: dict = {}  # (gid, shard, num) -> Future
            self._deletes: dict = {}
            skv.remote_fetch = self._remote_fetch
            skv.remote_delete = self._remote_delete
        # Durable state plane (distributed/stateplane.py): ship each
        # hosted group's snapshot+tail to rule-chosen standbys, and
        # receive other owners' shipments into a StandbyStore.  Wired
        # only in fleet mode with the fleet roster known.
        self._plane = None
        self._standby = None
        self._ship_futs: dict = {}  # proc -> in-flight ship Future
        self._ship_ends: dict = {}
        self._fleet_addrs = dict(fleet_addrs or {})
        if self._fleet_addrs and me is not None:
            from . import flightrec
            from .stateplane import StandbyStore, StatePlane

            self._standby = StandbyStore(obs=self._obs)
            self._plane = StatePlane(
                skv, me=int(me), n_procs=len(self._fleet_addrs),
                send=self._ship_send, rules=ship_rules,
                window_s=ship_window_s, sync=ship_sync,
                wal_seq_fn=(
                    (lambda: durability.wal.appended)
                    if durability is not None else None
                ),
                obs=self._obs, recorder=flightrec.get_recorder(),
            )
            # Attach AFTER the durability on_write hook above, so the
            # WAL record exists (wal.appended names it) when the plane
            # captures the write.
            self._plane.attach()
            if self._plane.sync and self._dur is not None:
                # Acks additionally gate on at least one standby having
                # acked the shipment covering the record (the zero-
                # acknowledged-write-loss mode of the chaos gate).
                self._dur.extra_sync_gate = self._plane.covered
        if self._pipe is not None and skv.driver.fused_eligible():
            # Warm the fused n-tick program before serving: its first
            # invocation pays the jit compile on this (loop) thread —
            # mid-serving it stalls the opening rate step's tail.  No
            # orchestration during construction; the backlog is empty,
            # so this is two liveness ticks.
            self.skv.pump(self._ticks, orchestrate=False)
        sched.call_soon(self._pump_loop)

    @property
    def obs(self):
        o = getattr(self, "_obs", None)
        if o is None:
            from .observe import Observability

            o = self._obs = Observability()
        return o

    @property
    def m(self):
        return self.obs.metrics

    # -- durability hooks (apply-time, loop thread) -----------------------

    def _on_insert_applied(self, gid, shard, num, data, latest):
        self._insert_seqs[(gid, shard, num)] = self._dur.log(
            ("insert", gid, shard, num, dict(data), dict(latest))
        )

    def _on_delete_applied(self, gid, shard, num):
        # Replayed on restore so a stale BEPULLING slot can't survive an
        # older checkpoint and wedge config advance.
        self._delete_seqs[(gid, shard, num)] = self._dur.log(
            ("delete", gid, shard, num)
        )

    def _on_confirm_applied(self, gid, shard, num):
        # Replayed on restore so recovery re-applies GCING→SERVING
        # locally instead of re-running the GC handshake — during
        # replay the loop thread is busy replaying, so an RPC to a
        # remote old owner could never resolve and recovery would
        # wedge (the confirm only ever committed because the delete
        # leg already succeeded pre-crash).
        self._dur.log(("confirm", gid, shard, num))

    # -- fleet migration hooks (run on the loop thread, inside pump) ------

    def _remote_fetch(self, src_gid: int, shard: int, num: int):
        from ..engine.shardkv import OK as SK_OK

        key = (src_gid, shard, num)
        fut = self._fetches.get(key)
        if fut is None:
            end = self.peers.get(src_gid)
            if end is None:
                return None  # unroutable: keep retrying (config may fix)
            self._fetches[key] = self.sched.with_timeout(
                end.call("EngineShardKV.pull_shard", (src_gid, shard, num)),
                self.MIGRATE_RPC_S,
            )
            return None
        if not fut.done:
            return None
        del self._fetches[key]  # resolved: consume or retry next sweep
        reply = fut.value
        if (
            reply is None or reply is TIMEOUT
            or not isinstance(reply, tuple) or reply[0] != SK_OK
        ):
            return None  # dropped / not ready: the sweep re-issues
        return reply[1], reply[2]

    def _remote_delete(self, src_gid: int, shard: int, num: int):
        from ..engine.shardkv import OK as SK_OK

        # Durability gate: never tell the old owner to delete a shard
        # whose inserted copy isn't fsynced locally yet — between its
        # delete and our next checkpoint/WAL-sync, a crash would lose
        # the only copy.  One pump's group fsync clears this.
        if self._dur is not None:
            for (g, s, n), seq in self._insert_seqs.items():
                if s == shard and n == num and not self._dur.synced(seq):
                    return None
        key = (src_gid, shard, num)
        fut = self._deletes.get(key)
        if fut is None:
            end = self.peers.get(src_gid)
            if end is None:
                return True  # owner unknown everywhere: nothing to delete
            self._deletes[key] = self.sched.with_timeout(
                end.call("EngineShardKV.delete_shard", (src_gid, shard, num)),
                self.MIGRATE_RPC_S,
            )
            return None
        if not fut.done:
            return None
        del self._deletes[key]
        reply = fut.value
        if reply is None or reply is TIMEOUT or not isinstance(reply, tuple):
            return None  # dropped: re-issue next sweep
        return reply[0] == SK_OK  # False = ErrNotReady, re-asked later

    # -- fleet migration RPC handlers (the serving side of the hooks) -----

    def pull_shard(self, args):
        """Return ``(OK, data, latest)`` for a shard this process's old
        owner holds, once it has applied the puller's config number —
        the cross-process form of the in-process applied-state read
        (engine/shardkv.py _orchestrate step (b))."""
        from ..engine.shardkv import ERR_NOT_READY, ERR_WRONG_GROUP
        from ..engine.shardkv import OK as SK_OK

        src_gid, shard, num = args
        self.m.inc("migrate.pulls_served")
        if src_gid not in self.skv.reps:
            return (ERR_WRONG_GROUP,)

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                rep = self.skv.reps[src_gid]
                if rep.cur.num >= num:
                    sh = rep.shards[shard]
                    return (SK_OK, dict(sh.data), dict(sh.latest))
                yield 0.01  # config catching up (the ErrNotReady gate)
            return (ERR_NOT_READY,)

        return run()

    def delete_shard(self, args):
        """Challenge-1 deletion on behalf of a remote puller: ride the
        local old owner's log (BatchedShardKV.delete_shard) and report
        the outcome."""
        from ..engine.shardkv import ERR_WRONG_GROUP
        from ..engine.shardkv import OK as SK_OK

        src_gid, shard, num = args
        self.m.inc("migrate.deletes_served")
        if src_gid not in self.skv.reps:
            return (ERR_WRONG_GROUP,)

        def run():
            t = self.skv.delete_shard(src_gid, shard, num)
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                if t.done:
                    if t.failed:
                        return (ERR_TIMEOUT,)
                    if t.err != SK_OK:
                        return (t.err,)
                    # Gate the OK on the delete's WAL record being
                    # fsynced: the puller confirms on our OK and never
                    # re-asks, so losing the record to a crash would
                    # strand a BEPULLING slot here forever.  (Absent =
                    # pruned = already durable, or the slot was already
                    # clear and no record was written — also durable.)
                    # Deadline-bounded: a stalled fsync must surface as
                    # a timeout the puller retries, not a pinned
                    # generator.
                    while self._dur is not None:
                        seq = self._delete_seqs.get((src_gid, shard, num))
                        if seq is None or self._dur.synced(seq):
                            break
                        if self.sched.now >= deadline:
                            return (ERR_TIMEOUT,)
                        yield 0.002
                    return (SK_OK,)
                yield 0.005
            return (ERR_TIMEOUT,)

        return run()

    # -- group placement RPCs (distributed/placement.py drives these) -----
    #
    # Whole-group migration between fleet processes: the controller
    # calls pull_group at the source (seal + export), adopt_group at
    # the destination (spare engine slot), drop_group back at the
    # source, then pushes the new placement map fleet-wide with
    # `place`.  All handlers are idempotent so the controller can
    # retry any leg after a timeout.

    ERR_NO_SLOT = "ErrNoSlot"

    def pull_group(self, args):
        """Seal ``gid`` and return ``(OK, blob)`` — its frozen applied
        state (BatchedShardKV.export_group).  Retries return the same
        blob: the seal stops every mutation."""
        from ..engine.shardkv import ERR_NOT_READY, ERR_WRONG_GROUP
        from ..engine.shardkv import OK as SK_OK

        gid = args[0] if isinstance(args, (tuple, list)) else args
        self.m.inc("place.pulls_served")

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                if gid not in self.skv.reps:
                    return (ERR_WRONG_GROUP,)
                blob = self.skv.export_group(gid)
                if blob is not None:
                    return (SK_OK, blob)
                yield 0.01  # mid-migration / config in flight: settle
            return (ERR_NOT_READY,)

        return run()

    def unseal_group(self, args):
        """Abort leg: only safe while the blob was never dispatched to
        any destination (see BatchedShardKV.unseal_group).  ``force``
        (second arg) overrides the post-dispatch refusal — the
        controller sends it only with the destination provably dead."""
        from ..engine.shardkv import OK as SK_OK

        if isinstance(args, (tuple, list)):
            gid = args[0]
            force = bool(args[1]) if len(args) > 1 else False
        else:
            gid, force = args, False
        try:
            self.skv.unseal_group(gid, force)
        except RuntimeError:
            return ("ErrDispatched",)
        return (SK_OK,)

    def adopt_group(self, args):
        """Host ``gid`` in a spare engine slot.  ``blob=None`` adopts
        empty (dead-source failover: the fresh replica re-pulls from
        whatever live owners remain).  Idempotent: a retried adopt of
        an already-hosted gid answers OK."""
        from ..engine.shardkv import OK as SK_OK

        gid, blob = args[0], args[1]
        if gid in self.skv.reps:
            return (SK_OK,)
        if self.skv.free_slots() <= 0:
            return (self.ERR_NO_SLOT,)
        self.skv.adopt_gid(gid, blob)
        self.peers.pop(gid, None)  # it's local now
        self.m.inc("place.adoptions")
        return (SK_OK,)

    def drop_group(self, args):
        """Free ``gid``'s slot after the destination adopted it.  Waits
        for the slot to quiesce (tail applies resolve as WRONG_GROUP
        no-ops) so slot reuse is safe.  Idempotent: already-dropped
        answers OK."""
        from ..engine.shardkv import OK as SK_OK

        gid = args[0] if isinstance(args, (tuple, list)) else args

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                if gid not in self.skv.reps:
                    return (SK_OK,)
                if self.skv.group_quiesced(gid):
                    self.skv.drop_gid(gid)
                    self._rebuild_peers()  # route it to its new owner
                    self.m.inc("place.drops")
                    return (SK_OK,)
                yield 0.005
            return (ERR_TIMEOUT,)

        return run()

    # -- state-plane RPCs (distributed/stateplane.py) ---------------------

    def ship(self, args):
        """Ingest one framed shipment into the local StandbyStore;
        returns the store's ack ``{"ok", "have", "gid"}`` (the shipper
        treats ``have`` as the authoritative resend frontier)."""
        payload = args[0] if isinstance(args, (tuple, list)) else args
        if self._standby is None:
            return {"ok": False, "have": -1}
        return self._standby.receive(payload)

    def standby_state(self, args):
        """Freshness of the local standby state for ``gid`` (None when
        holding nothing) — the controller's recovery-destination probe."""
        gid = args[0] if isinstance(args, (tuple, list)) else args
        if self._standby is None:
            return None
        return self._standby.freshness(gid)

    def recover_group(self, args):
        """Stateful failover: adopt ``gid`` from the LOCAL standby store
        (snapshot fast-forward + exactly-once tail replay through the
        group's own log), answering ``(OK, "recovered")``.  With no
        shipped state here, ``(OK, "empty")`` tells the controller to
        fall back to explicit empty adoption."""
        from ..engine.shardkv import OK as SK_OK

        gid = args[0] if isinstance(args, (tuple, list)) else args

        def run():
            from .stateplane import iter_replay_tail, recovery_blob

            held = (
                self._standby.get(gid)
                if self._standby is not None else None
            )
            if held is None:
                return (SK_OK, "empty")
            snap, tail = held
            if gid not in self.skv.reps:
                blob = recovery_blob(snap, self.skv.query_latest())
                if blob is None and not tail:
                    return (SK_OK, "empty")
                if self.skv.free_slots() <= 0:
                    return (self.ERR_NO_SLOT,)
                self.skv.adopt_gid(gid, blob)
                self.peers.pop(gid, None)  # it's local now
                self.m.inc("place.adoptions")
            if tail:
                yield from iter_replay_tail(self.skv, gid, tail)
            self._standby.drop(gid)
            self.m.inc("ship.recoveries")
            return (SK_OK, "recovered")

        return run()

    def _ship_send(self, proc: int, payload: bytes):
        """StatePlane delivery hook: ONE in-flight ship RPC per standby,
        resolved by polling — the pump loop must never block on the
        network.  Returns the PREVIOUS completed reply (None while one
        is still flying); correctness rides on the reply's ``have``
        frontier being authoritative and gid-tagged, not on pairing a
        reply with the payload it answered."""
        prev = self._ship_futs.get(proc)
        reply = None
        if prev is not None:
            if not prev.done:
                return None
            del self._ship_futs[proc]
            v = prev.value
            if isinstance(v, dict):
                reply = v
        addr = self._fleet_addrs.get(proc)
        if addr is None or self._make_end is None:
            return reply
        end = self._ship_ends.get(proc)
        if end is None:
            end = self._ship_ends[proc] = self._make_end(
                addr[0], int(addr[1])
            )
        self._ship_futs[proc] = self.sched.with_timeout(
            end.call("EngineShardKV.ship", (payload,)),
            self.MIGRATE_RPC_S,
        )
        return reply

    def place(self, args):
        """Placement push from the controller: ``(version, {gid:
        (host, port)})``.  Only a strictly newer version applies —
        reordered or replayed pushes are no-ops."""
        from ..engine.shardkv import OK as SK_OK

        version, pmap = args
        cur_ver, _ = self._placement
        if version > cur_ver:
            self._placement = (
                int(version),
                {int(g): (a[0], int(a[1])) for g, a in pmap.items()},
            )
            self._rebuild_peers()
            self.m.inc("place.pushes")
        return (SK_OK, self._placement[0])

    def placement(self, args=None):
        """Current placement view ``(version, {gid: (host, port)})`` —
        the fleet clerk's re-route source after ErrWrongGroup."""
        ver, pmap = self._placement
        return (ver, {g: tuple(a) for g, a in pmap.items()})

    # -- membership-change RPCs (self-healing replica sets) ---------------
    #
    # The placement controller's replace-dead-replica policy drives
    # these: add_learner seats a fresh non-voting incarnation in a
    # spare engine slot, learner_match gauges its catch-up, begin_joint
    # appends the C_old,new entry at the leader (the engine auto-exits
    # to C_new once it commits under BOTH quorums).  All handlers are
    # idempotent — BatchedShardKV's *_gid wrappers answer True when
    # the engine is already at or past the requested state — so the
    # controller can replay any leg after a crash or lost reply.

    def replica_config(self, args):
        """``(OK, cfg)`` — the leader's config view for ``gid``
        (voter sets, joint flag, epoch), ``cfg=None`` when leaderless
        or the gid is not hosted here."""
        from ..engine.shardkv import OK as SK_OK

        gid = args[0] if isinstance(args, (tuple, list)) else args
        return (SK_OK, self.skv.config_of_gid(gid))

    def add_learner(self, args):
        """Seat engine slot ``peer`` as a non-voting learner of
        ``gid``; ``(OK, bool)``."""
        from ..engine.shardkv import OK as SK_OK

        gid, peer = args[0], args[1]
        ok = self.skv.add_learner_gid(gid, int(peer))
        if ok:
            self.m.inc("reconfig.learners_seated")
        return (SK_OK, bool(ok))

    def learner_match(self, args):
        """``(OK, (leader's match for peer, leader's last index))`` —
        the catch-up gauge; ``(OK, None)`` when leaderless."""
        from ..engine.shardkv import OK as SK_OK

        gid, peer = args[0], args[1]
        return (SK_OK, self.skv.learner_match_gid(gid, int(peer)))

    def begin_joint(self, args):
        """Append the C_old,new entry making ``voters`` the target
        config of ``gid``; ``(OK, bool)``."""
        from ..engine.shardkv import OK as SK_OK

        gid, voters = args[0], args[1]
        ok = self.skv.begin_joint_gid(gid, [int(q) for q in voters])
        if ok:
            self.m.inc("reconfig.joints_entered")
        return (SK_OK, bool(ok))

    def kill_replica(self, args):
        """Chaos verb: permanently mark engine replica ``(gid, peer)``
        dead (nemesis / acceptance harnesses only); ``(OK, bool)``."""
        from ..engine.shardkv import OK as SK_OK

        gid, peer = args[0], args[1]
        ok = self.skv.kill_replica_gid(gid, int(peer))
        if ok:
            self.m.inc("reconfig.replicas_killed")
        return (SK_OK, bool(ok))

    def _rebuild_peers(self) -> None:
        """Re-derive the gid→end peer map from the placement view,
        skipping locally hosted gids.  Ends are cached per address."""
        if self._make_end is None:
            return
        _, pmap = self._placement
        peers = {}
        for g, addr in pmap.items():
            if g in self.skv.reps:
                continue
            addr = (addr[0], int(addr[1]))
            end = self._ends_by_addr.get(addr)
            if end is None:
                end = self._make_end(addr[0], addr[1])
                self._ends_by_addr[addr] = end
            peers[g] = end
        self.peers = peers

    def config(self, args):
        """Latest committed config as ``(num, shards, groups)`` — the
        fleet clerk's routing source (shardctrler Query analog)."""
        cfg = self.skv.query_latest()
        return (
            cfg.num,
            list(cfg.shards),
            {g: list(v) for g, v in cfg.groups.items()},
        )

    # Shared wire-level frame cap (clerks split on the same constant).
    MAX_FIREHOSE = MAX_FIREHOSE_ROWS

    def firehose(self, blob):
        """Columnar frame for the sharded service (engine/firehose.py):
        the group column carries GLOBAL gids; ownership re-checks at
        apply produce per-row WRONG_GROUP outcomes the client re-routes
        after a config refresh.  Gets answer from the applied frontier
        (get_fast's ownership-gated ReadIndex) at frame completion —
        but a get whose shard had a NON-OK write row in this frame
        mirrors that row's outcome instead, preserving
        read-after-own-frame-writes under migration."""
        import numpy as np

        from ..engine.firehose import (
            FH_NO_KEY,
            FH_OK,
            FH_RETRY,
            FH_WRONG_GROUP,
            pack_reply,
        )
        from ..engine.shardkv import ERR_NO_KEY, ERR_WRONG_GROUP, OK
        from ..services.shardkv import key2shard

        def run():
            raw = bytes(blob)
            if len(raw) < 4:
                return ("err", "ErrMalformedFrame")
            n = int(np.frombuffer(raw, np.dtype("<u4"), 1, 0)[0])
            if n > self.MAX_FIREHOSE:
                return ("err", f"ErrFrameTooLarge:{self.MAX_FIREHOSE}")
            try:
                f = self.skv.submit_frame(raw)
            except ValueError as e:
                return ("err", str(e))
            deadline = self.sched.now + self.DEADLINE_S
            while not f.done and self.sched.now < deadline:
                yield 0.002
            err = f.err.copy()
            # Durable mode: the shared firehose ack gate.
            if self._dur is not None:
                yield from demote_unsynced_rows(
                    self.sched, self._dur, self._write_seqs, f, err,
                    deadline,
                )
            # Shards whose write rows did not land OK: gets there mirror
            # the write outcome so the client re-frames them together.
            bad_shard_err: dict = {}
            for r in f.write_rows.tolist():
                if err[r] != FH_OK:
                    bad_shard_err[key2shard(f.keys[r])] = int(err[r])
            values = [b""] * len(f)
            for r in np.nonzero(f.ops == 0)[0].tolist():
                shard = key2shard(f.keys[r])
                if shard in bad_shard_err:
                    err[r] = bad_shard_err[shard]
                    continue
                t = self.skv.get_fast(f.keys[r])
                if t.err == ERR_WRONG_GROUP:
                    err[r] = FH_WRONG_GROUP
                elif t.err == ERR_NO_KEY:
                    err[r] = FH_NO_KEY
                else:
                    err[r] = FH_OK
                    values[r] = t.value.encode()
            return pack_reply(err, values)

        return run()

    def stop(self) -> None:
        self._stopped = True
        pipe = getattr(self, "_pipe", None)
        if pipe is not None:
            pipe.stop()

    def final_checkpoint(self) -> bool:
        """Graceful-shutdown hook — see EngineKVService."""
        if self._dur is None:
            return False
        self._drain_pipeline()  # driver.save refuses in-flight batches
        self._dur.checkpoint()
        return True

    def _arm_pump(self, delay: float) -> None:
        """Single-timer discipline — see EngineKVService."""
        t = self._pump_timer
        if t is not None:
            t.cancel()
        self._pump_timer = self.sched.call_after(delay, self._pump_loop)

    def _pump_loop(self) -> None:
        self._pump_timer = None
        if self._stopped:
            return
        d = self.skv.driver
        if self._pipe is not None and d.fused_eligible():
            # Pipelined path — see EngineKVService._pump_loop.
            if len(d._inflight) < self._depth:
                flush = getattr(self.sched, "flush_io", None)
                if flush is not None:
                    flush()
                cp0 = time.thread_time()
                pending = d.dispatch_ticks(self._ticks)
                pending.t_loop_cpu = time.thread_time() - cp0
                self._occ.add(time.perf_counter() - pending.t_dispatch)
                self._pipe.submit(
                    pending.fetch,
                    functools.partial(self._pump_done, pending),
                )
            self._arm_pump(self._cadence.next_delay(service_busy(self.skv)))
            return
        self._pump_sync()

    def _pump_sync(self) -> None:
        """Legacy synchronous pump (MRT_ENGINE_PIPELINE=0, mesh
        drivers, reorder chaos in flight)."""
        t0 = time.perf_counter()
        cp0 = time.thread_time()
        self.skv.pump(self._ticks)
        dt = time.perf_counter() - t0
        self._occ.add(dt)
        self._record_pump(dt, time.thread_time() - cp0)
        self._after_pump_durability()
        self._arm_pump(self._cadence.next_delay(service_busy(self.skv)))

    def _pump_done(self, pending, rec) -> None:
        """Loop-side completion of a dispatched batch — see
        EngineKVService._pump_done."""
        if isinstance(rec, BaseException):
            raise rec
        d = self.skv.driver
        if pending not in d._inflight:
            return  # already drained (final_checkpoint) or torn down
        t0 = time.perf_counter()
        cp0 = time.thread_time()
        d.complete_ticks(pending, rec)
        self.skv.after_step(pending.n, orchestrate=True)
        now = time.perf_counter()
        self._occ.add(now - t0)
        self._record_pump(
            now - pending.t_dispatch,
            (time.thread_time() - cp0) + pending.t_loop_cpu,
        )
        self._after_pump_durability()
        if self._stopped:
            return
        self._arm_pump(self._cadence.next_delay(service_busy(self.skv)))

    def _record_pump(self, dt: float, cdt: float) -> None:
        self.m.inc("pump.count")
        self.m.observe("pump.wall_s", dt)
        self.m.observe("pump.cpu_s", cdt)
        self.m.observe("cpu.engine_s", cdt)
        # Pump sequencing for the tail plane (twin of the flat engine
        # server's): tick id + dispatch stamp so a committing request
        # can attribute its parked time to the fused tick that
        # carried it.
        self._pumps += 1
        self._pump_t_dispatch = time.perf_counter() - dt

    def _after_pump_durability(self) -> None:
        if self._dur is not None:
            self._dur.after_pump()  # group fsync + periodic checkpoint
            for attr in ("_insert_seqs", "_write_seqs", "_admin_seqs",
                         "_delete_seqs"):
                seqs = getattr(self, attr)
                if seqs:
                    setattr(self, attr, {
                        k: v for k, v in seqs.items()
                        if not self._dur.synced(v)
                    })
        if self._plane is not None:
            self._plane.ship_round()

    def _drain_pipeline(self) -> None:
        """Complete every in-flight batch synchronously (checkpoint /
        shutdown path) — see EngineKVService."""
        d = self.skv.driver
        while d._inflight:
            p = d._inflight[0]
            d.complete_ticks(p, p.fetch())
            self.skv.after_step(p.n, orchestrate=True)

    def replay_wal(self) -> int:
        """Recovery replay — delegated to
        :class:`~.engine_durability.ShardWalReplay` (two-pass redo with
        migration paused; see its docstring for the full contract)."""
        n = ShardWalReplay(self.skv, self._dur).run()
        self.m.inc("wal.replays")
        self.m.inc("wal.replayed_records", n)
        return n

    # Largest multi-op frame one RPC may carry (see EngineKVService).
    MAX_BATCH = 1024

    def batch(self, args_list):
        """Multi-op frame for the SHARDED service.  Chains key on
        (client, shard) — a shard's dedup table travels with it and
        same-key ops share a shard — and run STRICTLY one op in flight
        each, the reference clerk's serial discipline
        (shardkv/client.go:68-129): pipelining within a chain is
        unsafe here because an away-and-back shard migration can let a
        later op apply while an earlier one bounced ErrWrongGroup, and
        the earlier op's retry then dedup-swallows into a false OK.
        The frame's parallelism comes from chains to DIFFERENT shards
        pipelining freely.  In fleet mode, ops whose shard a peer
        process owns answer ErrWrongGroup per-op so the fleet clerk
        re-frames them to the owner."""
        from ..engine.shardkv import ERR_WRONG_GROUP
        from ..services.shardkv import key2shard

        if len(args_list) > self.MAX_BATCH:
            return [
                EngineCmdReply(err=f"ErrBatchTooLarge:{self.MAX_BATCH}")
            ] * len(args_list)

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            replies = [None] * len(args_list)
            chains: dict = {}
            for i, a in enumerate(args_list):
                if a.op == "Get":
                    continue
                chains.setdefault(
                    (a.client_id, key2shard(a.key)), []
                ).append(i)

            def submit(a):
                cfg = self.skv.query_latest()
                gid = cfg.shards[key2shard(a.key)]
                if gid not in self.skv.reps:
                    return None  # peer-owned (or unassigned) shard
                if self._fleet and self.skv.is_sealed(gid):
                    return None  # mid-placement-migration: re-route
                return self.skv.submit(
                    gid, a.op, a.key, a.value,
                    client_id=a.client_id, command_id=a.command_id,
                )

            tickets: dict = {}   # frame idx -> resolved-OK ticket
            wrong: set = set()   # frame idx -> answer ErrWrongGroup
            heads: dict = {}     # chain -> (frame idx, live ticket)
            cursor = {qk: 0 for qk in chains}
            pending = set(chains)
            while pending and self.sched.now < deadline:
                progressed = False
                for qk in list(pending):
                    members = chains[qk]
                    if qk not in heads:
                        i = members[cursor[qk]]
                        t = submit(args_list[i])
                        if t is None:
                            if self._fleet:
                                # Peer-owned: the whole remaining chain
                                # belongs to that peer — punt it.
                                for j in members[cursor[qk]:]:
                                    wrong.add(j)
                                pending.discard(qk)
                                progressed = True
                            continue  # non-fleet: config moving; wait
                        heads[qk] = (i, t)
                        continue
                    i, t = heads[qk]
                    if not t.done:
                        continue
                    del heads[qk]
                    if t.failed or t.err == ERR_WRONG_GROUP:
                        continue  # resubmit next round (dedup-safe)
                    tickets[i] = t
                    cursor[qk] += 1
                    progressed = True
                    if cursor[qk] >= len(members):
                        pending.discard(qk)
                if pending and not progressed:
                    yield 0.002
            # Durable frame ack (shared gate — see _await_frame_synced).
            ok = {
                i for i, t in tickets.items()
                if t.done and not t.failed and t.err == OK
            }
            yield from await_frame_synced(
                self.sched, self._dur, self._write_seqs, ok,
                args_list, deadline,
            )
            for i, a in enumerate(args_list):
                if a.op == "Get":
                    t = self.skv.get_fast(a.key)
                    if t.err == ERR_WRONG_GROUP:
                        replies[i] = EngineCmdReply(err=ERR_WRONG_GROUP)
                    else:
                        replies[i] = EngineCmdReply(
                            err=OK, value=t.value if t.err == OK else ""
                        )
                elif i in wrong:
                    replies[i] = EngineCmdReply(err=ERR_WRONG_GROUP)
                elif i in ok:
                    replies[i] = EngineCmdReply(
                        err=OK, value=tickets[i].value
                    )
                else:
                    replies[i] = EngineCmdReply(err=ERR_TIMEOUT)
            return replies

        return run()

    def command(self, args: EngineCmdArgs):
        from ..engine.shardkv import ERR_WRONG_GROUP
        from ..services.shardkv import key2shard

        if args.op == "Get":
            self.m.inc("kv.gets")

            # ReadIndex fast read (BatchedShardKV.get_fast): no log
            # entry, gated on serving-shard ownership exactly like the
            # logged path; ErrWrongGroup during migration pumps and
            # retries like any clerk op.
            def run_get():
                deadline = self.sched.now + self.DEADLINE_S
                while self.sched.now < deadline:
                    t = self.skv.get_fast(args.key)
                    if t.err == ERR_WRONG_GROUP:
                        # Fleet: the owner is (probably) another
                        # process — answer so the clerk re-routes.
                        if self._fleet:
                            return EngineCmdReply(err=ERR_WRONG_GROUP)
                        yield 0.01  # config moving; shard not serving here
                        continue
                    value = t.value if t.err == OK else ""
                    return EngineCmdReply(err=OK, value=value)
                return EngineCmdReply(err=ERR_TIMEOUT)

            return run_get()

        # Request id + stage clock captured at handler entry (dispatch
        # breadcrumb — see EngineKVService.command).
        rid = self.obs.current_trace()
        stages = self.obs.current_stages()
        self.m.inc("kv.writes")

        def run():
            t_start = self.sched.now
            deadline = t_start + self.DEADLINE_S
            t_parked = 0.0
            while self.sched.now < deadline:
                cfg = self.skv.query_latest()
                gid = cfg.shards[key2shard(args.key)]
                if gid not in self.skv.reps:
                    if self._fleet:
                        # Hosted by a peer process: tell the clerk.
                        return EngineCmdReply(err=ERR_WRONG_GROUP)
                    yield 0.01  # shard unassigned; config still moving
                    continue
                if self._fleet and self.skv.is_sealed(gid):
                    # Mid-placement-migration: every apply would be a
                    # WRONG_GROUP no-op — tell the clerk NOW so it
                    # refreshes placement and retries at the adopter.
                    return EngineCmdReply(err=ERR_WRONG_GROUP)
                t = self.skv.submit(
                    gid, args.op, args.key, args.value,
                    client_id=args.client_id, command_id=args.command_id,
                )
                if stages is not None:
                    if not stages.engine:
                        # First submit closes the handler leg (routing
                        # + config queries); re-routes stay in the
                        # engine leg.
                        stages.engine = True
                        stages.fold(self.m, "handler")
                    # Parked from here until a pump carries the
                    # proposal (re-stamped per re-route).
                    t_parked = time.perf_counter()
                sub_deadline = min(
                    self.sched.now + self.RESUBMIT_S, deadline
                )
                while not t.done and self.sched.now < sub_deadline:
                    yield 0.002
                if not t.done or t.failed or t.err == ERR_WRONG_GROUP:
                    continue  # resubmit / re-route; dedup-safe
                if stages is not None:
                    # Commit observed; the fsync gate below lands in
                    # the ack leg (folded at dispatch completion).
                    stages.fold(self.m, "engine")
                    # Tail attribution: carrying tick + parked time
                    # (getattr: stub handlers built via __new__ in
                    # tests carry no pump state).
                    stages.tick = getattr(self, "_pumps", -1)
                    stages.pump_wait_s = max(
                        0.0,
                        getattr(self, "_pump_t_dispatch", 0.0)
                        - t_parked,
                    )
                # Ack gates on the apply-time WAL record being fsynced
                # (absent = pruned/duplicate = already durable).
                while self._dur is not None:
                    seq = self._write_seqs.get(
                        (args.client_id, args.command_id)
                    )
                    if seq is None or self._dur.synced(seq):
                        break
                    yield 0.002
                self.m.observe("kv.command_s", self.sched.now - t_start)
                if rid is not None:
                    self.obs.tracer.instant(
                        "commit",
                        time.perf_counter() * 1e6,
                        track="engine",
                        req=rid,
                        group=gid,
                    )
                return EngineCmdReply(err=OK, value=t.value)
            return EngineCmdReply(err=ERR_TIMEOUT)

        return run()

    ADMIN_OPS = ("join", "leave", "move")

    def admin(self, args):
        """Config administration: args = (kind, payload[, command_id])
        with kind in ADMIN_OPS — a network-supplied string must never
        getattr into arbitrary methods.  The optional command_id makes
        retries exactly-once through the ctrler dedup table; a FLEET
        admin MUST pass one (a duplicate apply would fork the config
        histories' numbering across processes and wedge migration)."""
        kind, payload = args[0], args[1]
        cmd = args[2] if len(args) > 2 else None
        if kind not in self.ADMIN_OPS:
            return EngineCmdReply(err=f"ErrBadAdminOp:{kind}")

        def run():
            # join/leave take their payload whole (a gid list / mapping);
            # move takes (shard, gid) as two positionals.
            if kind == "move":
                t = self.skv.move(*payload, command_id=cmd)
            else:
                t = getattr(self.skv, kind)(payload, command_id=cmd)
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                if t.done:
                    if t.failed:
                        return EngineCmdReply(err=ERR_TIMEOUT)
                    # Ack gates on the apply-time ("admin", ...) WAL
                    # record (logged by the on_ctrl hook in commit
                    # order) being fsynced.
                    while self._dur is not None:
                        seq = self._admin_seqs.get(t.command_id)
                        if seq is None or self._dur.synced(seq):
                            break
                        yield 0.002
                    return EngineCmdReply(err=OK)
                yield 0.005
            return EngineCmdReply(err=ERR_TIMEOUT)

        return run()


def serve_engine_shardkv(
    port: int,
    G: int = 4,
    host: str = "127.0.0.1",
    seed: int = 0,
    join_gids: Optional[Sequence[int]] = None,
    gids: Optional[Sequence[int]] = None,
    peer_addrs: Optional[dict] = None,  # gid -> (host, port) of the owner
    data_dir: Optional[str] = None,
    checkpoint_every_s: float = 30.0,
    mesh_devices: int = 0,
    spare_slots: int = 0,
    replicas: int = 3,
    voters: Optional[Sequence[int]] = None,
    fleet_addrs: Optional[dict] = None,  # proc -> (host, port), all procs
    me: Optional[int] = None,  # this process's index in fleet_addrs
    ship_rules=None,
    ship_sync: Optional[bool] = None,
    ship_window_s: Optional[float] = None,
) -> RpcNode:
    """The sharded engine behind TCP: BatchedShardKV (replicated config
    + per-shard migration pipeline) on one chip-owning process.

    Fleet mode: pass ``gids`` (the global gids THIS process hosts; the
    local engine is sized ``len(gids)+1``) and ``peer_addrs`` (owner
    address for every remotely hosted gid) — shard migration then rides
    ``pull_shard``/``delete_shard`` RPCs between processes.

    With ``data_dir`` the process is DURABLE (checkpoint + WAL of
    client writes, admin ops, and migration inserts/deletes); a
    restarted process recovers every acknowledged op, and in a fleet
    the GC handshake is gated so a migrated-in blob is never the only
    un-fsynced copy."""
    from ..engine.shardkv import BatchedShardKV

    node = RpcNode(listen=True, host=host, port=port)
    sched = node.sched
    local_gids = list(gids) if gids is not None else None
    # spare_slots: extra idle engine groups the placement controller
    # can adopt migrated gids into (distributed/placement.py).
    G_local = (
        (len(local_gids) + 1 + max(0, spare_slots))
        if local_gids is not None else G
    )
    peers = {
        g: node.client_end(h, p)
        for g, (h, p) in (peer_addrs or {}).items()
        if local_gids is None or g not in local_gids
    }
    # Version-0 placement view: the static spec (peer addrs + own gids).
    placement0 = None
    if local_gids is not None:
        placement0 = {
            int(g): (h, int(p)) for g, (h, p) in (peer_addrs or {}).items()
        }
        for g in local_gids:
            placement0[int(g)] = (host, int(port))

    def build():
        mesh = make_mesh(mesh_devices) if mesh_devices else None
        driver = None
        if data_dir:
            ckpt = os.path.join(data_dir, "engine.ckpt")
            if os.path.exists(ckpt):
                driver = EngineDriver.restore(ckpt, mesh=mesh)
        restored = driver is not None
        if restored:
            node.obs.metrics.inc("engine.restores")
        if not restored:
            cfg = EngineConfig(
                G=G_local, P=max(3, int(replicas)), L=64, E=8, INGEST=8
            )
            driver = EngineDriver(cfg, seed=seed, mesh=mesh)
            if voters is not None and len(set(voters)) < cfg.P:
                # Spare ENGINE REPLICA slots: only ``voters`` vote; the
                # remaining rows park dead until the placement
                # controller's replace-dead-replica policy seats a
                # learner in one (self-healing replica sets).  A
                # RESTORED process skips this — its config (voter
                # masks included) comes from the checkpoint.
                driver.seed_config(voters)
            # Warm-up before readiness (see serve_engine_kv):
            # elections + both tick compiles happen here, not under
            # client traffic.
            ok = driver.run_until_quiet_leaders(2000)
            assert ok, "engine groups failed to elect"
        skv = BatchedShardKV(driver, gids=local_gids)
        if restored:
            blob = driver.restored_extra.get("service")
            if blob:
                skv.load_state_dict(blob)
        # Warm the LOADED tick variant before the readiness line (the
        # jit compile takes tens of seconds on CPU and would otherwise
        # land under the first admin/client RPC and time it out).  A
        # None payload is the "binding lost" no-op: it exercises the
        # ingest path without touching config history — essential in
        # fleet mode, where every process's history must stay aligned.
        skv.driver.start(0, None)
        skv.pump(8)
        if not restored:
            # A restored process's config history lives in its
            # checkpoint + WAL — re-running the bootstrap joins would
            # allocate fresh ctrler ids the dedup table can't absorb
            # and append a spurious config per restart.
            for gid in join_gids or []:
                skv.admin_sync("join", [gid])
        dur = (
            EngineDurability(data_dir, driver, skv,
                             checkpoint_every_s=checkpoint_every_s,
                             metrics=node.obs.metrics)
            if data_dir else None
        )
        driver.metrics = node.obs.metrics  # scrapeable tick counter
        if node.tracer is not None:
            driver.tracer = node.tracer  # ticks + RPCs on one timeline
        svc = EngineShardKVService(sched, skv, peers=peers, durability=dur,
                                   obs=node.obs,
                                   fleet=local_gids is not None,
                                   make_end=node.client_end,
                                   placement0=placement0,
                                   fleet_addrs=fleet_addrs, me=me,
                                   ship_rules=ship_rules,
                                   ship_sync=ship_sync,
                                   ship_window_s=ship_window_s)
        if dur is not None:
            svc.replay_wal()  # recovery completes before readiness
            dur.checkpoint()  # fold replay into a fresh checkpoint
        return svc

    svc = sched.run_call(build, timeout=600.0)
    node.add_service("EngineShardKV", svc)
    node.engine_service = svc
    # Overload watch: stage-p99/queue-gauge bounds → OVERLOAD records.
    # Admission: the watch's brownout state drives shedding at dispatch.
    from .admission import install_admission
    from .overload import install_overload_watch
    from .wedge import install_wedge_watch

    install_admission(node)
    install_overload_watch(node)
    # Wedge watchdog: commit-frontier stall with proposals pending →
    # WEDGE records + gauge.wedged_groups (gray-failure liveness).
    install_wedge_watch(node)
    return node
