"""RPC endpoints over the native TCP transport.

Exposes the same contract the simulated network gives the framework —
``ClientEnd.call(svc_meth, args) → Future`` with ``None`` meaning "RPC
failed" (labrpc's boolean ``ok``, reference: labrpc/labrpc.go:87-126) —
but across real processes.  One :class:`RpcNode` per process owns one
epoll transport, one dispatcher thread, and the process's
``RealtimeScheduler``; every handler and future resolution runs on the
scheduler loop, so RaftNode/KVServer/clerk code is byte-identical
between sim and deployment.

Frames are codec-encoded tuples:

    ("req", req_id, svc_meth, args)   caller → callee
    ("rep", req_id, value)            callee → caller

Handlers returning generator coroutines (the wait-channel pattern,
reference: kvraft/server.go:56-96) are spawned; the reply ships when
their future resolves.  A dropped connection resolves all its pending
calls with ``None`` and the next call reconnects — the client-side
retry loops (reference: kvraft/client.go:47-71) handle the rest.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
from typing import Any, Dict, Optional, Tuple

from ..sim.scheduler import Future
from ..transport import codec
from .native import EV_CLOSED, EV_FRAME, NativeTransport
from .realtime import RealtimeScheduler

__all__ = ["RpcNode", "TcpClientEnd"]


class TcpClientEnd:
    """ClientEnd bound to a ``(host, port)`` server address."""

    def __init__(self, node: "RpcNode", host: str, port: int) -> None:
        self._node = node
        self.addr = (host, port)

    def call(self, svc_meth: str, args: Any) -> Future:
        return self._node._call(self.addr, svc_meth, args)


class RpcNode:
    """One process's RPC endpoint: optional listener + outbound calls."""

    def __init__(
        self,
        sched: Optional[RealtimeScheduler] = None,
        listen: bool = False,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self.sched = sched or RealtimeScheduler()
        self._tr = NativeTransport()
        self.host, self.port = host, 0
        if listen:
            self.port = self._tr.listen(host, port)
        self._services: Dict[str, Any] = {}
        self._req_ids = itertools.count(1)
        self._lock = threading.Lock()
        self._pending: Dict[int, Tuple[int, Future]] = {}  # req_id → (conn, fut)
        self._conns: Dict[Tuple[str, int], int] = {}  # addr → conn id
        self._closed = False
        self._poller = threading.Thread(
            target=self._poll_loop, name="mrt-rpc-poll", daemon=True
        )
        self._poller.start()

    # -- service side ------------------------------------------------------

    def add_service(self, name: str, obj: Any) -> None:
        """Register ``obj`` under ``name``; ``name.method`` dispatches to
        ``obj.method`` (CamelCase RPC names map via lowercase_underscore,
        mirroring the sim network's Service dispatch)."""
        self._services[name] = obj

    def client_end(self, host: str, port: int) -> TcpClientEnd:
        return TcpClientEnd(self, host, port)

    # -- internals ---------------------------------------------------------

    def _conn_for(self, addr: Tuple[str, int]) -> Optional[int]:
        # The addr→cid store must happen under the same lock section as
        # the connect itself: a failed non-blocking handshake can emit
        # EV_CLOSED before this thread stores the mapping, and
        # ``_on_closed`` (poller thread) must block on the lock until the
        # entry exists — otherwise the dead cid is cached forever and the
        # address goes permanently dark.
        with self._lock:
            cid = self._conns.get(addr)
            if cid is not None:
                return cid
            try:
                cid = self._tr.connect(*addr)
            except ConnectionError:
                return None
            self._conns[addr] = cid
        return cid

    def _call(self, addr: Tuple[str, int], svc_meth: str, args: Any) -> Future:
        fut = Future()
        cid = self._conn_for(addr)
        if cid is None:
            # Resolve asynchronously so callers may attach callbacks first.
            self.sched.call_soon(fut.resolve, None)
            return fut
        req_id = next(self._req_ids)
        with self._lock:
            self._pending[req_id] = (cid, fut)
        ok = self._tr.send(cid, codec.encode(("req", req_id, svc_meth, args)))
        if not ok:
            # The transport no longer knows this conn (torn down between
            # our lookup and the send) — drop the stale cache entry so the
            # next call reconnects instead of failing fast forever.
            with self._lock:
                self._pending.pop(req_id, None)
                if self._conns.get(addr) == cid:
                    del self._conns[addr]
            self.sched.call_soon(fut.resolve, None)
        return fut

    def _poll_loop(self) -> None:
        # MRT_DEBUG_RPC=1 traces every frame to stderr (wire-level debug).
        dbg = bool(os.environ.get("MRT_DEBUG_RPC"))
        while not self._closed:
            ev = self._tr.poll(0.2)
            if ev is None:
                continue
            conn, typ, payload = ev
            if typ == EV_FRAME:
                # One malformed frame must never kill the poller thread —
                # the node would go permanently dark.  Shape errors
                # (IndexError on msg[...]) are as fatal as decode errors.
                try:
                    msg = codec.decode(payload)
                    if dbg:
                        # Tracing must never affect delivery: a repr or
                        # stderr failure here is swallowed, not treated
                        # as a bad frame.
                        try:
                            head = f"{msg[0]} conn={conn} " + (
                                f"{msg[2]} {msg[3]!r}" if msg[0] == "req" else f"{msg[2]!r}"
                            )
                            print(f"[rpc] {head}"[:220], file=sys.stderr, flush=True)
                        except Exception:
                            pass
                    if msg[0] == "req":
                        _, req_id, svc_meth, args = msg
                        self.sched.post(self._dispatch, conn, req_id, svc_meth, args)
                    elif msg[0] == "rep":
                        _, req_id, value = msg
                        with self._lock:
                            entry = self._pending.pop(req_id, None)
                        if entry is not None:
                            self.sched.post(entry[1].resolve, value)
                except Exception as exc:
                    if dbg:
                        print(f"[rpc] bad frame dropped: {exc!r}",
                              file=sys.stderr, flush=True)
                    continue
            elif typ == EV_CLOSED:
                self._on_closed(conn)

    def _on_closed(self, conn: int) -> None:
        with self._lock:
            for addr, cid in list(self._conns.items()):
                if cid == conn:
                    del self._conns[addr]
            dead = [
                (rid, fut)
                for rid, (cid, fut) in self._pending.items()
                if cid == conn
            ]
            for rid, _ in dead:
                del self._pending[rid]
        for _, fut in dead:
            self.sched.post(fut.resolve, None)

    def _dispatch(self, conn: int, req_id: int, svc_meth: str, args: Any) -> None:
        # Runs on the scheduler loop.
        try:
            svc_name, meth = svc_meth.split(".", 1)
            obj = self._services[svc_name]
            py_name = _snake(meth)
            handler = getattr(obj, py_name)
            result = handler(args)
        except Exception:
            result = None
        if _is_gen(result):
            # Guard the coroutine body too: a handler that raises mid-wait
            # must still produce a reply (None = "RPC failed"), or the
            # caller retries the same failing request forever.
            reply_fut = self.sched.spawn(_guarded(result))
            reply_fut.add_done_callback(
                lambda f: self._reply(conn, req_id, f.value)
            )
        else:
            self._reply(conn, req_id, result)

    def _reply(self, conn: int, req_id: int, value: Any) -> None:
        try:
            self._tr.send(conn, codec.encode(("rep", req_id, value)))
        except Exception:
            pass

    def close(self) -> None:
        self._closed = True
        self._poller.join(timeout=2.0)
        self._tr.close()


def _is_gen(obj: Any) -> bool:
    import types

    return isinstance(obj, types.GeneratorType)


def _guarded(gen):
    """Run a handler coroutine, converting an escaped exception into a
    ``None`` result (labrpc's "RPC failed") instead of a lost reply."""
    try:
        result = yield from gen
    except Exception:
        result = None
    return result


def _snake(name: str) -> str:
    """``RequestVote`` → ``request_vote``; already-snake names pass through."""
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0 and (name[i - 1].islower() or name[i - 1].isdigit()):
            out.append("_")
        out.append(ch.lower())
    return "".join(out)
