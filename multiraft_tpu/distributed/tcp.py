"""RPC endpoints over the native TCP transport.

Exposes the same contract the simulated network gives the framework —
``ClientEnd.call(svc_meth, args) → Future`` with ``None`` meaning "RPC
failed" (labrpc's boolean ``ok``, reference: labrpc/labrpc.go:87-126) —
but across real processes.  One :class:`RpcNode` per process owns one
epoll transport and one :class:`IoScheduler` whose loop thread IS the
IO dispatcher: the transport's read reactor runs inline as the loop's
idle wait, and every handler and future resolution runs on that same
thread — so RaftNode/KVServer/clerk code is byte-identical between sim
and deployment, and an inbound frame reaches its handler with zero
futex handoffs (kernel wakes the loop, the loop decodes and
dispatches).  Replies write inline from the loop thread (the
transport's idle-connection fast path), so a serial RPC round trip
costs two socket wakeups total.

Frames are codec-encoded tuples:

    ("req", req_id, svc_meth, args)             caller → callee
    ("req", req_id, svc_meth, args, trace_id)   …with a request id
    ("rep", req_id, value)                      callee → caller
    ("repb", [(req_id, value), ...])            coalesced multi-reply
    ("hello", caps)                             capability negotiation
    ("busy", req_id, retry_after_s)             admission shed (negotiated)

The optional fifth element is a compact trace/request id (Dapper-style)
appended only when the caller supplies one, so untagged traffic and old
peers keep the 4-tuple wire shape.  The dispatcher stows it in
``_cur_trace`` (loop-thread breadcrumb) and tags the handler span with
it — one clerk request is followable clerk → server → engine commit
across processes by grepping one id.

Wire fast path (negotiated, old peers unaffected): a connecting node
sends ``("hello", caps)`` as its first frame and the acceptor answers
with its own.  Unknown tags fall through ``_handle_msg`` silently, so
an old peer simply never upgrades.  Once a connection's peer caps are
known, two upgrades engage: **reply coalescing** — replies are queued
per connection and flushed once per scheduler-loop iteration (the
``io_flush`` hook fires after every timer burst, before the loop
blocks), so the N replies one pump produces leave as one vectored
write, packed into a single ``repb`` frame when the peer speaks it —
and **out-of-band encoding** (``codec.encode_oob``), which ships numpy
columns and large blobs as raw buffer segments instead of copying them
through the pickle stream.  Requests are NOT queued: they may originate
off the loop thread and their latency is the caller's; only replies
(loop-thread-only by construction) coalesce.

Handlers returning generator coroutines (the wait-channel pattern,
reference: kvraft/server.go:56-96) are spawned; the reply ships when
their future resolves.  A dropped connection resolves all its pending
calls with ``None`` and the next call reconnects — the client-side
retry loops (reference: kvraft/client.go:47-71) handle the rest.

Fault injection: when ``self.chaos`` is set (see chaos.py), outbound
requests, inbound frames, and outbound replies each consult it —
dropped requests leave the caller's future unresolved (labrpc's lost
RPC; the caller's own timeout fires), delays reschedule the frame on
the loop, and ``sever`` cuts live connections mid-stream.  The hot
path pays one ``is None`` check per frame when chaos is off.
"""

from __future__ import annotations

import itertools
import os
import struct
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..sim.scheduler import Future
from ..transport import codec
from ..utils.knobs import knob_bool, knob_int, knob_str
from . import flightrec
from .admission import lane_of
from .engine_wire import busy_reply
from .native import EV_ACCEPT, EV_CLOSED, EV_FRAME, NativeTransport
from .observe import (
    Observability,
    StageClock,
    install_obs,
    is_control,
    stageclock_enabled,
)
from .profile import maybe_start_profiler
from .realtime import IoScheduler
from .sanitize import get_sanitizer
from .tail import TailStore, exemplar_from_clock, tail_enabled

__all__ = ["RpcNode", "TcpClientEnd"]

# Wire capabilities this build understands (hello payload).  "oob" =
# protocol-5 out-of-band codec segments; "repb" = coalesced multi-reply
# frames; "busy" = the peer decodes ("busy", req_id, retry_after_s)
# admission-shed frames (admission.py) — without it a shed degrades to
# a silent drop and the caller's own timeout.  Caps only ever UPGRADE
# encoding — a dropped/severed hello (chaos may eat it) just leaves the
# connection on the legacy shapes.
_WIRE_CAPS = ("oob", "repb", "busy")
# Oldest a queued reply may get before a soft flush (the after-timer
# call) sends it.  Well above a ticket-resolution burst (microseconds,
# keeps batching) and below an engine pump tick (milliseconds, must not
# wait out another one).
_FLUSH_MAX_AGE_S = 500e-6
# A blob reply at least this large flushes immediately instead of
# queueing: bulk results gate the (serial) sender's next frame, and the
# payload dwarfs any per-syscall saving batching could add.
_BULK_REPLY_BYTES = 2048

# Per-connection reply-queue cap (MRT_REPLY_Q_CAP overrides).  A client
# that stops draining its socket must not grow this node's memory: once
# a connection's queue hits the cap the OLDEST undelivered reply is
# shed (counted as rpc.reply_shed).  Shedding old over new is the right
# polarity for an RPC server — the caller of a shed reply has already
# timed out and retried, while the newest replies still have a waiting
# caller; session dedup keeps the retry exactly-once, the same
# machinery that already covers chaos-dropped replies.
_REPLY_Q_CAP = knob_int("MRT_REPLY_Q_CAP")
# Frame length prefix (big-endian u32) — must match transport.cpp's
# framing; send_parts writes raw so Python adds it per frame.
_U32 = struct.Struct(">I")


def _seg_len(seg: Any) -> int:
    return len(seg) if isinstance(seg, (bytes, bytearray)) else seg.nbytes


def _frame_header(nbytes: int) -> bytes:
    """Length prefix for one raw-written frame; the prefix is u32, so
    an oversized payload must fail loudly rather than wrap and desync
    the peer's frame parser."""
    if nbytes >= 2 ** 32:
        raise ValueError(
            f"frame payload of {nbytes} bytes overflows the u32 length "
            "prefix"
        )
    return _U32.pack(nbytes)


class TcpClientEnd:
    """ClientEnd bound to a ``(host, port)`` server address."""

    def __init__(self, node: "RpcNode", host: str, port: int) -> None:
        self._node = node
        self.addr = (host, port)

    def call(self, svc_meth: str, args: Any, trace: Optional[str] = None) -> Future:
        return self._node._call(self.addr, svc_meth, args, trace)


class RpcNode:
    """One process's RPC endpoint: optional listener + outbound calls."""

    _trace_seq = itertools.count()  # unique trace filenames per process

    def __init__(
        self,
        listen: bool = False,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self._tr = NativeTransport()
        self.host, self.port = host, 0
        if listen:
            self.port = self._tr.listen(host, port)
        self._services: Dict[str, Any] = {}
        self._handlers: Dict[str, Any] = {}  # "Svc.Meth" → bound method
        self._req_ids = itertools.count(1)
        self._lock = threading.Lock()
        # req_id → (conn, fut, svc_meth, t0, trace_id)
        self._pending: Dict[int, Tuple] = {}
        self._conns: Dict[Tuple[str, int], int] = {}  # addr → conn id
        self._accepted: set = set()  # inbound conn ids (for sever)
        self._closed = False
        # Wire fast path state.  _peer_caps: conn → negotiated caps
        # (written on the loop thread, read anywhere — dict ops are
        # atomic under the GIL).  _outq: conn → [(req_id, value), ...]
        # replies awaiting the per-iteration flush; LOOP THREAD ONLY.
        self._peer_caps: Dict[int, frozenset] = {}
        self._hello_sent: set = set()
        self._outq: Dict[int, List[Tuple[int, Any]]] = {}
        self._outq_since: float = 0.0  # when _outq went non-empty
        # Fault injection (chaos.py ChaosState); None = clean network.
        self.chaos = None
        # Admission control (admission.py install_admission); None =
        # every request dispatches.  The hot path pays one `is None`
        # check per inbound request when admission is off.
        self.admission = None
        # MRT_WIRE_LEGACY=1: operational kill-switch for the wire fast
        # path — no hello (so peers never negotiate oob/repb) and
        # replies ship immediately per frame instead of through the
        # per-iteration flush.  A/B lever and escape hatch.
        self._legacy_wire = knob_bool("MRT_WIRE_LEGACY")
        # MRT_DEBUG_RPC=1 traces every frame to stderr (wire-level debug).
        self._dbg = knob_bool("MRT_DEBUG_RPC")
        # The per-process observability plane: counters + bounded span
        # buffer, always on (a dict bump and one dict append per RPC),
        # scrapeable over the node's own socket via the "Obs" service.
        name = f"pid{os.getpid()}:{self.port}" if listen else None
        self.obs = Observability(name=name)
        self.obs.node = self
        self._cur_trace: Optional[str] = None
        # Stage-clock plane (observe.py): tagged requests carry their
        # send stamp in the rid wire element and every hop folds a
        # delta into a per-stage histogram.  MRT_STAGECLOCK=0 compiles
        # it out — no stamp, no StageClock, no folds (the A/B lever
        # for the overhead budget).
        self._stageclock = stageclock_enabled()
        self._cur_stages: Optional[StageClock] = None
        # conn → (reply-enqueue perf_counter stamp, StageClock|None)
        # pairs, strictly parallel to _outq (appended/shed/flushed/
        # closed together), so the flush fold knows how long each reply
        # coalesced and can finalize the request's tail exemplar with
        # its reply-queue age included.  LOOP THREAD ONLY, bounded by
        # _REPLY_Q_CAP like its twin.
        self._outq_stamps: Dict[int, List[Tuple[float, Optional[StageClock]]]] = {}
        # Loop-thread breadcrumb carrying the finished request's
        # StageClock from _done's reply() call into _reply's stamp
        # append (reply() is synchronous on the loop thread; a chaos
        # reply delay drops the breadcrumb, losing only that
        # exemplar).
        self._reply_st: Optional[StageClock] = None
        install_obs(self)
        # Continuous sampling profiler (profile.py): one per-process
        # daemon sampler shared by every node, default-on (MRT_PROFILE
        # gates), drained over this node's socket via Obs.profile.
        maybe_start_profiler()
        # Crash-surviving black box (flightrec.py): fixed-width event
        # records in an mmap ring, shared process-wide, env-gated
        # (MRT_FLIGHTREC_DIR).  None = disabled = zero hot-path cost
        # beyond one `is None` check per frame.
        self._frec = flightrec.get_recorder(name=name or "")
        # Tail microscope (tail.py): bounded per-request lifecycle
        # exemplar store, drained fleet-wide via Obs.tail.  Rides on
        # the stage-clock plane (no stamps → no lifecycle vector), so
        # both MRT_STAGECLOCK=0 and MRT_TAIL=0 compile it out; None =
        # off = no per-request dict, no offer.
        self.tail: Optional[TailStore] = (
            TailStore(frec=self._frec)
            if (self._stageclock and tail_enabled()) else None
        )
        # Runtime sanitizer (MRT_SANITIZE=1, sanitize.py): wraps this
        # node's and its transport's locks in order-recording proxies
        # (acyclicity asserted on every new edge) and checks the reply
        # queue's cap at every growth site.  None = off = zero cost.
        self._san = get_sanitizer()
        if self._san is not None:
            self._san.install_locks(self, {"_lock": "RpcNode._lock"})
            self._san.install_locks(
                self._tr, {"_lock": "NativeTransport._lock"}
            )
            self._san.register_metrics(self.obs.metrics)
        # MRT_TRACE_DIR=<dir>: save the span buffer on close().  Engine
        # servers additionally point their driver's tick spans at the
        # same tracer (via ``self.tracer``), so one timeline shows RPC
        # handling interleaved with device ticks.  Listening nodes only
        # — pure clients handle no RPCs and would litter the dir with
        # empty files.
        self.tracer = None
        self._trace_path = None
        trace_dir = knob_str("MRT_TRACE_DIR")
        if trace_dir and listen:
            os.makedirs(trace_dir, exist_ok=True)
            self.tracer = self.obs.tracer
            # Process-local counter, not id(self): CPython recycles ids,
            # and a recycled id would overwrite an earlier node's trace.
            seq = next(RpcNode._trace_seq)
            self._trace_path = os.path.join(
                trace_dir, f"rpc-{os.getpid()}-{seq}.json"
            )
        # Adaptive busy-poll: a serial RPC's next event lands tens of
        # µs out, so spinning that long before blocking removes the
        # futex wake from the round trip.  Pointless (and harmful —
        # the spinner starves the peer) on a single-CPU box, so the
        # default is gated on the AFFINITY-aware cpu count (a process
        # pinned to one core of a big host is a single-CPU box for
        # this purpose).  MRT_SPIN_US overrides.
        from ..utils.cpus import usable_cpus

        default_spin = 40 if usable_cpus() > 1 else 0
        self._tr.set_spin(knob_int("MRT_SPIN_US", default=default_spin))
        # Span construction is gated off the untraced hot path: only
        # tagged requests (trace_id present) or a trace-dir run build
        # span dicts; everything else is a counter bump (see _dispatch).
        self._trace_all = self.tracer is not None
        # The loop thread doubles as the transport's read reactor; it
        # owns all handler execution and future resolution.  io_flush
        # drains the reply queue once per loop iteration.
        # Loop thread named per node (listeners by port, clients by a
        # process-local seq) — profiler attribution and postmortem
        # lines stay readable when one process hosts several nodes.
        self.sched = IoScheduler(
            self._tr.poll, self._on_event, self._tr.wake,
            io_flush=self._flush_replies,
            name=(f"multiraft-loop/{self.port}" if listen
                  else f"multiraft-loop/client{next(RpcNode._trace_seq)}"),
        )

    # -- service side ------------------------------------------------------

    def add_service(self, name: str, obj: Any) -> None:
        """Register ``obj`` under ``name``; ``name.method`` dispatches to
        ``obj.method`` (CamelCase RPC names map via lowercase_underscore,
        mirroring the sim network's Service dispatch)."""
        self._services[name] = obj
        # Drop cached handlers bound to a previously registered object.
        self._handlers = {
            k: v for k, v in self._handlers.items()
            if not k.startswith(name + ".")
        }

    def client_end(self, host: str, port: int) -> TcpClientEnd:
        return TcpClientEnd(self, host, port)

    # -- internals ---------------------------------------------------------

    def _conn_for(self, addr: Tuple[str, int]) -> Optional[int]:
        # The addr→cid store must happen under the same lock section as
        # the connect itself: a failed non-blocking handshake can emit
        # EV_CLOSED before this thread stores the mapping, and
        # ``_on_closed`` (poller thread) must block on the lock until the
        # entry exists — otherwise the dead cid is cached forever and the
        # address goes permanently dark.
        with self._lock:
            cid = self._conns.get(addr)
            if cid is not None:
                return cid
            try:
                cid = self._tr.connect(*addr)
            except ConnectionError:
                return None
            self._conns[addr] = cid
            # First frame out: offer our wire caps.  The transport
            # queues it until the handshake completes, so it always
            # precedes every request on this connection.
            if not self._legacy_wire:
                # Bounded by open connections (discarded on close).
                self._hello_sent.add(cid)  # graftlint: disable=unbounded-queue
                try:
                    self._tr.send(cid, codec.encode(("hello", _WIRE_CAPS)))
                except Exception:
                    pass  # negotiation is best-effort; legacy shapes remain
        return cid

    def _call(
        self,
        addr: Tuple[str, int],
        svc_meth: str,
        args: Any,
        trace_id: Optional[str] = None,
    ) -> Future:
        fut = Future()
        m = self.obs.metrics
        m.inc("rpc.calls")
        if trace_id is not None and self._stageclock:
            # The clerk-send stamp: the rid element becomes
            # (rid, t_send).  CLOCK_MONOTONIC is machine-wide, so the
            # server can fold the wire leg directly on one box; the
            # fleet aggregator's clock alignment covers the rest.
            trace_id = (trace_id, time.perf_counter())
        chaos = self.chaos
        if chaos is not None and not is_control(svc_meth):
            act = chaos.decide_out(addr)
            if act == "drop":
                # Lost request: the future never resolves — the
                # caller's with_timeout fires and its retry loop takes
                # over (labrpc's "server never heard it").
                m.inc("rpc.chaos_out_dropped")
                return fut
            if act != "pass":  # a delay in seconds
                m.inc("rpc.chaos_out_delayed")
                self.sched.call_after(
                    act, self._send_request, addr, svc_meth, args, fut, trace_id
                )
                return fut
        self._send_request(addr, svc_meth, args, fut, trace_id)
        return fut

    def _send_request(
        self,
        addr: Tuple[str, int],
        svc_meth: str,
        args: Any,
        fut: Future,
        trace_id: Optional[str] = None,
    ) -> None:
        m = self.obs.metrics
        cid = self._conn_for(addr)
        if cid is None:
            # Resolve asynchronously so callers may attach callbacks first.
            m.inc("rpc.conn_fail")
            self.sched.call_soon(fut.resolve, None)
            return
        req_id = next(self._req_ids)
        with self._lock:
            self._pending[req_id] = (
                cid, fut, svc_meth, time.perf_counter(), trace_id
            )
        if trace_id is None:
            frame = ("req", req_id, svc_meth, args)
        else:
            frame = ("req", req_id, svc_meth, args, trace_id)
        caps = self._peer_caps.get(cid)
        if caps is not None and "oob" in caps:
            segs = codec.encode_oob(frame)
            nbytes = sum(_seg_len(s) for s in segs)
            if len(segs) > 1:
                m.inc("rpc.oob_buffers", len(segs) - 1)
                ok = self._tr.send_parts(cid, [_frame_header(nbytes), *segs])
            else:
                ok = self._tr.send(cid, segs[0])
        else:
            buf = codec.encode(frame)
            nbytes = len(buf)
            ok = self._tr.send(cid, buf)
        if not ok:
            # The transport no longer knows this conn (torn down between
            # our lookup and the send) — drop the stale cache entry so the
            # next call reconnects instead of failing fast forever.
            with self._lock:
                self._pending.pop(req_id, None)
                if self._conns.get(addr) == cid:
                    del self._conns[addr]
            m.inc("rpc.conn_fail")
            self.sched.call_soon(fut.resolve, None)
            return
        m.inc("rpc.frames_out")
        m.inc("rpc.bytes_out", nbytes)
        fr = self._frec
        if fr is not None and not is_control(svc_meth):
            fr.record(
                flightrec.RPC_OUT, a=req_id, b=nbytes, tag=svc_meth
            )

    def _on_event(self, ev: Tuple[int, int, bytes]) -> None:
        # Runs on the scheduler loop (the IO reactor thread).
        conn, typ, payload = ev
        if typ == EV_FRAME:
            # One malformed frame must never kill the loop — the node
            # would go permanently dark.  Shape errors (IndexError on
            # msg[...]) are as fatal as decode errors.
            t_read = time.perf_counter() if self._stageclock else None
            m = self.obs.metrics
            m.inc("rpc.frames_in")
            m.inc("rpc.bytes_in", len(payload))
            try:
                # cpu.wire_s: ingress decode's CPU cost (thread-CPU
                # delta around the decode — the profiling plane's
                # cost-accounting twin of the wall stage clock).
                c0 = time.thread_time() if t_read is not None else 0.0
                msg = codec.decode(payload)
                if t_read is not None:
                    m.observe("cpu.wire_s", time.thread_time() - c0)
                if self._dbg:
                    # Tracing must never affect delivery: a repr or
                    # stderr failure here is swallowed, not treated
                    # as a bad frame.
                    try:
                        head = f"{msg[0]} conn={conn} " + (
                            f"{msg[2]} {msg[3]!r}" if msg[0] == "req" else f"{msg[2]!r}"
                        )
                        print(f"[rpc] {head}"[:220], file=sys.stderr, flush=True)
                    except Exception:
                        pass
                chaos = self.chaos
                if chaos is not None and not (
                    msg[0] == "req" and is_control(msg[2])
                ):
                    # Control frames (Chaos./Obs.) are exempt: a chaos
                    # layer that can partition away its own antidote —
                    # or blind the observer watching it — wedges the run.
                    act = chaos.decide_in()
                    if act == "drop":
                        m.inc("rpc.chaos_in_dropped")
                        return
                    if act != "pass":  # delayed delivery (may reorder)
                        m.inc("rpc.chaos_in_delayed")
                        self.sched.call_after(
                            act, self._handle_msg, conn, msg
                        )
                        return
                self._handle_msg(conn, msg, t_read)
            except Exception as exc:
                m.inc("rpc.bad_frames")
                if self._dbg:
                    print(f"[rpc] bad frame dropped: {exc!r}",
                          file=sys.stderr, flush=True)
        elif typ == EV_ACCEPT:
            self._accepted.add(conn)
        elif typ == EV_CLOSED:
            self.obs.metrics.inc("rpc.conns_closed")
            self._accepted.discard(conn)
            self._on_closed(conn)

    def _handle_msg(
        self, conn: int, msg: Any, t_read: Optional[float] = None
    ) -> None:
        if msg[0] == "req":
            # 4-tuple = untagged (old wire shape); 5th element = trace id.
            trace_id = msg[4] if len(msg) > 4 else None
            self._dispatch(conn, msg[1], msg[2], msg[3], trace_id, t_read)
        elif msg[0] == "rep":
            _, req_id, value = msg
            self._complete(req_id, value)
        elif msg[0] == "repb":
            # Coalesced multi-reply (negotiated; we asked for it via
            # hello, so the peer knows we decode it).
            for req_id, value in msg[1]:
                self._complete(req_id, value)
        elif msg[0] == "busy":
            # Admission shed at the peer (negotiated "busy" cap):
            # resolve the pending call NOW with an ErrBusy reply
            # carrying the retry hint, instead of letting the caller
            # burn its full timeout on a request the server refused.
            hint = float(msg[2]) if len(msg) > 2 else 0.0
            self.obs.metrics.inc("rpc.busy_in")
            self._complete(msg[1], busy_reply(hint))
        elif msg[0] == "hello":
            # Peer capability offer.  Answer once per connection with
            # ours (the acceptor side of the handshake); the initiator
            # already sent its hello at connect time.  A legacy-wire
            # node stays silent: never answering keeps the peer on the
            # legacy shapes in BOTH directions.
            if self._legacy_wire:
                return
            self._peer_caps[conn] = frozenset(msg[1])
            if conn not in self._hello_sent:
                # Bounded by open connections (discarded on close).
                self._hello_sent.add(conn)  # graftlint: disable=unbounded-queue
                try:
                    self._tr.send(conn, codec.encode(("hello", _WIRE_CAPS)))
                except Exception:
                    pass

    def _complete(self, req_id: int, value: Any) -> None:
        with self._lock:
            entry = self._pending.pop(req_id, None)
        if entry is not None:
            _, fut, svc_meth, t0, trace_id = entry
            dt = time.perf_counter() - t0
            self.obs.metrics.observe("rpc.client.call_s", dt)
            if type(trace_id) is tuple:
                # Stage-clocked call: rid element is (rid, t_send).
                # Fold the end-to-end leg on the CLIENT's registry —
                # the number the load curve plots against the
                # server-side decomposition.
                trace_id = trace_id[0]
                self.obs.metrics.observe("stage.total_s", dt)
            fr = self._frec
            if fr is not None and not is_control(svc_meth):
                fr.record(
                    flightrec.RPC_CLIENT, a=int(dt * 1e6),
                    b=int(value is not None), tag=svc_meth,
                )
            if trace_id is not None:
                # Caller-side leg of the cross-process span pair.
                self.obs.tracer.span(
                    svc_meth, t0 * 1e6, dt * 1e6, track="rpc-out",
                    req=trace_id,
                )
            fut.resolve(value)

    def _on_closed(self, conn: int) -> None:
        # Mid-stream loss drops queued-but-unflushed replies with the
        # connection — same contract as bytes lost in the kernel buffer.
        self._outq.pop(conn, None)
        self._outq_stamps.pop(conn, None)
        self._peer_caps.pop(conn, None)
        self._hello_sent.discard(conn)
        if self.admission is not None:
            self.admission.conn_closed(conn)
        with self._lock:
            for addr, cid in list(self._conns.items()):
                if cid == conn:
                    del self._conns[addr]
            dead = [
                (rid, entry[1])
                for rid, entry in self._pending.items()
                if entry[0] == conn
            ]
            for rid, _ in dead:
                del self._pending[rid]
        if dead:
            self.obs.metrics.inc("rpc.pending_failed", len(dead))
        for _, fut in dead:
            fut.resolve(None)

    def _dispatch(
        self,
        conn: int,
        req_id: int,
        svc_meth: str,
        args: Any,
        trace_id: Optional[str] = None,
        t_read: Optional[float] = None,
    ) -> None:
        # Runs on the scheduler loop.  Admission first: a shed request
        # must cost decode + one small frame, nothing downstream of
        # here (no handler, no stage clock, no span).
        adm = self.admission
        lane = None
        if adm is not None:
            lane = lane_of(svc_meth, trace_id)
            hint = adm.admit(conn, lane)
            if hint is not None:
                tl = self.tail
                if tl is not None and type(trace_id) is tuple:
                    # Shed requests bypass the stage clocks (nothing
                    # downstream runs) but still belong in the tail
                    # story: the exemplar records the admission outcome
                    # and the two waits the request DID accrue before
                    # being refused.  Stat histograms stay untouched —
                    # sheds must not skew the stage percentiles.
                    s_rid, s_t_send = trace_id
                    now = time.perf_counter()
                    tr = t_read if t_read is not None else now
                    wire = max(0.0, tr - s_t_send)
                    disp = max(0.0, now - tr)
                    tl.offer({
                        "rid": s_rid, "outcome": "shed", "tick": -1,
                        "total_s": round(wire + disp, 6),
                        "stages": {"wire": round(wire, 6),
                                   "dispatch": round(disp, 6)},
                        "waits": {"wire": round(wire, 6),
                                  "dispatch": round(disp, 6),
                                  "pump": 0.0, "flush": 0.0},
                    })
                self._shed(conn, req_id, hint)
                return
        # Control replies bypass reply chaos (same exemption as the
        # inbound path).
        reply = self._reply if is_control(svc_meth) else self._reply_chaos
        obs = self.obs
        obs.metrics.inc("rpc.handled")
        t0 = time.perf_counter()
        c0 = time.thread_time() if self._stageclock else None

        # Stage clock: a tuple rid element is (rid, t_send) from a
        # stage-clocked caller.  Fold the wire leg (send → socket read)
        # and the dispatch leg (read → here: decode, chaos delay, loop
        # backlog), then hand the clock to the handler via the
        # loop-thread breadcrumb.
        st = None
        if type(trace_id) is tuple:
            rid, t_send = trace_id
            trace_id = rid
            if self._stageclock:
                # The lifecycle vector dict exists only when the tail
                # plane will read it — stage histograms alone need no
                # per-request allocation.
                st = StageClock(
                    rid, t_send,
                    vec={} if self.tail is not None else None,
                )
                st.fold(
                    obs.metrics, "wire",
                    t_read if t_read is not None else t0,
                )
                st.fold(obs.metrics, "dispatch", t0)

        # Span dicts are only built when someone will read them: a
        # tagged request (cross-process follow-the-id) or a trace-dir
        # run.  The untraced hot path is a counter bump + one observe.
        want_span = trace_id is not None or self._trace_all

        frec = self._frec

        def _done(conn_, req_id_, value):
            ca = time.thread_time() if c0 is not None else 0.0
            if adm is not None:
                # Frees this dispatch's slot in the bounded
                # per-connection queue (pairs with the admit above).
                adm.release(conn_, lane)
            dt = time.perf_counter() - t0
            obs.metrics.observe("rpc.handle_s", dt)
            if st is not None:
                # Engine handlers folded handler/engine themselves and
                # this closes the ack leg (commit → reply enqueue);
                # plain handlers close their whole body as handler.
                st.fold(obs.metrics, "ack" if st.engine else "handler")
                if st.vec is not None:
                    # Ambient context rides on the exemplar: what the
                    # process looked like the moment this request
                    # finished (the exemplar is finalized — and the
                    # reply-queue age folded — at flush).
                    st.ambient = self._tail_ambient(conn_)
            if frec is not None and not is_control(svc_meth):
                frec.record(
                    flightrec.RPC_HANDLE, a=int(dt * 1e6),
                    b=int(value is not None), tag=svc_meth,
                )
            if want_span:
                sargs: Dict[str, Any] = {
                    "outcome": "ok" if value is not None else "none"
                }
                if trace_id is not None:
                    sargs["req"] = trace_id
                obs.tracer.span(
                    svc_meth, t0 * 1e6, dt * 1e6, track="rpc", **sargs
                )
            if st is not None and st.vec is not None:
                self._reply_st = st
            reply(conn_, req_id_, value)
            self._reply_st = None
            if c0 is not None:
                # cpu.ack_s: completion bookkeeping + reply enqueue
                # (the flush write itself lands in cpu.flush_s).
                obs.metrics.observe("cpu.ack_s", time.thread_time() - ca)

        try:
            handler = self._handlers.get(svc_meth)
            if handler is None:
                svc_name, meth = svc_meth.split(".", 1)
                obj = self._services[svc_name]
                handler = getattr(obj, _snake(meth))
                self._handlers[svc_meth] = handler
            # Loop-thread-only breadcrumbs: _cur_conn lets a handler
            # exempt the connection its own request rode in on
            # (Chaos.sever must not cut the control channel out from
            # under its reply); _cur_trace carries the request id so
            # service code can tag downstream spans with it.
            self._cur_conn = conn
            self._cur_trace = trace_id
            self._cur_stages = st
            if c0 is not None:
                # cpu.dispatch_s: admission + stage setup + handler
                # lookup; cpu.handler_s: the synchronous handler body
                # (generator handlers count creation here and fold
                # their own submit cost — see engine_server.command).
                ch = time.thread_time()
                obs.metrics.observe("cpu.dispatch_s", ch - c0)
                result = handler(args)
                obs.metrics.observe(
                    "cpu.handler_s", time.thread_time() - ch
                )
            else:
                result = handler(args)
        except Exception:
            obs.metrics.inc("rpc.handler_errors")
            result = None
        if _is_gen(result):
            # Guard the coroutine body too: a handler that raises mid-wait
            # must still produce a reply (None = "RPC failed"), or the
            # caller retries the same failing request forever.
            reply_fut = self.sched.spawn(_guarded(result))
            reply_fut.add_done_callback(
                lambda f: _done(conn, req_id, f.value)
            )
        else:
            _done(conn, req_id, result)

    def _tail_ambient(self, conn: int) -> Dict[str, Any]:
        """Completion-time context for a tail exemplar (loop thread,
        cheap attribute reads only): the queue depths and degradation
        state a human asks about first when staring at an outlier —
        was the process deep in replies, shedding, browned out, or
        inside a chaos window when this request finished?"""
        amb: Dict[str, Any] = {"replyq": len(self._outq.get(conn, ()))}
        adm = self.admission
        if adm is not None:
            amb["inflight"] = adm.inflight_total()
            amb["adm_level"] = adm.level
        ow = getattr(self, "overload_watch", None)
        if ow is not None:
            amb["brownout"] = ow.brownout.state
        ch = self.chaos
        if ch is not None:
            active = [
                k for k in ("all_in", "all_out", "reply")
                if getattr(ch, k, None) is not None
            ]
            if ch.peer_out:
                active.append("peer_out")
            if active:
                amb["chaos"] = active
        return amb

    def _shed(self, conn: int, req_id: int, retry_after_s: float) -> None:
        """Admission refused the request.  A busy-capable peer gets an
        immediate ``("busy", ...)`` frame — shed replies must not wait
        out a coalescing flush; their whole point is a fast hint.  A
        legacy peer (no hello, or MRT_WIRE_LEGACY) gets nothing: the
        unknown tag would fall through its ``_handle_msg`` anyway, so
        the shed degrades to a silent drop and the caller's ordinary
        timeout + backoff — the pre-round-8 overload behavior."""
        m = self.obs.metrics
        m.inc("rpc.shed")
        caps = self._peer_caps.get(conn)
        if caps is None or "busy" not in caps:
            return
        try:
            buf = codec.encode(("busy", req_id, retry_after_s))
            self._tr.send(conn, buf)
            m.inc("rpc.frames_out")
            m.inc("rpc.bytes_out", len(buf))
        except Exception:
            m.inc("rpc.reply_send_fail")

    def _reply_chaos(self, conn: int, req_id: int, value: Any) -> None:
        """Reply path with fault injection: labrpc's dropped-reply case
        — the handler RAN (the op may have applied), the caller never
        learns.  Only session dedup keeps the ensuing retry
        exactly-once, which is exactly the bug class this exercises."""
        chaos = self.chaos
        if chaos is not None:
            act = chaos.decide_reply()
            if act == "drop":
                self.obs.metrics.inc("rpc.replies_dropped")
                return
            if act != "pass":
                self.obs.metrics.inc("rpc.replies_delayed")
                self.sched.call_after(act, self._reply, conn, req_id, value)
                return
        self._reply(conn, req_id, value)

    def _reply(self, conn: int, req_id: int, value: Any) -> None:
        # Queue for the end-of-iteration flush.  Replies are produced
        # on the loop thread by construction (dispatch, future
        # callbacks, chaos-delay timers all run there), so every reply
        # from one timer burst coalesces into one vectored write per
        # connection; a non-loop caller (defensive) sends immediately.
        if not self._legacy_wire and self.sched.on_loop_thread():
            if not self._outq:
                self._outq_since = time.perf_counter()
            q = self._outq.setdefault(conn, [])
            if len(q) >= _REPLY_Q_CAP:
                q.pop(0)  # shed-oldest: that caller already retried
                self.obs.metrics.inc("rpc.reply_shed")
            q.append((req_id, value))
            if self._stageclock:
                # Parallel enqueue stamp for the flush-stage fold;
                # shed/flushed/closed in lockstep with q above, so the
                # reply cap bounds this list too.
                sq = self._outq_stamps.setdefault(conn, [])
                if len(sq) >= len(q):
                    sq.pop(0)  # twin of the shed above
                sq.append((time.perf_counter(), self._reply_st))  # graftlint: disable=unbounded-queue
            if self._san is not None:
                self._san.guard_queue("rpc.outq", len(q), _REPLY_Q_CAP)
            # Bulk blob replies (a firehose frame's results) gate a
            # serial client's next frame: flush now — mid-tick, like
            # the legacy inline send — instead of riding out the rest
            # of a pump tick.  Anything already queued coalesces in.
            if (
                isinstance(value, (bytes, bytearray, memoryview))
                and len(value) >= _BULK_REPLY_BYTES
            ):
                self._flush_replies()
            return
        self._reply_now(conn, req_id, value)

    def _reply_now(self, conn: int, req_id: int, value: Any) -> None:
        try:
            buf = codec.encode(("rep", req_id, value))
            self._tr.send(conn, buf)
            m = self.obs.metrics
            m.inc("rpc.frames_out")
            m.inc("rpc.bytes_out", len(buf))
        except Exception:
            self.obs.metrics.inc("rpc.reply_send_fail")

    def _flush_replies(self, force: bool = True) -> None:
        """Drain the per-connection reply queues.  The scheduler calls
        this forced right before it blocks in the poller (no reply ever
        waits out an idle sleep) and soft (``force=False``) after every
        timer callback.  The soft call flushes only once the oldest
        queued reply has aged past ``_FLUSH_MAX_AGE_S``: back-to-back
        cheap callbacks (a pump burst resolving tickets) keep batching,
        but a reply never waits out more than ~one engine tick when the
        timer heap is saturated and the before-poll flush would starve.
        Each connection's batch leaves as ONE vectored write: a single
        ``repb`` frame when the peer negotiated it, else its frames
        back to back in one syscall."""
        q = self._outq
        if not q:
            return
        if not force and (
            time.perf_counter() - self._outq_since < _FLUSH_MAX_AGE_S
        ):
            return
        self._outq = {}
        stamps_by_conn, self._outq_stamps = self._outq_stamps, {}
        m = self.obs.metrics
        cf = time.thread_time() if self._stageclock else None
        if stamps_by_conn:
            # Flush-stage fold: how long each reply coalesced between
            # enqueue and this vectored write (stat-only; folded even
            # for a failed send — the reply left the queue either way).
            # Stamps carrying a StageClock fold through it instead, so
            # the flush leg lands in the lifecycle vector too and the
            # completed exemplar — total now closed t0→flush — goes to
            # the tail store.
            t_flush = time.perf_counter()
            tl = self.tail
            for stamps in stamps_by_conn.values():
                for ts, st in stamps:
                    if st is None:
                        m.observe("stage.flush_s", t_flush - ts)
                        continue
                    st.fold(m, "flush", t_flush)
                    if tl is not None:
                        # Deferred build: the store decides from the
                        # total alone whether this completion is kept;
                        # dropped ones (saturation past the SLO cap)
                        # never materialize their exemplar dicts.
                        tl.offer_deferred(
                            max(0.0, st.last - st.t0),
                            lambda st=st: exemplar_from_clock(
                                st, ambient=st.ambient
                            ),
                        )
        for conn, pairs in q.items():
            caps = self._peer_caps.get(conn)
            oob = caps is not None and "oob" in caps
            try:
                if caps is not None and "repb" in caps and len(pairs) > 1:
                    frames: List[Tuple] = [("repb", pairs)]
                else:
                    frames = [("rep", rid, val) for rid, val in pairs]
                parts: List[Any] = []
                nbytes = 0
                for fr in frames:
                    segs = codec.encode_oob(fr) if oob else [codec.encode(fr)]
                    if len(segs) > 1:
                        m.inc("rpc.oob_buffers", len(segs) - 1)
                    n = sum(_seg_len(s) for s in segs)
                    parts.append(_frame_header(n))
                    parts.extend(segs)
                    nbytes += n
                if len(parts) == 2 and isinstance(parts[1], bytes):
                    # Lone in-band reply: the transport's plain send
                    # frames and writes header‖body in one shot, without
                    # the vectored path's per-part pointer marshalling.
                    ok = self._tr.send(conn, parts[1])
                else:
                    ok = self._tr.send_parts(conn, parts)
                if not ok:
                    m.inc("rpc.reply_send_fail", len(pairs))
                    continue
                m.inc("rpc.frames_out", len(frames))
                m.inc("rpc.bytes_out", nbytes)
                m.inc("rpc.flushes")
                # Counter twin of the sample: flush_replies / flushes
                # is the exact mean coalescing factor (samples only
                # surface percentiles in snapshots).
                m.inc("rpc.flush_replies", len(pairs))
                m.observe("rpc.frames_per_flush", float(len(pairs)))
            except Exception:
                m.inc("rpc.reply_send_fail", len(pairs))
        if cf is not None:
            # cpu.flush_s: reply encode + vectored write for the whole
            # batch (one segment per flush, not per reply).
            m.observe("cpu.flush_s", time.thread_time() - cf)

    def sever(
        self,
        addr: Optional[Tuple[str, int]] = None,
        exclude: Optional[int] = None,
    ) -> int:
        """Forcibly close live connections (chaos: mid-stream
        connection loss).  ``addr`` limits the cut to that outbound
        edge; ``None`` cuts every connection this node knows about —
        outbound and accepted, except ``exclude`` (the control
        connection a Chaos.sever request arrived on — cutting it would
        strand the reply).  Local pending calls on the cut connections
        fail immediately (resolve ``None``); the peer sees EV_CLOSED
        and fails its own side.  Returns the number cut."""
        with self._lock:
            if addr is not None:
                cid = self._conns.get(addr)
                cids = [cid] if cid is not None else []
            else:
                cids = list(self._conns.values()) + list(self._accepted)
        cids = [c for c in cids if c != exclude]
        for cid in cids:
            self._tr.close_conn(cid)
            self._accepted.discard(cid)
            # close_conn is locally silent (no EV_CLOSED to ourselves):
            # fail the pending calls and drop the addr cache now, the
            # way a remote reset would.
            self._on_closed(cid)
        return len(cids)

    def close(self) -> None:
        """Stop the scheduler loop (joining the reactor thread), then
        tear down the transport.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.sched.stop()
        self._tr.close()
        if self._frec is not None:
            # Clean-shutdown marker: its absence as the ring's last
            # record is how the postmortem doctor tells an unclean
            # death from an orderly exit.  The shared recorder itself
            # stays open (other nodes in this process still write).
            self._frec.record(flightrec.NODE_CLOSE, tag=self.obs.name)
        if self.tracer is not None and self._trace_path:
            try:
                self.tracer.save(self._trace_path)
            except Exception:
                pass  # tracing must never fail a shutdown


def _is_gen(obj: Any) -> bool:
    import types

    return isinstance(obj, types.GeneratorType)


def _guarded(gen):
    """Run a handler coroutine, converting an escaped exception into a
    ``None`` result (labrpc's "RPC failed") instead of a lost reply."""
    try:
        result = yield from gen
    except Exception:
        result = None
    return result


def _snake(name: str) -> str:
    """``RequestVote`` → ``request_vote``; already-snake names pass through."""
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0 and (name[i - 1].islower() or name[i - 1].isdigit()):
            out.append("_")
        out.append(ch.lower())
    return "".join(out)
