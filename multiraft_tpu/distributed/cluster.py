"""Multi-process cluster deployment.

The reference never leaves the simulated network — "serving" means test
harnesses (SURVEY §0).  This module is the real thing: each Raft/KV
server runs in its own OS process on a ``RealtimeScheduler`` + TCP
``RpcNode`` with a crash-atomic ``DiskPersister``; clients talk to the
cluster through the unmodified :class:`~multiraft_tpu.services.kvraft.Clerk`
over :class:`TcpClientEnd`\\ s.

Crash/restart testing here is *literal*: ``kill -9`` the process, start
a new one on the same data directory, and Raft recovers from disk — the
deployment analog of the sim fixture's Persister-copy rebirth
(reference: raft/config.go:113-142).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence

from ..sim.scheduler import TIMEOUT
from .disk import DiskPersister
from .realtime import RealtimeScheduler
from .tcp import RpcNode

__all__ = [
    "serve_kv",
    "serve_ctrler",
    "serve_shardkv",
    "EngineProcessCluster",
    "EngineFleetCluster",
    "BlockingEngineClerk",
    "BlockingFleetClerk",
    "KVProcessCluster",
    "ShardKVProcessCluster",
    "BlockingClerk",
    "BlockingShardClerk",
]


def _addr_end(node: RpcNode, name: str):
    """Resolve a ``"host:port"`` group-server name to a TcpClientEnd —
    the deployment's ``make_end`` (the sim passes opaque endnames;
    here the controller's group tables carry real addresses)."""
    host, port = name.rsplit(":", 1)
    return node.client_end(host, int(port))


def _launch_server(spec: dict, label: Any) -> subprocess.Popen:
    """Spawn one server subprocess (shared by both cluster drivers):
    env setup, optional MRT_SERVER_LOG_DIR stderr capture, Popen."""
    import json

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")  # server procs never need a chip
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    log_dir = os.environ.get("MRT_SERVER_LOG_DIR")
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        stderr = open(os.path.join(log_dir, f"server-{label}.err"), "a")
    else:
        stderr = subprocess.DEVNULL
    try:
        return subprocess.Popen(
            [sys.executable, "-m", "multiraft_tpu.distributed.cluster",
             json.dumps(spec)],
            stdout=subprocess.PIPE, stderr=stderr, env=env, text=True,
        )
    finally:
        if log_dir:
            stderr.close()


def _check_ready(
    proc: subprocess.Popen, label: Any, timeout: float = 120.0
) -> None:
    """Block until the child prints its readiness line, bounded by
    ``timeout`` — a child that starts but hangs before printing (e.g.
    stuck in jax/native-build import) must not wedge the launcher
    forever.  On timeout the child is killed and the failure raised.
    Callers must register ``proc`` for reaping BEFORE calling this — a
    child that fails the check is still a live process."""
    import select
    import time as _time

    deadline = _time.monotonic() + timeout
    buf = ""
    while True:
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            proc.kill()
            raise RuntimeError(
                f"server {label} produced no readiness line within "
                f"{timeout:.0f}s; killed"
            )
        ready, _, _ = select.select([proc.stdout], [], [], remaining)
        if not ready:
            continue
        chunk = os.read(proc.stdout.fileno(), 4096).decode(
            "utf-8", "replace"
        )
        if chunk == "":
            raise RuntimeError(f"server {label} failed to start: {buf!r}")
        buf += chunk
        if "\n" in buf:
            line = buf.split("\n", 1)[0]
            if not line.startswith("ready"):
                raise RuntimeError(
                    f"server {label} failed to start: {line!r}"
                )
            return


def serve_kv(
    me: int,
    ports: Sequence[int],
    data_dir: str,
    host: str = "127.0.0.1",
    maxraftstate: int = -1,
) -> RpcNode:
    """Bring up one KV server process component: RealtimeScheduler +
    listening RpcNode + KVServer/RaftNode on a DiskPersister.  Returns
    the RpcNode (caller keeps the process alive)."""
    from ..services.kvraft import KVServer

    node = RpcNode(listen=True, host=host, port=ports[me])
    sched = node.sched
    ends = [node.client_end(host, p) for p in ports]
    persister = DiskPersister(os.path.join(data_dir, f"server-{me}"))

    # KVServer mutates consensus state from RPC handlers; construct it on
    # the loop thread so initialization obeys the single-mutator rule.
    srv = sched.run_call(
        lambda: KVServer(
            sched, ends, me, persister, maxraftstate=maxraftstate, seed=me
        )
    )
    node.add_service("KVServer", srv)
    node.add_service("Raft", srv.rf)
    if os.environ.get("MRT_DEBUG"):
        def _dump() -> None:
            print(f"[{time.monotonic():.2f}] {srv.rf!r}", file=sys.stderr, flush=True)
            sched.call_after(1.0, _dump)
        sched.call_soon(_dump)
    return node


def serve_ctrler(
    me: int, ports: Sequence[int], data_dir: str, host: str = "127.0.0.1"
) -> RpcNode:
    """One shard-controller replica process (the config RSM,
    reference: shardctrler/server.go:164-182 — over real sockets)."""
    from ..services.shardctrler import ShardCtrler

    node = RpcNode(listen=True, host=host, port=ports[me])
    sched = node.sched
    ends = [node.client_end(host, p) for p in ports]
    persister = DiskPersister(os.path.join(data_dir, f"ctrler-{me}"))
    srv = sched.run_call(
        lambda: ShardCtrler(sched, ends, me, persister, seed=1000 + me)
    )
    node.add_service("ShardCtrler", srv)
    node.add_service("Raft", srv.rf)
    return node


def serve_shardkv(
    me: int,
    gid: int,
    group_ports: Sequence[int],
    ctrler_ports: Sequence[int],
    data_dir: str,
    host: str = "127.0.0.1",
    maxraftstate: int = -1,
) -> RpcNode:
    """One replica of one shard group (the full migration-capable
    server, reference: shardkv/server.go:77-98 wiring — raft +
    controller clerk + make_end, here resolving "host:port" names to
    TCP ends so groups pull shards from each other across processes)."""
    from ..services.shardkv import ShardKVServer

    node = RpcNode(listen=True, host=host, port=group_ports[me])
    sched = node.sched
    ends = [node.client_end(host, p) for p in group_ports]
    ctrler_ends = [node.client_end(host, p) for p in ctrler_ports]
    persister = DiskPersister(os.path.join(data_dir, f"g{gid}-{me}"))
    srv = sched.run_call(
        lambda: ShardKVServer(
            sched, ends, me, persister, gid, ctrler_ends,
            lambda name: _addr_end(node, name),
            maxraftstate=maxraftstate, seed=gid * 100 + me,
        )
    )
    node.add_service("ShardKV", srv)
    node.add_service("Raft", srv.rf)
    return node


def _pin_platform(spec: dict) -> None:
    """Engine server processes import jax; pin the backend BEFORE any
    backend init.  The env var alone cannot steer it when the TPU
    plugin registers itself at interpreter start (it sets
    jax_platforms programmatically) — tests pin "cpu"; production
    passes "tpu" to own the chip."""
    plat = spec.get("platform", "cpu")
    import jax

    try:
        jax.config.update("jax_platforms", plat)
    except Exception as exc:
        # A chip-owning server silently falling back to CPU would be
        # orders of magnitude slower with no error anywhere: fatal for
        # tpu; loud for cpu (tests would still pass, just slower).
        if plat != "cpu":
            raise RuntimeError(
                f"engine server could not pin platform {plat!r}: {exc!r}"
            )
        print(
            f"warning: could not pin jax platform to cpu: {exc!r}",
            file=sys.stderr, flush=True,
        )


def _server_main() -> None:  # pragma: no cover - subprocess entry
    import json

    spec = json.loads(sys.argv[2])
    kind = spec.get("kind", "kv")
    if kind == "kv":
        node = serve_kv(
            me=spec["me"],
            ports=spec["ports"],
            data_dir=spec["data_dir"],
            maxraftstate=spec.get("maxraftstate", -1),
        )
    elif kind == "ctrler":
        node = serve_ctrler(spec["me"], spec["ports"], spec["data_dir"])
    elif kind == "shardkv":
        node = serve_shardkv(
            me=spec["me"],
            gid=spec["gid"],
            group_ports=spec["ports"],
            ctrler_ports=spec["ctrler_ports"],
            data_dir=spec["data_dir"],
            maxraftstate=spec.get("maxraftstate", -1),
        )
    elif kind == "engine_kv":
        _pin_platform(spec)
        from .engine_server import serve_engine_kv

        node = serve_engine_kv(
            port=spec["ports"][0],
            G=spec.get("groups", 64),
            seed=spec.get("seed", 0),
            data_dir=spec.get("data_dir"),
            checkpoint_every_s=spec.get("checkpoint_every_s", 30.0),
            mesh_devices=spec.get("mesh_devices", 0),
        )
    elif kind == "engine_shardkv":
        _pin_platform(spec)
        from .engine_server import serve_engine_shardkv

        node = serve_engine_shardkv(
            port=spec["ports"][0],
            G=spec.get("groups", 4),
            seed=spec.get("seed", 0),
            join_gids=spec.get("join_gids"),
            data_dir=spec.get("data_dir"),
            checkpoint_every_s=spec.get("checkpoint_every_s", 30.0),
            mesh_devices=spec.get("mesh_devices", 0),
        )
    elif kind == "engine_fleet":
        _pin_platform(spec)
        from .engine_server import serve_engine_shardkv

        node = serve_engine_shardkv(
            port=spec["ports"][0],
            seed=spec.get("seed", 0),
            gids=spec["gids"],
            # JSON round trip stringifies gid keys and listifies tuples.
            peer_addrs={
                int(g): (a[0], int(a[1]))
                for g, a in spec["peer_addrs"].items()
            },
            data_dir=spec.get("data_dir"),
            checkpoint_every_s=spec.get("checkpoint_every_s", 30.0),
            mesh_devices=spec.get("mesh_devices", 0),
        )
    elif kind == "split_kv":
        _pin_platform(spec)
        from .split_server import serve_split_kv

        node = serve_split_kv(
            port=spec["ports"][spec["me"]],
            me=spec["me"],
            # JSON stringifies the group keys and listifies slot lists.
            owners={int(g): list(o) for g, o in spec["owners"].items()},
            peer_addrs={
                i: (spec.get("host", "127.0.0.1"), p)
                for i, p in enumerate(spec["ports"])
            },
            G=spec.get("groups", 8),
            host=spec.get("host", "127.0.0.1"),
            seed=spec.get("seed", 0),
            delay_elections=spec.get("delay_elections", 0),
            data_dir=spec.get("data_dir"),
            snapshot_every_s=spec.get("snapshot_every_s", 30.0),
        )
    elif kind == "split_shardkv":
        _pin_platform(spec)
        from .split_shard_server import serve_split_shardkv

        node = serve_split_shardkv(
            port=spec["ports"][spec["me"]],
            me=spec["me"],
            # JSON stringifies the group keys and listifies slot lists.
            owners={int(g): list(o) for g, o in spec["owners"].items()},
            peer_addrs={
                i: (spec.get("host", "127.0.0.1"), p)
                for i, p in enumerate(spec["ports"])
            },
            G=spec.get("groups", 3),
            host=spec.get("host", "127.0.0.1"),
            seed=spec.get("seed", 0),
            delay_elections=spec.get("delay_elections", 0),
        )
    else:
        raise ValueError(f"unknown server kind {kind!r}")
    print(f"ready {node.port}", flush=True)
    while True:
        time.sleep(3600)


class _BlockingClerkBase:
    """Synchronous client facade: drives a generator-coroutine clerk on
    a RealtimeScheduler and blocks the calling thread for the result.
    Subclasses construct ``self._clerk`` (anything with get/put/append
    generator methods)."""

    sched: RealtimeScheduler
    node: RpcNode
    _clerk: Any

    def _run(self, gen, timeout: float) -> Any:
        fut = self.sched.spawn(gen)
        value = self.sched.wait(fut, timeout)
        if value is TIMEOUT:
            # Cancel the abandoned retry loop (resolving the spawn future
            # halts the coroutine at its next step) — otherwise it would
            # spin forever and race the caller's next command on this
            # single-outstanding-op clerk.
            self.sched.post(fut.resolve, TIMEOUT)
            raise TimeoutError("cluster did not answer in time")
        return value

    def get(self, key: str, timeout: float = 30.0) -> str:
        return self._run(self._clerk.get(key), timeout)

    def put(self, key: str, value: str, timeout: float = 30.0) -> None:
        self._run(self._clerk.put(key, value), timeout)

    def append(self, key: str, value: str, timeout: float = 30.0) -> None:
        self._run(self._clerk.append(key, value), timeout)

    def close(self) -> None:
        """Close the RPC node (its scheduler loop stops with it)."""
        self.node.close()


class BlockingClerk(_BlockingClerkBase):
    """Blocking client of a :class:`KVProcessCluster`."""

    def __init__(
        self, ports: Sequence[int], host: str = "127.0.0.1",
        node: Optional[RpcNode] = None,
    ) -> None:
        from ..services.kvraft import Clerk

        self.node = node or RpcNode()
        self.sched = self.node.sched
        ends = [self.node.client_end(host, p) for p in ports]
        self._clerk = Clerk(self.sched, ends)


class BlockingShardClerk(_BlockingClerkBase):
    """Blocking client of a sharded process cluster: drives the
    unmodified :class:`~multiraft_tpu.services.shardkv.ShardClerk`
    (config-tracking, per-group retry) over TCP ends."""

    def __init__(
        self, ctrler_ports: Sequence[int], host: str = "127.0.0.1"
    ) -> None:
        from ..services.shardkv import ShardClerk

        self.node = RpcNode()
        self.sched = self.node.sched
        ctrler_ends = [self.node.client_end(host, p) for p in ctrler_ports]
        self._clerk = ShardClerk(
            self.sched, ctrler_ends, lambda name: _addr_end(self.node, name)
        )


class KVProcessCluster:
    """Launch and manage ``n`` KV server OS processes (test/ops driver)."""

    def __init__(
        self,
        n: int,
        data_dir: str,
        host: str = "127.0.0.1",
        maxraftstate: int = -1,
    ) -> None:
        self.n = n
        self.host = host
        self.data_dir = data_dir
        self.maxraftstate = maxraftstate
        # Reserve n distinct ephemeral ports by bind/close.  There is a
        # small window where another process could grab one before the
        # child listens — in that case start() raises and the caller
        # builds a fresh cluster; acceptable for a test/ops driver.
        self.ports: List[int] = _reserve_ports(n, host)
        self.procs: List[Optional[subprocess.Popen]] = [None] * n

    def start(self, i: int) -> None:
        assert self.procs[i] is None or self.procs[i].poll() is not None
        spec = {
            "me": i,
            "ports": self.ports,
            "data_dir": self.data_dir,
            "maxraftstate": self.maxraftstate,
        }
        # Register before the readiness check so shutdown() can reap a
        # half-started server even when the check raises.
        self.procs[i] = _launch_server(spec, i)
        _check_ready(self.procs[i], i)

    def start_all(self) -> None:
        for i in range(self.n):
            self.start(i)

    def kill(self, i: int) -> None:
        """SIGKILL — a real crash; durable state must carry the restart."""
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()
        self.procs[i] = None

    def clerk(self) -> BlockingClerk:
        return BlockingClerk(self.ports, host=self.host)

    def shutdown(self) -> None:
        for i in range(self.n):
            self.kill(i)


class EngineProcessCluster:
    """One chip-owning engine server process (kind ``engine_kv`` or
    ``engine_shardkv``) + blocking clerks — the engine-backed network
    cluster (SURVEY §2.2's sidecar story, step 1: a single front door
    coalescing clerk RPCs into device ticks).  Unlike the per-replica
    ``KVProcessCluster``, consensus replication happens ON CHIP across
    the engine's (G, P) lanes; the network carries client traffic only.
    """

    def __init__(
        self,
        kind: str = "engine_kv",
        groups: int = 64,
        host: str = "127.0.0.1",
        seed: int = 0,
        join_gids: Optional[List[int]] = None,
        data_dir: Optional[str] = None,
        checkpoint_every_s: float = 30.0,
        mesh_devices: int = 0,
    ) -> None:
        assert kind in ("engine_kv", "engine_shardkv")
        self.kind = kind
        self.host = host
        self.spec = {
            "kind": kind,
            "ports": _reserve_ports(1, host),
            "groups": groups,
            "seed": seed,
            "platform": os.environ.get("MRT_ENGINE_PLATFORM", "cpu"),
        }
        if join_gids is not None:
            self.spec["join_gids"] = list(join_gids)
        if data_dir is not None:
            # Durable mode: checkpoint + WAL under data_dir; kill() +
            # start() then recovers every acknowledged op.
            self.spec["data_dir"] = data_dir
            self.spec["checkpoint_every_s"] = checkpoint_every_s
        if mesh_devices:
            # Multi-chip mode: the server runs the shard_map tick over
            # this many local devices (G must divide evenly).
            self.spec["mesh_devices"] = mesh_devices
        self.proc: Optional[subprocess.Popen] = None

    @property
    def port(self) -> int:
        return self.spec["ports"][0]

    def start(self) -> None:
        assert self.proc is None or self.proc.poll() is not None
        self.proc = _launch_server(self.spec, "engine")
        _check_ready(self.proc, "engine", timeout=300.0)

    def kill(self) -> None:
        """SIGKILL the server process (literal crash; restart with
        :meth:`start` — durable mode recovers from data_dir)."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()

    def clerk(self) -> "BlockingEngineClerk":
        return BlockingEngineClerk(
            self.port, host=self.host,
            service="EngineKV" if self.kind == "engine_kv"
            else "EngineShardKV",
        )

    def shutdown(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self.proc = None


class SplitProcessCluster:
    """Several engine processes SHARING each replica group's peer slots
    (engine/split.py + distributed/split_server.py) — the deployment
    where one process's death loses only its owned peer slots, and any
    group whose surviving slots hold a quorum keeps serving with every
    acknowledged write intact (no WAL, no disk: replication is the
    durability).  Contrast :class:`EngineFleetCluster`, which
    partitions whole gids per process.

    ``owners[g][p]`` = process index owning peer slot ``p`` of group
    ``g`` (same map for every process).  ``delay_elections[i]`` biases
    process ``i``'s first election deadlines later — tests use it to
    park initial leadership on a chosen process."""

    def __init__(
        self,
        owners: Dict[int, Sequence[int]],
        n_procs: int,
        groups: int = 8,
        host: str = "127.0.0.1",
        seed: int = 0,
        delay_elections: Optional[Sequence[int]] = None,
        data_dir: Optional[str] = None,
        snapshot_every_s: float = 30.0,
    ) -> None:
        from . import engine_server  # noqa: F401  (codec registration)
        from . import split_server  # noqa: F401

        self.host = host
        self.ports = _reserve_ports(n_procs, host)
        self.specs = []
        for i in range(n_procs):
            spec = {
                "kind": "split_kv",
                "me": i,
                "host": host,
                "ports": self.ports,
                "owners": {str(g): list(o) for g, o in owners.items()},
                "groups": groups,
                "seed": seed + i,
                "delay_elections": (
                    int(delay_elections[i]) if delay_elections else 0
                ),
                "platform": os.environ.get("MRT_ENGINE_PLATFORM", "cpu"),
            }
            if data_dir is not None:
                # Durable peer identity (SplitPersistence): kill(i) +
                # start(i) REJOINS from the persisted term/vote/log.
                spec["data_dir"] = os.path.join(data_dir, f"proc-{i}")
                spec["snapshot_every_s"] = snapshot_every_s
            self.specs.append(spec)
        self.durable = data_dir is not None
        self._killed: set = set()
        self.procs: List[Optional[subprocess.Popen]] = [None] * n_procs

    def start(self, i: int) -> None:
        assert self.procs[i] is None or self.procs[i].poll() is not None
        # Restarting a previously-killed member is only safe in durable
        # mode — a fresh-state restart under an old peer identity can
        # double-vote (engine/split.py crash-model note).
        assert self.durable or i not in self._killed, (
            f"process {i} was killed; a non-durable split peer must "
            "stay dead (pass data_dir= for safe rejoin)"
        )
        self.procs[i] = _launch_server(self.specs[i], f"split-{i}")
        _check_ready(self.procs[i], f"split-{i}", timeout=300.0)

    def start_all(self) -> None:
        # Same double-vote guard as start(): relaunching a previously
        # killed member with fresh state is only safe in durable mode.
        assert self.durable or not self._killed, (
            f"processes {sorted(self._killed)} were killed; a "
            "non-durable split peer must stay dead (pass data_dir= "
            "for safe rejoin)"
        )
        for i, spec in enumerate(self.specs):
            self.procs[i] = _launch_server(spec, f"split-{i}")
        for i, p in enumerate(self.procs):
            _check_ready(p, f"split-{i}", timeout=300.0)

    def kill(self, i: int) -> None:
        """SIGKILL process ``i``.  Durable mode: :meth:`start` rejoins
        it from its data_dir.  Non-durable: it must stay dead — a split
        peer restarted with fresh state can double-vote (see
        engine/split.py's crash-model note)."""
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()
        self.procs[i] = None
        self._killed.add(i)

    def clerk(self) -> "BlockingSplitClerk":
        return BlockingSplitClerk(self.ports, host=self.host)

    def shutdown(self) -> None:
        for i in range(len(self.procs)):
            self.kill(i)


class BlockingSplitClerk(_BlockingClerkBase):
    """Blocking client of a :class:`SplitProcessCluster`."""

    def __init__(
        self, ports: Sequence[int], host: str = "127.0.0.1"
    ) -> None:
        from .split_server import SplitNetClerk

        self.node = RpcNode()
        self.sched = self.node.sched
        ends = [self.node.client_end(host, p) for p in ports]
        self._clerk = SplitNetClerk(self.sched, ends)


class SplitShardProcessCluster:
    """Several engine processes SHARING the sharded stack's peer slots
    (engine/split_shard.py + distributed/split_shard_server.py): the
    config RSM and every replica group survive any minority-owner
    process death — including mid-migration (the reference shardkv
    failure model, shardkv/config.go:204-262, at the process level).
    Non-durable by design: replication across surviving quorums IS the
    durability; a killed member must stay dead."""

    def __init__(
        self,
        owners: Dict[int, Sequence[int]],
        n_procs: int,
        groups: int = 3,
        host: str = "127.0.0.1",
        seed: int = 0,
        delay_elections: Optional[Sequence[int]] = None,
    ) -> None:
        from . import engine_server  # noqa: F401  (codec registration)
        from . import split_shard_server  # noqa: F401

        self.host = host
        self.ports = _reserve_ports(n_procs, host)
        self.specs = []
        for i in range(n_procs):
            self.specs.append({
                "kind": "split_shardkv",
                "me": i,
                "host": host,
                "ports": self.ports,
                "owners": {str(g): list(o) for g, o in owners.items()},
                "groups": groups,
                "seed": seed + i,
                "delay_elections": (
                    int(delay_elections[i]) if delay_elections else 0
                ),
                "platform": os.environ.get("MRT_ENGINE_PLATFORM", "cpu"),
            })
        self._killed: set = set()
        self.procs: List[Optional[subprocess.Popen]] = [None] * n_procs

    def start_all(self) -> None:
        assert not self._killed, (
            "a killed split peer must stay dead (non-durable identity)"
        )
        for i, spec in enumerate(self.specs):
            self.procs[i] = _launch_server(spec, f"splitshard-{i}")
        for i, p in enumerate(self.procs):
            _check_ready(p, f"splitshard-{i}", timeout=300.0)

    def kill(self, i: int) -> None:
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()
        self.procs[i] = None
        self._killed.add(i)

    def clerk(self) -> "BlockingSplitShardClerk":
        return BlockingSplitShardClerk(self.ports, host=self.host)

    def shutdown(self) -> None:
        for i in range(len(self.procs)):
            self.kill(i)


class BlockingSplitShardClerk(_BlockingClerkBase):
    """Blocking client of a :class:`SplitShardProcessCluster`, with
    the admin (join/leave/move) and status probes exposed."""

    def __init__(
        self, ports: Sequence[int], host: str = "127.0.0.1"
    ) -> None:
        from .split_shard_server import SplitShardNetClerk

        self.node = RpcNode()
        self.sched = self.node.sched
        ends = [self.node.client_end(host, p) for p in ports]
        self._clerk = SplitShardNetClerk(self.sched, ends)

    def admin(self, kind: str, payload, timeout: float = 60.0) -> None:
        self._run(self._clerk.admin(kind, payload), timeout)

    def status(self, proc: int, timeout: float = 10.0):
        return self._run(self._clerk.status(proc), timeout)


class EngineFleetCluster:
    """Several chip-owning engine shard processes splitting one global
    gid space — SURVEY §2.2's end state at the process level: clerk
    traffic and shard migration ride the real network BETWEEN engines,
    consensus stays on each process's device.

    ``assignment[i]`` is the gid list process ``i`` hosts.  Admin ops
    are mirrored to every process in issue order with an explicit
    command id, so retries cannot fork the fleet's config histories.
    """

    def __init__(
        self,
        assignment: Sequence[Sequence[int]],
        host: str = "127.0.0.1",
        seed: int = 0,
        data_dir: Optional[str] = None,
        checkpoint_every_s: float = 30.0,
        mesh_devices: int = 0,
    ) -> None:
        # Registers the wire dataclasses (EngineCmdArgs/Reply) with the
        # codec — admin replies are refused as unregistered otherwise.
        from . import engine_server  # noqa: F401

        self.host = host
        self.assignment = [list(g) for g in assignment]
        self.ports = _reserve_ports(len(self.assignment), host)
        self.owner_addrs = {}
        for i, gl in enumerate(self.assignment):
            for g in gl:
                self.owner_addrs[g] = (host, self.ports[i])
        self.specs = []
        for i, gl in enumerate(self.assignment):
            spec = {
                "kind": "engine_fleet",
                "ports": [self.ports[i]],
                "gids": gl,
                "peer_addrs": {
                    str(g): list(a) for g, a in self.owner_addrs.items()
                    if g not in gl
                },
                "seed": seed + i,
                "platform": os.environ.get("MRT_ENGINE_PLATFORM", "cpu"),
            }
            if data_dir is not None:
                spec["data_dir"] = os.path.join(data_dir, f"proc-{i}")
                spec["checkpoint_every_s"] = checkpoint_every_s
            if mesh_devices:
                # Each process runs its engine over a local mesh; its
                # len(gids)+1 engine groups must divide evenly over
                # mesh_devices (loud error from engine/mesh.py if not).
                spec["mesh_devices"] = mesh_devices
            self.specs.append(spec)
        self.procs: List[Optional[subprocess.Popen]] = [None] * len(self.specs)
        self._admin_node: Optional[RpcNode] = None
        self._admin_cmd = 0
        self._admin_inflight = None  # ((kind, repr(arg)), cmd) being retried

    def start_all(self) -> None:
        # Launch all processes first (jit warm-up dominates and runs in
        # parallel), then collect readiness lines.
        for i, spec in enumerate(self.specs):
            self.procs[i] = _launch_server(spec, f"fleet-{i}")
        for i, p in enumerate(self.procs):
            _check_ready(p, f"fleet-{i}", timeout=300.0)

    def kill(self, i: int) -> None:
        """SIGKILL fleet process ``i`` (its gids go dark until
        :meth:`start` revives it — from its data_dir in durable mode)."""
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()

    def start(self, i: int) -> None:
        """(Re)start fleet process ``i`` on its original spec/ports."""
        assert self.procs[i] is None or self.procs[i].poll() is not None
        self.procs[i] = _launch_server(self.specs[i], f"fleet-{i}")
        _check_ready(self.procs[i], f"fleet-{i}", timeout=300.0)

    def admin(self, kind: str, arg: Any, timeout: float = 60.0) -> None:
        """Mirror one config op to every process (same order, same
        command id → identical config histories; see the service's
        ``admin`` docstring for why the id is mandatory here).

        Retryable after a TimeoutError: re-issuing the SAME (kind, arg)
        reuses the interrupted attempt's command id, so processes that
        already applied it dedup instead of applying twice (a fresh id
        on retry would fork the fleet's config numbering)."""
        if self._admin_node is None:
            self._admin_node = RpcNode()
        op_key = (kind, repr(arg))
        if self._admin_inflight and self._admin_inflight[0] == op_key:
            cmd = self._admin_inflight[1]  # resume the interrupted op
        else:
            self._admin_cmd += 1
            cmd = self._admin_cmd
            self._admin_inflight = (op_key, cmd)
        sched = self._admin_node.sched
        deadline = time.monotonic() + timeout
        for port in self.ports:
            end = self._admin_node.client_end(self.host, port)
            while True:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"fleet admin {kind} timed out")
                reply = sched.wait(
                    end.call("EngineShardKV.admin", (kind, arg, cmd)),
                    6.0,
                )
                if (
                    reply is not None
                    and reply is not TIMEOUT
                    and getattr(reply, "err", None) == "OK"
                ):
                    break  # committed on this process; next one
        self._admin_inflight = None

    def clerk(self) -> "BlockingFleetClerk":
        return BlockingFleetClerk(self.owner_addrs)

    def shutdown(self) -> None:
        if self._admin_node is not None:
            self._admin_node.close()
            self._admin_node = None
        for i, p in enumerate(self.procs):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
            self.procs[i] = None


class BlockingFleetClerk(_BlockingClerkBase):
    """Blocking client of an :class:`EngineFleetCluster`."""

    def __init__(self, owner_addrs: dict) -> None:
        from .engine_server import EngineFleetClerk

        self.node = RpcNode()
        self.sched = self.node.sched
        ends = {
            g: self.node.client_end(h, p)
            for g, (h, p) in owner_addrs.items()
        }
        self._clerk = EngineFleetClerk(self.sched, ends)

    @property
    def client_id(self) -> int:
        return self._clerk.client_id


class BlockingEngineClerk(_BlockingClerkBase):
    """Blocking client of an :class:`EngineProcessCluster`."""

    def __init__(
        self, port: int, host: str = "127.0.0.1",
        service: str = "EngineKV",
    ) -> None:
        from .engine_server import EngineClerk

        self.node = RpcNode()
        self.sched = self.node.sched
        end = self.node.client_end(host, port)
        self._clerk = EngineClerk(self.sched, end, service=service)

    @property
    def client_id(self) -> int:
        return self._clerk.client_id


def _reserve_ports(n: int, host: str) -> List[int]:
    import socket

    ports, socks = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind((host, 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class ShardKVProcessCluster:
    """The full sharded stack as OS processes: ``nctrlers`` controller
    replicas plus ``n`` replicas per group, all over TCP with disk
    persistence — the deployment form of the reference's shardkv
    harness (reference: shardkv/config.go:338-382, which only ever
    builds one in-process simulated network)."""

    def __init__(
        self,
        data_dir: str,
        gids: Sequence[int] = (100, 101),
        n: int = 3,
        nctrlers: int = 3,
        host: str = "127.0.0.1",
        maxraftstate: int = -1,
    ) -> None:
        self.host = host
        self.data_dir = data_dir
        self.maxraftstate = maxraftstate
        self.gids = list(gids)
        self.n = n
        self.ctrler_ports = _reserve_ports(nctrlers, host)
        self.group_ports = {g: _reserve_ports(n, host) for g in self.gids}
        self.procs: dict = {}  # ("ctrler", i) | (gid, i) -> Popen
        self._admin_sched: Optional[RealtimeScheduler] = None
        self._admin_node: Optional[RpcNode] = None
        self._admin_ck: Any = None

    # -- process management -----------------------------------------------

    def _spawn(self, key, spec) -> None:
        old = self.procs.get(key)
        assert old is None or old.poll() is not None
        # Register before the readiness check so shutdown() can reap a
        # half-started server even when the check raises.
        self.procs[key] = _launch_server(spec, key)
        _check_ready(self.procs[key], key)

    def start_ctrler(self, i: int) -> None:
        self._spawn(("ctrler", i), {
            "kind": "ctrler", "me": i, "ports": self.ctrler_ports,
            "data_dir": self.data_dir,
        })

    def start_server(self, gid: int, i: int) -> None:
        self._spawn((gid, i), {
            "kind": "shardkv", "me": i, "gid": gid,
            "ports": self.group_ports[gid],
            "ctrler_ports": self.ctrler_ports,
            "data_dir": self.data_dir,
            "maxraftstate": self.maxraftstate,
        })

    def start_all(self) -> None:
        for i in range(len(self.ctrler_ports)):
            self.start_ctrler(i)
        for g in self.gids:
            for i in range(self.n):
                self.start_server(g, i)

    def kill(self, key) -> None:
        """SIGKILL ("ctrler", i) or (gid, i); disk carries the restart."""
        p = self.procs.get(key)
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()
        self.procs[key] = None

    def shutdown(self) -> None:
        for key in list(self.procs):
            self.kill(key)
        if self._admin_sched is not None:
            self._admin_node.close()
            self._admin_sched.stop()
            self._admin_sched = self._admin_node = self._admin_ck = None

    # -- admin (controller ops over TCP) ----------------------------------

    def _group_names(self, gid: int) -> List[str]:
        return [f"{self.host}:{p}" for p in self.group_ports[gid]]

    def _admin(self, fn, timeout: float = 30.0) -> Any:
        """Run a controller-clerk op on a lazily-created persistent
        admin client (one scheduler thread + node for the cluster's
        lifetime — callers poll query() in loops)."""
        from ..services.shardctrler import CtrlerClerk

        if self._admin_sched is None:
            self._admin_node = RpcNode()
            self._admin_sched = self._admin_node.sched
            self._admin_ck = CtrlerClerk(
                self._admin_sched,
                [self._admin_node.client_end(self.host, p)
                 for p in self.ctrler_ports],
            )
        sched = self._admin_sched
        fut = sched.spawn(fn(self._admin_ck))
        value = sched.wait(fut, timeout)
        if value is TIMEOUT:
            sched.post(fut.resolve, TIMEOUT)
            raise TimeoutError("controller did not answer in time")
        return value

    def join(self, gid: int) -> None:
        self._admin(lambda ck: ck.join({gid: self._group_names(gid)}))

    def leave(self, gid: int) -> None:
        self._admin(lambda ck: ck.leave([gid]))

    def query(self):
        return self._admin(lambda ck: ck.query(-1))

    def clerk(self) -> BlockingShardClerk:
        return BlockingShardClerk(self.ctrler_ports, host=self.host)


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.argv = [sys.argv[0], "serve", sys.argv[1]]
    _server_main()
