"""Multi-process cluster deployment.

The reference never leaves the simulated network — "serving" means test
harnesses (SURVEY §0).  This module is the real thing: each Raft/KV
server runs in its own OS process on a ``RealtimeScheduler`` + TCP
``RpcNode`` with a crash-atomic ``DiskPersister``; clients talk to the
cluster through the unmodified :class:`~multiraft_tpu.services.kvraft.Clerk`
over :class:`TcpClientEnd`\\ s.

Crash/restart testing here is *literal*: ``kill -9`` the process, start
a new one on the same data directory, and Raft recovers from disk — the
deployment analog of the sim fixture's Persister-copy rebirth
(reference: raft/config.go:113-142).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Any, List, Optional, Sequence

from ..sim.scheduler import TIMEOUT
from .disk import DiskPersister
from .realtime import RealtimeScheduler
from .tcp import RpcNode

__all__ = [
    "serve_kv",
    "KVProcessCluster",
    "BlockingClerk",
]


def serve_kv(
    me: int,
    ports: Sequence[int],
    data_dir: str,
    host: str = "127.0.0.1",
    maxraftstate: int = -1,
) -> RpcNode:
    """Bring up one KV server process component: RealtimeScheduler +
    listening RpcNode + KVServer/RaftNode on a DiskPersister.  Returns
    the RpcNode (caller keeps the process alive)."""
    from ..services.kvraft import KVServer

    sched = RealtimeScheduler()
    node = RpcNode(sched, listen=True, host=host, port=ports[me])
    ends = [node.client_end(host, p) for p in ports]
    persister = DiskPersister(os.path.join(data_dir, f"server-{me}"))

    # KVServer mutates consensus state from RPC handlers; construct it on
    # the loop thread so initialization obeys the single-mutator rule.
    srv = sched.run_call(
        lambda: KVServer(
            sched, ends, me, persister, maxraftstate=maxraftstate, seed=me
        )
    )
    node.add_service("KVServer", srv)
    node.add_service("Raft", srv.rf)
    if os.environ.get("MRT_DEBUG"):
        def _dump() -> None:
            print(f"[{time.monotonic():.2f}] {srv.rf!r}", file=sys.stderr, flush=True)
            sched.call_after(1.0, _dump)
        sched.call_soon(_dump)
    return node


def _server_main() -> None:  # pragma: no cover - subprocess entry
    import json

    spec = json.loads(sys.argv[2])
    node = serve_kv(
        me=spec["me"],
        ports=spec["ports"],
        data_dir=spec["data_dir"],
        maxraftstate=spec.get("maxraftstate", -1),
    )
    print(f"ready {node.port}", flush=True)
    while True:
        time.sleep(3600)


class BlockingClerk:
    """Synchronous client facade: drives the generator-coroutine Clerk on
    a RealtimeScheduler and blocks the calling thread for the result."""

    def __init__(
        self, ports: Sequence[int], host: str = "127.0.0.1",
        sched: Optional[RealtimeScheduler] = None,
        node: Optional[RpcNode] = None,
    ) -> None:
        from ..services.kvraft import Clerk

        self.sched = sched or RealtimeScheduler()
        self.node = node or RpcNode(self.sched)
        ends = [self.node.client_end(host, p) for p in ports]
        self._clerk = Clerk(self.sched, ends)

    def _run(self, gen, timeout: float) -> Any:
        fut = self.sched.spawn(gen)
        value = self.sched.wait(fut, timeout)
        if value is TIMEOUT:
            # Cancel the abandoned retry loop (resolving the spawn future
            # halts the coroutine at its next step) — otherwise it would
            # spin forever and race the caller's next command on this
            # single-outstanding-op Clerk.
            self.sched.post(fut.resolve, TIMEOUT)
            raise TimeoutError("cluster did not answer in time")
        return value

    def get(self, key: str, timeout: float = 30.0) -> str:
        return self._run(self._clerk.get(key), timeout)

    def put(self, key: str, value: str, timeout: float = 30.0) -> None:
        self._run(self._clerk.put(key, value), timeout)

    def append(self, key: str, value: str, timeout: float = 30.0) -> None:
        self._run(self._clerk.append(key, value), timeout)

    def close(self) -> None:
        self.node.close()


class KVProcessCluster:
    """Launch and manage ``n`` KV server OS processes (test/ops driver)."""

    def __init__(
        self,
        n: int,
        data_dir: str,
        host: str = "127.0.0.1",
        maxraftstate: int = -1,
    ) -> None:
        import socket

        self.n = n
        self.host = host
        self.data_dir = data_dir
        self.maxraftstate = maxraftstate
        # Reserve n distinct ephemeral ports by bind/close.  There is a
        # small window where another process could grab one before the
        # child listens — in that case start() raises and the caller
        # builds a fresh cluster; acceptable for a test/ops driver.
        self.ports: List[int] = []
        socks = []
        for _ in range(n):
            s = socket.socket()
            s.bind((host, 0))
            socks.append(s)
            self.ports.append(s.getsockname()[1])
        for s in socks:
            s.close()
        self.procs: List[Optional[subprocess.Popen]] = [None] * n

    def start(self, i: int) -> None:
        import json

        assert self.procs[i] is None or self.procs[i].poll() is not None
        spec = {
            "me": i,
            "ports": self.ports,
            "data_dir": self.data_dir,
            "maxraftstate": self.maxraftstate,
        }
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")  # server procs never need a chip
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        log_dir = os.environ.get("MRT_SERVER_LOG_DIR")
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            stderr = open(os.path.join(log_dir, f"server-{i}.err"), "a")
        else:
            stderr = subprocess.DEVNULL
        try:
            self.procs[i] = subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "multiraft_tpu.distributed.cluster",
                    json.dumps(spec),
                ],
                stdout=subprocess.PIPE,
                stderr=stderr,
                env=env,
                text=True,
            )
        finally:
            if log_dir:
                stderr.close()
        line = self.procs[i].stdout.readline()
        if not line.startswith("ready"):
            raise RuntimeError(f"server {i} failed to start: {line!r}")

    def start_all(self) -> None:
        for i in range(self.n):
            self.start(i)

    def kill(self, i: int) -> None:
        """SIGKILL — a real crash; durable state must carry the restart."""
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()
        self.procs[i] = None

    def clerk(self) -> BlockingClerk:
        return BlockingClerk(self.ports, host=self.host)

    def shutdown(self) -> None:
        for i in range(self.n):
            self.kill(i)


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.argv = [sys.argv[0], "serve", sys.argv[1]]
    _server_main()
