"""Multi-process cluster deployment.

The reference never leaves the simulated network — "serving" means test
harnesses (SURVEY §0).  This module is the real thing: each Raft/KV
server runs in its own OS process on a ``RealtimeScheduler`` + TCP
``RpcNode`` with a crash-atomic ``DiskPersister``; clients talk to the
cluster through the unmodified :class:`~multiraft_tpu.services.kvraft.Clerk`
over :class:`TcpClientEnd`\\ s.

Crash/restart testing here is *literal*: ``kill -9`` the process, start
a new one on the same data directory, and Raft recovers from disk — the
deployment analog of the sim fixture's Persister-copy rebirth
(reference: raft/config.go:113-142).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence

from ..sim.scheduler import TIMEOUT
from ..utils.knobs import knob_bool
from .disk import DiskPersister
from .launch import (
    BlockingClerkBase as _BlockingClerkBase,
    check_ready as _check_ready,
    launch_server as _launch_server,
    reserve_ports as _reserve_ports,
)
from .tcp import RpcNode

__all__ = [
    "serve_kv",
    "serve_ctrler",
    "serve_shardkv",
    "EngineProcessCluster",
    "EngineFleetCluster",
    "BlockingEngineClerk",
    "BlockingFleetClerk",
    "KVProcessCluster",
    "ShardKVProcessCluster",
    "BlockingClerk",
    "BlockingShardClerk",
]


def _addr_end(node: RpcNode, name: str):
    """Resolve a ``"host:port"`` group-server name to a TcpClientEnd —
    the deployment's ``make_end`` (the sim passes opaque endnames;
    here the controller's group tables carry real addresses)."""
    host, port = name.rsplit(":", 1)
    return node.client_end(host, int(port))

def serve_kv(
    me: int,
    ports: Sequence[int],
    data_dir: str,
    host: str = "127.0.0.1",
    maxraftstate: int = -1,
) -> RpcNode:
    """Bring up one KV server process component: RealtimeScheduler +
    listening RpcNode + KVServer/RaftNode on a DiskPersister.  Returns
    the RpcNode (caller keeps the process alive)."""
    from ..services.kvraft import KVServer

    node = RpcNode(listen=True, host=host, port=ports[me])
    sched = node.sched
    ends = [node.client_end(host, p) for p in ports]
    persister = DiskPersister(os.path.join(data_dir, f"server-{me}"))

    # KVServer mutates consensus state from RPC handlers; construct it on
    # the loop thread so initialization obeys the single-mutator rule.
    srv = sched.run_call(
        lambda: KVServer(
            sched, ends, me, persister, maxraftstate=maxraftstate, seed=me
        )
    )
    node.add_service("KVServer", srv)
    node.add_service("Raft", srv.rf)
    if knob_bool("MRT_DEBUG"):
        def _dump() -> None:
            print(f"[{time.monotonic():.2f}] {srv.rf!r}", file=sys.stderr, flush=True)
            sched.call_after(1.0, _dump)
        sched.call_soon(_dump)
    return node


def serve_ctrler(
    me: int, ports: Sequence[int], data_dir: str, host: str = "127.0.0.1"
) -> RpcNode:
    """One shard-controller replica process (the config RSM,
    reference: shardctrler/server.go:164-182 — over real sockets)."""
    from ..services.shardctrler import ShardCtrler

    node = RpcNode(listen=True, host=host, port=ports[me])
    sched = node.sched
    ends = [node.client_end(host, p) for p in ports]
    persister = DiskPersister(os.path.join(data_dir, f"ctrler-{me}"))
    srv = sched.run_call(
        lambda: ShardCtrler(sched, ends, me, persister, seed=1000 + me)
    )
    node.add_service("ShardCtrler", srv)
    node.add_service("Raft", srv.rf)
    return node


def serve_shardkv(
    me: int,
    gid: int,
    group_ports: Sequence[int],
    ctrler_ports: Sequence[int],
    data_dir: str,
    host: str = "127.0.0.1",
    maxraftstate: int = -1,
) -> RpcNode:
    """One replica of one shard group (the full migration-capable
    server, reference: shardkv/server.go:77-98 wiring — raft +
    controller clerk + make_end, here resolving "host:port" names to
    TCP ends so groups pull shards from each other across processes)."""
    from ..services.shardkv import ShardKVServer

    node = RpcNode(listen=True, host=host, port=group_ports[me])
    sched = node.sched
    ends = [node.client_end(host, p) for p in group_ports]
    ctrler_ends = [node.client_end(host, p) for p in ctrler_ports]
    persister = DiskPersister(os.path.join(data_dir, f"g{gid}-{me}"))
    srv = sched.run_call(
        lambda: ShardKVServer(
            sched, ends, me, persister, gid, ctrler_ends,
            lambda name: _addr_end(node, name),
            maxraftstate=maxraftstate, seed=gid * 100 + me,
        )
    )
    node.add_service("ShardKV", srv)
    node.add_service("Raft", srv.rf)
    return node


def _pin_platform(spec: dict) -> None:
    """Engine server processes import jax; pin the backend BEFORE any
    backend init.  The env var alone cannot steer it when the TPU
    plugin registers itself at interpreter start (it sets
    jax_platforms programmatically) — tests pin "cpu"; production
    passes "tpu" to own the chip."""
    plat = spec.get("platform", "cpu")
    import jax

    try:
        jax.config.update("jax_platforms", plat)
    except Exception as exc:
        # A chip-owning server silently falling back to CPU would be
        # orders of magnitude slower with no error anywhere: fatal for
        # tpu; loud for cpu (tests would still pass, just slower).
        if plat != "cpu":
            raise RuntimeError(
                f"engine server could not pin platform {plat!r}: {exc!r}"
            )
        print(
            f"warning: could not pin jax platform to cpu: {exc!r}",
            file=sys.stderr, flush=True,
        )


def _server_main() -> None:  # pragma: no cover - subprocess entry
    import json

    # Before the first jit: server processes share one persistent
    # compilation cache and may be SIGKILLed at any point (crash
    # tests, the nemesis) — upstream's in-place cache write lets a
    # torn entry segfault the next reader (utils/jaxcache.py).
    from ..utils.jaxcache import harden_persistent_cache

    harden_persistent_cache()

    spec = json.loads(sys.argv[2])
    kind = spec.get("kind", "kv")
    if kind == "kv":
        node = serve_kv(
            me=spec["me"],
            ports=spec["ports"],
            data_dir=spec["data_dir"],
            maxraftstate=spec.get("maxraftstate", -1),
        )
    elif kind == "ctrler":
        node = serve_ctrler(spec["me"], spec["ports"], spec["data_dir"])
    elif kind == "shardkv":
        node = serve_shardkv(
            me=spec["me"],
            gid=spec["gid"],
            group_ports=spec["ports"],
            ctrler_ports=spec["ctrler_ports"],
            data_dir=spec["data_dir"],
            maxraftstate=spec.get("maxraftstate", -1),
        )
    elif kind == "engine_kv":
        _pin_platform(spec)
        from .engine_server import serve_engine_kv

        node = serve_engine_kv(
            port=spec["ports"][0],
            G=spec.get("groups", 64),
            seed=spec.get("seed", 0),
            data_dir=spec.get("data_dir"),
            checkpoint_every_s=spec.get("checkpoint_every_s", 30.0),
            mesh_devices=spec.get("mesh_devices", 0),
        )
    elif kind == "engine_shardkv":
        _pin_platform(spec)
        from .engine_server import serve_engine_shardkv

        node = serve_engine_shardkv(
            port=spec["ports"][0],
            G=spec.get("groups", 4),
            seed=spec.get("seed", 0),
            join_gids=spec.get("join_gids"),
            data_dir=spec.get("data_dir"),
            checkpoint_every_s=spec.get("checkpoint_every_s", 30.0),
            mesh_devices=spec.get("mesh_devices", 0),
        )
    elif kind == "engine_fleet":
        _pin_platform(spec)
        from .engine_server import serve_engine_shardkv

        node = serve_engine_shardkv(
            port=spec["ports"][0],
            seed=spec.get("seed", 0),
            gids=spec["gids"],
            # JSON round trip stringifies gid keys and listifies tuples.
            peer_addrs={
                int(g): (a[0], int(a[1]))
                for g, a in spec["peer_addrs"].items()
            },
            data_dir=spec.get("data_dir"),
            checkpoint_every_s=spec.get("checkpoint_every_s", 30.0),
            mesh_devices=spec.get("mesh_devices", 0),
            spare_slots=spec.get("spare_slots", 0),
            replicas=spec.get("replicas", 3),
            voters=spec.get("voters"),
            # State plane (distributed/stateplane.py): the full fleet
            # roster + own index turn snapshot/tail shipping on.
            fleet_addrs=(
                {
                    int(p): (a[0], int(a[1]))
                    for p, a in spec["fleet_addrs"].items()
                }
                if spec.get("fleet_addrs") else None
            ),
            me=spec.get("me"),
            ship_sync=spec.get("ship_sync"),
            ship_window_s=spec.get("ship_window_s"),
        )
    elif kind == "split_kv":
        _pin_platform(spec)
        from .split_server import serve_split_kv

        node = serve_split_kv(
            port=spec["ports"][spec["me"]],
            me=spec["me"],
            # JSON stringifies the group keys and listifies slot lists.
            owners={int(g): list(o) for g, o in spec["owners"].items()},
            peer_addrs={
                i: (spec.get("host", "127.0.0.1"), p)
                for i, p in enumerate(spec["ports"])
            },
            G=spec.get("groups", 8),
            host=spec.get("host", "127.0.0.1"),
            seed=spec.get("seed", 0),
            delay_elections=spec.get("delay_elections", 0),
            data_dir=spec.get("data_dir"),
            snapshot_every_s=spec.get("snapshot_every_s", 30.0),
        )
    elif kind == "split_shardkv":
        _pin_platform(spec)
        from .split_shard_server import serve_split_shardkv

        node = serve_split_shardkv(
            port=spec["ports"][spec["me"]],
            me=spec["me"],
            # JSON stringifies the group keys and listifies slot lists.
            owners={int(g): list(o) for g, o in spec["owners"].items()},
            peer_addrs={
                i: (spec.get("host", "127.0.0.1"), p)
                for i, p in enumerate(spec["ports"])
            },
            G=spec.get("groups", 3),
            host=spec.get("host", "127.0.0.1"),
            seed=spec.get("seed", 0),
            delay_elections=spec.get("delay_elections", 0),
            data_dir=spec.get("data_dir"),
            snapshot_every_s=spec.get("snapshot_every_s", 30.0),
        )
    else:
        raise ValueError(f"unknown server kind {kind!r}")
    if spec.get("chaos_seed") is not None:
        # Fault-injection hooks + the "Chaos" control RPC, for every
        # server kind — the nemesis harness reconfigures the live
        # fleet over the same sockets it serves on (chaos.py).
        from .chaos import install_chaos

        install_chaos(node, int(spec["chaos_seed"]))
    print(f"ready {node.port}", flush=True)
    while True:
        time.sleep(3600)

class BlockingClerk(_BlockingClerkBase):
    """Blocking client of a :class:`KVProcessCluster`."""

    def __init__(
        self, ports: Sequence[int], host: str = "127.0.0.1",
        node: Optional[RpcNode] = None,
    ) -> None:
        from ..services.kvraft import Clerk

        self.node = node or RpcNode()
        self.sched = self.node.sched
        ends = [self.node.client_end(host, p) for p in ports]
        self._clerk = Clerk(self.sched, ends)


class BlockingShardClerk(_BlockingClerkBase):
    """Blocking client of a sharded process cluster: drives the
    unmodified :class:`~multiraft_tpu.services.shardkv.ShardClerk`
    (config-tracking, per-group retry) over TCP ends."""

    def __init__(
        self, ctrler_ports: Sequence[int], host: str = "127.0.0.1"
    ) -> None:
        from ..services.shardkv import ShardClerk

        self.node = RpcNode()
        self.sched = self.node.sched
        ctrler_ends = [self.node.client_end(host, p) for p in ctrler_ports]
        self._clerk = ShardClerk(
            self.sched, ctrler_ends, lambda name: _addr_end(self.node, name)
        )


class KVProcessCluster:
    """Launch and manage ``n`` KV server OS processes (test/ops driver)."""

    def __init__(
        self,
        n: int,
        data_dir: str,
        host: str = "127.0.0.1",
        maxraftstate: int = -1,
    ) -> None:
        self.n = n
        self.host = host
        self.data_dir = data_dir
        self.maxraftstate = maxraftstate
        # Reserve n distinct ephemeral ports by bind/close.  There is a
        # small window where another process could grab one before the
        # child listens — in that case start() raises and the caller
        # builds a fresh cluster; acceptable for a test/ops driver.
        self.ports: List[int] = _reserve_ports(n, host)
        self.procs: List[Optional[subprocess.Popen]] = [None] * n

    def start(self, i: int) -> None:
        assert self.procs[i] is None or self.procs[i].poll() is not None
        spec = {
            "me": i,
            "ports": self.ports,
            "data_dir": self.data_dir,
            "maxraftstate": self.maxraftstate,
        }
        # Register before the readiness check so shutdown() can reap a
        # half-started server even when the check raises.
        self.procs[i] = _launch_server(spec, i)
        _check_ready(self.procs[i], i)

    def start_all(self) -> None:
        for i in range(self.n):
            self.start(i)

    def kill(self, i: int) -> None:
        """SIGKILL — a real crash; durable state must carry the restart."""
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()
        self.procs[i] = None

    def clerk(self) -> BlockingClerk:
        return BlockingClerk(self.ports, host=self.host)

    def shutdown(self) -> None:
        for i in range(self.n):
            self.kill(i)


class ShardKVProcessCluster:
    """The full sharded stack as OS processes: ``nctrlers`` controller
    replicas plus ``n`` replicas per group, all over TCP with disk
    persistence — the deployment form of the reference's shardkv
    harness (reference: shardkv/config.go:338-382, which only ever
    builds one in-process simulated network)."""

    def __init__(
        self,
        data_dir: str,
        gids: Sequence[int] = (100, 101),
        n: int = 3,
        nctrlers: int = 3,
        host: str = "127.0.0.1",
        maxraftstate: int = -1,
    ) -> None:
        self.host = host
        self.data_dir = data_dir
        self.maxraftstate = maxraftstate
        self.gids = list(gids)
        self.n = n
        self.ctrler_ports = _reserve_ports(nctrlers, host)
        self.group_ports = {g: _reserve_ports(n, host) for g in self.gids}
        self.procs: dict = {}  # ("ctrler", i) | (gid, i) -> Popen
        self._admin_sched: Optional[RealtimeScheduler] = None
        self._admin_node: Optional[RpcNode] = None
        self._admin_ck: Any = None

    # -- process management -----------------------------------------------

    def _spawn(self, key, spec) -> None:
        old = self.procs.get(key)
        assert old is None or old.poll() is not None
        # Register before the readiness check so shutdown() can reap a
        # half-started server even when the check raises.
        self.procs[key] = _launch_server(spec, key)
        _check_ready(self.procs[key], key)

    def start_ctrler(self, i: int) -> None:
        self._spawn(("ctrler", i), {
            "kind": "ctrler", "me": i, "ports": self.ctrler_ports,
            "data_dir": self.data_dir,
        })

    def start_server(self, gid: int, i: int) -> None:
        self._spawn((gid, i), {
            "kind": "shardkv", "me": i, "gid": gid,
            "ports": self.group_ports[gid],
            "ctrler_ports": self.ctrler_ports,
            "data_dir": self.data_dir,
            "maxraftstate": self.maxraftstate,
        })

    def start_all(self) -> None:
        for i in range(len(self.ctrler_ports)):
            self.start_ctrler(i)
        for g in self.gids:
            for i in range(self.n):
                self.start_server(g, i)

    def kill(self, key) -> None:
        """SIGKILL ("ctrler", i) or (gid, i); disk carries the restart."""
        p = self.procs.get(key)
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()
        self.procs[key] = None

    def shutdown(self) -> None:
        for key in list(self.procs):
            self.kill(key)
        if self._admin_sched is not None:
            self._admin_node.close()
            self._admin_sched.stop()
            self._admin_sched = self._admin_node = self._admin_ck = None

    # -- admin (controller ops over TCP) ----------------------------------

    def _group_names(self, gid: int) -> List[str]:
        return [f"{self.host}:{p}" for p in self.group_ports[gid]]

    def _admin(self, fn, timeout: float = 30.0) -> Any:
        """Run a controller-clerk op on a lazily-created persistent
        admin client (one scheduler thread + node for the cluster's
        lifetime — callers poll query() in loops)."""
        from ..services.shardctrler import CtrlerClerk

        if self._admin_sched is None:
            self._admin_node = RpcNode()
            self._admin_sched = self._admin_node.sched
            self._admin_ck = CtrlerClerk(
                self._admin_sched,
                [self._admin_node.client_end(self.host, p)
                 for p in self.ctrler_ports],
            )
        sched = self._admin_sched
        fut = sched.spawn(fn(self._admin_ck))
        value = sched.wait(fut, timeout)
        if value is TIMEOUT:
            sched.post(fut.resolve, TIMEOUT)
            raise TimeoutError("controller did not answer in time")
        return value

    def join(self, gid: int) -> None:
        self._admin(lambda ck: ck.join({gid: self._group_names(gid)}))

    def leave(self, gid: int) -> None:
        self._admin(lambda ck: ck.leave([gid]))

    def query(self):
        return self._admin(lambda ck: ck.query(-1))

    def clerk(self) -> BlockingShardClerk:
        return BlockingShardClerk(self.ctrler_ports, host=self.host)


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.argv = [sys.argv[0], "serve", sys.argv[1]]
    _server_main()


# Backwards-compatible re-exports: the engine-backed clusters moved to
# engine_cluster.py in the round-4 decomposition; in-repo callers and
# tests import them from here.
from .engine_cluster import (  # noqa: E402,F401
    BlockingEngineClerk,
    BlockingFleetClerk,
    BlockingSplitClerk,
    BlockingSplitShardClerk,
    EngineFleetCluster,
    EngineProcessCluster,
    SplitProcessCluster,
    SplitShardProcessCluster,
)
