"""Overload visibility: a periodic watch that turns saturation into
flight-recorder evidence *while it is happening*.

The closed-loop harness never sees queueing collapse — clerks wait for
replies, so offered load self-throttles.  Under open-loop traffic
(benchmarks/openloop.py) an overloaded server grows queues until
latency diverges; before this module the only witnesses were the
post-hoc flight recorder ring and whatever Obs.snapshot happened to be
scraped.  The watch closes that gap: every ``interval`` seconds it

* diffs each ``stage.*_s`` histogram against its previous scrape
  (``Hist.sub`` — the same windowing the fleet aggregator uses) and
  checks the WINDOWED p99 against a per-stage bound, and
* reads the live queue-depth gauges (tcp reply queues, engine dispatch
  backlog, WAL pending — ObsControl.gauges) against their bounds.

A crossing writes an ``OVERLOAD`` record (flightrec.py) naming the
stage or gauge, its value, and its bound; a stage trip additionally
records the deepest queue gauge at that instant (``gauge_ctx``) so the
postmortem doctor can name the first saturated stage *and* the queue
it backed up into — the "queueing collapse" anomaly.  Metrics mirror:
``overload.trips`` counts crossings, ``overload.active`` gauges how
many names are currently over bound (scrapeable mid-run, e.g. by the
load-curve sweep).

Bounds (env-tunable):

* ``MRT_OVERLOAD_P99_MS``   windowed stage-p99 bound, ms (default 100)
* ``MRT_OVERLOAD_REPLYQ``   total queued replies (default 1024)
* ``MRT_OVERLOAD_BACKLOG``  engine dispatch backlog (default 4096)
* ``MRT_OVERLOAD_WAL``      WAL appended-but-unsynced (default 4096)
* ``MRT_OVERLOAD_INTERVAL`` watch period, seconds (default 0.25)
* ``MRT_OVERLOAD_WATCH=0``  disable the watch entirely

The watch runs on the node's scheduler loop (same thread as dispatch),
so reading the loop-thread-only reply queues is safe; each tick costs
a handful of 128-int diffs — far below one pump tick.

Round 8 closes the loop: each tick also feeds a trip count into a
:class:`BrownoutMachine` (healthy → shedding → brownout, with
hysteresis so a p99 oscillating around the bound cannot flap the
state) — but only the trips admission can RELIEVE: post-admission
stage p99s (dispatch/handler/engine/ack/flush) and the queue gauges.
A tripping ``stage.wire`` is ingress parse backlog upstream of the
admission check; it is recorded and counted, but shedding harder
cannot drain it, so letting it drive the machine would pin the node
in brownout with no latency to show for the lost goodput.  The
machine's state drives the admission controller
(admission.py) — tightening the token buckets and the per-connection
dispatch bound as the node browns out, instead of only emitting
OVERLOAD flight records.  State *transitions* get their own OVERLOAD
record (kind "brownout") so the postmortem doctor reports "shedding
engaged" distinctly from "queueing collapse".

* ``MRT_BROWNOUT_UP``    consecutive tripping ticks to escalate (2)
* ``MRT_BROWNOUT_DOWN``  consecutive clean ticks to de-escalate (8)
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..utils.knobs import knob_bool, knob_float, knob_int
from ..utils.metrics import Hist
from . import flightrec
from .observe import ObsControl

__all__ = [
    "OverloadWatch",
    "BrownoutMachine",
    "install_overload_watch",
    "HEALTHY",
    "SHEDDING",
    "BROWNOUT",
]

# Minimum samples in a window before its p99 means anything — a
# two-sample window's "p99" is just its max.
_MIN_WINDOW_COUNT = 20


# -- brownout state machine -------------------------------------------------

HEALTHY, SHEDDING, BROWNOUT = 0, 1, 2

STATE_NAMES = {HEALTHY: "healthy", SHEDDING: "shedding",
               BROWNOUT: "brownout"}


class BrownoutMachine:
    """Overload trips → admission level, with hysteresis.

    One :meth:`update` per watch tick with that tick's trip count.
    Escalation needs ``up`` CONSECUTIVE tripping ticks; de-escalation
    needs ``down`` consecutive clean ones, and each crossing resets the
    opposite streak — a p99 oscillating around its bound (trip, clean,
    trip, clean, ...) can therefore neither escalate nor de-escalate:
    the state holds instead of flapping.  Pure and clock-free so the
    unit tests drive it tick by tick."""

    def __init__(self, up: Optional[int] = None,
                 down: Optional[int] = None) -> None:
        self.up = max(1, int(up if up is not None
                             else knob_int("MRT_BROWNOUT_UP")))
        self.down = max(1, int(down if down is not None
                               else knob_int("MRT_BROWNOUT_DOWN")))
        self.state = HEALTHY
        self._over = 0   # consecutive tripping ticks
        self._under = 0  # consecutive clean ticks

    def update(self, trips: int) -> int:
        if trips > 0:
            self._over += 1
            self._under = 0
            if self._over >= self.up and self.state < BROWNOUT:
                self.state += 1
                self._over = 0
        else:
            self._under += 1
            self._over = 0
            if self._under >= self.down and self.state > HEALTHY:
                self.state -= 1
                self._under = 0
        return self.state


class OverloadWatch:
    """Periodic stage-p99 + queue-gauge bound check on one node."""

    def __init__(self, node: Any, interval: Optional[float] = None) -> None:
        self.node = node
        self.interval = (
            interval if interval is not None
            else knob_float("MRT_OVERLOAD_INTERVAL")
        )
        self.p99_bound_s = knob_float("MRT_OVERLOAD_P99_MS") / 1e3
        self.gauge_bounds: Dict[str, float] = {
            "gauge.replyq": knob_float("MRT_OVERLOAD_REPLYQ"),
            "gauge.backlog": knob_float("MRT_OVERLOAD_BACKLOG"),
            "gauge.wal_pending": knob_float("MRT_OVERLOAD_WAL"),
        }
        self._ctl = ObsControl(node)
        self._prev: Dict[str, Hist] = {}  # stage hist snapshots, last tick
        self.brownout = BrownoutMachine()
        self._stopped = False
        node.sched.call_after(self.interval, self._tick)

    def stop(self) -> None:
        self._stopped = True

    # -- one watch tick ---------------------------------------------------

    def _tick(self) -> None:
        if self._stopped or getattr(self.node, "_closed", False):
            return
        try:
            self.check()
        except Exception:
            # The watch must never take the serving loop down.
            self.node.obs.metrics.inc("overload.watch_errors")
        self.node.sched.call_after(self.interval, self._tick)

    def check(self) -> int:
        """Run one bound check; returns the number of crossings."""
        m = self.node.obs.metrics
        frec = getattr(self.node, "_frec", None)
        gauges = self._ctl.gauges()
        trips = 0
        relievable = 0  # trips shedding can actually fix (post-admission)
        stage_tripped = False

        # Windowed stage p99s: cumulative hist minus last tick's copy.
        for name, h in list(m.hists.items()):
            if not name.startswith("stage."):
                continue
            prev = self._prev.get(name)
            window = Hist.sub(h, prev) if prev is not None else h
            # Snapshot for next tick (copy via dump round trip is
            # wasteful; clone counts directly).
            snap = Hist()
            snap.counts = list(h.counts)
            snap.count = h.count
            snap.total = h.total
            snap.vmin = h.vmin
            snap.vmax = h.vmax
            self._prev[name] = snap
            if window.count < _MIN_WINDOW_COUNT:
                continue
            p99 = window.percentile(0.99)
            if p99 is None or p99 <= self.p99_bound_s:
                continue
            trips += 1
            stage_tripped = True
            # The wire stage (client send -> socket read) sits BEFORE
            # admission: its backlog is ingress parse cost, and
            # admitting fewer requests cannot drain it — feeding it to
            # the brownout machine just death-spirals goodput while the
            # latency stays.  It still trips an OVERLOAD record (it is
            # how "queueing collapse" gets named); only the
            # post-admission stages drive shedding.
            if name != "stage.wire_s":
                relievable += 1
            m.inc("overload.trips")
            if frec is not None:
                frec.record(
                    flightrec.OVERLOAD,
                    code=flightrec.OVERLOAD_KIND_CODES["stage_p99"],
                    a=int(p99 * 1e6), b=int(self.p99_bound_s * 1e6),
                    c=window.count, tag=name,
                )

        # Queue gauges against their bounds.
        for gname, bound in self.gauge_bounds.items():
            val = gauges.get(gname)
            if val is None or val <= bound:
                continue
            trips += 1
            relievable += 1  # queue gauges are all post-admission
            m.inc("overload.trips")
            if frec is not None:
                frec.record(
                    flightrec.OVERLOAD,
                    code=flightrec.OVERLOAD_KIND_CODES["gauge"],
                    a=int(val), b=int(bound), tag=gname,
                )

        # Context record: the deepest queue at the moment a stage
        # tripped, even if under its own bound — the doctor pairs it
        # with the first saturated stage.
        if stage_tripped and frec is not None and gauges:
            deepest = max(gauges, key=lambda k: gauges[k])
            frec.record(
                flightrec.OVERLOAD,
                code=flightrec.OVERLOAD_KIND_CODES["gauge_ctx"],
                a=int(gauges[deepest]),
                b=int(self.gauge_bounds.get(deepest, 0)),
                tag=deepest,
            )
        m.set("overload.active", float(trips))

        # Feed the brownout machine and drive admission.  Transitions
        # (either direction) are flight-recorded; the steady state is
        # just a gauge.
        prev_state = self.brownout.state
        state = self.brownout.update(relievable)
        if state != prev_state:
            m.inc("overload.brownout_transitions")
            if frec is not None:
                frec.record(
                    flightrec.OVERLOAD,
                    code=flightrec.OVERLOAD_KIND_CODES["brownout"],
                    a=state, b=prev_state, c=trips, tag="brownout",
                )
        m.set("overload.state", float(state))
        adm = getattr(self.node, "admission", None)
        if adm is not None:
            adm.set_level(state)
        return trips


def install_overload_watch(
    node: Any, interval: Optional[float] = None
) -> Optional[OverloadWatch]:
    """Attach the watch to a serving node (no-op when
    ``MRT_OVERLOAD_WATCH=0``).  Returns the watch, kept reachable on
    ``node.overload_watch``."""
    if not knob_bool("MRT_OVERLOAD_WATCH"):
        return None
    watch = OverloadWatch(node, interval=interval)
    node.overload_watch = watch
    return watch
