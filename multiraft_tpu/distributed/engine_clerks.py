"""Clerk-side clients of the engine serving stack (split out of
engine_server.py round 4): the single-server retry clerk, the
pipelined multi-op frame clerk, and the fleet clerks that route
key→shard→gid→process from the replicated config (reference loops:
kvraft/client.go:47-71, shardkv/client.go:68-129).
"""

from __future__ import annotations

import itertools
import time

from ..sim.scheduler import TIMEOUT, Future
from ..utils.ids import unique_client_id
from .engine_wire import (
    ERR_BUSY,
    OK,
    EngineCmdArgs,
    EngineCmdReply,
    retry_after_of,
)
from .realtime import Backoff


def _busy_delay(backoff: Backoff, reply) -> float:
    """Delay before retrying a shed (ErrBusy) request: the server's
    ``retry_after_s`` hint, jittered.  The server hands the SAME hint
    to every clerk it sheds in a burst — honored verbatim, those
    clerks would re-offer in one synchronized wave and shed again;
    equal jitter spreads the wave.  No hint (legacy peer whose reply
    predates the field) → the ordinary doubling backoff."""
    hint = retry_after_of(reply)
    if hint <= 0.0:
        return backoff.next_delay()
    return backoff.jittered(hint)


def _end_obs(end):
    """The observability plane of the node behind a TcpClientEnd (the
    clerk's own process), or a private stand-in for exotic ends."""
    node = getattr(end, "_node", None)
    if node is not None and getattr(node, "obs", None) is not None:
        return node.obs
    from .observe import Observability

    return Observability()

__all__ = [
    "EngineClerk",
    "FirehoseClerk",
    "ShardFirehoseClerk",
    "PipelinedClerk",
    "EngineShardNetClerk",
    "EngineFleetClerk",
    "PipelinedFleetClerk",
]


class EngineClerk:
    """Generator-coroutine client of an engine KV/shard server —
    retry-until-answer with session dedup, mirroring the reference
    clerk loop (kvraft/client.go:47-71) against the single front door."""

    # Clerks are created from concurrent threads (one per blocking
    # client); the counter allocation must be atomic or two clerks
    # share a client_id and dedup silently drops one's writes.
    _next = itertools.count(1)

    def __init__(
        self, sched, end, service: str = "EngineKV", lane: str = "",
    ) -> None:
        self.sched = sched
        self.end = end
        self.service = service
        self.client_id = unique_client_id(next(EngineClerk._next))
        self.command_id = 0
        # Priority lane: a non-empty lane prefixes every rid, and the
        # server's admission layer (admission.py) exempts recognized
        # lanes — the porcupine sampler passes "verify" so the
        # linearizability witness keeps flowing while user traffic
        # sheds.
        self.lane = lane
        # Failed calls that fail FAST (connection refused while the
        # server restarts, a partitioned minority) must not turn the
        # retry loop into a hot spin against the recovering process.
        self._backoff = Backoff()
        # Observability: per-call latency/retry counters + a span per
        # logical command, all tagged with a compact request id that
        # also rides the wire (every retry reuses it, so the clerk span
        # here and the server's dispatch spans correlate by one id).
        self.obs = _end_obs(end)
        self._rid_seq = itertools.count(1)

    def _rid(self) -> str:
        rid = f"{self.client_id & 0xFFFFFF:06x}.{next(self._rid_seq)}"
        return f"{self.lane}.{rid}" if self.lane else rid

    def _command(self, op: str, key: str, value: str = ""):
        if op != "Get":
            self.command_id += 1
        args = EngineCmdArgs(
            op=op, key=key, value=value,
            client_id=self.client_id, command_id=self.command_id,
        )
        rid = self._rid()
        m = self.obs.metrics
        m.inc("clerk.calls")
        t0 = time.perf_counter()
        attempts = 0
        while True:
            attempts += 1
            fut: Future = self.end.call(
                f"{self.service}.command", args, trace=rid
            )
            reply = yield self.sched.with_timeout(fut, 3.5)
            if (
                reply is None
                or reply is TIMEOUT
                or reply.err != OK
            ):
                # lost/timed out/old leader/shed: retry (dedup-safe)
                m.inc("clerk.retries")
                if (
                    reply is not None and reply is not TIMEOUT
                    and reply.err == ERR_BUSY
                ):
                    # Admission shed: the server told us when to come
                    # back — honor it (jittered) instead of doubling.
                    m.inc("clerk.busy")
                    delay = _busy_delay(self._backoff, reply)
                else:
                    delay = self._backoff.next_delay()
                m.observe("clerk.backoff_s", delay)
                yield delay
                continue
            self._backoff.reset()
            dur = time.perf_counter() - t0
            m.observe("clerk.call_s", dur)
            self.obs.tracer.span(
                f"clerk.{op}", t0 * 1e6, dur * 1e6, track="clerk",
                req=rid, attempts=attempts,
            )
            return reply.value

    def get(self, key: str):
        return self._command("Get", key)

    def put(self, key: str, value: str):
        return self._command("Put", key, value)

    def append(self, key: str, value: str):
        return self._command("Append", key, value)


class PipelinedClerk(EngineClerk):
    """Clerk that ships a whole batch of ops as ONE ``batch`` frame —
    the reference clerk's serial loop (kvraft/client.go:47-71) widened
    for the engine's coalescing front door: the server applies the
    frame in one pump, so per-op RPC overhead amortizes ~frame-fold.
    Whole-frame retry is dedup-safe (same client/command ids)."""

    # Mirror of EngineKVService.MAX_BATCH: oversized op lists split
    # into compliant frames client-side (the server's rejection is
    # permanent, so retrying an oversized frame would spin forever).
    MAX_FRAME = 1024

    def run_batch(self, ops):
        """ops = [(op, key, value), ...] → list of values (Gets) in
        order.  Generator (spawn on the scheduler)."""
        out = []
        for s in range(0, len(ops), self.MAX_FRAME):
            part = yield from self._one_frame(ops[s:s + self.MAX_FRAME])
            out.extend(part)
        return out

    def _one_frame(self, ops):
        frame = []
        for op, key, value in ops:
            if op != "Get":
                self.command_id += 1
            frame.append(
                EngineCmdArgs(
                    op=op, key=key, value=value,
                    client_id=self.client_id,
                    command_id=self.command_id,
                )
            )
        rid = self._rid()
        self.obs.metrics.inc("clerk.batch_frames")
        while True:
            fut: Future = self.end.call(
                f"{self.service}.batch", frame, trace=rid
            )
            reply = yield self.sched.with_timeout(fut, 10.0)
            if isinstance(reply, EngineCmdReply):
                # The dispatch layer shed the whole frame (ErrBusy)
                # before the handler saw it — a single reply, not the
                # per-op list.  Honor the hint and re-ship (dedup-safe).
                self.obs.metrics.inc("clerk.busy")
                yield _busy_delay(self._backoff, reply)
                continue
            if reply is not None and reply is not TIMEOUT and any(
                r.err.startswith("ErrBatchTooLarge") for r in reply
            ):
                # Permanent: the server's cap shrank below ours.
                raise ValueError(reply[0].err)
            if (
                reply is None
                or reply is TIMEOUT
                or any(r.err != OK for r in reply)
            ):
                # lost/partial frame: retry whole (dedup-safe)
                yield self._backoff.next_delay()
                continue
            self._backoff.reset()
            return [r.value for r in reply]


class FirehoseClerk(EngineClerk):
    """Columnar clerk: packs a whole op batch into ONE firehose blob
    (engine/firehose.py) and retries only the rows the server failed —
    per-row RETRY errs come back in the reply columns, and the retry
    frame reuses the same command ids, so session dedup keeps the
    at-least-once wire exactly-once.

    This is the client half of the columnar serving path: no per-op
    dataclasses, no per-op codec — numpy columns end to end."""

    # The server's wire-level cap, from the shared wire module:
    # oversized batches split into compliant frames client-side (the
    # server's rejection is permanent, so retrying an oversized frame
    # would spin forever).
    from ..engine.firehose import MAX_FIREHOSE_ROWS as MAX_FRAME

    def __init__(
        self, sched, end, service: str = "EngineKV", lane: str = "",
    ) -> None:
        super().__init__(sched, end, service, lane=lane)
        self._G = None

    def _topology(self, deadline):
        while self._G is None:
            if self.sched.now >= deadline:
                raise TimeoutError("topology fetch exceeded deadline")
            fut: Future = self.end.call(f"{self.service}.info", None)
            reply = yield self.sched.with_timeout(fut, 3.5)
            if reply is not None and reply is not TIMEOUT:
                self._G = int(reply["G"])
            else:
                yield self._backoff.next_delay()
        self._backoff.reset()
        return self._G

    def run_batch(self, ops, deadline_s: float = 30.0):
        """ops = [(op, key, value), ...] → list of values (Gets) in
        order.  Generator (spawn on the scheduler)."""
        out = []
        for s in range(0, len(ops), self.MAX_FRAME):
            part = yield from self._one_frame(
                ops[s: s + self.MAX_FRAME], deadline_s
            )
            out.extend(part)
        return out

    def _one_frame(self, ops, deadline_s: float):
        import numpy as np

        from ..engine.firehose import (
            FH_OK,
            pack_request,
            unpack_reply,
        )
        from .engine_wire import _OPCODE, route_group

        deadline = self.sched.now + deadline_s
        G = yield from self._topology(deadline)
        n = len(ops)
        op_col = np.zeros(n, np.uint8)
        group_col = np.zeros(n, np.uint32)
        cmd_col = np.zeros(n, np.uint64)
        keys = [b""] * n
        vals = [b""] * n
        for i, (op, key, value) in enumerate(ops):
            op_col[i] = _OPCODE[op]
            group_col[i] = route_group(key, G)
            if op != "Get":
                self.command_id += 1
                cmd_col[i] = self.command_id
            keys[i] = key.encode()
            vals[i] = value.encode()
        client_col = np.full(n, self.client_id, np.uint64)

        values = [""] * n
        todo = np.arange(n)
        while len(todo) and self.sched.now < deadline:
            blob = pack_request(
                op_col[todo], group_col[todo], client_col[todo],
                cmd_col[todo],
                [keys[i] for i in todo.tolist()],
                [vals[i] for i in todo.tolist()],
            )
            fut: Future = self.end.call(f"{self.service}.firehose", blob)
            reply = yield self.sched.with_timeout(fut, 10.0)
            if reply is None or reply is TIMEOUT:
                # whole frame lost: retry whole (dedup-safe)
                yield self._backoff.next_delay()
                continue
            if isinstance(reply, EngineCmdReply):
                # Shed at dispatch (ErrBusy) — the firehose blob never
                # reached the handler.  Honor the hint, retry whole.
                self.obs.metrics.inc("clerk.busy")
                yield _busy_delay(self._backoff, reply)
                continue
            if isinstance(reply, tuple) and reply and reply[0] == "err":
                raise ValueError(reply[1])
            self._backoff.reset()
            err, row_vals = unpack_reply(reply)
            ok = err == FH_OK
            for j in np.nonzero(ok)[0].tolist():
                values[int(todo[j])] = row_vals[j]
            todo = todo[~ok]
        if len(todo):
            raise TimeoutError(
                f"{len(todo)} rows unresolved after {deadline_s}s"
            )
        return values


class ShardFirehoseClerk:
    """Columnar clerk for the SHARDED fleet: each round partitions its
    rows by owning gid (key→shard→gid from the replicated config) and
    ships ONE firehose blob per process; WRONG_GROUP rows refresh the
    config and re-route; RETRY rows resubmit under the same command
    ids (per-shard dedup travels with the shard, so the retry stays
    exactly-once across migrations).

    Order safety: within a round at most ONE write per shard is in
    flight from this clerk, and a shard's ops never reorder (a
    deferred op defers everything after it on that shard).  A
    pipelined same-shard chain could otherwise invert across an
    away-and-back migration — op N bounces WRONG_GROUP while N+1
    applies, and N's retry dedup-swallows into a false OK (the hazard
    the per-op fleet clerk's serial chains guard, engine_shard_server.
    batch).  Cross-shard rows keep full columnar parallelism."""

    from ..engine.firehose import MAX_FIREHOSE_ROWS as MAX_FRAME

    def __init__(self, sched, ends_by_gid: dict) -> None:
        self.sched = sched
        self.ends = dict(ends_by_gid)
        self._all = list(dict.fromkeys(self.ends.values()))
        self.client_id = unique_client_id(next(EngineClerk._next))
        self.command_id = 0
        self._cfg = None
        self._backoff = Backoff()

    def _refresh_config(self, deadline):
        while True:
            if self.sched.now >= deadline:
                raise TimeoutError("config fetch exceeded deadline")
            for end in self._all:
                fut: Future = end.call("EngineShardKV.config", None)
                reply = yield self.sched.with_timeout(fut, 3.5)
                if reply is not None and reply is not TIMEOUT:
                    self._cfg = reply
                    self._backoff.reset()
                    return reply
            yield self._backoff.next_delay()

    def run_batch(self, ops, deadline_s: float = 60.0):
        """ops = [(op, key, value), ...] → list of values in order.
        Generator (spawn on the scheduler)."""
        import numpy as np

        from ..engine.firehose import (
            FH_NO_KEY,
            FH_OK,
            FH_WRONG_GROUP,
            pack_request,
            unpack_reply,
        )
        from ..services.shardkv import key2shard
        from .engine_wire import _OPCODE

        n = len(ops)
        rows = []
        for op, key, value in ops:
            cmd = 0
            if op != "Get":
                self.command_id += 1
                cmd = self.command_id
            rows.append((op, key, value, cmd))
        shards = [key2shard(key) for _, key, _, _ in rows]
        results = [""] * n
        done = [False] * n
        deadline = self.sched.now + deadline_s
        remaining = list(range(n))
        while remaining:
            if self.sched.now >= deadline:
                raise TimeoutError(
                    f"{len(remaining)} rows unresolved after {deadline_s}s"
                )
            # ROUND: program-order prefix per shard — one in-flight
            # write per shard; a deferred op defers everything after
            # it on that shard.
            taken = []
            write_taken: set = set()
            deferred: set = set()
            for i in remaining:
                sh = shards[i]
                if sh in deferred:
                    continue
                if rows[i][0] != "Get":
                    if sh in write_taken:
                        deferred.add(sh)
                        continue
                    write_taken.add(sh)
                taken.append(i)
                if len(taken) >= self.MAX_FRAME:
                    break
            todo = list(taken)
            while todo and self.sched.now < deadline:
                cfg = self._cfg
                if cfg is None:
                    cfg = yield from self._refresh_config(deadline)
                by_end: dict = {}
                retry = []
                unrouted = 0
                for i in todo:
                    gid = cfg[1][shards[i]]
                    end = self.ends.get(gid)
                    if end is None:
                        # Shard unassigned (gid 0) or owned by a
                        # process we have no end for: wait for the
                        # config to move — re-query, don't spin.
                        unrouted += 1
                        retry.append(i)
                    else:
                        by_end.setdefault(end, []).append((i, gid))
                if unrouted:
                    self._cfg = None
                    yield self.sched.sleep(self._backoff.jittered(0.03))
                flights = []
                busy = None
                for end, members in by_end.items():
                    idxs = [i for i, _ in members]
                    blob = pack_request(
                        np.array([_OPCODE[rows[i][0]] for i in idxs],
                                 np.uint8),
                        np.array([g for _, g in members], np.uint32),
                        np.full(len(idxs), self.client_id, np.uint64),
                        np.array([rows[i][3] for i in idxs], np.uint64),
                        [rows[i][1].encode() for i in idxs],
                        [rows[i][2].encode() for i in idxs],
                    )
                    flights.append(
                        (idxs, end.call("EngineShardKV.firehose", blob))
                    )
                for idxs, fut in flights:
                    reply = yield self.sched.with_timeout(fut, 10.0)
                    if reply is None or reply is TIMEOUT:
                        retry.extend(idxs)
                        continue
                    if isinstance(reply, EngineCmdReply):
                        # Shed at dispatch (ErrBusy): requeue the
                        # rows; the hint is honored once, after the
                        # round's other flights resolve.
                        retry.extend(idxs)
                        busy = reply
                        continue
                    if (
                        isinstance(reply, tuple)
                        and reply
                        and reply[0] == "err"
                    ):
                        raise ValueError(reply[1])
                    err, vals = unpack_reply(reply)
                    for j, i in enumerate(idxs):
                        if err[j] == FH_OK:
                            done[i] = True
                            results[i] = vals[j]
                        elif err[j] == FH_NO_KEY:
                            done[i] = True
                            results[i] = ""
                        else:
                            if err[j] == FH_WRONG_GROUP:
                                self._cfg = None  # routing moved
                            retry.append(i)
                if busy is not None:
                    yield _busy_delay(self._backoff, busy)
                elif retry and self._cfg is None:
                    yield self.sched.sleep(self._backoff.jittered(0.03))
                todo = sorted(retry)
            remaining = [i for i in remaining if not done[i]]
        return results


class EngineShardNetClerk(EngineClerk):
    def __init__(self, sched, end) -> None:
        super().__init__(sched, end, service="EngineShardKV")


class EngineFleetClerk:
    """Clerk for a fleet of engine shard servers: route key→shard→gid→
    process from the replicated config, re-query and re-route on
    ErrWrongGroup — the reference clerk loop (shardkv/client.go:68-129)
    where each "group" is a chip-owning process."""

    # Per-fetch budget: one config fetch attempt (cycling every known
    # process with backoff) is bounded; a caller's retry loop decides
    # whether to try again.  A fully partitioned clerk then cycles
    # fetch → backoff → fetch instead of pinning its coroutine inside
    # an unbounded inner loop.
    CONFIG_DEADLINE_S = 30.0

    def __init__(self, sched, ends_by_gid: dict, make_end=None) -> None:
        self.sched = sched
        self.ends = dict(ends_by_gid)  # gid -> TcpClientEnd
        self._all = list(dict.fromkeys(self.ends.values()))
        self.client_id = unique_client_id(next(EngineClerk._next))
        self.command_id = 0
        self._cfg = None  # cached (num, shards, groups)
        self._backoff = Backoff()
        # Placement awareness (distributed/placement.py): with a
        # ``make_end`` factory the clerk re-derives its gid→end map from
        # the fleet's placement view after ErrWrongGroup — a config
        # re-query alone cannot re-route a gid the controller MOVED to
        # another process.  Without the factory the static map stands.
        self._make_end = make_end
        self._place_ver = 0
        self._place_stale = False
        self._ends_by_addr: dict = {}
        # Observability (see EngineClerk): every end shares the
        # process's one node, so any end's plane is THE plane.
        self.obs = _end_obs(self._all[0]) if self._all else _end_obs(None)
        self._rid_seq = itertools.count(1)

    def _rid(self) -> str:
        return f"{self.client_id & 0xFFFFFF:06x}.{next(self._rid_seq)}"

    def _refresh_config(self, deadline=None):
        if deadline is None:
            deadline = self.sched.now + self.CONFIG_DEADLINE_S
        if self._place_stale:
            yield from self._refresh_placement()
        while True:
            if self.sched.now >= deadline:
                raise TimeoutError("config fetch exceeded deadline")
            for end in self._all:
                fut = end.call("EngineShardKV.config", ())
                reply = yield self.sched.with_timeout(fut, 2.0)
                if reply is not None and reply is not TIMEOUT:
                    self._cfg = reply
                    self._backoff.reset()
                    return reply
            yield self._backoff.next_delay()

    def _refresh_placement(self):
        """Rebuild the gid→end map from any process's placement view
        (``EngineShardKV.placement``).  Version-gated: only a strictly
        newer view replaces the map, so a process holding a stale view
        cannot roll the clerk back mid-migration."""
        self._place_stale = False
        if self._make_end is None:
            return
        for end in list(self._all):
            fut = end.call("EngineShardKV.placement", ())
            reply = yield self.sched.with_timeout(fut, 2.0)
            if (
                reply is None or reply is TIMEOUT
                or not isinstance(reply, tuple) or len(reply) != 2
            ):
                continue
            ver, pmap = reply
            if ver > self._place_ver and pmap:
                self._place_ver = ver
                ends = {}
                for g, addr in pmap.items():
                    addr = (addr[0], int(addr[1]))
                    e = self._ends_by_addr.get(addr)
                    if e is None:
                        e = self._make_end(addr[0], addr[1])
                        self._ends_by_addr[addr] = e
                    ends[int(g)] = e
                self.ends = ends
                self._all = list(dict.fromkeys(self.ends.values()))
            return

    def _command(self, op: str, key: str, value: str = ""):
        from ..engine.shardkv import ERR_WRONG_GROUP
        from ..services.shardkv import key2shard

        if op != "Get":
            self.command_id += 1
        args = EngineCmdArgs(
            op=op, key=key, value=value,
            client_id=self.client_id, command_id=self.command_id,
        )
        rid = self._rid()
        m = self.obs.metrics
        m.inc("clerk.calls")
        t0 = time.perf_counter()
        attempts = 0
        while True:
            cfg = self._cfg
            if cfg is None:
                try:
                    cfg = yield from self._refresh_config()
                except TimeoutError:
                    # Whole fleet unreachable for a full fetch budget:
                    # back off and re-enter (the blocking facade's own
                    # deadline bounds the caller).
                    m.inc("clerk.retries")
                    yield self._backoff.next_delay()
                    continue
            gid = cfg[1][key2shard(key)]
            end = self.ends.get(gid)
            if end is None:  # unassigned shard / unknown gid: re-query
                yield self._backoff.next_delay()
                self._cfg = None
                continue
            attempts += 1
            fut = end.call("EngineShardKV.command", args, trace=rid)
            reply = yield self.sched.with_timeout(fut, 3.5)
            if reply is None or reply is TIMEOUT:
                self._cfg = None
                self._place_stale = True  # the process may be gone
                m.inc("clerk.retries")
                delay = self._backoff.next_delay()
                m.observe("clerk.backoff_s", delay)
                yield delay
                continue  # dropped / wedged: re-route and retry
            if reply.err == OK:
                self._backoff.reset()
                dur = time.perf_counter() - t0
                m.observe("clerk.call_s", dur)
                self.obs.tracer.span(
                    f"clerk.{op}", t0 * 1e6, dur * 1e6, track="clerk",
                    req=rid, attempts=attempts,
                )
                return reply.value
            if reply.err == ERR_WRONG_GROUP:
                self._cfg = None  # stale routing: re-query the config
                self._place_stale = True  # ...or the gid itself moved
            m.inc("clerk.retries")
            if reply.err == ERR_BUSY:
                # Shed at dispatch: routing is fine, the process is
                # overloaded — honor its jittered hint and retry there.
                m.inc("clerk.busy")
                yield _busy_delay(self._backoff, reply)
            else:
                yield self._backoff.next_delay()

    def get(self, key: str):
        return self._command("Get", key)

    def put(self, key: str, value: str):
        return self._command("Put", key, value)

    def append(self, key: str, value: str):
        return self._command("Append", key, value)


class PipelinedFleetClerk(EngineFleetClerk):
    """Multi-op frames over a sharded fleet: each round partitions the
    remaining ops by owning process (key→shard→gid→end from the
    replicated config) and ships one ``batch`` frame per process; ops
    answered ErrWrongGroup (shard mid-migration / stale routing)
    re-frame to the new owner next round.  Order safety: a frame's
    chains fully resolve server-side before it answers, so re-framed
    retries can never interleave with in-flight ops."""

    # Ops per sequential WINDOW.  An oversized batch must NOT split
    # into concurrently-in-flight frames: a (client, shard) chain
    # spanning two live frames breaks the serial-chain discipline the
    # server's dedup safety rests on (op N+1 applying while op N is
    # unresolved lets N's retry dedup-swallow into a false OK).  Each
    # window fully resolves before the next ships.
    MAX_FRAME = 1024

    def run_batch(self, ops):
        """ops = [(op, key, value), ...] → list of values in order."""
        out = []
        for s in range(0, len(ops), self.MAX_FRAME):
            part = yield from self._one_window(ops[s:s + self.MAX_FRAME])
            out.extend(part)
        return out

    def _one_window(self, ops):
        from ..services.shardkv import key2shard

        frame_args = []
        for op, key, value in ops:
            if op != "Get":
                self.command_id += 1
            frame_args.append(
                EngineCmdArgs(
                    op=op, key=key, value=value,
                    client_id=self.client_id,
                    command_id=self.command_id,
                )
            )
        rid = self._rid()
        self.obs.metrics.inc("clerk.batch_frames")
        results = [None] * len(ops)
        todo = list(range(len(ops)))
        while todo:
            cfg = self._cfg
            if cfg is None:
                try:
                    cfg = yield from self._refresh_config()
                except TimeoutError:
                    yield self._backoff.next_delay()
                    continue
            by_end: dict = {}
            unrouted = []
            for i in todo:
                gid = cfg[1][key2shard(frame_args[i].key)]
                end = self.ends.get(gid)
                if end is None:
                    unrouted.append(i)
                else:
                    by_end.setdefault(end, []).append(i)
            retry = list(unrouted)
            busy = None
            # Dispatch every process's frame FIRST, then collect:
            # wall-clock is the slowest frame, not the sum.  (Frames
            # are per-process partitions of one ≤MAX_FRAME window, so
            # none can exceed the server's cap.)
            flights = [
                (idxs, end.call(
                    "EngineShardKV.batch",
                    [frame_args[i] for i in idxs],
                    trace=rid,
                ))
                for end, idxs in by_end.items()
            ]
            for part, fut in flights:
                reply = yield self.sched.with_timeout(fut, 10.0)
                if reply is None or reply is TIMEOUT:
                    retry.extend(part)
                    continue
                if isinstance(reply, EngineCmdReply):
                    # Shed at dispatch (ErrBusy): one reply for the
                    # whole frame, not the per-op list.
                    retry.extend(part)
                    busy = reply
                    continue
                if any(
                    r.err.startswith("ErrBatchTooLarge") for r in reply
                ):
                    # Permanent: the server's cap shrank below ours.
                    raise ValueError(reply[0].err)
                for i, r in zip(part, reply):
                    if r.err == OK:
                        results[i] = r.value
                    else:
                        retry.append(i)
            todo = sorted(retry)
            if todo:
                if busy is not None:
                    # Overload, not stale routing: honor the jittered
                    # hint without burning a config re-query.
                    self.obs.metrics.inc("clerk.busy")
                    yield _busy_delay(self._backoff, busy)
                else:
                    self._cfg = None  # routing moved: re-query
                    self._place_stale = True  # ...maybe to a new process
                    yield self.sched.sleep(self._backoff.jittered(0.03))
        return results
