"""Serving layer for cross-process replica groups (engine/split.py).

Each process runs ``serve_split_kv``: one chip-owning engine whose
split groups share their P peer slots with peer processes, per-tick
boundary mailbox slabs riding ``SplitEngine.slab`` RPCs between them
(SURVEY §2.2's "node↔node over DCN/gRPC").  Unlike
``serve_engine_kv``'s whole-group engine, losing one of these
processes loses only its owned peer slots — a group whose surviving
peers still hold a quorum keeps electing and committing, and every
acknowledged write survives from replication alone (no WAL replay).

Client surface mirrors the reference kvraft deployment: a clerk
carries (client_id, command_id) sessions and rotates processes on
ErrWrongLeader/timeout (reference: kvraft/client.go:47-71); the server
gates submission on an owned slot actually leading the group and rides
EVERY op — Gets included — through the log (reference semantics,
SURVEY §3.4; the single-process ReadIndex collapse does not reason
across processes).
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Dict, Optional, Sequence, Tuple

from ..engine.core import EngineConfig
from ..engine.host import EngineDriver
from ..engine.kv import KVOp
from ..engine.split import SplitKV, SplitPeering, SplitSpec
from ..porcupine.kv import OP_APPEND, OP_GET, OP_PUT
from ..sim.scheduler import TIMEOUT, Future
from ..utils.ids import unique_client_id
from .engine_server import (
    ERR_TIMEOUT,
    OK,
    EngineCmdArgs,
    EngineCmdReply,
    route_group,
)
from .realtime import PumpCadence, service_busy
from .realtime import RealtimeScheduler
from .tcp import RpcNode

__all__ = [
    "ERR_WRONG_LEADER",
    "SplitPersistence",
    "SplitKVService",
    "SplitNetClerk",
    "serve_split_kv",
]

ERR_WRONG_LEADER = "ErrWrongLeader"

_OPCODE = {"Get": OP_GET, "Put": OP_PUT, "Append": OP_APPEND}

# Raft columns a split process must persist for its owned slots (the
# reference's Persister contract — term/vote/log survive a crash,
# raft/persister.go — at engine-slice granularity).
_RAFT_COLS = ("term", "voted_for", "base", "base_term", "log_len",
              "log_term")


class SplitPersistence:
    """Per-process durability for split-group peers: safe crash +
    REJOIN under the same peer identity.

    Raft's persistence rules, mapped to the slab-exchange runtime: a
    peer must never emit a message reflecting state it could forget —
    a forgotten term/vote double-votes, a forgotten acked log entry
    un-commits acknowledged writes.  Slabs leave once per pump, so the
    whole contract collapses to ONE invariant: **fsync the owned
    slots' raft slice before this pump's slabs are extracted/sent**
    (``SplitKVService._pump_loop`` orders pump → ``after_pump()`` →
    extract/send).  A crash between append and fsync tears the tail
    record — and no slab for that pump was sent, so the restored
    (previous-pump) state is exactly what the world saw.

    On disk: an atomic SNAPSHOT (service state + live payload
    candidates + raft slice; superseding) plus a WAL of per-pump
    records — ``raft`` (full owned slice; the LAST one wins),
    ``pay`` (new payload candidates), ``app`` (applied (g, idx, term)
    — the service-state redo log).  Recovery = snapshot + last raft
    record + pay union + app replay; volatile columns (role, commit,
    applied, votes, timers) restart fresh, commit/applied rewound to
    base (the restart_replica discipline — commit knowledge is
    volatile in Raft)."""

    def __init__(self, data_dir: str, kv, peering,
                 snapshot_every_s: float = 30.0, fsync: bool = True) -> None:
        import pickle

        from .wal import WriteAheadLog

        os.makedirs(data_dir, exist_ok=True)
        self._pickle = pickle
        self.snap_path = os.path.join(data_dir, "split.snap")
        self.wal = WriteAheadLog(os.path.join(data_dir, "split.wal"),
                                 fsync=fsync)
        self.kv = kv
        self.peering = peering
        self.every = snapshot_every_s
        self._last_snap = time.monotonic()
        self._new_pays: list = []
        self._new_apps: list = []
        self._last_slice = None   # idle dedup: last persisted raft slice
        self._need_snapshot = False
        # App records carry (g, idx, term, wire|None): term >= 0 →
        # replay resolves the candidate; term -1 (fallback apply) →
        # the op rides IN the record so replay reproduces exactly what
        # the live path applied, never a silent skip.
        kv.on_applied = lambda g, idx, term, payload: (
            self._new_apps.append((
                g, idx, term,
                kv.export_payload(payload)
                if term < 0 and payload is not None else None,
            ))
        )
        peering.on_candidate = lambda g, idx, term, payload: (
            self._new_pays.append(
                (g, idx, term, kv.export_payload(payload))
            )
        )
        # An InstallSnapshot blob replaced service state whose device
        # base jumped with it: the next after_pump MUST checkpoint
        # before fsyncing that raft slice, or a crash in the window
        # restores base past a service state that never saw the blob.
        kv.on_snapshot_installed = (
            lambda g: setattr(self, "_need_snapshot", True)
        )

    # -- write path --------------------------------------------------------

    def _raft_slice(self) -> dict:
        import jax
        import numpy as np

        st = self.kv.driver.state
        gi = self.peering._g_index
        out = jax.device_get(
            {f: getattr(st, f)[gi] for f in _RAFT_COLS}
        )
        return {f: np.asarray(v) for f, v in out.items()}

    def after_pump(self) -> None:
        """Persist this pump's effects and fsync — called BEFORE the
        pump's slabs are extracted/sent (the one invariant)."""
        import numpy as np

        if self._need_snapshot:
            # Installed-snapshot service state must hit disk before the
            # raft slice whose base jumped with it.
            self._need_snapshot = False
            self.snapshot()
        slice_ = self._raft_slice()
        if (
            not self._new_pays
            and not self._new_apps
            and self._last_slice is not None
            and all(
                np.array_equal(slice_[f], self._last_slice[f])
                for f in _RAFT_COLS
            )
        ):
            return  # idle pump: nothing new to make durable, no fsync
        rec = ("pump", slice_, self._new_pays, self._new_apps)
        self._new_pays = []
        self._new_apps = []
        self._last_slice = slice_
        self.wal.append(self._pickle.dumps(rec, protocol=4))
        self.wal.sync()
        if self.every > 0 and (
            time.monotonic() - self._last_snap >= self.every
        ):
            self.snapshot()

    def snapshot(self) -> None:
        import numpy as np

        gs = self.peering.split_gs
        blob = {
            "svc": {
                # (applied_upto, service blob) via the service adapter
                # (SplitKV / SplitShardKV persist_group).
                g: self.kv.persist_group(g)
                for g in gs
            },
            "cands": [
                (g, idx, term, self.kv.export_payload(p))
                for (g, idx), by_term in self.peering._cands.items()
                for term, p in by_term.items()
            ],
            "raft": self._raft_slice(),
        }
        tmp = self.snap_path + ".tmp"
        with open(tmp, "wb") as f:
            self._pickle.dump(blob, f, protocol=4)
            f.flush()
            # Intentional loop-thread sync point: the snapshot MUST be
            # durable before wal.rotate() discards its records (same
            # contract as the WAL's allowlisted group-commit fsync).
            os.fsync(f.fileno())  # graftlint: disable=blocking-in-callback
        os.replace(tmp, self.snap_path)
        dfd = os.open(os.path.dirname(self.snap_path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)  # graftlint: disable=blocking-in-callback
        finally:
            os.close(dfd)
        # A crash between replace and rotate leaves redundant WAL
        # records — raft records supersede and app replay dedups on
        # applied_upto, so replay is merely redundant, never wrong.
        self.wal.rotate()
        self._last_snap = time.monotonic()
        for g in gs:
            self.peering.gc_floor[g] = self.kv.applied_upto[g]

    # -- recovery ----------------------------------------------------------

    def load_and_install(self) -> bool:
        """Restore the previous incarnation's persisted state into the
        (freshly built) driver/service/peering.  Returns False when no
        prior state exists (first boot).  Must run BEFORE the first
        tick — pre-restore state must never act."""
        import numpy as np

        blob = None
        if os.path.exists(self.snap_path):
            with open(self.snap_path, "rb") as f:
                blob = self._pickle.load(f)
        raft = blob["raft"] if blob else None
        pays = list(blob["cands"]) if blob else []
        apps: list = []
        for body in self.wal.replay():
            kind, slice_, rec_pays, rec_apps = self._pickle.loads(body)
            raft = slice_  # last record wins
            pays.extend(rec_pays)
            apps.extend(rec_apps)
        if raft is None:
            return False

        kv, peering = self.kv, self.peering
        drv = kv.driver
        # 1. Device columns for OWNED slots (+ commit/applied rewound
        #    to base; volatile leadership state stays fresh).
        host = {
            f: np.asarray(getattr(drv.state, f)).copy()
            for f in _RAFT_COLS + ("commit", "applied")
        }
        for gi, g in enumerate(peering.split_gs):
            for p in peering._owned[g]:
                for f in _RAFT_COLS:
                    host[f][g, p] = raft[f][gi, p]
                host["commit"][g, p] = raft["base"][gi, p]
                host["applied"][g, p] = raft["base"][gi, p]
        import jax.numpy as jnp

        # copy=True: ``host`` columns mix device-copied rows with rows
        # assigned from the unpickled WAL snapshot; on the CPU backend a
        # zero-copy asarray would alias that host memory into state the
        # donated tick writes through (the PR 1 restore segfault).
        drv.state = drv.state._replace(
            **{f: jnp.array(v, copy=True) for f, v in host.items()}
        )
        # 2. Service state from the snapshot (service adapter).
        if blob:
            for g, (upto, sblob) in blob["svc"].items():
                kv.restore_group(g, upto, sblob)
        # 3. Payload candidates (snapshot + WAL increments).
        for g, idx, term, wire in pays:
            payload = kv.import_payload(wire)
            peering._cands.setdefault((g, idx), {})[term] = payload
            if (g, idx) not in drv.payloads:
                drv.payloads[(g, idx)] = payload
        # 4. Service-state redo: applied entries since the snapshot,
        #    in commit order, exact by (idx, term) — fallback applies
        #    (term -1) carry their op in the record itself.  The
        #    service adapter's replay_apply routes through the same
        #    apply path as live serving, so recovery can never drift
        #    from serving semantics.
        for g, idx, term, wire in apps:
            if idx <= kv.applied_upto[g]:
                continue  # already inside the snapshot
            payload = None
            if term >= 0:
                payload = peering._cands.get((g, idx), {}).get(term)
            elif wire is not None:
                payload = kv.import_payload(wire)
            if payload is not None:
                kv.replay_apply(g, idx, payload)
            kv.applied_upto[g] = idx
        for g in peering.split_gs:
            peering.gc_floor[g] = kv.applied_upto[g]
        return True


class SplitKVService:
    """``SplitKV.command`` + ``SplitEngine.slab`` on one process.

    The pump loop advances the device one tick at a time and ships the
    boundary slabs immediately — per-tick granularity matters here
    (multi-tick pumps would drop the intermediate ticks' boundary
    messages, doubling effective RTT across the process boundary)."""

    RESUBMIT_S = 0.25
    DEADLINE_S = 3.0

    def __init__(
        self,
        sched: RealtimeScheduler,
        kv: SplitKV,
        peering: SplitPeering,
        peer_ends: Dict[int, object],  # proc index -> TcpClientEnd
        pump_interval: float = 0.002,
        persistence: Optional[SplitPersistence] = None,
    ) -> None:
        self.sched = sched
        self.kv = kv
        self.peering = peering
        self.peer_ends = dict(peer_ends)
        self.G = kv.driver.cfg.G
        self._cadence = PumpCadence(pump_interval)
        self._stopped = False
        self._persist = persistence
        sched.call_soon(self._pump_loop)

    def stop(self) -> None:
        self._stopped = True

    def _pump_loop(self) -> None:
        if self._stopped:
            return
        self.kv.pump(1)
        if self._persist is not None:
            # THE persistence invariant: the pump's raft slice is
            # fsynced before any of its slabs leave the process.
            self._persist.after_pump()
        for proc, slab in self.peering.extract().items():
            end = self.peer_ends.get(proc)
            if end is not None:
                # Fire-and-forget: a lost slab is a dropped message and
                # Raft retries; the timeout just reclaims the future.
                self.sched.with_timeout(
                    end.call("SplitEngine.slab", slab), 1.0
                )
        self.sched.call_after(
            self._cadence.next_delay(service_busy(self.kv)),
            self._pump_loop,
        )

    # -- peer-facing -------------------------------------------------------

    def slab(self, blob: dict):
        """Boundary mailbox lanes (+payloads/snapshots) from a peer
        process — merged before the next tick (same loop thread)."""
        self.peering.inject(blob)
        return True

    # -- client-facing -----------------------------------------------------

    MAX_BATCH = 1024

    def batch(self, args_list):
        """Multi-op frame on the split server (same chain discipline
        as EngineKVService.batch — split groups carry plain-KV
        semantics, so per-(client, group) chains pipeline whole, with
        suffix-only resubmission after full-chain resolution).  A
        group without a local leader answers ErrWrongLeader per-op;
        the clerk re-frames those at the peer process."""
        if len(args_list) > self.MAX_BATCH:
            return [
                EngineCmdReply(err=f"ErrBatchTooLarge:{self.MAX_BATCH}")
            ] * len(args_list)

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            replies = [None] * len(args_list)
            chains: dict = {}
            for i, a in enumerate(args_list):
                chains.setdefault(
                    (a.client_id, route_group(a.key, self.G)), []
                ).append(i)

            def submit(a):
                return self.kv.submit_local(
                    route_group(a.key, self.G),
                    KVOp(op=_OPCODE[a.op], key=a.key, value=a.value,
                         client_id=a.client_id, command_id=a.command_id),
                )

            tickets: dict = {}
            wrong: set = set()
            pending = set(chains)
            while pending and self.sched.now < deadline:
                progressed = False
                for qk in list(pending):
                    members = chains[qk]
                    sub = [i for i in members if i in tickets]
                    if any(not tickets[i].done for i in sub):
                        continue  # wait for the whole chain
                    k_bad = next(
                        (k for k, i in enumerate(members)
                         if i not in tickets or tickets[i].failed),
                        None,
                    )
                    if k_bad is None:
                        pending.discard(qk)
                        progressed = True
                        continue
                    if self.kv.local_leader(qk[1]) is None:
                        # The leader lives at a peer process: punt the
                        # unresolved members to the clerk.
                        for i in members[k_bad:]:
                            if i not in tickets or tickets[i].failed:
                                wrong.add(i)
                                tickets.pop(i, None)
                        pending.discard(qk)
                        progressed = True
                        continue
                    ok = True
                    for i in members[k_bad:]:
                        t = submit(args_list[i])
                        if t is None:
                            ok = False
                            break  # leadership just moved; re-check
                        tickets[i] = t
                    progressed = progressed or ok
                if pending and not progressed:
                    yield 0.002
            for i, a in enumerate(args_list):
                t = tickets.get(i)
                if i in wrong:
                    # Confirmed: the group's leader lives elsewhere.
                    replies[i] = EngineCmdReply(err=ERR_WRONG_LEADER)
                elif t is None:
                    # Never submitted before the deadline (leadership
                    # flapped locally the whole time) — a timeout, not
                    # a routing verdict (ADVICE r03).
                    replies[i] = EngineCmdReply(err=ERR_TIMEOUT)
                elif t.done and not t.failed:
                    replies[i] = EngineCmdReply(err=OK, value=t.value)
                else:
                    replies[i] = EngineCmdReply(err=ERR_TIMEOUT)
            return replies

        return run()

    def command(self, args: EngineCmdArgs):
        g = route_group(args.key, self.G)

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                t = self.kv.submit_local(
                    g,
                    KVOp(
                        op=_OPCODE[args.op],
                        key=args.key,
                        value=args.value,
                        client_id=args.client_id,
                        command_id=args.command_id,
                    ),
                )
                if t is None:
                    # No owned slot leads this group: the leader lives
                    # in (or is being elected by) a peer process.
                    return EngineCmdReply(err=ERR_WRONG_LEADER)
                sub_deadline = min(
                    self.sched.now + self.RESUBMIT_S, deadline
                )
                while not t.done and self.sched.now < sub_deadline:
                    yield 0.002
                if t.done and not t.failed:
                    return EngineCmdReply(err=OK, value=t.value)
                # failed (lost slot / lost leadership) or wedged:
                # re-check leadership and resubmit — dedup-safe.
            return EngineCmdReply(err=ERR_TIMEOUT)

        return run()


class SplitNetClerk:
    """Generator-coroutine clerk over a set of split-KV processes:
    session dedup + rotate-on-ErrWrongLeader/timeout with a per-group
    leader cache (reference clerk loop, kvraft/client.go:47-71)."""

    _next = itertools.count(1)

    def __init__(self, sched, ends: Sequence) -> None:
        self.sched = sched
        self.ends = list(ends)
        self.client_id = unique_client_id(next(SplitNetClerk._next))
        self.command_id = 0
        self._leader: Dict[str, int] = {}  # key -> ends index

    def _command(self, op: str, key: str, value: str = ""):
        if op != "Get":
            self.command_id += 1
        args = EngineCmdArgs(
            op=op, key=key, value=value,
            client_id=self.client_id, command_id=self.command_id,
        )
        # Group routing is server-side and the clerk does not know the
        # server's G, so the leader cache keys per-KEY (ADVICE r03: a
        # bucket over the ends count aliases distinct groups and they
        # evict each other's entries) — exact, and bounded by the
        # client's own working set.
        gkey = key
        i = self._leader.get(gkey, 0)
        while True:
            end = self.ends[i % len(self.ends)]
            fut: Future = end.call("SplitKV.command", args)
            reply = yield self.sched.with_timeout(fut, 3.5)
            if (
                reply is None
                or reply is TIMEOUT
                or reply.err != OK
            ):
                i += 1  # rotate: dropped / wrong leader / timed out
                yield self.sched.sleep(0.02)
                continue
            self._leader[gkey] = i % len(self.ends)
            return reply.value

    def get(self, key: str):
        return self._command("Get", key)

    def put(self, key: str, value: str):
        return self._command("Put", key, value)

    def append(self, key: str, value: str):
        return self._command("Append", key, value)

    # Sequential-window cap: an oversized batch must not split a
    # (client, group) chain across frames whose resolutions can
    # interleave (a timed-out chain-tail op retried after a later
    # frame applied the chain's next op dedup-swallows into a false
    # OK).  Windows run strictly one after another.
    MAX_FRAME = 1024

    def run_batch(self, ops):
        """Multi-op frames against the split cluster: each ≤MAX_FRAME
        window ships whole to one process; ops answered ErrWrongLeader
        (their group's leader lives elsewhere) re-frame to the next
        process; a window fully resolves before the next ships.
        Generator (spawn on the scheduler)."""
        out = []
        for s in range(0, len(ops), self.MAX_FRAME):
            part = yield from self._one_window(ops[s:s + self.MAX_FRAME])
            out.extend(part)
        return out

    def _one_window(self, ops):
        frame = []
        for op, key, value in ops:
            if op != "Get":
                self.command_id += 1
            frame.append(
                EngineCmdArgs(
                    op=op, key=key, value=value,
                    client_id=self.client_id,
                    command_id=self.command_id,
                )
            )
        results = [None] * len(ops)
        todo = list(range(len(ops)))
        i_end = 0
        while todo:
            end = self.ends[i_end % len(self.ends)]
            fut: Future = end.call(
                "SplitKV.batch", [frame[i] for i in todo]
            )
            reply = yield self.sched.with_timeout(fut, 10.0)
            retry = []
            if reply is None or reply is TIMEOUT:
                retry = list(todo)
            else:
                if any(
                    r.err.startswith("ErrBatchTooLarge") for r in reply
                ):
                    raise ValueError(reply[0].err)
                for i, r in zip(todo, reply):
                    if r.err == OK:
                        results[i] = r.value
                    else:
                        retry.append(i)
            if retry:
                i_end += 1  # rotate: those groups lead elsewhere
                yield self.sched.sleep(0.02)
            todo = sorted(retry)
        return results


def serve_split_kv(
    port: int,
    me: int,
    owners: Dict[int, Sequence[int]],
    peer_addrs: Dict[int, Tuple[str, int]],
    G: int = 8,
    host: str = "127.0.0.1",
    seed: int = 0,
    delay_elections: int = 0,
    data_dir: Optional[str] = None,
    snapshot_every_s: float = 30.0,
) -> RpcNode:
    """Bring up one split-KV process: engine over ``G`` groups, peer
    slots placed per ``owners`` (see :class:`SplitSpec` — every process
    passes the SAME map), slab exchange with ``peer_addrs``.

    ``delay_elections`` biases this process's owned slots' first
    election deadlines later — deployments use it to steer initial
    leadership (tests park leaders on a chosen process; a real rollout
    can spread them).  Readiness prints before leaders exist: elections
    converge once the peers are up, and clerks retry ErrWrongLeader
    until then.

    With ``data_dir`` the process is DURABLE under its peer identity
    (:class:`SplitPersistence`): a kill -9'd process may be restarted
    on the same dir and REJOINS the cluster safely — its persisted
    term/vote/log make double-votes and acked-entry loss impossible
    (the reference's Persister-carryover crash model,
    raft/config.go:113-142).  Without it, a killed process must stay
    dead (fresh state under an old identity can double-vote)."""
    node = RpcNode(listen=True, host=host, port=port)
    sched = node.sched

    def build():
        cfg = EngineConfig(G=G, P=3, L=64, E=8, INGEST=8,
                           host_paced_compaction=True)
        driver = EngineDriver(cfg, seed=seed)
        kv = SplitKV(driver)
        peering = SplitPeering(
            driver, kv, SplitSpec(me=me, owners={
                int(g): list(o) for g, o in owners.items()
            })
        )
        persist = None
        if data_dir is not None:
            persist = SplitPersistence(
                data_dir, kv, peering, snapshot_every_s=snapshot_every_s
            )
            # BEFORE any tick: pre-restore state must never act.
            persist.load_and_install()
        if delay_elections:
            driver.state = driver.state._replace(
                elect_dl=driver.state.elect_dl + int(delay_elections)
            )
        # Warm both tick variants before the readiness line (first jit
        # compile would otherwise starve RPC dispatch under the first
        # client — see serve_engine_kv).
        driver.start(0, (KVOp(op=OP_GET, key=""), None))
        kv.pump(4)
        ends = {
            int(p): node.client_end(h, int(pt))
            for p, (h, pt) in peer_addrs.items()
            if int(p) != me
        }
        return SplitKVService(sched, kv, peering, ends,
                              persistence=persist)

    svc = sched.run_call(build, timeout=600.0)
    node.add_service("SplitKV", svc)
    node.add_service("SplitEngine", svc)
    node.engine_service = svc
    return node
