"""Serving layer for cross-process replica groups (engine/split.py).

Each process runs ``serve_split_kv``: one chip-owning engine whose
split groups share their P peer slots with peer processes, per-tick
boundary mailbox slabs riding ``SplitEngine.slab`` RPCs between them
(SURVEY §2.2's "node↔node over DCN/gRPC").  Unlike
``serve_engine_kv``'s whole-group engine, losing one of these
processes loses only its owned peer slots — a group whose surviving
peers still hold a quorum keeps electing and committing, and every
acknowledged write survives from replication alone (no WAL replay).

Client surface mirrors the reference kvraft deployment: a clerk
carries (client_id, command_id) sessions and rotates processes on
ErrWrongLeader/timeout (reference: kvraft/client.go:47-71); the server
gates submission on an owned slot actually leading the group and rides
EVERY op — Gets included — through the log (reference semantics,
SURVEY §3.4; the single-process ReadIndex collapse does not reason
across processes).
"""

from __future__ import annotations

import itertools
from typing import Dict, Optional, Sequence, Tuple

from ..engine.core import EngineConfig
from ..engine.host import EngineDriver
from ..engine.kv import KVOp
from ..engine.split import SplitKV, SplitPeering, SplitSpec
from ..porcupine.kv import OP_APPEND, OP_GET, OP_PUT
from ..sim.scheduler import TIMEOUT, Future
from ..utils.ids import unique_client_id
from .engine_server import (
    ERR_TIMEOUT,
    OK,
    EngineCmdArgs,
    EngineCmdReply,
    route_group,
)
from .realtime import RealtimeScheduler
from .tcp import RpcNode

__all__ = [
    "ERR_WRONG_LEADER",
    "SplitKVService",
    "SplitNetClerk",
    "serve_split_kv",
]

ERR_WRONG_LEADER = "ErrWrongLeader"

_OPCODE = {"Get": OP_GET, "Put": OP_PUT, "Append": OP_APPEND}


class SplitKVService:
    """``SplitKV.command`` + ``SplitEngine.slab`` on one process.

    The pump loop advances the device one tick at a time and ships the
    boundary slabs immediately — per-tick granularity matters here
    (multi-tick pumps would drop the intermediate ticks' boundary
    messages, doubling effective RTT across the process boundary)."""

    RESUBMIT_S = 0.25
    DEADLINE_S = 3.0

    def __init__(
        self,
        sched: RealtimeScheduler,
        kv: SplitKV,
        peering: SplitPeering,
        peer_ends: Dict[int, object],  # proc index -> TcpClientEnd
        pump_interval: float = 0.002,
    ) -> None:
        self.sched = sched
        self.kv = kv
        self.peering = peering
        self.peer_ends = dict(peer_ends)
        self.G = kv.driver.cfg.G
        self._interval = pump_interval
        self._stopped = False
        sched.call_soon(self._pump_loop)

    def stop(self) -> None:
        self._stopped = True

    def _pump_loop(self) -> None:
        if self._stopped:
            return
        self.kv.pump(1)
        for proc, slab in self.peering.extract().items():
            end = self.peer_ends.get(proc)
            if end is not None:
                # Fire-and-forget: a lost slab is a dropped message and
                # Raft retries; the timeout just reclaims the future.
                self.sched.with_timeout(
                    end.call("SplitEngine.slab", slab), 1.0
                )
        self.sched.call_after(self._interval, self._pump_loop)

    # -- peer-facing -------------------------------------------------------

    def slab(self, blob: dict):
        """Boundary mailbox lanes (+payloads/snapshots) from a peer
        process — merged before the next tick (same loop thread)."""
        self.peering.inject(blob)
        return True

    # -- client-facing -----------------------------------------------------

    def command(self, args: EngineCmdArgs):
        g = route_group(args.key, self.G)

        def run():
            deadline = self.sched.now + self.DEADLINE_S
            while self.sched.now < deadline:
                t = self.kv.submit_local(
                    g,
                    KVOp(
                        op=_OPCODE[args.op],
                        key=args.key,
                        value=args.value,
                        client_id=args.client_id,
                        command_id=args.command_id,
                    ),
                )
                if t is None:
                    # No owned slot leads this group: the leader lives
                    # in (or is being elected by) a peer process.
                    return EngineCmdReply(err=ERR_WRONG_LEADER)
                sub_deadline = min(
                    self.sched.now + self.RESUBMIT_S, deadline
                )
                while not t.done and self.sched.now < sub_deadline:
                    yield 0.002
                if t.done and not t.failed:
                    return EngineCmdReply(err=OK, value=t.value)
                # failed (lost slot / lost leadership) or wedged:
                # re-check leadership and resubmit — dedup-safe.
            return EngineCmdReply(err=ERR_TIMEOUT)

        return run()


class SplitNetClerk:
    """Generator-coroutine clerk over a set of split-KV processes:
    session dedup + rotate-on-ErrWrongLeader/timeout with a per-group
    leader cache (reference clerk loop, kvraft/client.go:47-71)."""

    _next = itertools.count(1)

    def __init__(self, sched, ends: Sequence) -> None:
        self.sched = sched
        self.ends = list(ends)
        self.client_id = unique_client_id(next(SplitNetClerk._next))
        self.command_id = 0
        self._leader: Dict[int, int] = {}  # route bucket -> ends index

    def _command(self, op: str, key: str, value: str = ""):
        if op != "Get":
            self.command_id += 1
        args = EngineCmdArgs(
            op=op, key=key, value=value,
            client_id=self.client_id, command_id=self.command_id,
        )
        # Group routing is server-side; the leader cache keys on the
        # key's route bucket (stable across retries of the same key).
        gkey = route_group(key, max(len(self.ends), 1))
        i = self._leader.get(gkey, 0)
        while True:
            end = self.ends[i % len(self.ends)]
            fut: Future = end.call("SplitKV.command", args)
            reply = yield self.sched.with_timeout(fut, 3.5)
            if (
                reply is None
                or reply is TIMEOUT
                or reply.err != OK
            ):
                i += 1  # rotate: dropped / wrong leader / timed out
                yield self.sched.sleep(0.02)
                continue
            self._leader[gkey] = i % len(self.ends)
            return reply.value

    def get(self, key: str):
        return self._command("Get", key)

    def put(self, key: str, value: str):
        return self._command("Put", key, value)

    def append(self, key: str, value: str):
        return self._command("Append", key, value)


def serve_split_kv(
    port: int,
    me: int,
    owners: Dict[int, Sequence[int]],
    peer_addrs: Dict[int, Tuple[str, int]],
    G: int = 8,
    host: str = "127.0.0.1",
    seed: int = 0,
    delay_elections: int = 0,
) -> RpcNode:
    """Bring up one split-KV process: engine over ``G`` groups, peer
    slots placed per ``owners`` (see :class:`SplitSpec` — every process
    passes the SAME map), slab exchange with ``peer_addrs``.

    ``delay_elections`` biases this process's owned slots' first
    election deadlines later — deployments use it to steer initial
    leadership (tests park leaders on a chosen process; a real rollout
    can spread them).  Readiness prints before leaders exist: elections
    converge once the peers are up, and clerks retry ErrWrongLeader
    until then."""
    node = RpcNode(listen=True, host=host, port=port)
    sched = node.sched

    def build():
        cfg = EngineConfig(G=G, P=3, L=64, E=8, INGEST=8,
                           host_paced_compaction=True)
        driver = EngineDriver(cfg, seed=seed)
        kv = SplitKV(driver)
        peering = SplitPeering(
            driver, kv, SplitSpec(me=me, owners={
                int(g): list(o) for g, o in owners.items()
            })
        )
        if delay_elections:
            driver.state = driver.state._replace(
                elect_dl=driver.state.elect_dl + int(delay_elections)
            )
        # Warm both tick variants before the readiness line (first jit
        # compile would otherwise starve RPC dispatch under the first
        # client — see serve_engine_kv).
        driver.start(0, (KVOp(op=OP_GET, key=""), None))
        kv.pump(4)
        ends = {
            int(p): node.client_end(h, int(pt))
            for p, (h, pt) in peer_addrs.items()
            if int(p) != me
        }
        return SplitKVService(sched, kv, peering, ends)

    svc = sched.run_call(build, timeout=600.0)
    node.add_service("SplitKV", svc)
    node.add_service("SplitEngine", svc)
    node.engine_service = svc
    return node
