"""Simulated fault-injecting RPC fabric — the labrpc equivalent.

Multi-node-without-a-cluster: every "server" is an object registered in a
:class:`Network` under a name; every directed client→server edge is a
uniquely named :class:`ClientEnd` that can be individually enabled or
disabled, so partitions are per-edge and asymmetric-capable
(reference: labrpc/labrpc.go:316-364).

Fault model reproduced from ``Network.processReq``
(reference: labrpc/labrpc.go:221-312), on virtual time:

* disabled / unknown server → failure (``None``) after U(0, 100 ms), or
  U(0, 7 s) with ``long_delays`` — emulating a timeout.
* unreliable → U(0, 26 ms) request delay, then 10 % request drop
  (immediate failure), then 10 % reply drop after execution.
* ``long_reordering`` → 2/3 of surviving replies delayed a further
  200–2400 ms.
* replies from a server instance that has been deleted or replaced are
  suppressed (crash-before-reply; reference: labrpc/labrpc.go:267-277).

Unlike the reference there are no goroutines: a call returns a
:class:`~multiraft_tpu.sim.scheduler.Future` resolved by scheduler events,
and all randomness comes from one seeded RNG, so runs are deterministic.

RPC payloads pass through :mod:`multiraft_tpu.transport.codec` both ways,
giving value isolation and honest byte counters
(reference: labrpc/labrpc.go:375-383).
"""

from __future__ import annotations

import random
from collections import defaultdict
from typing import Any, Callable, Dict, Optional

from ..sim.scheduler import Future, Scheduler
from ..utils.config import FaultModel, settings
from ..utils.metrics import Metrics
from . import codec

__all__ = ["Network", "ClientEnd", "Server", "Service"]

# Reliable-mode per-hop latency.  labrpc executes reliable RPCs
# "immediately" on a fresh goroutine; its measured cost is ~22 µs/RPC
# (reference: labrpc/test_test.go:596).  A small nonzero hop keeps
# happened-before ordering visible in virtual time.
RELIABLE_HOP_DELAY = 11e-6


class Service:
    """Dispatch wrapper exposing an object's public methods as RPC handlers
    (reference: labrpc/labrpc.go:481-516, reflection-based dispatch)."""

    def __init__(self, obj: Any, name: Optional[str] = None) -> None:
        self.obj = obj
        self.name = name or type(obj).__name__

    def dispatch(self, method: str, args: Any) -> Any:
        fn = getattr(self.obj, method, None)
        if fn is None or not callable(fn) or method.startswith("_"):
            raise KeyError(
                f"Service.dispatch: unknown method {self.name}.{method}"
            )
        return fn(args)


class Server:
    """A named collection of services (reference: labrpc/labrpc.go:387-443)."""

    def __init__(self) -> None:
        self._services: Dict[str, Service] = {}
        self.rpc_count = 0

    def add_service(self, svc: Service) -> None:
        self._services[svc.name] = svc

    def dispatch(self, svc_meth: str, args: Any) -> Any:
        self.rpc_count += 1
        svc_name, _, method = svc_meth.partition(".")
        svc = self._services.get(svc_name)
        if svc is None:
            raise KeyError(
                f"Server.dispatch: unknown service {svc_name} in {svc_meth}; "
                f"have {sorted(self._services)}"
            )
        return svc.dispatch(method, args)


class ClientEnd:
    """One directed client→server edge (reference: labrpc/labrpc.go:81-126)."""

    def __init__(self, network: "Network", endname: Any) -> None:
        self._network = network
        self.endname = endname

    def call(self, svc_meth: str, args: Any) -> Future:
        """Fire an RPC; the future resolves to the decoded reply, or
        ``None`` on drop/timeout/dead-server — labrpc's ``ok=false``."""
        return self._network._process(self.endname, svc_meth, args)


class Network:
    def __init__(
        self,
        sched: Scheduler,
        seed: int = 0,
        faults: Optional["FaultModel"] = None,
        metrics: Optional[Metrics] = None,
    ) -> None:
        self.sched = sched
        self.rng = random.Random(seed)
        self.reliable = True
        self.long_delays = False
        self.long_reordering = False
        # All fault constants come from the config system's FaultModel
        # (utils/config.py) — one authoritative copy of the labrpc
        # numbers instead of literals scattered through this file.
        self.faults = faults or settings().faults
        # RPC/byte accounting lives in a Metrics registry (shared with
        # the harness, utils/metrics.py); get_total_count()/get_count()
        # read through it.
        self.metrics = metrics or Metrics()
        self._ends: Dict[Any, ClientEnd] = {}
        self._servers: Dict[Any, Optional[Server]] = {}
        self._connections: Dict[Any, Any] = {}  # endname -> servername
        self._enabled: Dict[Any, bool] = {}
        self._count: Dict[Any, int] = defaultdict(int)  # delivered per server
        self._done = False
        # Optional utils.trace.Tracer: every RPC becomes a span
        # (send→resolve) tagged with its outcome; None = zero overhead.
        self.tracer = None

    def _trace_rpc(
        self, endname: Any, svc_meth: str, t0: float, end: float, status: str
    ) -> None:
        if self.tracer is not None:
            self.tracer.span(
                svc_meth,
                t0 * 1e6,
                (end - t0) * 1e6,
                track=str(endname),
                status=status,
            )

    # -- topology ---------------------------------------------------------

    def make_end(self, endname: Any) -> ClientEnd:
        if endname in self._ends:
            raise ValueError(f"make_end: {endname!r} already exists")
        end = ClientEnd(self, endname)
        self._ends[endname] = end
        self._enabled[endname] = False
        self._connections[endname] = None
        return end

    def add_server(self, servername: Any, server: Server) -> None:
        self._servers[servername] = server

    def delete_server(self, servername: Any) -> None:
        """Remove a server; in-flight replies from the old instance are
        suppressed (reference: labrpc/labrpc.go:267-277)."""
        self._servers[servername] = None

    def connect(self, endname: Any, servername: Any) -> None:
        self._connections[endname] = servername

    def enable(self, endname: Any, enabled: bool) -> None:
        self._enabled[endname] = enabled

    def set_reliable(self, yes: bool) -> None:
        self.reliable = yes

    def set_long_delays(self, yes: bool) -> None:
        self.long_delays = yes

    def set_long_reordering(self, yes: bool) -> None:
        self.long_reordering = yes

    def cleanup(self) -> None:
        self._done = True

    # -- statistics (reference: labrpc/labrpc.go:370-383) -----------------

    def get_count(self, servername: Any) -> int:
        return self._count[servername]

    def get_total_count(self) -> int:
        return self.metrics.counters["rpcs_total"]

    def get_total_bytes(self) -> int:
        return self.metrics.counters["bytes_total"]

    # -- the fault model --------------------------------------------------

    def _process(self, endname: Any, svc_meth: str, args: Any) -> Future:
        fut: Future = Future()
        if self._done:
            return fut  # never resolves after Cleanup, like a closed network
        self.metrics.inc("rpcs_total")
        req_bytes = codec.encode(args)
        t0 = self.sched.now

        enabled = self._enabled.get(endname, False)
        servername = self._connections.get(endname)
        server = self._servers.get(servername) if servername is not None else None

        if not enabled or server is None:
            # Simulate no reply and an eventual timeout
            # (reference: labrpc/labrpc.go:296-310).
            if self.long_delays:
                delay = self.rng.uniform(0, self.faults.long_dead_timeout)
            else:
                delay = self.rng.uniform(0, self.faults.dead_timeout)
            self.sched.call_after(delay, fut.resolve, None)
            self._trace_rpc(endname, svc_meth, t0, t0 + delay, "timeout")
            return fut

        delay = RELIABLE_HOP_DELAY
        if not self.reliable:
            # Short delay before the request arrives
            # (reference: labrpc/labrpc.go:228-231).
            delay += self.rng.uniform(0, self.faults.unreliable_delay)
            if self.rng.random() < self.faults.drop_request:
                # Drop the request: caller sees a failure quickly
                # (reference: labrpc/labrpc.go:233-239).
                self.sched.call_after(delay, fut.resolve, None)
                self._trace_rpc(
                    endname, svc_meth, t0, t0 + delay, "drop_request"
                )
                return fut
        self.sched.call_after(
            delay, self._execute, endname, servername, server, svc_meth,
            req_bytes, fut, t0,
        )
        return fut

    def _execute(
        self,
        endname: Any,
        servername: Any,
        server: Server,
        svc_meth: str,
        req_bytes: bytes,
        fut: Future,
        t0: float,
    ) -> None:
        # Fresh decode per delivery: value isolation across the wire.
        if self._servers.get(servername) is not server:
            # Server crashed while the request was in flight
            # (reference: labrpc/labrpc.go:253-265 death polling).
            self._dead_server_reply(fut, endname, svc_meth, t0, "dead_server")
            return
        args = codec.decode(req_bytes)
        self._count[servername] += 1
        self.metrics.inc("bytes_total", len(req_bytes))
        result = server.dispatch(svc_meth, args)
        done = self.sched.spawn(result) if _is_gen(result) else None
        if done is None:
            self._finish(endname, servername, server, result, fut, svc_meth, t0)
        else:
            done.add_done_callback(
                lambda f: self._finish(
                    endname, servername, server, f.value, fut, svc_meth, t0
                )
            )

    def _finish(
        self,
        endname: Any,
        servername: Any,
        server: Server,
        reply: Any,
        fut: Future,
        svc_meth: str,
        t0: float,
    ) -> None:
        if self._servers.get(servername) is not server:
            # DeleteServer() while the handler ran: suppress the reply so a
            # client can't receive an answer from a crashed server
            # (reference: labrpc/labrpc.go:267-277).
            self._dead_server_reply(
                fut, endname, svc_meth, t0, "reply_suppressed"
            )
            return
        reply_bytes = codec.encode(reply)
        if not self.reliable and self.rng.random() < self.faults.drop_reply:
            # Drop the reply (reference: labrpc/labrpc.go:279-284).
            self.sched.call_after(RELIABLE_HOP_DELAY, fut.resolve, None)
            self._trace_rpc(
                endname, svc_meth, t0,
                self.sched.now + RELIABLE_HOP_DELAY, "drop_reply",
            )
            return
        delay = RELIABLE_HOP_DELAY
        if self.long_reordering and self.rng.random() < self.faults.reorder_fraction:
            # Delay the response for a while
            # (reference: labrpc/labrpc.go:285-294).
            lo, hi = self.faults.reorder_delay
            delay += lo + self.rng.uniform(0, hi - lo)
        self.metrics.inc("bytes_total", len(reply_bytes))
        self.sched.call_after(delay, fut.resolve, codec.decode(reply_bytes))
        self._trace_rpc(endname, svc_meth, t0, self.sched.now + delay, "ok")

    def _dead_server_reply(
        self,
        fut: Future,
        endname: Any = None,
        svc_meth: str = "",
        t0: float = 0.0,
        status: str = "dead_server",
    ) -> None:
        delay = self.rng.uniform(0, self.faults.dead_timeout)
        self.sched.call_after(delay, fut.resolve, None)
        if svc_meth:
            self._trace_rpc(endname, svc_meth, t0, self.sched.now + delay, status)


def _is_gen(obj: Any) -> bool:
    import types

    return isinstance(obj, types.GeneratorType)
