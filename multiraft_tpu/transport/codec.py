"""Self-describing wire codec with misuse linting — labgob's equivalent.

The reference wraps ``encoding/gob`` and lints two classes of silent bug:
unexported (non-serialized) struct fields and decoding into a non-default
destination (reference: labgob/labgob.go:68-113, :122-177).

Python's analog of those hazards is different, so the lints are too:

* **Unregistered message types.**  gob requires ``Register`` for interface
  values; we require every *top-level* RPC/persistence payload class to be
  registered so that wire schemas are explicit and typos in message types
  fail fast instead of decoding to garbage.
* **Value isolation.**  gob gives value semantics across the wire; naive
  in-process Python "RPC" would share mutable objects between caller and
  callee.  ``encode``/``decode`` always produce a deep, independent copy,
  so mutating a received message never aliases the sender's state.  (This
  also makes the "decode into non-default value" bug structurally
  impossible: decode always builds a fresh object.)
* **Slot-field coverage.**  If a registered class declares ``__slots__``
  or dataclass fields, encoding an instance with missing attributes warns
  — the closest analog of gob's lower-case-field warning.

Encoding is ``pickle`` under the hood (self-describing, fast, stdlib); the
registry is the schema-checking layer on top.
"""

from __future__ import annotations

import dataclasses
import io
import pickle
import warnings
from typing import Any, Iterable, Type

__all__ = ["register", "registered", "encode", "decode", "CodecError", "wire_size"]


class CodecError(TypeError):
    pass


_REGISTRY: dict[str, Type] = {}
# Primitive payloads allowed without registration (matches gob's built-in
# support for basic kinds).
_PRIMITIVES = (type(None), bool, int, float, str, bytes)


def register(*classes: Type) -> None:
    """Register message/payload classes (labgob.Register equivalent)."""
    for cls in classes:
        _REGISTRY[cls.__qualname__] = cls


def registered(cls: Type) -> Type:
    """Class decorator form of :func:`register`."""
    register(cls)
    return cls


def _check_encodable(obj: Any) -> None:
    if isinstance(obj, _PRIMITIVES):
        return
    if isinstance(obj, (list, tuple, set, frozenset)):
        for item in obj:
            _check_encodable(item)
        return
    if isinstance(obj, dict):
        for k, v in obj.items():
            _check_encodable(k)
            _check_encodable(v)
        return
    cls = type(obj)
    if cls.__qualname__ not in _REGISTRY:
        raise CodecError(
            f"codec: {cls.__qualname__} is not registered; call "
            f"codec.register({cls.__name__}) before sending it on the wire "
            "(labgob.Register equivalent)"
        )
    if dataclasses.is_dataclass(obj):
        missing_ok = not hasattr(obj, "__dict__")  # slotted: trust hasattr
        for field in dataclasses.fields(obj):
            absent = (
                not hasattr(obj, field.name)
                if missing_ok
                else field.name not in obj.__dict__
            )
            if absent:
                warnings.warn(
                    f"codec: {cls.__qualname__}.{field.name} missing at "
                    "encode time; receiver will see a partial message",
                    stacklevel=3,
                )


def encode(obj: Any) -> bytes:
    """Serialize ``obj`` to self-describing bytes, enforcing registration."""
    _check_encodable(obj)
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def decode(data: bytes) -> Any:
    """Deserialize bytes produced by :func:`encode` into a fresh object."""
    return _RestrictedUnpickler(io.BytesIO(data)).load()


def wire_size(obj: Any) -> int:
    """Byte size of ``obj`` on the wire (used by the network's byte
    counters, reference: labrpc/labrpc.go:375-383)."""
    return len(encode(obj))


class _RestrictedUnpickler(pickle.Unpickler):
    """Only resolves registered classes plus stdlib builtins — the decode
    side of the schema check."""

    _ALLOWED_MODULES = {"builtins", "collections"}

    def find_class(self, module: str, name: str) -> Any:
        short = name.rsplit(".", 1)[-1]
        for qualname, cls in _REGISTRY.items():
            if cls.__module__ == module and cls.__qualname__ == name:
                return cls
        if module in self._ALLOWED_MODULES:
            return super().find_class(module, name)
        raise CodecError(
            f"codec: refusing to decode unregistered class {module}.{name}"
        )
