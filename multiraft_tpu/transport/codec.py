"""Self-describing wire codec with misuse linting — labgob's equivalent.

The reference wraps ``encoding/gob`` and lints two classes of silent bug:
unexported (non-serialized) struct fields and decoding into a non-default
destination (reference: labgob/labgob.go:68-113, :122-177).

Python's analog of those hazards is different, so the lints are too:

* **Unregistered message types.**  gob requires ``Register`` for interface
  values; we require every *top-level* RPC/persistence payload class to be
  registered so that wire schemas are explicit and typos in message types
  fail fast instead of decoding to garbage.
* **Value isolation.**  gob gives value semantics across the wire; naive
  in-process Python "RPC" would share mutable objects between caller and
  callee.  ``encode``/``decode`` always produce a deep, independent copy,
  so mutating a received message never aliases the sender's state.  (This
  also makes the "decode into non-default value" bug structurally
  impossible: decode always builds a fresh object.)
* **Slot-field coverage.**  If a registered class declares ``__slots__``
  or dataclass fields, encoding an instance with missing attributes warns
  — the closest analog of gob's lower-case-field warning.

Encoding is ``pickle`` under the hood (self-describing, fast, stdlib); the
registry is the schema-checking layer on top.

Out-of-band fast path
---------------------

:func:`encode_oob` is the zero-copy variant for the serving hot path: it
pickles at protocol 5 with a ``buffer_callback``, so numpy arrays and
large ``bytes`` blobs (wrapped in :class:`pickle.PickleBuffer`) ship as
raw buffer segments instead of being copied into the pickle stream.  The
return value is a list of wire *segments* — ``[header ‖ pickle-bytes,
buffer, buffer, ...]`` — which a vectored transport writes without ever
joining them.  The segments concatenate to one self-describing payload:

    0x01 ‖ u32 nbufs ‖ nbufs × u64 buffer-len ‖ pickle5 ‖ buffers...

A legacy pickle stream always starts with ``0x80`` (the PROTO opcode), so
:func:`decode` dispatches on the first byte and handles both formats.
When a payload yields no out-of-band buffers, :func:`encode_oob`
degrades to a single legacy-format segment — old peers never see the
``0x01`` format unless the caller negotiated it (tcp.py's hello
exchange).  Decode copies every buffer region into a fresh writable
``bytearray`` before handing it to the unpickler, preserving the value-
isolation guarantee: decoded buffers never alias the sender OR the
transport's receive buffer.
"""

from __future__ import annotations

import dataclasses
import io
import pickle
import struct
import warnings
from typing import Any, List, Tuple, Type

try:  # numpy is baked into this image, but the codec must not require it
    import numpy as _np
except Exception:  # pragma: no cover - numpy-less fallback
    _np = None  # type: ignore[assignment]

__all__ = [
    "register",
    "registered",
    "encode",
    "encode_oob",
    "decode",
    "CodecError",
    "wire_size",
]


class CodecError(TypeError):
    pass


_REGISTRY: dict[str, Type] = {}
# Primitive payloads allowed without registration (matches gob's built-in
# support for basic kinds).  bytearray/memoryview join bytes: they are
# pure buffer payloads (the OOB path produces them on decode, so a
# handler echoing one back must stay encodable).
_PRIMITIVES = (type(None), bool, int, float, str, bytes, bytearray, memoryview)

# Per-type registry-validation memo: class → dataclass field-name tuple
# (empty for non-dataclasses).  Registry lookup + dataclasses.fields()
# re-ran on EVERY encode of every frame; payload *types* are a small
# closed set, so one dict hit replaces both.  Presence of a key means
# "registered"; register() invalidates so a type registered after a
# failed encode is picked up.
_CHECK_MEMO: dict[type, Tuple[str, ...]] = {}


def register(*classes: Type) -> None:
    """Register message/payload classes (labgob.Register equivalent)."""
    for cls in classes:
        _REGISTRY[cls.__qualname__] = cls
    _CHECK_MEMO.clear()


def registered(cls: Type) -> Type:
    """Class decorator form of :func:`register`."""
    register(cls)
    return cls


def _check_encodable(obj: Any) -> None:
    if isinstance(obj, _PRIMITIVES):
        return
    if isinstance(obj, (list, tuple, set, frozenset)):
        for item in obj:
            _check_encodable(item)
        return
    if isinstance(obj, dict):
        for k, v in obj.items():
            _check_encodable(k)
            _check_encodable(v)
        return
    cls = type(obj)
    fields = _CHECK_MEMO.get(cls)
    if fields is None:
        if _np is not None and isinstance(obj, _np.ndarray):
            if obj.dtype.hasobject:
                raise CodecError(
                    "codec: object-dtype arrays smuggle arbitrary Python "
                    "objects past the registry; send a registered class "
                    "or a plain-dtype array"
                )
            return  # plain-dtype arrays are buffer payloads, not schemas
        if cls.__qualname__ not in _REGISTRY:
            raise CodecError(
                f"codec: {cls.__qualname__} is not registered; call "
                f"codec.register({cls.__name__}) before sending it on the wire "
                "(labgob.Register equivalent)"
            )
        fields = (
            tuple(f.name for f in dataclasses.fields(obj))
            if dataclasses.is_dataclass(obj)
            else ()
        )
        _CHECK_MEMO[cls] = fields
    if fields:
        missing_ok = not hasattr(obj, "__dict__")  # slotted: trust hasattr
        d = None if missing_ok else obj.__dict__
        for name in fields:
            absent = not hasattr(obj, name) if missing_ok else name not in d
            if absent:
                warnings.warn(
                    f"codec: {cls.__qualname__}.{name} missing at "
                    "encode time; receiver will see a partial message",
                    stacklevel=3,
                )


def encode(obj: Any) -> bytes:
    """Serialize ``obj`` to self-describing bytes, enforcing registration.

    Runs the same buffer rewrite as :func:`encode_oob` but without a
    ``buffer_callback``, so wrapped buffers serialize in-band — one
    self-contained segment, but memoryview payloads (which raw pickle
    rejects) still encode.  Readonly buffers reconstruct as ``bytes``,
    writable ones as ``bytearray``."""
    _check_encodable(obj)
    return pickle.dumps(_wrap_buffers(obj), protocol=pickle.HIGHEST_PROTOCOL)


# -- out-of-band fast path --------------------------------------------------

# Header: format byte ‖ u32 buffer count; then per-buffer u64 lengths.
_OOB_FIRST = 0x01
_OOB_HDR = struct.Struct(">BI")
_OOB_LEN = struct.Struct(">Q")
# bytes blobs below this stay in-band: the PickleBuffer indirection and
# the extra iovec entry cost more than a small memcpy saves.
_OOB_MIN_BYTES = 2048
# Wrap depth: frame tuple → repb pair list → (req_id, value) pairs.
_OOB_DEPTH = 3


def _wrap_buffers(obj: Any, depth: int = _OOB_DEPTH) -> Any:
    """Shallow rebuild of ``obj`` with large bytes wrapped in
    PickleBuffer so protocol 5 ships them out-of-band.  numpy arrays
    need no wrapping (their reducer is already buffer-aware).  Depth-
    bounded: only frame-shaped nesting is rewritten, deep payload
    structure is left to the pickler.

    memoryview is wrapped regardless of size: the pickler cannot
    serialize one raw, and handlers legitimately hold them — OOB decode
    hands out views over the receive-side copy, and echoing a payload
    back is the simplest server.  Without a buffer_callback the wrapper
    serializes in-band and reconstructs as bytes/bytearray, so the same
    rewrite also makes the legacy :func:`encode` path view-safe."""
    if isinstance(obj, bytes) and len(obj) >= _OOB_MIN_BYTES:
        return pickle.PickleBuffer(obj)
    if isinstance(obj, memoryview):
        # PickleBuffer refuses non-contiguous views; flatten those first.
        return pickle.PickleBuffer(obj if obj.contiguous else obj.tobytes())
    if isinstance(obj, bytearray) and len(obj) >= _OOB_MIN_BYTES:
        return pickle.PickleBuffer(obj)
    if depth > 0:
        if type(obj) is tuple:
            return tuple(_wrap_buffers(x, depth - 1) for x in obj)
        if type(obj) is list:
            return [_wrap_buffers(x, depth - 1) for x in obj]
    return obj


def encode_oob(obj: Any) -> List[Any]:
    """Serialize ``obj`` into wire segments whose concatenation is one
    :func:`decode`-able payload, shipping numpy arrays and large bytes
    blobs as raw out-of-band segments (no serialize copy).  Falls back
    to a single legacy-format segment when the payload yields no
    buffers, so callers can use it unconditionally once the peer
    negotiated the format."""
    _check_encodable(obj)
    bufs: List[pickle.PickleBuffer] = []
    # buffer_callback returning a FALSY value is what takes the buffer
    # out-of-band (truthy would serialize it in-band as well) —
    # list.append's None is exactly right.
    pkl = pickle.dumps(
        _wrap_buffers(obj),
        protocol=5,
        buffer_callback=bufs.append,
    )
    if not bufs:
        return [pkl]
    views = []
    lens = bytearray()
    for pb in bufs:
        mv = pb.raw()
        under = getattr(mv, "obj", None)
        if isinstance(under, bytes) and len(under) == mv.nbytes:
            # The buffer IS a whole bytes object — pass it through so
            # the ctypes layer gets a pointer without a view wrapper.
            views.append(under)
        else:
            views.append(mv)
        lens.extend(_OOB_LEN.pack(mv.nbytes))
    if len(views) >= 2 ** 32:
        # The header's buffer count is u32; wrapping it would desync
        # every buffer offset on decode.
        raise CodecError(
            f"codec: payload yields {len(views)} out-of-band buffers; "
            f"the wire header caps the count below {2 ** 32}"
        )
    head = _OOB_HDR.pack(_OOB_FIRST, len(views)) + bytes(lens) + pkl
    return [head, *views]


def decode(data: Any) -> Any:
    """Deserialize bytes produced by :func:`encode` (or a joined
    :func:`encode_oob` segment list) into a fresh object."""
    mv = memoryview(data)
    if mv.nbytes and mv[0] == _OOB_FIRST:
        _, nbufs = _OOB_HDR.unpack_from(mv, 0)
        off = _OOB_HDR.size
        sizes = [
            _OOB_LEN.unpack_from(mv, off + i * _OOB_LEN.size)[0]
            for i in range(nbufs)
        ]
        off += nbufs * _OOB_LEN.size
        tail = sum(sizes)
        pkl = mv[off: mv.nbytes - tail]
        # Fresh writable copies: decoded buffers must never alias the
        # sender's objects or the transport's receive buffer (value
        # isolation), and numpy rebuilds writable arrays over them.
        bufs = []
        boff = mv.nbytes - tail
        for n in sizes:
            bufs.append(bytearray(mv[boff: boff + n]))
            boff += n
        return _RestrictedUnpickler(io.BytesIO(pkl), buffers=bufs).load()
    return _RestrictedUnpickler(io.BytesIO(mv)).load()


def wire_size(obj: Any) -> int:
    """Byte size of ``obj`` on the wire (used by the network's byte
    counters, reference: labrpc/labrpc.go:375-383)."""
    return len(encode(obj))


class _RestrictedUnpickler(pickle.Unpickler):
    """Only resolves registered classes plus stdlib builtins — the decode
    side of the schema check."""

    # numpy's array reconstructors moved between numpy 1.x and 2.x;
    # allow both spellings (find_class sees whichever the encoder's
    # numpy emitted).
    _ALLOWED_MODULES = {
        "builtins",
        "collections",
        "numpy",
        "numpy.core.multiarray",
        "numpy.core.numeric",
        "numpy._core.multiarray",
        "numpy._core.numeric",
    }

    def find_class(self, module: str, name: str) -> Any:
        short = name.rsplit(".", 1)[-1]
        for qualname, cls in _REGISTRY.items():
            if cls.__module__ == module and cls.__qualname__ == name:
                return cls
        if module in self._ALLOWED_MODULES:
            return super().find_class(module, name)
        raise CodecError(
            f"codec: refusing to decode unregistered class {module}.{name}"
        )
