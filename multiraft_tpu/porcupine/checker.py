"""Wing–Gong / Lowe linearizability checker
(reference: porcupine/checker.go:140-353, porcupine/bitset.go).

The algorithm: order call/return events by time into a doubly-linked
list; DFS over "linearize next" choices among currently-pending calls,
memoizing (linearized-set, automaton-state) pairs so revisited frontiers
prune (reference: porcupine/checker.go:140-152 cache,
:159-177 lift/unlift).

Beyond the verdict, the checker can capture **partial linearizations**
(reference: porcupine/checker.go:219-253): for every operation, the
longest linearizable prefix that includes it, recorded at each
backtrack.  On an ILLEGAL or UNKNOWN verdict these show exactly where
linearization got stuck — the visualizer renders them
(:mod:`.visualization`).

Per-partition histories are checked **in parallel** across a process
pool with a shared kill switch (reference: porcupine/checker.go:274-353
checkParallel): the first ILLEGAL partition terminates the remaining
workers when no info is requested, and a wall-clock timeout downgrades
the verdict to UNKNOWN.

The linearized set is a Python int bitmask (arbitrary width — the
bitset.go equivalent); a C++ fast path for the DFS lives in
``multiraft_tpu/porcupine/native`` with this implementation as fallback
and oracle.  Both plain and VERBOSE checks ride the native path for
the KV model (the C++ pass computes verdict and computePartial
evidence together, like the reference's one DFS); the Python DFS runs
when the toolchain is unavailable or a model supplies no native hooks.
"""

from __future__ import annotations

import dataclasses
import multiprocessing
import os
import time as _time
from typing import Any, List, Optional, Tuple

from .model import CheckResult, Model, Operation

__all__ = [
    "check_operations",
    "check_operations_verbose",
    "check_history",
    "LinearizationInfo",
]

# Work thresholds below which the serial path is used (fork + IPC
# overhead dominates tiny checks): auto-parallel needs either this many
# operations across all partitions, or one partition this large.
_PARALLEL_MIN_TOTAL_OPS = 2000
_PARALLEL_MIN_PART_OPS = 300


@dataclasses.dataclass
class LinearizationInfo:
    """Partial-linearization evidence (reference:
    porcupine/checker.go:24-27 linearizationInfo).

    ``partitions[i]`` is the i-th sub-history; ``partials[i]`` is a set
    of distinct partial linearizations for it, each a list of operation
    indices (into ``partitions[i]``) in linearized order.  For an OK
    partition there is exactly one entry: the full linearization.  For
    an ILLEGAL/UNKNOWN partition, each operation's longest prefix that
    linearizes it is included — the visualization's raw material.
    ``verdicts[i]`` is that partition's own verdict, or None if the
    kill switch dropped it before it ran (the visualizer renders those
    neutrally rather than as failures)."""

    partitions: List[List[Operation]]
    partials: List[List[List[int]]]
    verdicts: List[Optional[CheckResult]] = dataclasses.field(
        default_factory=list
    )

    def largest(self, i: int) -> List[int]:
        """The longest partial linearization of partition ``i``."""
        return max(self.partials[i], key=len, default=[])


class _Entry:
    __slots__ = ("op_id", "inp", "out", "is_return", "match", "prev", "next")

    def __init__(self, op_id: int, inp: Any, out: Any, is_return: bool) -> None:
        self.op_id = op_id
        self.inp = inp
        self.out = out
        self.is_return = is_return
        self.match: Optional[_Entry] = None  # return entry, on calls
        self.prev: Optional[_Entry] = None
        self.next: Optional[_Entry] = None


def _make_entries(history: List[Operation]) -> _Entry:
    """Build the time-ordered doubly-linked entry list; returns a dummy
    head.  Ties order calls before returns, so operations touching at a
    single instant count as concurrent (permissive, deterministic)."""
    events: List[Tuple[float, int, int, Operation]] = []
    for i, op in enumerate(history):
        if op.ret < op.call:
            raise ValueError(f"operation {i} returns before it calls")
        events.append((op.call, 0, i, op))
        events.append((op.ret, 1, i, op))
    events.sort(key=lambda e: (e[0], e[1]))

    head = _Entry(-1, None, None, False)
    tail = head
    calls: dict[int, _Entry] = {}
    for t, kind, i, op in events:
        if kind == 0:
            e = _Entry(i, op.input, op.output, is_return=False)
            calls[i] = e
        else:
            e = _Entry(i, op.input, op.output, is_return=True)
            calls[i].match = e
        tail.next = e
        e.prev = tail
        tail = e
    return head


def _lift(call: _Entry) -> None:
    """Remove a call and its return from the list
    (reference: porcupine/checker.go:159-168)."""
    ret = call.match
    call.prev.next = call.next
    if call.next is not None:
        call.next.prev = call.prev
    ret.prev.next = ret.next
    if ret.next is not None:
        ret.next.prev = ret.prev


def _unlift(call: _Entry) -> None:
    """Reinsert a lifted call/return pair
    (reference: porcupine/checker.go:170-177)."""
    ret = call.match
    ret.prev.next = ret
    if ret.next is not None:
        ret.next.prev = ret
    call.prev.next = call
    if call.next is not None:
        call.next.prev = call


def _check_single(
    model: Model,
    history: List[Operation],
    deadline: Optional[float],
    compute_partial: bool = False,
    stats: Optional[dict] = None,
) -> Tuple[CheckResult, List[List[int]]]:
    """DFS over one partition (reference: porcupine/checker.go:179-253).

    Returns ``(verdict, partials)``; ``partials`` is non-empty only
    when ``compute_partial`` — the distinct longest linearizable
    prefixes covering each operation (recorded at every backtrack), or
    the single full linearization on OK.  ``stats`` (optional dict)
    receives ``{"steps": N}`` — the speed-ratio diagnostics compare it
    against the native DFS's step counter."""
    if not history:
        return CheckResult.OK, ([[]] if compute_partial else [])
    head = _make_entries(history)
    n = len(history)
    linearized = 0
    cache: set = set()
    calls: List[Tuple[_Entry, Any]] = []
    # Longest linearizable prefix that includes each op, as a shared
    # list (identity-deduplicated at the end) — the lazy-seq trick of
    # the reference (checker.go:219-234).
    longest: List[Optional[List[int]]] = [None] * n
    state = model.init()
    entry = head.next
    steps = 0
    verdict: Optional[CheckResult] = None
    while head.next is not None:
        steps += 1
        if deadline is not None and steps % 4096 == 0:
            if _time.monotonic() > deadline:
                verdict = CheckResult.UNKNOWN
                break
        if not entry.is_return:
            ok, new_state = model.step(state, entry.inp, entry.out)
            advanced = False
            if ok:
                new_linearized = linearized | (1 << entry.op_id)
                key = (new_linearized, model.key_of(new_state))
                if key not in cache:
                    cache.add(key)
                    calls.append((entry, state))
                    state = new_state
                    linearized = new_linearized
                    _lift(entry)
                    entry = head.next
                    advanced = True
            if not advanced:
                entry = entry.next
        else:
            # A return with no linearizable choice above it: backtrack
            # (reference: porcupine/checker.go:231-246).
            if not calls:
                verdict = CheckResult.ILLEGAL
                break
            if compute_partial:
                seq: Optional[List[int]] = None
                for e, _ in calls:
                    cur = longest[e.op_id]
                    if cur is None or len(calls) > len(cur):
                        if seq is None:
                            seq = [c.op_id for c, _ in calls]
                        longest[e.op_id] = seq
            top, state = calls.pop()
            linearized &= ~(1 << top.op_id)
            _unlift(top)
            entry = top.next
    if stats is not None:
        stats["steps"] = steps
    if verdict is None:
        verdict = CheckResult.OK
    if (
        compute_partial
        and verdict is CheckResult.UNKNOWN
        and calls
    ):
        # Timeout mid-descent: the live stack is a linearizable prefix
        # no backtrack recorded — capture it so the evidence is never
        # empty for exactly the runs verbose mode exists to debug
        # (mirrored by the native DFS).
        seq = None
        for e, _ in calls:
            cur = longest[e.op_id]
            if cur is None or len(calls) > len(cur):
                if seq is None:
                    seq = [c.op_id for c, _ in calls]
                longest[e.op_id] = seq
    partials: List[List[int]] = []
    if compute_partial:
        if verdict is CheckResult.OK:
            partials = [[c.op_id for c, _ in calls]]
        else:
            uniq: dict[int, List[int]] = {}
            for seq in longest:
                if seq is not None:
                    uniq[id(seq)] = seq
            partials = list(uniq.values())
    return verdict, partials


# -- model-generic native DFS (reference contract: model.go:5-49) ----------

def _native_generic(
    model: Model,
    part: List[Operation],
    deadline: Optional[float],
    compute_partial: bool,
) -> Optional[Tuple[CheckResult, List[List[int]]]]:
    """Run one partition through the model-GENERIC C++ DFS: the search
    (entry list, lift/unlift, set×state memo) runs compiled; the
    model's own ``step`` is consulted through a callback once per
    DISTINCT (state, op) pair — the C++ side memoizes transitions over
    integer state ids, so an exponential DFS pays Python cost only
    linear in the reachable transition graph.  Returns None (caller
    falls back to the Python DFS) when the toolchain is unavailable,
    the history is malformed (the Python entry builder raises the
    proper error), or the model callback itself raised.
    """
    from .native import (
        check_generic_partition_native,
        check_generic_partition_native_verbose,
    )

    if not part:
        return None  # _check_single owns the empty-history convention
    if any(op.ret < op.call for op in part):
        return None
    if deadline is None:
        max_steps, max_wall = 0, 0.0
    else:
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            return CheckResult.UNKNOWN, []
        max_steps, max_wall = 0, remaining  # wall clock is the budget
    events: List[Tuple[float, int, int]] = []
    for i, op in enumerate(part):
        events.append((op.call, 0, i))
        events.append((op.ret, 1, i))
    events.sort(key=lambda e: (e[0], e[1]))
    ev = [(i, bool(kind)) for _, kind, i in events]

    # Caller-owned automaton state ids: 0 is the initial state; new
    # states are interned by their ``key_of`` (the same hashability
    # contract the Python DFS's memo already imposes).
    states: List[Any] = [model.init()]
    ids: dict = {model.key_of(states[0]): 0}
    errors: List[BaseException] = []

    def step_cb(sid: int, op_id: int, out_ptr) -> int:
        try:
            op = part[op_id]
            ok, new_state = model.step(states[sid], op.input, op.output)
            if not ok:
                return 0
            key = model.key_of(new_state)
            nid = ids.get(key)
            if nid is None:
                nid = len(states)
                if nid > 0x7FFFFFFF:  # pragma: no cover - absurd history
                    raise OverflowError("state id space exhausted")
                states.append(new_state)
                ids[key] = nid
            out_ptr[0] = nid
            return 1
        except BaseException as e:  # must not unwind through C
            errors.append(e)
            return -1

    if compute_partial:
        out = check_generic_partition_native_verbose(
            ev, len(part), step_cb, max_steps=max_steps, max_wall_s=max_wall
        )
        if out is None:
            return None
        rc, partials, _steps = out
    else:
        out = check_generic_partition_native(
            ev, len(part), step_cb, max_steps=max_steps, max_wall_s=max_wall
        )
        if out is None:
            return None
        rc, _steps = out
        partials = []
    if errors and not isinstance(errors[0], Exception):
        raise errors[0]  # KeyboardInterrupt/SystemExit must propagate
    if rc == 3 or errors:
        return None  # re-run in Python so the model's exception surfaces
    return (
        {0: CheckResult.ILLEGAL, 1: CheckResult.OK, 2: CheckResult.UNKNOWN}[rc],
        partials,
    )


# -- parallel partition checking (reference: checker.go:274-353) -----------

# Budgets for re-confirming a native ILLEGAL verdict with the exact
# Python checker (see _worker).  Small partitions — the only place a
# Zobrist collision plausibly flips a verdict — re-check well inside
# these; a huge provably-illegal history keeps the native verdict
# instead of paying the Python DFS's constant factor for nothing.
_CONFIRM_BUDGET_S = 5.0
_CONFIRM_MAX_OPS = 2000


def _worker(
    args: Tuple[int, Model, List[Operation], Optional[float], bool],
) -> Tuple[int, CheckResult, List[List[int]]]:
    idx, model, part, remaining, compute_partial = args
    deadline = _time.monotonic() + remaining if remaining is not None else None
    res = None
    partials: List[List[int]] = []
    native = False
    if compute_partial and model.native_check_verbose is not None:
        out = model.native_check_verbose(part, deadline)
        if out is not None:
            res, partials = out
            native = True
    elif model.native_check is not None and not compute_partial:
        res = model.native_check(part, deadline)
        native = res is not None
    if res is None and model.native_generic and (
        model.native_check is None or compute_partial
    ):
        out = _native_generic(model, part, deadline, compute_partial)
        if out is not None:
            res, partials = out
            native = True
    if (
        native
        and res is CheckResult.ILLEGAL
        and len(part) <= _CONFIRM_MAX_OPS
    ):
        # The native DFS memoizes visited (linearized-set, state) pairs
        # by a 128-bit Zobrist hash with no exact confirmation, so a
        # hash collision can prune a branch that actually linearizes
        # and yield a *false* ILLEGAL (probability ~2^-128 per pair,
        # but ILLEGAL is the verdict tests fail on).  Confirm with the
        # exact-memo Python checker before letting it stand; only an
        # UNKNOWN (budget hit) re-run keeps the native verdict.  The
        # confirmation gets its own small budget: it costs nothing on
        # passing histories (never triggers), catches the realistic
        # collision case (small partitions re-check in milliseconds),
        # and huge already-failing histories don't pay the Python
        # DFS's constant factor.  See docs/ARCHITECTURE.md §8.
        confirm = _time.monotonic() + _CONFIRM_BUDGET_S
        if deadline is not None:
            confirm = min(confirm, deadline)
        res2, partials2 = _check_single(
            model, part, confirm, compute_partial
        )
        if res2 is not CheckResult.UNKNOWN:
            res, partials = res2, partials2
    if res is None:
        res, partials = _check_single(model, part, deadline, compute_partial)
    return idx, res, partials


def _fork_safe() -> bool:
    """Whether auto-parallel may use a fork pool: fork must exist on
    this platform, and the process must not carry the multithreaded
    JAX/XLA runtime (forking a threaded runtime can deadlock the
    children; JAX documents fork as unsupported).  Explicit
    ``parallel=True`` overrides — the caller owns that risk."""
    import sys

    try:
        multiprocessing.get_context("fork")
    except ValueError:  # pragma: no cover - non-POSIX
        return False
    return "jax" not in sys.modules


def _check_partitions(
    model: Model,
    parts: List[List[Operation]],
    deadline: Optional[float],
    compute_partial: bool,
    parallel: Optional[bool],
) -> Tuple[CheckResult, List[List[List[int]]], List[Optional[CheckResult]]]:
    """Fan the per-partition DFS across a process pool (the Python
    analog of checkParallel's goroutines + atomic kill,
    reference: porcupine/checker.go:274-353).  Without
    ``compute_partial``, the first ILLEGAL terminates the pool — the
    kill switch.  With it, all partitions run to completion so every
    partial is collected (the reference waits likewise).  Also returns
    each partition's own verdict (None where the kill switch dropped
    it before it ran)."""
    if parallel is None:
        total_ops = sum(len(p) for p in parts)
        parallel = (
            len(parts) >= 2
            and (
                total_ops >= _PARALLEL_MIN_TOTAL_OPS
                or max(len(p) for p in parts) >= _PARALLEL_MIN_PART_OPS
            )
            and (os.cpu_count() or 1) > 1
            and _fork_safe()
        )
    all_partials: List[List[List[int]]] = [[] for _ in parts]
    verdicts: List[Optional[CheckResult]] = [None] * len(parts)

    def remaining() -> Optional[float]:
        return None if deadline is None else deadline - _time.monotonic()

    if not parallel:
        illegal = False
        unknown = False
        for i, part in enumerate(parts):
            rem = remaining()
            if rem is not None and rem <= 0:
                unknown = True
                break
            _, res, partials = _worker(
                (i, model, part, rem, compute_partial)
            )
            all_partials[i] = partials
            verdicts[i] = res
            if res is CheckResult.ILLEGAL:
                illegal = True
                if not compute_partial:
                    break  # kill switch: evidence not requested
            elif res is CheckResult.UNKNOWN:
                unknown = True
        if illegal:
            return CheckResult.ILLEGAL, all_partials, verdicts
        return (
            CheckResult.UNKNOWN if unknown else CheckResult.OK
        ), all_partials, verdicts

    ctx = multiprocessing.get_context("fork")
    nproc = min(len(parts), os.cpu_count() or 2)
    illegal = False
    unknown = False
    with ctx.Pool(processes=nproc) as pool:
        jobs = [
            (i, model, part, remaining(), compute_partial)
            for i, part in enumerate(parts)
        ]
        it = pool.imap_unordered(_worker, jobs)
        done = 0
        while done < len(parts):
            rem = remaining()
            try:
                idx, res, partials = it.next(timeout=rem)
            except multiprocessing.TimeoutError:
                unknown = True
                pool.terminate()  # shared kill switch: drop the rest
                break
            except StopIteration:  # pragma: no cover - defensive
                break
            done += 1
            all_partials[idx] = partials
            verdicts[idx] = res
            if res is CheckResult.ILLEGAL:
                illegal = True
                if not compute_partial:
                    pool.terminate()  # kill switch on first failure
                    break
            elif res is CheckResult.UNKNOWN:
                unknown = True
    if illegal:
        return CheckResult.ILLEGAL, all_partials, verdicts
    return (
        CheckResult.UNKNOWN if unknown else CheckResult.OK
    ), all_partials, verdicts


def check_operations(
    model: Model,
    history: List[Operation],
    timeout: Optional[float] = None,
    parallel: Optional[bool] = None,
) -> CheckResult:
    """Check a full history, partitioned per the model
    (reference: porcupine/porcupine.go CheckOperationsTimeout).

    ``timeout`` is wall-clock seconds across all partitions; on expiry
    the result is UNKNOWN (the reference's convention, treated by the
    test suite as "probably fine, too expensive to prove",
    kvraft/test_test.go:379-381).  ``parallel`` forces the process-pool
    path on/off (default: auto — pools kick in for ≥2 partitions once
    the op counts clear the thresholds in ``_check_parallel``, on
    fork-safe multi-core hosts)."""
    deadline = _time.monotonic() + timeout if timeout is not None else None
    verdict, _, _ = _check_partitions(
        model, model.partitions(history), deadline, False, parallel
    )
    return verdict


def check_operations_verbose(
    model: Model,
    history: List[Operation],
    timeout: Optional[float] = None,
    parallel: Optional[bool] = None,
) -> Tuple[CheckResult, LinearizationInfo]:
    """Check and return partial-linearization evidence
    (reference: porcupine/porcupine.go:19-27 CheckOperationsVerbose).
    Pass the info to :func:`multiraft_tpu.porcupine.visualize` to
    render where linearization got stuck."""
    deadline = _time.monotonic() + timeout if timeout is not None else None
    parts = model.partitions(history)
    verdict, partials, verdicts = _check_partitions(
        model, parts, deadline, True, parallel
    )
    return verdict, LinearizationInfo(
        partitions=parts, partials=partials, verdicts=verdicts
    )


def check_history(model: Model, history: List[Operation]) -> bool:
    """Convenience: True iff linearizable (UNKNOWN counts as True)."""
    return check_operations(model, history) is not CheckResult.ILLEGAL
