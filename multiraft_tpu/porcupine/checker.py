"""Wing–Gong / Lowe linearizability checker
(reference: porcupine/checker.go:140-353, porcupine/bitset.go).

The algorithm: order call/return events by time into a doubly-linked
list; DFS over "linearize next" choices among currently-pending calls,
memoizing (linearized-set, automaton-state) pairs so revisited frontiers
prune (reference: porcupine/checker.go:140-152 cache,
:159-177 lift/unlift).  Per-partition histories are checked
independently with a shared kill switch
(reference: porcupine/checker.go:274-353 checkParallel).

The linearized set is a Python int bitmask (arbitrary width — the
bitset.go equivalent); a C++ fast path for the DFS lives in
``multiraft_tpu/porcupine/native`` with this implementation as fallback
and oracle.
"""

from __future__ import annotations

import time as _time
from typing import Any, List, Optional, Tuple

from .model import CheckResult, Model, Operation

__all__ = ["check_operations", "check_history"]


class _Entry:
    __slots__ = ("op_id", "inp", "out", "is_return", "match", "prev", "next")

    def __init__(self, op_id: int, inp: Any, out: Any, is_return: bool) -> None:
        self.op_id = op_id
        self.inp = inp
        self.out = out
        self.is_return = is_return
        self.match: Optional[_Entry] = None  # return entry, on calls
        self.prev: Optional[_Entry] = None
        self.next: Optional[_Entry] = None


def _make_entries(history: List[Operation]) -> _Entry:
    """Build the time-ordered doubly-linked entry list; returns a dummy
    head.  Ties order calls before returns, so operations touching at a
    single instant count as concurrent (permissive, deterministic)."""
    events: List[Tuple[float, int, int, Operation]] = []
    for i, op in enumerate(history):
        if op.ret < op.call:
            raise ValueError(f"operation {i} returns before it calls")
        events.append((op.call, 0, i, op))
        events.append((op.ret, 1, i, op))
    events.sort(key=lambda e: (e[0], e[1]))

    head = _Entry(-1, None, None, False)
    tail = head
    calls: dict[int, _Entry] = {}
    for t, kind, i, op in events:
        if kind == 0:
            e = _Entry(i, op.input, op.output, is_return=False)
            calls[i] = e
        else:
            e = _Entry(i, op.input, op.output, is_return=True)
            calls[i].match = e
        tail.next = e
        e.prev = tail
        tail = e
    return head


def _lift(call: _Entry) -> None:
    """Remove a call and its return from the list
    (reference: porcupine/checker.go:159-168)."""
    ret = call.match
    call.prev.next = call.next
    if call.next is not None:
        call.next.prev = call.prev
    ret.prev.next = ret.next
    if ret.next is not None:
        ret.next.prev = ret.prev


def _unlift(call: _Entry) -> None:
    """Reinsert a lifted call/return pair
    (reference: porcupine/checker.go:170-177)."""
    ret = call.match
    ret.prev.next = ret
    if ret.next is not None:
        ret.next.prev = ret
    call.prev.next = call
    if call.next is not None:
        call.next.prev = call


def _check_single(
    model: Model,
    history: List[Operation],
    deadline: Optional[float],
) -> CheckResult:
    """DFS over one partition (reference: porcupine/checker.go:179-253)."""
    if not history:
        return CheckResult.OK
    head = _make_entries(history)
    n = len(history)
    linearized = 0
    cache: set = set()
    calls: List[Tuple[_Entry, Any]] = []
    state = model.init()
    entry = head.next
    steps = 0
    while head.next is not None:
        steps += 1
        if deadline is not None and steps % 4096 == 0:
            if _time.monotonic() > deadline:
                return CheckResult.UNKNOWN
        if not entry.is_return:
            ok, new_state = model.step(state, entry.inp, entry.out)
            advanced = False
            if ok:
                new_linearized = linearized | (1 << entry.op_id)
                key = (new_linearized, model.key_of(new_state))
                if key not in cache:
                    cache.add(key)
                    calls.append((entry, state))
                    state = new_state
                    linearized = new_linearized
                    _lift(entry)
                    entry = head.next
                    advanced = True
            if not advanced:
                entry = entry.next
        else:
            # A return with no linearizable choice above it: backtrack
            # (reference: porcupine/checker.go:231-246).
            if not calls:
                return CheckResult.ILLEGAL
            top, state = calls.pop()
            linearized &= ~(1 << top.op_id)
            _unlift(top)
            entry = top.next
    return CheckResult.OK


def check_operations(
    model: Model,
    history: List[Operation],
    timeout: Optional[float] = None,
) -> CheckResult:
    """Check a full history, partitioned per the model
    (reference: porcupine/porcupine.go CheckOperationsTimeout).

    ``timeout`` is wall-clock seconds across all partitions; on expiry
    the result is UNKNOWN (the reference's convention, treated by the
    test suite as "probably fine, too expensive to prove",
    kvraft/test_test.go:379-381)."""
    deadline = _time.monotonic() + timeout if timeout is not None else None
    unknown = False
    for part in model.partitions(history):
        if deadline is not None and _time.monotonic() > deadline:
            unknown = True
            break
        res = None
        if model.native_check is not None:
            res = model.native_check(part, deadline)
        if res is None:
            res = _check_single(model, part, deadline)
        if res is CheckResult.ILLEGAL:
            return CheckResult.ILLEGAL
        if res is CheckResult.UNKNOWN:
            unknown = True
    return CheckResult.UNKNOWN if unknown else CheckResult.OK


def check_history(model: Model, history: List[Operation]) -> bool:
    """Convenience: True iff linearizable (UNKNOWN counts as True)."""
    return check_operations(model, history) is not CheckResult.ILLEGAL
