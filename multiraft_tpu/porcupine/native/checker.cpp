// Native linearizability DFS — the single-thread hot loop the survey
// flags as "keep compiled" (SURVEY §2 #22).
//
// Same Wing–Gong/Lowe algorithm as the Python fallback
// (multiraft_tpu/porcupine/checker.py; reference: porcupine/checker.go:
// 140-253): doubly-linked entry list, lift/unlift, (linearized-set,
// state) memoization, and — in the verbose entry point — the
// reference's computePartial (checker.go:219-234): the distinct
// longest linearizable prefixes covering each operation, captured at
// every backtrack, for the visualizer.
//
// Specialised to the KV per-key partition model (reference:
// models/kv.go:40-54) where a partition's automaton state is the
// key's current string value.  Two representation choices make this
// scale to 100k-op partitions where the generic formulation cannot:
//
//  * The PATH state is one growable byte buffer with per-frame undo
//    (append saves a length; put saves the replaced value), so the
//    current value is always exact — Get compares bytes, never a
//    hash.
//  * The MEMO stores a 128-bit hash of (linearized-set, value): TWO
//    independent Zobrist hashes over op-ids (one xor each per step)
//    mixed with two independent polynomial hashes of the value — the
//    two words share no state, so the collision bound is a genuine
//    ~2^-128 per explored pair.  Memory per memo entry is O(1)
//    instead of O(|value|); a collision could only over-prune (flip
//    a true OK to ILLEGAL) — negligible odds, and the failure mode
//    is loud (a spurious ILLEGAL gets investigated), never a silent
//    pass.
//
// Exposed via a C ABI for ctypes (no pybind11 in this image).
// Return codes: 1 = linearizable, 0 = not, 2 = budget exhausted
// (UNKNOWN).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Entry {
  int op;          // operation id, -1 for head
  bool is_return;
  Entry* match;    // call -> its return
  Entry* prev;
  Entry* next;
};

// Operation kinds (must match porcupine/kv.py).
constexpr int kGet = 0;
constexpr int kPut = 1;
constexpr int kAppend = 2;

constexpr uint64_t kP1 = 0x100000001b3ull;        // poly bases (odd)
constexpr uint64_t kP2 = 0xda942042e4dd58b5ull;

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

struct Key128 {
  uint64_t a, b;
  bool operator==(const Key128& o) const { return a == o.a && b == o.b; }
};
struct Key128Hash {
  size_t operator()(const Key128& k) const {
    return static_cast<size_t>(k.a ^ (k.b * 0x9e3779b97f4a7c15ull));
  }
};

// Polynomial hash helpers over byte strings.
inline void poly_absorb(uint64_t& h1, uint64_t& h2, const uint8_t* p,
                        int32_t len) {
  for (int32_t i = 0; i < len; i++) {
    h1 = h1 * kP1 + p[i] + 1;
    h2 = h2 * kP2 + p[i] + 1;
  }
}

struct Checker {
  int32_t n;
  const int32_t* op_kind;
  const uint8_t* const* op_value;
  const int32_t* op_value_len;
  const uint8_t* const* op_output;
  const int32_t* op_output_len;

  std::vector<Entry> pool;
  Entry* head;

  // Exact current value + per-frame undo.
  std::string cur;
  uint64_t vh1 = 0, vh2 = 0;  // incremental value hash
  uint64_t zob = 0, zob2 = 0; // independent Zobrist set hashes
  std::vector<uint64_t> zkeys, zkeys2;

  struct Frame {
    Entry* call;
    uint8_t kind;          // op kind (undo discriminator)
    uint32_t old_len;      // append undo
    std::string old_value; // put undo (the replaced value)
    uint64_t old_vh1, old_vh2;
  };
  std::vector<Frame> stack;
  std::unordered_set<Key128, Key128Hash> memo;

  void build(const int32_t* ev_op, const uint8_t* ev_is_ret) {
    const int64_t n_events = 2 * static_cast<int64_t>(n);
    pool.resize(n_events + 1);
    std::vector<Entry*> call_of(n, nullptr);
    head = &pool[0];
    head->op = -1;
    head->is_return = false;
    head->prev = nullptr;
    Entry* tail = head;
    for (int64_t i = 0; i < n_events; i++) {
      Entry* e = &pool[i + 1];
      e->op = ev_op[i];
      e->is_return = ev_is_ret[i] != 0;
      e->match = nullptr;
      if (!e->is_return) {
        call_of[e->op] = e;
      } else {
        call_of[e->op]->match = e;
      }
      tail->next = e;
      e->prev = tail;
      tail = e;
    }
    tail->next = nullptr;
    zkeys.resize(n);
    zkeys2.resize(n);
    for (int32_t i = 0; i < n; i++) {
      zkeys[i] = splitmix64(0xC0FFEE ^ i);
      zkeys2[i] = splitmix64(0xB00B1E5ull + 0x9E37ull * i);
    }
    stack.reserve(n);
  }

  static void lift(Entry* call) {
    Entry* ret = call->match;
    call->prev->next = call->next;
    if (call->next) call->next->prev = call->prev;
    ret->prev->next = ret->next;
    if (ret->next) ret->next->prev = ret->prev;
  }
  static void unlift(Entry* call) {
    Entry* ret = call->match;
    ret->prev->next = ret;
    if (ret->next) ret->next->prev = ret;
    call->prev->next = call;
    if (call->next) call->next->prev = call;
  }

  // Try to linearize `op` next: returns whether the model step is
  // legal, and (on true) fills the would-be post-state hash WITHOUT
  // mutating, so the memo can be consulted first.
  bool step_ok(int op, uint64_t& nvh1, uint64_t& nvh2) const {
    switch (op_kind[op]) {
      case kGet: {
        const int32_t olen = op_output_len[op];
        if (static_cast<size_t>(olen) != cur.size()) return false;
        if (olen && std::memcmp(op_output[op], cur.data(), olen) != 0)
          return false;
        nvh1 = vh1;
        nvh2 = vh2;
        return true;
      }
      case kPut: {
        nvh1 = 0;
        nvh2 = 0;
        poly_absorb(nvh1, nvh2, op_value[op], op_value_len[op]);
        return true;
      }
      case kAppend: {
        nvh1 = vh1;
        nvh2 = vh2;
        poly_absorb(nvh1, nvh2, op_value[op], op_value_len[op]);
        return true;
      }
      default:
        return false;
    }
  }

  void apply(Entry* call, uint64_t nvh1, uint64_t nvh2) {
    const int op = call->op;
    Frame f;
    f.call = call;
    f.kind = static_cast<uint8_t>(op_kind[op]);
    f.old_vh1 = vh1;
    f.old_vh2 = vh2;
    f.old_len = static_cast<uint32_t>(cur.size());
    if (f.kind == kPut) {
      f.old_value.swap(cur);
      cur.assign(reinterpret_cast<const char*>(op_value[op]),
                 op_value_len[op]);
    } else if (f.kind == kAppend) {
      cur.append(reinterpret_cast<const char*>(op_value[op]),
                 op_value_len[op]);
    }
    vh1 = nvh1;
    vh2 = nvh2;
    zob ^= zkeys[op];
    zob2 ^= zkeys2[op];
    stack.push_back(std::move(f));
    lift(call);
  }

  Entry* backtrack() {
    Frame& f = stack.back();
    if (f.kind == kPut) {
      cur.swap(f.old_value);
    } else if (f.kind == kAppend) {
      cur.resize(f.old_len);
    }
    vh1 = f.old_vh1;
    vh2 = f.old_vh2;
    zob ^= zkeys[f.call->op];
    zob2 ^= zkeys2[f.call->op];
    unlift(f.call);
    Entry* resume = f.call->next;
    stack.pop_back();
    return resume;
  }

  Key128 memo_key(uint64_t nzob, uint64_t nzob2, uint64_t nvh1,
                  uint64_t nvh2) const {
    // Two fully independent 64-bit words (separate Zobrist tables,
    // separate polynomial bases) — a real 128-bit collision bound.
    return Key128{splitmix64(nzob ^ nvh1), splitmix64(nzob2 ^ nvh2)};
  }
};

inline double mono_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

// computePartial capture (reference: porcupine/checker.go:219-234):
// record the current DFS stack as the longest linearizable prefix for
// every op on it that lacks a longer one, sharing one materialized
// sequence per call (the reference's lazy-seq trick).  Works over both
// checkers' Frame types (each has `call`); used at every backtrack and
// for the live stack when a budget expires mid-descent.
template <typename Stack>
void capture_partials(const Stack& stack, std::vector<int32_t>& longest,
                      std::vector<std::vector<int32_t>>& seqs) {
  int32_t seq_idx = -1;
  const size_t depth = stack.size();
  for (const auto& f : stack) {
    const int op = f.call->op;
    if (longest[op] < 0 || seqs[longest[op]].size() < depth) {
      if (seq_idx < 0) {
        std::vector<int32_t> s;
        s.reserve(depth);
        for (const auto& g : stack) s.push_back(g.call->op);
        seqs.push_back(std::move(s));
        seq_idx = static_cast<int32_t>(seqs.size()) - 1;
      }
      longest[op] = seq_idx;
    }
  }
}

// Flatten the partial linearizations into the caller-freed int32 buffer
// [n_seqs, len_0, ops_0..., len_1, ops_1...].  On OK the single full
// linearization (the final stack) is emitted; otherwise the identity-
// distinct longest prefixes in FIRST-REFERENCING-OP order — exactly the
// Python oracle's insertion-ordered dedup, so native and fallback
// produce identical evidence.
template <typename Stack>
void emit_partials(int verdict, const Stack& stack, int32_t n,
                   const std::vector<int32_t>& longest,
                   const std::vector<std::vector<int32_t>>& seqs,
                   int32_t** out_buf, int64_t* out_len) {
  std::vector<int32_t> full;
  std::vector<const std::vector<int32_t>*> outs;
  if (verdict == 1) {
    for (const auto& f : stack) full.push_back(f.call->op);
    outs.push_back(&full);
  } else {
    std::vector<char> emitted(seqs.size(), 0);
    for (int32_t i = 0; i < n; i++) {
      const int32_t s = longest[i];
      if (s >= 0 && !emitted[s]) {
        emitted[s] = 1;
        outs.push_back(&seqs[s]);
      }
    }
  }
  int64_t total = 1;
  for (const auto* s : outs) total += 1 + static_cast<int64_t>(s->size());
  int32_t* buf =
      static_cast<int32_t*>(std::malloc(total * sizeof(int32_t)));
  if (buf == nullptr) return;  // partials dropped, verdict kept
  int64_t w = 0;
  buf[w++] = static_cast<int32_t>(outs.size());
  for (const auto* s : outs) {
    buf[w++] = static_cast<int32_t>(s->size());
    for (int32_t v : *s) buf[w++] = v;
  }
  *out_buf = buf;
  *out_len = w;
}

}  // namespace

extern "C" {

// ops laid out as parallel arrays of length n, events pre-sorted by the
// caller (Python) into a single interleaved sequence of 2n event slots:
//   ev_op[i]     — operation id of event i
//   ev_is_ret[i] — 0 call, 1 return
// op_kind[j], op_value/op_value_len [j] — the put/append argument utf-8
// op_output/op_output_len [j]           — get's observed value
// max_steps — DFS step budget (0 = unlimited)
//
// Verbose form additionally returns the partial linearizations
// (reference computePartial): *out_buf = int32 array
// [n_seqs, len_0, ops_0..., len_1, ops_1...] (caller frees via
// mrt_buf_free).  On OK the single full linearization is returned.
static int check_impl(
    int32_t n,
    const int32_t* ev_op,
    const uint8_t* ev_is_ret,
    const int32_t* op_kind,
    const uint8_t* const* op_value,
    const int32_t* op_value_len,
    const uint8_t* const* op_output,
    const int32_t* op_output_len,
    int64_t max_steps,
    double max_wall_s,
    bool compute_partial,
    int32_t** out_buf,
    int64_t* out_len) {
  if (out_buf) {
    *out_buf = nullptr;
    *out_len = 0;
  }
  if (n == 0) return 1;

  Checker c;
  c.n = n;
  c.op_kind = op_kind;
  c.op_value = op_value;
  c.op_value_len = op_value_len;
  c.op_output = op_output;
  c.op_output_len = op_output_len;
  c.build(ev_op, ev_is_ret);

  // computePartial bookkeeping: longest[op] = index into `seqs` of the
  // longest linearizable prefix covering op (shared, lazily
  // materialized per backtrack — the reference's lazy-seq trick).
  std::vector<int32_t> longest;
  std::vector<std::vector<int32_t>> seqs;
  if (compute_partial) longest.assign(n, -1);

  // Wall-clock deadline checked every 8192 steps — the step budget
  // alone under-counts verbose mode (each backtrack's computePartial
  // capture is O(stack depth)), and the timeout-as-UNKNOWN convention
  // must bound WALL time (Python DFS parity: checker.py's
  // steps % 4096 check).
  const double wall_deadline =
      max_wall_s > 0 ? mono_s() + max_wall_s : 0.0;
  Entry* entry = c.head->next;
  int64_t steps = 0;
  int verdict = -1;
  while (c.head->next != nullptr) {
    ++steps;
    if (max_steps > 0 && steps > max_steps) {
      verdict = 2;
      break;
    }
    if (wall_deadline > 0 && (steps & 8191) == 0 &&
        mono_s() > wall_deadline) {
      verdict = 2;
      break;
    }
    if (!entry->is_return) {
      uint64_t nvh1, nvh2;
      bool advanced = false;
      if (c.step_ok(entry->op, nvh1, nvh2)) {
        const uint64_t nzob = c.zob ^ c.zkeys[entry->op];
        const uint64_t nzob2 = c.zob2 ^ c.zkeys2[entry->op];
        if (c.memo.insert(c.memo_key(nzob, nzob2, nvh1, nvh2)).second) {
          c.apply(entry, nvh1, nvh2);
          entry = c.head->next;
          advanced = true;
        }
      }
      if (!advanced) entry = entry->next;
    } else {
      if (c.stack.empty()) {
        verdict = 0;
        break;
      }
      if (compute_partial) capture_partials(c.stack, longest, seqs);
      entry = c.backtrack();
    }
  }
  if (verdict < 0) verdict = 1;

  if (compute_partial && verdict == 2 && !c.stack.empty()) {
    // Budget/deadline expired mid-descent: the LIVE stack is a
    // linearizable prefix no backtrack recorded yet — capture it so
    // the evidence is never empty for exactly the runs verbose mode
    // exists to debug.
    capture_partials(c.stack, longest, seqs);
  }

  if (compute_partial && out_buf) {
    emit_partials(verdict, c.stack, n, longest, seqs, out_buf, out_len);
  }
  return verdict;
}

// ---------------------------------------------------------------------------
// Model-GENERIC DFS (reference contract: porcupine/model.go:5-49 — any
// Model, not just KV).  The automaton state is an opaque int32 id
// owned by the caller; transitions are resolved through a callback
// (Python model.step) but MEMOIZED in an in-C++ table, so the
// callback fires once per distinct (state, op) pair and the
// exponential DFS — revisits, lift/unlift, set-memo pruning — runs
// entirely compiled.  This is what keeps a pure-Python model at
// compiled speed: the search is native, the semantics stay Python.
//
// step_cb(state_id, op_id, &new_state_id) -> 1 legal / 0 illegal /
// negative = caller error (aborts the DFS with rc=3; the Python shim
// falls back to the pure DFS, which raises the real exception).

typedef int (*mrt_step_cb)(int32_t state_id, int32_t op_id,
                           int32_t* new_state_id);

namespace {

struct GenericChecker {
  int32_t n;
  mrt_step_cb step_cb;

  std::vector<Entry> pool;
  Entry* head;
  int32_t state = 0;  // id of the automaton state (0 = initial)
  uint64_t zob = 0, zob2 = 0;
  std::vector<uint64_t> zkeys, zkeys2;

  struct Frame {
    Entry* call;
    int32_t old_state;
  };
  std::vector<Frame> stack;
  std::unordered_set<Key128, Key128Hash> memo;
  // (state_id << 32 | op) -> (ok << 32 | new_state_id).  Exact — ids,
  // not hashes — so the callback result is never conflated.
  std::unordered_map<uint64_t, uint64_t> trans;

  void build(const int32_t* ev_op, const uint8_t* ev_is_ret) {
    const int64_t n_events = 2 * static_cast<int64_t>(n);
    pool.resize(n_events + 1);
    std::vector<Entry*> call_of(n, nullptr);
    head = &pool[0];
    head->op = -1;
    head->is_return = false;
    head->prev = nullptr;
    Entry* tail = head;
    for (int64_t i = 0; i < n_events; i++) {
      Entry* e = &pool[i + 1];
      e->op = ev_op[i];
      e->is_return = ev_is_ret[i] != 0;
      e->match = nullptr;
      if (!e->is_return) {
        call_of[e->op] = e;
      } else {
        call_of[e->op]->match = e;
      }
      tail->next = e;
      e->prev = tail;
      tail = e;
    }
    tail->next = nullptr;
    zkeys.resize(n);
    zkeys2.resize(n);
    for (int32_t i = 0; i < n; i++) {
      zkeys[i] = splitmix64(0xC0FFEE ^ i);
      zkeys2[i] = splitmix64(0xB00B1E5ull + 0x9E37ull * i);
    }
    stack.reserve(n);
  }

  // 1 legal (fills next), 0 illegal, -1 callback error.
  int step_ok(int op, int32_t& next) {
    const uint64_t tkey =
        (static_cast<uint64_t>(static_cast<uint32_t>(state)) << 32) |
        static_cast<uint32_t>(op);
    auto it = trans.find(tkey);
    if (it != trans.end()) {
      if (!(it->second >> 32)) return 0;
      next = static_cast<int32_t>(it->second & 0xffffffffull);
      return 1;
    }
    int32_t out = 0;
    const int rc = step_cb(state, op, &out);
    if (rc < 0) return -1;
    trans.emplace(tkey, (static_cast<uint64_t>(rc != 0) << 32) |
                            static_cast<uint32_t>(out));
    if (!rc) return 0;
    next = out;
    return 1;
  }

  Key128 memo_key(uint64_t nzob, uint64_t nzob2, int32_t nstate) const {
    const uint64_t s1 = splitmix64(0x5EED0001ull + nstate);
    const uint64_t s2 = splitmix64(0x5EED0002ull * 0x9E3779B9ull + nstate);
    return Key128{splitmix64(nzob ^ s1), splitmix64(nzob2 ^ s2)};
  }
};

int check_generic_impl(
    int32_t n,
    const int32_t* ev_op,
    const uint8_t* ev_is_ret,
    mrt_step_cb step_cb,
    int64_t max_steps,
    double max_wall_s,
    bool compute_partial,
    int32_t** out_buf,
    int64_t* out_len,
    int64_t* steps_done) {
  if (out_buf) {
    *out_buf = nullptr;
    *out_len = 0;
  }
  if (steps_done) *steps_done = 0;
  if (n == 0) return 1;

  GenericChecker c;
  c.n = n;
  c.step_cb = step_cb;
  c.build(ev_op, ev_is_ret);

  std::vector<int32_t> longest;
  std::vector<std::vector<int32_t>> seqs;
  if (compute_partial) longest.assign(n, -1);

  const double wall_deadline =
      max_wall_s > 0 ? mono_s() + max_wall_s : 0.0;
  Entry* entry = c.head->next;
  int64_t steps = 0;
  int verdict = -1;
  while (c.head->next != nullptr) {
    ++steps;
    if (max_steps > 0 && steps > max_steps) {
      verdict = 2;
      break;
    }
    if (wall_deadline > 0 && (steps & 8191) == 0 &&
        mono_s() > wall_deadline) {
      verdict = 2;
      break;
    }
    if (!entry->is_return) {
      int32_t nstate = 0;
      bool advanced = false;
      const int ok = c.step_ok(entry->op, nstate);
      if (ok < 0) {
        verdict = 3;  // callback error
        break;
      }
      if (ok) {
        const uint64_t nzob = c.zob ^ c.zkeys[entry->op];
        const uint64_t nzob2 = c.zob2 ^ c.zkeys2[entry->op];
        if (c.memo.insert(c.memo_key(nzob, nzob2, nstate)).second) {
          c.stack.push_back({entry, c.state});
          c.state = nstate;
          c.zob = nzob;
          c.zob2 = nzob2;
          Checker::lift(entry);
          entry = c.head->next;
          advanced = true;
        }
      }
      if (!advanced) entry = entry->next;
    } else {
      if (c.stack.empty()) {
        verdict = 0;
        break;
      }
      if (compute_partial) capture_partials(c.stack, longest, seqs);
      GenericChecker::Frame& f = c.stack.back();
      c.state = f.old_state;
      c.zob ^= c.zkeys[f.call->op];
      c.zob2 ^= c.zkeys2[f.call->op];
      Checker::unlift(f.call);
      entry = f.call->next;
      c.stack.pop_back();
    }
  }
  if (verdict < 0) verdict = 1;
  if (steps_done) *steps_done = steps;

  if (compute_partial && verdict == 2 && !c.stack.empty()) {
    // Budget expired mid-descent: capture the live stack (same
    // convention as the KV DFS above).
    capture_partials(c.stack, longest, seqs);
  }

  if (compute_partial && out_buf && verdict != 3) {
    emit_partials(verdict, c.stack, n, longest, seqs, out_buf, out_len);
  }
  return verdict;
}

}  // namespace

int check_kv_partition(
    int32_t n,
    const int32_t* ev_op,
    const uint8_t* ev_is_ret,
    const int32_t* op_kind,
    const uint8_t* const* op_value,
    const int32_t* op_value_len,
    const uint8_t* const* op_output,
    const int32_t* op_output_len,
    int64_t max_steps,
    double max_wall_s) {
  return check_impl(n, ev_op, ev_is_ret, op_kind, op_value, op_value_len,
                    op_output, op_output_len, max_steps, max_wall_s,
                    false, nullptr, nullptr);
}

int check_kv_partition_verbose(
    int32_t n,
    const int32_t* ev_op,
    const uint8_t* ev_is_ret,
    const int32_t* op_kind,
    const uint8_t* const* op_value,
    const int32_t* op_value_len,
    const uint8_t* const* op_output,
    const int32_t* op_output_len,
    int64_t max_steps,
    double max_wall_s,
    int32_t** out_buf,
    int64_t* out_len) {
  return check_impl(n, ev_op, ev_is_ret, op_kind, op_value, op_value_len,
                    op_output, op_output_len, max_steps, max_wall_s,
                    true, out_buf, out_len);
}

// Model-generic DFS over caller-owned int32 state ids (0 = initial
// state).  ``step_cb`` resolves transitions (memoized in C++, so it
// fires once per distinct (state, op) pair).  rc: 1 OK / 0 ILLEGAL /
// 2 budget exhausted / 3 callback error (caller falls back to its own
// DFS to surface the real exception).  ``steps_done`` (optional)
// reports DFS steps executed — the speed-ratio diagnostics use it.
int check_generic_partition(
    int32_t n,
    const int32_t* ev_op,
    const uint8_t* ev_is_ret,
    mrt_step_cb step_cb,
    int64_t max_steps,
    double max_wall_s,
    int64_t* steps_done) {
  return check_generic_impl(n, ev_op, ev_is_ret, step_cb, max_steps,
                            max_wall_s, false, nullptr, nullptr,
                            steps_done);
}

int check_generic_partition_verbose(
    int32_t n,
    const int32_t* ev_op,
    const uint8_t* ev_is_ret,
    mrt_step_cb step_cb,
    int64_t max_steps,
    double max_wall_s,
    int32_t** out_buf,
    int64_t* out_len,
    int64_t* steps_done) {
  return check_generic_impl(n, ev_op, ev_is_ret, step_cb, max_steps,
                            max_wall_s, true, out_buf, out_len,
                            steps_done);
}

void mrt_buf_free(int32_t* buf) { std::free(buf); }

}  // extern "C"
