// Native linearizability DFS — the single-thread hot loop the survey
// flags as "keep compiled" (SURVEY §2 #22).
//
// Same Wing–Gong/Lowe algorithm as the Python fallback
// (multiraft_tpu/porcupine/checker.py; reference: porcupine/checker.go:
// 140-253): doubly-linked entry list, lift/unlift, (linearized-set,
// state) memoization, and — in the verbose entry point — the
// reference's computePartial (checker.go:219-234): the distinct
// longest linearizable prefixes covering each operation, captured at
// every backtrack, for the visualizer.
//
// Specialised to the KV per-key partition model (reference:
// models/kv.go:40-54) where a partition's automaton state is the
// key's current string value.  Two representation choices make this
// scale to 100k-op partitions where the generic formulation cannot:
//
//  * The PATH state is one growable byte buffer with per-frame undo
//    (append saves a length; put saves the replaced value), so the
//    current value is always exact — Get compares bytes, never a
//    hash.
//  * The MEMO stores a 128-bit hash of (linearized-set, value): TWO
//    independent Zobrist hashes over op-ids (one xor each per step)
//    mixed with two independent polynomial hashes of the value — the
//    two words share no state, so the collision bound is a genuine
//    ~2^-128 per explored pair.  Memory per memo entry is O(1)
//    instead of O(|value|); a collision could only over-prune (flip
//    a true OK to ILLEGAL) — negligible odds, and the failure mode
//    is loud (a spurious ILLEGAL gets investigated), never a silent
//    pass.
//
// Exposed via a C ABI for ctypes (no pybind11 in this image).
// Return codes: 1 = linearizable, 0 = not, 2 = budget exhausted
// (UNKNOWN).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <string>
#include <unordered_set>
#include <vector>

namespace {

struct Entry {
  int op;          // operation id, -1 for head
  bool is_return;
  Entry* match;    // call -> its return
  Entry* prev;
  Entry* next;
};

// Operation kinds (must match porcupine/kv.py).
constexpr int kGet = 0;
constexpr int kPut = 1;
constexpr int kAppend = 2;

constexpr uint64_t kP1 = 0x100000001b3ull;        // poly bases (odd)
constexpr uint64_t kP2 = 0xda942042e4dd58b5ull;

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

struct Key128 {
  uint64_t a, b;
  bool operator==(const Key128& o) const { return a == o.a && b == o.b; }
};
struct Key128Hash {
  size_t operator()(const Key128& k) const {
    return static_cast<size_t>(k.a ^ (k.b * 0x9e3779b97f4a7c15ull));
  }
};

// Polynomial hash helpers over byte strings.
inline void poly_absorb(uint64_t& h1, uint64_t& h2, const uint8_t* p,
                        int32_t len) {
  for (int32_t i = 0; i < len; i++) {
    h1 = h1 * kP1 + p[i] + 1;
    h2 = h2 * kP2 + p[i] + 1;
  }
}

struct Checker {
  int32_t n;
  const int32_t* op_kind;
  const uint8_t* const* op_value;
  const int32_t* op_value_len;
  const uint8_t* const* op_output;
  const int32_t* op_output_len;

  std::vector<Entry> pool;
  Entry* head;

  // Exact current value + per-frame undo.
  std::string cur;
  uint64_t vh1 = 0, vh2 = 0;  // incremental value hash
  uint64_t zob = 0, zob2 = 0; // independent Zobrist set hashes
  std::vector<uint64_t> zkeys, zkeys2;

  struct Frame {
    Entry* call;
    uint8_t kind;          // op kind (undo discriminator)
    uint32_t old_len;      // append undo
    std::string old_value; // put undo (the replaced value)
    uint64_t old_vh1, old_vh2;
  };
  std::vector<Frame> stack;
  std::unordered_set<Key128, Key128Hash> memo;

  void build(const int32_t* ev_op, const uint8_t* ev_is_ret) {
    const int64_t n_events = 2 * static_cast<int64_t>(n);
    pool.resize(n_events + 1);
    std::vector<Entry*> call_of(n, nullptr);
    head = &pool[0];
    head->op = -1;
    head->is_return = false;
    head->prev = nullptr;
    Entry* tail = head;
    for (int64_t i = 0; i < n_events; i++) {
      Entry* e = &pool[i + 1];
      e->op = ev_op[i];
      e->is_return = ev_is_ret[i] != 0;
      e->match = nullptr;
      if (!e->is_return) {
        call_of[e->op] = e;
      } else {
        call_of[e->op]->match = e;
      }
      tail->next = e;
      e->prev = tail;
      tail = e;
    }
    tail->next = nullptr;
    zkeys.resize(n);
    zkeys2.resize(n);
    for (int32_t i = 0; i < n; i++) {
      zkeys[i] = splitmix64(0xC0FFEE ^ i);
      zkeys2[i] = splitmix64(0xB00B1E5ull + 0x9E37ull * i);
    }
    stack.reserve(n);
  }

  static void lift(Entry* call) {
    Entry* ret = call->match;
    call->prev->next = call->next;
    if (call->next) call->next->prev = call->prev;
    ret->prev->next = ret->next;
    if (ret->next) ret->next->prev = ret->prev;
  }
  static void unlift(Entry* call) {
    Entry* ret = call->match;
    ret->prev->next = ret;
    if (ret->next) ret->next->prev = ret;
    call->prev->next = call;
    if (call->next) call->next->prev = call;
  }

  // Try to linearize `op` next: returns whether the model step is
  // legal, and (on true) fills the would-be post-state hash WITHOUT
  // mutating, so the memo can be consulted first.
  bool step_ok(int op, uint64_t& nvh1, uint64_t& nvh2) const {
    switch (op_kind[op]) {
      case kGet: {
        const int32_t olen = op_output_len[op];
        if (static_cast<size_t>(olen) != cur.size()) return false;
        if (olen && std::memcmp(op_output[op], cur.data(), olen) != 0)
          return false;
        nvh1 = vh1;
        nvh2 = vh2;
        return true;
      }
      case kPut: {
        nvh1 = 0;
        nvh2 = 0;
        poly_absorb(nvh1, nvh2, op_value[op], op_value_len[op]);
        return true;
      }
      case kAppend: {
        nvh1 = vh1;
        nvh2 = vh2;
        poly_absorb(nvh1, nvh2, op_value[op], op_value_len[op]);
        return true;
      }
      default:
        return false;
    }
  }

  void apply(Entry* call, uint64_t nvh1, uint64_t nvh2) {
    const int op = call->op;
    Frame f;
    f.call = call;
    f.kind = static_cast<uint8_t>(op_kind[op]);
    f.old_vh1 = vh1;
    f.old_vh2 = vh2;
    f.old_len = static_cast<uint32_t>(cur.size());
    if (f.kind == kPut) {
      f.old_value.swap(cur);
      cur.assign(reinterpret_cast<const char*>(op_value[op]),
                 op_value_len[op]);
    } else if (f.kind == kAppend) {
      cur.append(reinterpret_cast<const char*>(op_value[op]),
                 op_value_len[op]);
    }
    vh1 = nvh1;
    vh2 = nvh2;
    zob ^= zkeys[op];
    zob2 ^= zkeys2[op];
    stack.push_back(std::move(f));
    lift(call);
  }

  Entry* backtrack() {
    Frame& f = stack.back();
    if (f.kind == kPut) {
      cur.swap(f.old_value);
    } else if (f.kind == kAppend) {
      cur.resize(f.old_len);
    }
    vh1 = f.old_vh1;
    vh2 = f.old_vh2;
    zob ^= zkeys[f.call->op];
    zob2 ^= zkeys2[f.call->op];
    unlift(f.call);
    Entry* resume = f.call->next;
    stack.pop_back();
    return resume;
  }

  Key128 memo_key(uint64_t nzob, uint64_t nzob2, uint64_t nvh1,
                  uint64_t nvh2) const {
    // Two fully independent 64-bit words (separate Zobrist tables,
    // separate polynomial bases) — a real 128-bit collision bound.
    return Key128{splitmix64(nzob ^ nvh1), splitmix64(nzob2 ^ nvh2)};
  }
};

inline double mono_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

}  // namespace

extern "C" {

// ops laid out as parallel arrays of length n, events pre-sorted by the
// caller (Python) into a single interleaved sequence of 2n event slots:
//   ev_op[i]     — operation id of event i
//   ev_is_ret[i] — 0 call, 1 return
// op_kind[j], op_value/op_value_len [j] — the put/append argument utf-8
// op_output/op_output_len [j]           — get's observed value
// max_steps — DFS step budget (0 = unlimited)
//
// Verbose form additionally returns the partial linearizations
// (reference computePartial): *out_buf = int32 array
// [n_seqs, len_0, ops_0..., len_1, ops_1...] (caller frees via
// mrt_buf_free).  On OK the single full linearization is returned.
static int check_impl(
    int32_t n,
    const int32_t* ev_op,
    const uint8_t* ev_is_ret,
    const int32_t* op_kind,
    const uint8_t* const* op_value,
    const int32_t* op_value_len,
    const uint8_t* const* op_output,
    const int32_t* op_output_len,
    int64_t max_steps,
    double max_wall_s,
    bool compute_partial,
    int32_t** out_buf,
    int64_t* out_len) {
  if (out_buf) {
    *out_buf = nullptr;
    *out_len = 0;
  }
  if (n == 0) return 1;

  Checker c;
  c.n = n;
  c.op_kind = op_kind;
  c.op_value = op_value;
  c.op_value_len = op_value_len;
  c.op_output = op_output;
  c.op_output_len = op_output_len;
  c.build(ev_op, ev_is_ret);

  // computePartial bookkeeping: longest[op] = index into `seqs` of the
  // longest linearizable prefix covering op (shared, lazily
  // materialized per backtrack — the reference's lazy-seq trick).
  std::vector<int32_t> longest;
  std::vector<std::vector<int32_t>> seqs;
  if (compute_partial) longest.assign(n, -1);

  // Wall-clock deadline checked every 8192 steps — the step budget
  // alone under-counts verbose mode (each backtrack's computePartial
  // capture is O(stack depth)), and the timeout-as-UNKNOWN convention
  // must bound WALL time (Python DFS parity: checker.py's
  // steps % 4096 check).
  const double wall_deadline =
      max_wall_s > 0 ? mono_s() + max_wall_s : 0.0;
  Entry* entry = c.head->next;
  int64_t steps = 0;
  int verdict = -1;
  while (c.head->next != nullptr) {
    ++steps;
    if (max_steps > 0 && steps > max_steps) {
      verdict = 2;
      break;
    }
    if (wall_deadline > 0 && (steps & 8191) == 0 &&
        mono_s() > wall_deadline) {
      verdict = 2;
      break;
    }
    if (!entry->is_return) {
      uint64_t nvh1, nvh2;
      bool advanced = false;
      if (c.step_ok(entry->op, nvh1, nvh2)) {
        const uint64_t nzob = c.zob ^ c.zkeys[entry->op];
        const uint64_t nzob2 = c.zob2 ^ c.zkeys2[entry->op];
        if (c.memo.insert(c.memo_key(nzob, nzob2, nvh1, nvh2)).second) {
          c.apply(entry, nvh1, nvh2);
          entry = c.head->next;
          advanced = true;
        }
      }
      if (!advanced) entry = entry->next;
    } else {
      if (c.stack.empty()) {
        verdict = 0;
        break;
      }
      if (compute_partial) {
        int32_t seq_idx = -1;
        const size_t depth = c.stack.size();
        for (const auto& f : c.stack) {
          const int op = f.call->op;
          if (longest[op] < 0 ||
              seqs[longest[op]].size() < depth) {
            if (seq_idx < 0) {
              std::vector<int32_t> s;
              s.reserve(depth);
              for (const auto& g : c.stack) s.push_back(g.call->op);
              seqs.push_back(std::move(s));
              seq_idx = static_cast<int32_t>(seqs.size()) - 1;
            }
            longest[op] = seq_idx;
          }
        }
      }
      entry = c.backtrack();
    }
  }
  if (verdict < 0) verdict = 1;

  if (compute_partial && verdict == 2 && !c.stack.empty()) {
    // Budget/deadline expired mid-descent: the LIVE stack is a
    // linearizable prefix no backtrack recorded yet — capture it so
    // the evidence is never empty for exactly the runs verbose mode
    // exists to debug.
    int32_t seq_idx = -1;
    const size_t depth = c.stack.size();
    for (const auto& f : c.stack) {
      const int op = f.call->op;
      if (longest[op] < 0 || seqs[longest[op]].size() < depth) {
        if (seq_idx < 0) {
          std::vector<int32_t> s;
          s.reserve(depth);
          for (const auto& g : c.stack) s.push_back(g.call->op);
          seqs.push_back(std::move(s));
          seq_idx = static_cast<int32_t>(seqs.size()) - 1;
        }
        longest[op] = seq_idx;
      }
    }
  }

  if (compute_partial && out_buf) {
    std::vector<int32_t> full;
    std::vector<const std::vector<int32_t>*> outs;
    if (verdict == 1) {
      // Full linearization from the final stack.
      for (const auto& f : c.stack) full.push_back(f.call->op);
      outs.push_back(&full);
    } else {
      // Identity-distinct longest prefixes, emitted in
      // FIRST-REFERENCING-OP order — exactly the Python oracle's
      // dedup (`for seq in longest: uniq[id(seq)] = seq`, insertion-
      // ordered), so native and fallback produce identical evidence.
      std::vector<char> emitted(seqs.size(), 0);
      for (int32_t i = 0; i < n; i++) {
        const int32_t s = longest[i];
        if (s >= 0 && !emitted[s]) {
          emitted[s] = 1;
          outs.push_back(&seqs[s]);
        }
      }
    }
    int64_t total = 1;
    for (const auto* s : outs) total += 1 + static_cast<int64_t>(s->size());
    int32_t* buf =
        static_cast<int32_t*>(std::malloc(total * sizeof(int32_t)));
    if (buf == nullptr) return verdict;  // partials dropped, verdict kept
    int64_t w = 0;
    buf[w++] = static_cast<int32_t>(outs.size());
    for (const auto* s : outs) {
      buf[w++] = static_cast<int32_t>(s->size());
      for (int32_t v : *s) buf[w++] = v;
    }
    *out_buf = buf;
    *out_len = w;
  }
  return verdict;
}

int check_kv_partition(
    int32_t n,
    const int32_t* ev_op,
    const uint8_t* ev_is_ret,
    const int32_t* op_kind,
    const uint8_t* const* op_value,
    const int32_t* op_value_len,
    const uint8_t* const* op_output,
    const int32_t* op_output_len,
    int64_t max_steps,
    double max_wall_s) {
  return check_impl(n, ev_op, ev_is_ret, op_kind, op_value, op_value_len,
                    op_output, op_output_len, max_steps, max_wall_s,
                    false, nullptr, nullptr);
}

int check_kv_partition_verbose(
    int32_t n,
    const int32_t* ev_op,
    const uint8_t* ev_is_ret,
    const int32_t* op_kind,
    const uint8_t* const* op_value,
    const int32_t* op_value_len,
    const uint8_t* const* op_output,
    const int32_t* op_output_len,
    int64_t max_steps,
    double max_wall_s,
    int32_t** out_buf,
    int64_t* out_len) {
  return check_impl(n, ev_op, ev_is_ret, op_kind, op_value, op_value_len,
                    op_output, op_output_len, max_steps, max_wall_s,
                    true, out_buf, out_len);
}

void mrt_buf_free(int32_t* buf) { std::free(buf); }

}  // extern "C"
