// Native linearizability DFS — the single-thread hot loop the survey
// flags as "keep compiled" (SURVEY §2 #22).
//
// Same Wing–Gong/Lowe algorithm as the Python fallback
// (multiraft_tpu/porcupine/checker.py; reference: porcupine/checker.go:
// 140-253): doubly-linked entry list, lift/unlift, (linearized-bitset,
// state) memo cache.  Specialised to the KV per-key partition model
// (reference: models/kv.go:40-54) where a partition's automaton state is
// just the key's current string value; the memo cache keys on
// (bitset, value bytes).
//
// Exposed via a tiny C ABI for ctypes (no pybind11 in this image):
//   check_kv_partition(n, op_kinds, call_order, ret_order, outputs, ...)
// Returns 1 = linearizable, 0 = not, 2 = step budget exhausted (UNKNOWN).

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_set>
#include <vector>

namespace {

struct Entry {
  int op;          // operation id, -1 for head
  bool is_return;
  Entry* match;    // call -> its return
  Entry* prev;
  Entry* next;
};

// Operation kinds (must match porcupine/kv.py).
constexpr int kGet = 0;
constexpr int kPut = 1;
constexpr int kAppend = 2;

struct Frame {
  Entry* call;
  // Saved value-state: an index into the `states` vector (append-only).
  int saved_state;
};

}  // namespace

extern "C" {

// ops laid out as parallel arrays of length n, events pre-sorted by the
// caller (Python) into a single interleaved sequence of 2n event slots:
//   ev_op[i]     — operation id of event i
//   ev_is_ret[i] — 0 call, 1 return
// op_kind[j], op_value/op_value_len [j] — the put/append argument utf-8
// op_output/op_output_len [j]           — get's observed value
// max_steps — DFS step budget (0 = unlimited)
int check_kv_partition(
    int32_t n,
    const int32_t* ev_op,
    const uint8_t* ev_is_ret,
    const int32_t* op_kind,
    const uint8_t* const* op_value,
    const int32_t* op_value_len,
    const uint8_t* const* op_output,
    const int32_t* op_output_len,
    int64_t max_steps) {
  if (n == 0) return 1;
  if (n > 62) {
    // Bitset is a uint64 here; larger partitions fall back to Python.
    return 3;
  }
  const int64_t n_events = 2 * static_cast<int64_t>(n);

  // Build the linked list.
  std::vector<Entry> pool(n_events + 1);
  std::vector<Entry*> call_of(n, nullptr);
  Entry* head = &pool[0];
  head->op = -1;
  head->is_return = false;
  head->prev = nullptr;
  Entry* tail = head;
  for (int64_t i = 0; i < n_events; i++) {
    Entry* e = &pool[i + 1];
    e->op = ev_op[i];
    e->is_return = ev_is_ret[i] != 0;
    e->match = nullptr;
    if (!e->is_return) {
      call_of[e->op] = e;
    } else {
      call_of[e->op]->match = e;
    }
    tail->next = e;
    e->prev = tail;
    tail = e;
  }
  tail->next = nullptr;

  auto lift = [](Entry* call) {
    Entry* ret = call->match;
    call->prev->next = call->next;
    if (call->next) call->next->prev = call->prev;
    ret->prev->next = ret->next;
    if (ret->next) ret->next->prev = ret->prev;
  };
  auto unlift = [](Entry* call) {
    Entry* ret = call->match;
    ret->prev->next = ret;
    if (ret->next) ret->next->prev = ret;
    call->prev->next = call;
    if (call->next) call->next->prev = call;
  };

  auto value_of = [&](int op) {
    return std::string(reinterpret_cast<const char*>(op_value[op]),
                       op_value_len[op]);
  };
  auto output_of = [&](int op) {
    return std::string(reinterpret_cast<const char*>(op_output[op]),
                       op_output_len[op]);
  };

  // step: returns {ok, new_state} given current value (by index).
  std::vector<std::string> states;
  states.emplace_back("");  // initial value
  int cur_state = 0;

  uint64_t linearized = 0;
  std::unordered_set<std::string> cache;
  std::vector<Frame> stack;
  stack.reserve(n);

  auto cache_key = [&](uint64_t mask, const std::string& val) {
    std::string k;
    k.reserve(8 + val.size());
    k.append(reinterpret_cast<const char*>(&mask), 8);
    k.append(val);
    return k;
  };

  Entry* entry = head->next;
  int64_t steps = 0;
  while (head->next != nullptr) {
    if (max_steps > 0 && ++steps > max_steps) return 2;
    if (!entry->is_return) {
      const int op = entry->op;
      bool ok = false;
      std::string new_val;
      const std::string& cur = states[cur_state];
      switch (op_kind[op]) {
        case kGet:
          ok = output_of(op) == cur;
          if (ok) new_val = cur;
          break;
        case kPut:
          ok = true;
          new_val = value_of(op);
          break;
        case kAppend:
          ok = true;
          new_val = cur + value_of(op);
          break;
        default:
          return 0;
      }
      bool advanced = false;
      if (ok) {
        const uint64_t new_mask = linearized | (1ull << op);
        std::string key = cache_key(new_mask, new_val);
        if (cache.insert(std::move(key)).second) {
          stack.push_back({entry, cur_state});
          states.push_back(std::move(new_val));
          cur_state = static_cast<int>(states.size()) - 1;
          linearized = new_mask;
          lift(entry);
          entry = head->next;
          advanced = true;
        }
      }
      if (!advanced) entry = entry->next;
    } else {
      if (stack.empty()) return 0;
      Frame f = stack.back();
      stack.pop_back();
      cur_state = f.saved_state;
      linearized &= ~(1ull << f.call->op);
      unlift(f.call);
      entry = f.call->next;
    }
  }
  return 1;
}

}  // extern "C"
