"""ctypes loader for the native KV linearizability checker.

Builds ``libporcupine.so`` from ``checker.cpp`` on first use (g++ -O2;
no pybind11 in this image — plain C ABI + ctypes) and exposes
:func:`check_kv_partition_native` (verdict only) and
:func:`check_kv_partition_native_verbose` (verdict + partial
linearizations, the reference's computePartial).  Falls back to the
Python DFS when the toolchain is unavailable.  Partition size is
unbounded — the C++ DFS memoizes through a 128-bit hash, not a
fixed-width bitset.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Tuple

from ...utils.native_build import build_and_load

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "checker.cpp")
_SO = os.path.join(_HERE, "libporcupine.so")

_lib = None
_build_failed = False

_COMMON_ARGS = [
    ctypes.c_int32,
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_char_p),
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_char_p),
    ctypes.POINTER(ctypes.c_int32),
    ctypes.c_int64,   # max_steps (0 = unlimited)
    ctypes.c_double,  # max_wall_s (0 = unlimited); checked in-loop
]


# Transition callback for the model-generic DFS:
# (state_id, op_id, *new_state_id) -> 1 legal / 0 illegal / <0 error.
STEP_CB = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int32, ctypes.c_int32,
    ctypes.POINTER(ctypes.c_int32),
)

_GENERIC_ARGS = [
    ctypes.c_int32,
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_uint8),
    STEP_CB,
    ctypes.c_int64,   # max_steps (0 = unlimited)
    ctypes.c_double,  # max_wall_s (0 = unlimited)
]


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    try:
        lib = build_and_load(_SRC, _SO)
        lib.check_kv_partition.restype = ctypes.c_int
        lib.check_kv_partition.argtypes = list(_COMMON_ARGS)
        lib.check_kv_partition_verbose.restype = ctypes.c_int
        lib.check_kv_partition_verbose.argtypes = list(_COMMON_ARGS) + [
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.check_generic_partition.restype = ctypes.c_int
        lib.check_generic_partition.argtypes = list(_GENERIC_ARGS) + [
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.check_generic_partition_verbose.restype = ctypes.c_int
        lib.check_generic_partition_verbose.argtypes = list(_GENERIC_ARGS) + [
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mrt_buf_free.restype = None
        lib.mrt_buf_free.argtypes = [ctypes.POINTER(ctypes.c_int32)]
        _lib = lib
        return lib
    except Exception:
        _build_failed = True
        return None


def native_available() -> bool:
    return _load() is not None


def _marshal(events, op_kinds, op_values, op_outputs):
    n = len(op_kinds)
    # A malformed event order (return before call, duplicate events)
    # would walk the C++ DFS off its linked list — reject it here with
    # a Python error instead of a segfault.
    seen = bytearray(n)  # 0 = unseen, 1 = called, 2 = returned
    for op, is_ret in events:
        if not (0 <= op < n):
            raise ValueError(f"event references op {op} outside [0,{n})")
        want = 1 if is_ret else 0
        if seen[op] != want:
            raise ValueError(
                f"malformed event order: op {op} "
                + ("returned before call" if is_ret else "called twice")
            )
        seen[op] = want + 1
    if any(s != 2 for s in seen):
        raise ValueError("malformed history: op missing call/return")
    ev_op = (ctypes.c_int32 * len(events))(*[e[0] for e in events])
    ev_ret = (ctypes.c_uint8 * len(events))(*[1 if e[1] else 0 for e in events])
    kinds = (ctypes.c_int32 * n)(*op_kinds)
    vals = [v.encode() for v in op_values]
    outs = [o.encode() for o in op_outputs]
    val_ptrs = (ctypes.c_char_p * n)(*vals)
    out_ptrs = (ctypes.c_char_p * n)(*outs)
    val_lens = (ctypes.c_int32 * n)(*[len(v) for v in vals])
    out_lens = (ctypes.c_int32 * n)(*[len(o) for o in outs])
    # Keep the bytes objects alive until the call returns.
    keepalive = (vals, outs)
    return (
        n, ev_op, ev_ret, kinds,
        ctypes.cast(val_ptrs, ctypes.POINTER(ctypes.c_char_p)), val_lens,
        ctypes.cast(out_ptrs, ctypes.POINTER(ctypes.c_char_p)), out_lens,
    ), keepalive


def check_kv_partition_native(
    events, op_kinds, op_values, op_outputs, max_steps=0, max_wall_s=0.0
):
    """Run the C++ DFS on one pre-sorted partition.

    events: list of (op_id, is_return) in time order.
    Returns 1 linearizable / 0 illegal / 2 budget exhausted / None if
    native path unavailable (caller falls back to Python).
    """
    lib = _load()
    if lib is None:
        return None
    args, _keep = _marshal(events, op_kinds, op_values, op_outputs)
    return lib.check_kv_partition(*args, max_steps, max_wall_s)


def _parse_partials(lib, buf, buf_len) -> List[List[int]]:
    partials: List[List[int]] = []
    if buf and buf_len.value > 0:
        try:
            flat = buf[: buf_len.value]
            n_seqs = flat[0]
            w = 1
            for _ in range(n_seqs):
                ln = flat[w]
                w += 1
                partials.append(list(flat[w: w + ln]))
                w += ln
        finally:
            lib.mrt_buf_free(buf)
    return partials


def check_generic_partition_native(
    events, n, step_cb, max_steps=0, max_wall_s=0.0,
) -> Optional[Tuple[int, int]]:
    """Run the model-generic C++ DFS on one pre-sorted partition.

    ``step_cb(state_id, op_id, new_state_id_ptr)`` resolves transitions
    (fired once per distinct pair — the C++ side memoizes).  Returns
    ``(rc, steps_done)`` with rc 1 OK / 0 ILLEGAL / 2 budget /
    3 callback error, or None when the native path is unavailable.
    """
    lib = _load()
    if lib is None:
        return None
    ev_op = (ctypes.c_int32 * len(events))(*[e[0] for e in events])
    ev_ret = (ctypes.c_uint8 * len(events))(*[1 if e[1] else 0 for e in events])
    cb = STEP_CB(step_cb)
    steps = ctypes.c_int64(0)
    rc = lib.check_generic_partition(
        n, ev_op, ev_ret, cb, max_steps, max_wall_s, ctypes.byref(steps)
    )
    return rc, steps.value


def check_generic_partition_native_verbose(
    events, n, step_cb, max_steps=0, max_wall_s=0.0,
) -> Optional[Tuple[int, List[List[int]], int]]:
    """Verbose generic DFS: ``(rc, partials, steps_done)`` — same
    computePartial evidence as the KV fast path.  None = unavailable."""
    lib = _load()
    if lib is None:
        return None
    ev_op = (ctypes.c_int32 * len(events))(*[e[0] for e in events])
    ev_ret = (ctypes.c_uint8 * len(events))(*[1 if e[1] else 0 for e in events])
    cb = STEP_CB(step_cb)
    steps = ctypes.c_int64(0)
    buf = ctypes.POINTER(ctypes.c_int32)()
    buf_len = ctypes.c_int64(0)
    rc = lib.check_generic_partition_verbose(
        n, ev_op, ev_ret, cb, max_steps, max_wall_s,
        ctypes.byref(buf), ctypes.byref(buf_len), ctypes.byref(steps),
    )
    return rc, _parse_partials(lib, buf, buf_len), steps.value


def check_kv_partition_native_verbose(
    events, op_kinds, op_values, op_outputs, max_steps=0, max_wall_s=0.0
) -> Optional[Tuple[int, List[List[int]]]]:
    """Verbose C++ DFS: returns ``(rc, partials)`` where partials is
    the reference computePartial output — op-id sequences, the single
    full linearization on OK, the distinct longest linearizable
    prefixes otherwise.  None = native path unavailable."""
    lib = _load()
    if lib is None:
        return None
    args, _keep = _marshal(events, op_kinds, op_values, op_outputs)
    buf = ctypes.POINTER(ctypes.c_int32)()
    buf_len = ctypes.c_int64(0)
    rc = lib.check_kv_partition_verbose(
        *args, max_steps, max_wall_s, ctypes.byref(buf), ctypes.byref(buf_len)
    )
    return rc, _parse_partials(lib, buf, buf_len)
