"""ctypes loader for the native KV linearizability checker.

Builds ``libporcupine.so`` from ``checker.cpp`` on first use (g++ -O2;
no pybind11 in this image — plain C ABI + ctypes) and exposes
:func:`check_kv_partition_native`.  Falls back to the Python DFS when
the toolchain is unavailable or the partition exceeds the native
bitset width (>62 ops).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

from ...utils.native_build import build_and_load

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "checker.cpp")
_SO = os.path.join(_HERE, "libporcupine.so")

_lib = None
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    try:
        lib = build_and_load(_SRC, _SO)
        lib.check_kv_partition.restype = ctypes.c_int
        lib.check_kv_partition.argtypes = [
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
        ]
        _lib = lib
        return lib
    except Exception:
        _build_failed = True
        return None


def native_available() -> bool:
    return _load() is not None


def check_kv_partition_native(events, op_kinds, op_values, op_outputs, max_steps=0):
    """Run the C++ DFS on one pre-sorted partition.

    events: list of (op_id, is_return) in time order.
    Returns 1 linearizable / 0 illegal / 2 budget exhausted / None if
    native path unavailable (caller falls back to Python).
    """
    lib = _load()
    n = len(op_kinds)
    if lib is None or n > 62:
        return None
    ev_op = (ctypes.c_int32 * len(events))(*[e[0] for e in events])
    ev_ret = (ctypes.c_uint8 * len(events))(*[1 if e[1] else 0 for e in events])
    kinds = (ctypes.c_int32 * n)(*op_kinds)
    vals = [v.encode() for v in op_values]
    outs = [o.encode() for o in op_outputs]
    val_ptrs = (ctypes.c_char_p * n)(*vals)
    out_ptrs = (ctypes.c_char_p * n)(*outs)
    val_lens = (ctypes.c_int32 * n)(*[len(v) for v in vals])
    out_lens = (ctypes.c_int32 * n)(*[len(o) for o in outs])
    return lib.check_kv_partition(
        n,
        ev_op,
        ev_ret,
        kinds,
        ctypes.cast(val_ptrs, ctypes.POINTER(ctypes.c_char_p)),
        val_lens,
        ctypes.cast(out_ptrs, ctypes.POINTER(ctypes.c_char_p)),
        out_lens,
        max_steps,
    )
