"""Porcupine model/operation types (reference: porcupine/model.go:5-49,
porcupine/porcupine.go:5-39 — a vendored copy of anishathalye/porcupine).

A :class:`Model` is a specification automaton; a history of
:class:`Operation` s is linearizable iff some total order of the
operations, consistent with real-time precedence, drives the automaton
with every step legal.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, List, Optional

__all__ = ["Operation", "Model", "CheckResult"]


class CheckResult(enum.Enum):
    """(reference: porcupine/porcupine.go CheckResult)"""

    OK = "ok"
    ILLEGAL = "illegal"
    UNKNOWN = "unknown"  # checker timed out; treated as pass-with-warning


@dataclasses.dataclass
class Operation:
    """One client operation with its real-time interval
    (reference: porcupine/model.go Operation)."""

    client_id: int
    input: Any
    call: float  # invocation time
    output: Any
    ret: float  # response time


@dataclasses.dataclass
class Model:
    """Specification automaton (reference: porcupine/model.go Model).

    ``partition`` splits a history into independently-checkable
    sub-histories (e.g. per key); ``init`` returns the initial state;
    ``step(state, input, output) -> (ok, new_state)`` applies one
    operation.  States must be hashable, or supply ``freeze`` to map a
    state to a hashable key (used for memoization)."""

    init: Callable[[], Any]
    step: Callable[[Any, Any, Any], tuple]
    partition: Optional[Callable[[List[Operation]], List[List[Operation]]]] = None
    freeze: Optional[Callable[[Any], Any]] = None
    describe_operation: Optional[Callable[[Any, Any], str]] = None
    # Optional compiled fast path: fn(partition, deadline) -> CheckResult
    # | None (None = punt to the generic Python DFS).  ``deadline`` is a
    # time.monotonic() instant or None for unbounded.  Used by the KV
    # model's C++ checker (porcupine/native).
    native_check: Optional[Callable[[List[Operation], Optional[float]], Any]] = None
    # Verbose twin: fn(partition, deadline) -> (CheckResult, partials)
    # | None — same DFS, additionally returning the computePartial
    # evidence (op-id sequences) so check_operations_verbose runs at
    # native speed too (reference: porcupine/checker.go:179-253, one
    # pass computes both).
    native_check_verbose: Optional[
        Callable[[List[Operation], Optional[float]], Any]
    ] = None
    # Whether the model-GENERIC compiled DFS may be used when no
    # specialized hook applies (reference: the Go checker is generic
    # over any Model, porcupine/model.go:5-49 + checker.go:179-253).
    # The generic path runs the search compiled and consults ``step``
    # through a callback once per distinct (state, op) pair; semantics
    # are identical to the Python DFS.  Off = always use the Python
    # DFS (differential-test oracles set this).
    native_generic: bool = True

    def partitions(self, history: List[Operation]) -> List[List[Operation]]:
        if self.partition is None:
            return [history]
        return self.partition(history)

    def key_of(self, state: Any) -> Any:
        return self.freeze(state) if self.freeze is not None else state
