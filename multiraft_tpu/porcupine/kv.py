"""KV specification model for porcupine (reference: models/kv.go:17-69).

Partitioned by key (reference: models/kv.go:18-34); state per partition
is just the key's current string value.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import List

from ..transport import codec
from .model import Model, Operation

__all__ = [
    "KvInput",
    "KvOutput",
    "kv_model",
    "kv_model_py",
    "OP_GET",
    "OP_PUT",
    "OP_APPEND",
]

OP_GET = 0
OP_PUT = 1
OP_APPEND = 2

_OP_NAMES = {OP_GET: "get", OP_PUT: "put", OP_APPEND: "append"}


@codec.registered
@dataclasses.dataclass(frozen=True)
class KvInput:
    op: int = OP_GET
    key: str = ""
    value: str = ""


@codec.registered
@dataclasses.dataclass(frozen=True)
class KvOutput:
    value: str = ""


def _partition(history: List[Operation]) -> List[List[Operation]]:
    by_key: dict = defaultdict(list)
    for op in history:
        by_key[op.input.key].append(op)
    return list(by_key.values())


def _init() -> str:
    return ""


def _step(state: str, inp: KvInput, out: KvOutput):
    """(reference: models/kv.go:40-54)"""
    if inp.op == OP_GET:
        return out.value == state, state
    if inp.op == OP_PUT:
        return True, inp.value
    return True, state + inp.value  # append


def _describe(inp: KvInput, out: KvOutput) -> str:
    """(reference: models/kv.go:55-68)"""
    name = _OP_NAMES.get(inp.op, "?")
    if inp.op == OP_GET:
        return f"get('{inp.key}') -> '{out.value}'"
    if inp.op == OP_PUT:
        return f"put('{inp.key}', '{inp.value}')"
    return f"append('{inp.key}', '{inp.value}')"


# Measured conservatively: the C++ DFS sustains well over this many
# steps/sec, so the budget under-runs a wall-clock deadline.
_NATIVE_STEPS_PER_SEC = 20_000_000


def _native_args(part: List[Operation], deadline):
    """Shared marshalling for the plain/verbose native entry points:
    the interleaved event order + parallel op arrays + step budget.
    Returns None when the deadline already expired, or "malformed"
    for a ret-before-call history — the caller falls back to the
    Python DFS, whose entry builder raises the proper ValueError
    (the C++ builder would dereference a missing call entry)."""
    import time as _time

    if any(op.ret < op.call for op in part):
        return "malformed"
    if deadline is None:
        max_steps = 0   # unlimited: exhaustive, like the Python DFS
        max_wall = 0.0
    else:
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            return None
        # max(1, ·): int() of a sub-50ns remainder would truncate to
        # the 0 = UNLIMITED sentinel and turn an expired deadline into
        # an exhaustive search.  The wall clock is the real bound (the
        # C++ loop checks it every 8k steps — verbose backtracks cost
        # O(depth), so a step budget alone under-counts); the step
        # budget stays as a belt for clock-free callers.
        max_steps = max(1, int(remaining * _NATIVE_STEPS_PER_SEC))
        max_wall = remaining
    events = []
    for i, op in enumerate(part):
        events.append((op.call, 0, i))
        events.append((op.ret, 1, i))
    events.sort(key=lambda e: (e[0], e[1]))
    ev = [(i, bool(kind)) for _, kind, i in events]
    kinds = [op.input.op for op in part]
    values = [op.input.value for op in part]
    outputs = [op.output.value for op in part]
    return ev, kinds, values, outputs, max_steps, max_wall


def _rc_result(rc):
    from .checker import CheckResult

    return {0: CheckResult.ILLEGAL, 1: CheckResult.OK,
            2: CheckResult.UNKNOWN}[rc]


def _native_check(part: List[Operation], deadline=None):
    """C++ DFS fast path for one per-key partition (falls back to the
    Python DFS on None).  The step budget is derived from the remaining
    wall-clock deadline — unlimited when no timeout was requested, so an
    ILLEGAL verdict can never be masked as UNKNOWN by an arbitrary
    budget.  No partition-size cap: the native memo is hash-based, not
    a fixed-width bitset."""
    from .checker import CheckResult  # local import to avoid a cycle
    from .native import check_kv_partition_native

    if len(part) == 0:
        return None
    args = _native_args(part, deadline)
    if args == "malformed":
        return None  # Python DFS raises the proper ValueError
    if args is None:
        return CheckResult.UNKNOWN
    ev, kinds, values, outputs, max_steps, max_wall = args
    rc = check_kv_partition_native(
        ev, kinds, values, outputs, max_steps=max_steps,
        max_wall_s=max_wall,
    )
    if rc is None:
        return None
    return _rc_result(rc)


def _native_check_verbose(part: List[Operation], deadline=None):
    """Verbose C++ fast path: ``(verdict, partials)`` with the
    reference's computePartial output (porcupine/checker.go:219-234) —
    so a large FAILING history debugs at the same speed the plain
    check caught it (round-2 verdict: the evidence pass must not be
    orders slower than the checking pass)."""
    from .checker import CheckResult
    from .native import check_kv_partition_native_verbose

    if len(part) == 0:
        return None
    args = _native_args(part, deadline)
    if args == "malformed":
        return None
    if args is None:
        return CheckResult.UNKNOWN, []
    ev, kinds, values, outputs, max_steps, max_wall = args
    out = check_kv_partition_native_verbose(
        ev, kinds, values, outputs, max_steps=max_steps,
        max_wall_s=max_wall,
    )
    if out is None:
        return None
    rc, partials = out
    return _rc_result(rc), partials


kv_model = Model(
    init=_init,
    step=_step,
    partition=_partition,
    describe_operation=_describe,
    native_check=_native_check,
    native_check_verbose=_native_check_verbose,
)

# Pure-Python variant (oracle for differential tests of the native DFS);
# derived from kv_model so the two can never drift apart.
# native_generic is off too: the oracle must be the Python DFS itself,
# not the generic compiled path.
kv_model_py = dataclasses.replace(
    kv_model, native_check=None, native_check_verbose=None,
    native_generic=False,
)
