"""KV specification model for porcupine (reference: models/kv.go:17-69).

Partitioned by key (reference: models/kv.go:18-34); state per partition
is just the key's current string value.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Any, List

from ..transport import codec
from .model import Model, Operation

__all__ = ["KvInput", "KvOutput", "kv_model", "OP_GET", "OP_PUT", "OP_APPEND"]

OP_GET = 0
OP_PUT = 1
OP_APPEND = 2

_OP_NAMES = {OP_GET: "get", OP_PUT: "put", OP_APPEND: "append"}


@codec.registered
@dataclasses.dataclass(frozen=True)
class KvInput:
    op: int = OP_GET
    key: str = ""
    value: str = ""


@codec.registered
@dataclasses.dataclass(frozen=True)
class KvOutput:
    value: str = ""


def _partition(history: List[Operation]) -> List[List[Operation]]:
    by_key: dict = defaultdict(list)
    for op in history:
        by_key[op.input.key].append(op)
    return list(by_key.values())


def _init() -> str:
    return ""


def _step(state: str, inp: KvInput, out: KvOutput):
    """(reference: models/kv.go:40-54)"""
    if inp.op == OP_GET:
        return out.value == state, state
    if inp.op == OP_PUT:
        return True, inp.value
    return True, state + inp.value  # append


def _describe(inp: KvInput, out: KvOutput) -> str:
    """(reference: models/kv.go:55-68)"""
    name = _OP_NAMES.get(inp.op, "?")
    if inp.op == OP_GET:
        return f"get('{inp.key}') -> '{out.value}'"
    if inp.op == OP_PUT:
        return f"put('{inp.key}', '{inp.value}')"
    return f"append('{inp.key}', '{inp.value}')"


kv_model = Model(
    init=_init,
    step=_step,
    partition=_partition,
    describe_operation=_describe,
)
