"""Compare-and-swap register specification model.

A NON-KV model exercising the checker's model-generic contract
(reference: porcupine/model.go:5-49 — the Go checker is generic over
any Model; the KV model, models/kv.go, is just one instance).  CAS
semantics cannot be expressed by the KV specialization: whether the
state changes depends on a comparison against the *observed* output
(``ok``), so this model rides the model-generic compiled DFS
(:func:`..checker._native_generic`) rather than the KV-specialized
C++ fast path.

Partitioned per register name, like the KV model's per-key split
(reference: models/kv.go:18-34).
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import List

from .model import Model, Operation

__all__ = [
    "RegInput",
    "RegOutput",
    "cas_register_model",
    "cas_register_model_py",
    "REG_READ",
    "REG_WRITE",
    "REG_CAS",
]

REG_READ = 0
REG_WRITE = 1
REG_CAS = 2

_OP_NAMES = {REG_READ: "read", REG_WRITE: "write", REG_CAS: "cas"}


@dataclasses.dataclass(frozen=True)
class RegInput:
    op: int = REG_READ
    reg: str = ""
    # write: ``arg1`` is the new value.
    # cas:   ``arg1`` is the expected value, ``arg2`` the replacement.
    arg1: int = 0
    arg2: int = 0


@dataclasses.dataclass(frozen=True)
class RegOutput:
    value: int = 0   # read's observed value
    ok: bool = False  # cas's observed success


def _partition(history: List[Operation]) -> List[List[Operation]]:
    by_reg: dict = defaultdict(list)
    for op in history:
        by_reg[op.input.reg].append(op)
    return list(by_reg.values())


def _init() -> int:
    return 0  # registers start at zero


def _step(state: int, inp: RegInput, out: RegOutput):
    if inp.op == REG_READ:
        return out.value == state, state
    if inp.op == REG_WRITE:
        return True, inp.arg1
    # CAS: legal iff the observed success bit matches whether the
    # expected value held; the state advances only on success.
    succeeded = state == inp.arg1
    if out.ok != succeeded:
        return False, state
    return True, inp.arg2 if succeeded else state


def _describe(inp: RegInput, out: RegOutput) -> str:
    name = _OP_NAMES.get(inp.op, "?")
    if inp.op == REG_READ:
        return f"read('{inp.reg}') -> {out.value}"
    if inp.op == REG_WRITE:
        return f"write('{inp.reg}', {inp.arg1})"
    return (
        f"cas('{inp.reg}', {inp.arg1} -> {inp.arg2}) = "
        f"{'ok' if out.ok else 'failed'}"
    )


cas_register_model = Model(
    init=_init,
    step=_step,
    partition=_partition,
    describe_operation=_describe,
)

# Pure-Python oracle for differential tests of the generic native DFS.
cas_register_model_py = dataclasses.replace(
    cas_register_model, native_generic=False
)
