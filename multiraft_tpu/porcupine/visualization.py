"""Interactive HTML timeline for operation histories — the visualizer
component (reference: porcupine/visualization.go:89-109, which emits a
self-contained HTML/JS page; this is a clean-room equivalent, not a
port of its template).

``visualize(model, history, path)`` writes one self-contained HTML file:
each client is a row, each operation a bar spanning [call, ret] on the
virtual-time axis, grouped per partition, with the operation description
(from ``model.describe_operation``) on hover and a pass/fail banner from
the checker verdict.

**Partial linearizations are rendered** (the reference's headline
feature, visualization.go:89-109 + checker.go:219-253): the longest
partial linearization of each partition is drawn as numbered
linearization points connected by a path; operations it could not
absorb are flagged red — on a failed or timed-out check this shows
exactly where linearization got stuck.  Clicking an operation switches
the path to the longest partial that includes *that* operation;
clicking the background restores the largest.  Used by the
kvraft/shardkv harnesses to dump failing histories (reference:
kvraft/test_test.go:365-381 dumps visualization on porcupine failure).
"""

from __future__ import annotations

import html
import json
from typing import List, Optional

from .checker import (
    CheckResult,
    LinearizationInfo,
    check_operations_verbose,
)
from .model import Model, Operation

__all__ = ["visualize", "visualize_info", "assert_linearizable"]

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>history: {title}</title>
<style>
 body {{ font: 13px system-ui, sans-serif; margin: 20px; background: #fafafa; }}
 .banner {{ padding: 8px 12px; border-radius: 6px; display: inline-block;
           margin-bottom: 14px; color: #fff; font-weight: 600; }}
 .ok {{ background: #2e7d32; }} .illegal {{ background: #c62828; }}
 .unknown {{ background: #ef6c00; }}
 .hint {{ color: #666; margin: 0 0 10px; }}
 .partition {{ margin: 18px 0 6px; font-weight: 600; color: #333; }}
 svg {{ background: #fff; border: 1px solid #ddd; border-radius: 4px; }}
 .op {{ cursor: pointer; }}
 .op rect {{ fill: #90caf9; stroke: #1565c0; }}
 .op.lin rect {{ fill: #a5d6a7; stroke: #2e7d32; }}
 .op.stuck rect {{ fill: #ef9a9a; stroke: #c62828; }}
 .op.sel rect {{ stroke-width: 2.5; }}
 .op:hover rect {{ fill: #ffe082; }}
 .op text {{ font-size: 10px; pointer-events: none; }}
 .linpath {{ fill: none; stroke: #37474f; stroke-width: 1.2;
            stroke-dasharray: 4 3; pointer-events: none; }}
 .linpt circle {{ fill: #263238; }}
 .linpt text {{ fill: #fff; font-size: 8px; text-anchor: middle;
               pointer-events: none; }}
 .linpt {{ pointer-events: none; }}
 #tip {{ position: fixed; background: #212121; color: #fff; padding: 4px 8px;
        border-radius: 4px; font-size: 12px; display: none; z-index: 10; }}
 .legend {{ margin: 0 0 12px; font-size: 12px; color: #444; }}
 .legend span {{ display: inline-block; margin-right: 16px; }}
 .sw {{ display: inline-block; width: 14px; height: 11px; border-radius: 2px;
       vertical-align: -1px; margin-right: 4px; border: 1px solid; }}
 .sw.lin {{ background: #a5d6a7; border-color: #2e7d32; }}
 .sw.stuck {{ background: #ef9a9a; border-color: #c62828; }}
 .sw.plain {{ background: #90caf9; border-color: #1565c0; }}
 .sw.pt {{ background: #263238; border-color: #263238; border-radius: 50%;
          width: 11px; }}
 .jump {{ margin: 2px 0 6px; font-size: 12px; }}
</style></head><body>
<h2>Operation history</h2>
<div class="banner {verdict_class}">{verdict}</div>
<p class="hint">Numbered dots mark linearization points of the longest
partial linearization; red bars never linearized within it.  Click a
bar to show the longest partial that includes that operation; click
the background to restore the largest.  The per-partition selector
jumps to (and selects) any operation by description.</p>
<div class="legend">
 <span><i class="sw lin"></i>linearized in the shown partial</span>
 <span><i class="sw stuck"></i>not absorbed by it</span>
 <span><i class="sw plain"></i>unchecked partition</span>
 <span><i class="sw pt"></i>linearization point (numbered in order)</span>
</div>
<div id="tip"></div>
<div id="content"></div>
<script>
const DATA = {data};
const tip = document.getElementById('tip');
const content = document.getElementById('content');
for (const part of DATA.partitions) {{
  // A partition the kill switch dropped (or that timed out before
  // recording any evidence) renders neutrally: red means "proven
  // stuck", never "not checked".
  const neutral = part.status === 'unchecked' ||
    (part.status === 'unknown' && part.partials.length === 0);
  const div = document.createElement('div');
  div.className = 'partition';
  div.textContent = 'partition: ' + part.name + ' — ' + part.status +
    ' (' + part.ops.length + ' ops' + (neutral ? ', no evidence recorded'
    : ', ' + part.partials.length + ' partial linearization(s), largest ' +
    (part.largest >= 0 ? part.partials[part.largest].length : 0) + '/' +
    part.ops.length) + ')';
  content.appendChild(div);
  // Jump-to-operation: select an op by description to scroll to it,
  // select it, and show the longest partial containing it.
  const jump = document.createElement('select');
  jump.className = 'jump';
  const opt0 = document.createElement('option');
  opt0.textContent = 'jump to operation…';
  opt0.value = '-1';
  jump.appendChild(opt0);
  part.ops.forEach((op, i) => {{
    const o = document.createElement('option');
    o.value = String(i);
    o.textContent = '#' + i + '  ' + op.desc;
    jump.appendChild(o);
  }});
  content.appendChild(jump);
  const clients = [...new Set(part.ops.map(o => o.client))].sort((a,b)=>a-b);
  const rowH = 26, pad = 44, width = 1100;
  const t0 = Math.min(...part.ops.map(o => o.call));
  const t1 = Math.max(...part.ops.map(o => o.ret));
  const scale = (width - pad - 10) / Math.max(t1 - t0, 1e-9);
  const X = t => pad + (t - t0) * scale;
  const rowY = i => clients.indexOf(part.ops[i].client) * rowH;
  const svgNS = 'http://www.w3.org/2000/svg';
  const svg = document.createElementNS(svgNS, 'svg');
  svg.setAttribute('width', width);
  svg.setAttribute('height', clients.length * rowH + 24);
  clients.forEach((c, row) => {{
    const label = document.createElementNS(svgNS, 'text');
    label.textContent = 'client ' + c;
    label.setAttribute('x', 2); label.setAttribute('y', row * rowH + 17);
    label.setAttribute('font-size', '11'); svg.appendChild(label);
  }});
  const opEls = [];
  part.ops.forEach((op, i) => {{
    const row = clients.indexOf(op.client);
    const g = document.createElementNS(svgNS, 'g');
    g.setAttribute('class', 'op');
    const r = document.createElementNS(svgNS, 'rect');
    const x = X(op.call);
    const w = Math.max((op.ret - op.call) * scale, 3);
    r.setAttribute('x', x); r.setAttribute('y', row * rowH + 4);
    r.setAttribute('width', w); r.setAttribute('height', rowH - 10);
    r.setAttribute('rx', 3);
    g.appendChild(r);
    const t = document.createElementNS(svgNS, 'text');
    t.textContent = op.desc.slice(0, Math.max(w / 6, 4));
    t.setAttribute('x', x + 3); t.setAttribute('y', row * rowH + 16);
    g.appendChild(t);
    g.addEventListener('mousemove', ev => {{
      tip.style.display = 'block';
      tip.style.left = (ev.clientX + 12) + 'px';
      tip.style.top = (ev.clientY + 12) + 'px';
      const where = g.dataset.linorder !== undefined
        ? '  linearized #' + g.dataset.linorder : '  (not linearized)';
      tip.textContent = op.desc + '  [' + op.call.toFixed(6) + ', '
                        + op.ret.toFixed(6) + ']' + where;
    }});
    g.addEventListener('mouseleave', () => tip.style.display = 'none');
    g.addEventListener('click', ev => {{
      ev.stopPropagation();
      if (part.op_partial[i] >= 0) showPartial(part.op_partial[i], i);
    }});
    svg.appendChild(g);
    opEls.push(g);
  }});
  const overlay = document.createElementNS(svgNS, 'g');
  svg.appendChild(overlay);
  function showPartial(pi, selected) {{
    overlay.innerHTML = '';
    const seq = pi >= 0 ? part.partials[pi] : [];
    const inSeq = new Set(seq);
    opEls.forEach((g, i) => {{
      let cls = 'op';
      if (!neutral) cls += inSeq.has(i) ? ' lin' : ' stuck';
      if (i === selected) cls += ' sel';
      g.setAttribute('class', cls);
      delete g.dataset.linorder;
    }});
    // Linearization points: each inside its op's interval, strictly
    // after the previous point.
    let prevX = -1e9;
    const pts = [];
    seq.forEach((i, k) => {{
      const op = part.ops[i];
      let x = Math.max(X(op.call) + 4, prevX + 9);
      x = Math.min(x, X(op.ret) - 2);
      // A concurrent op may lawfully linearize left of the previous
      // point (its window ends there) — keep its point inside its own
      // bar, but never drag LATER points leftward with it.
      prevX = Math.max(prevX, x);
      pts.push([x, rowY(i) + rowH / 2 - 1]);
      opEls[i].dataset.linorder = k + 1;
    }});
    if (pts.length > 1) {{
      const pl = document.createElementNS(svgNS, 'polyline');
      pl.setAttribute('class', 'linpath');
      pl.setAttribute('points', pts.map(p => p.join(',')).join(' '));
      overlay.appendChild(pl);
    }}
    pts.forEach((p, k) => {{
      const g = document.createElementNS(svgNS, 'g');
      g.setAttribute('class', 'linpt');
      const c = document.createElementNS(svgNS, 'circle');
      c.setAttribute('cx', p[0]); c.setAttribute('cy', p[1]);
      c.setAttribute('r', 6);
      g.appendChild(c);
      const t = document.createElementNS(svgNS, 'text');
      t.textContent = k + 1;
      t.setAttribute('x', p[0]); t.setAttribute('y', p[1] + 1);
      g.appendChild(t);
      overlay.appendChild(g);
    }});
  }}
  showPartial(part.largest, -1);
  document.body.addEventListener('click', () => showPartial(part.largest, -1));
  jump.addEventListener('click', ev => ev.stopPropagation());
  jump.addEventListener('change', ev => {{
    ev.stopPropagation();
    const i = parseInt(jump.value, 10);
    if (i < 0) return;
    showPartial(part.op_partial[i] >= 0 ? part.op_partial[i] : part.largest, i);
    opEls[i].scrollIntoView({{ block: 'center', behavior: 'smooth' }});
  }});
  content.appendChild(svg);
}}
</script></body></html>
"""


def _describe(model: Model, op: Operation) -> str:
    if model.describe_operation is not None:
        return model.describe_operation(op.input, op.output)
    return f"{op.input!r} -> {op.output!r}"


def visualize_info(
    model: Model,
    info: LinearizationInfo,
    path: str,
    verdict: CheckResult,
    title: str = "history",
) -> str:
    """Render a checked history from its partial-linearization evidence
    (reference: porcupine/visualization.go:102-109 VisualizePath).
    Returns the path."""
    partitions = []
    for i, part in enumerate(info.partitions):
        name = getattr(part[0].input, "key", str(i)) if part else str(i)
        partials = info.partials[i]
        largest = -1
        if partials:
            largest = max(range(len(partials)), key=lambda j: len(partials[j]))
        # Longest partial containing each op (for click-to-explore).
        op_partial = [-1] * len(part)
        for j, seq in enumerate(partials):
            for op_id in seq:
                cur = op_partial[op_id]
                if cur < 0 or len(partials[j]) > len(partials[cur]):
                    op_partial[op_id] = j
        pv = info.verdicts[i] if i < len(info.verdicts) else None
        status = "unchecked" if pv is None else pv.value
        partitions.append(
            {
                "name": str(name),
                "ops": [
                    {
                        "client": op.client_id,
                        "call": op.call,
                        "ret": op.ret,
                        "desc": _describe(model, op),
                    }
                    for op in part
                ],
                "partials": partials,
                "largest": largest,
                "op_partial": op_partial,
                "status": status,
            }
        )
    verdict_class = {
        CheckResult.OK: "ok",
        CheckResult.ILLEGAL: "illegal",
        CheckResult.UNKNOWN: "unknown",
    }[verdict]
    page = _PAGE.format(
        title=html.escape(title),
        verdict=f"linearizability: {verdict.value}",
        verdict_class=verdict_class,
        data=json.dumps(
            {"partitions": partitions},
        ),
    )
    with open(path, "w") as f:
        f.write(page)
    return path


def visualize(
    model: Model,
    history: List[Operation],
    path: str,
    verdict: Optional[CheckResult] = None,
    title: str = "history",
    timeout: Optional[float] = 5.0,
) -> str:
    """Check ``history`` (verbose: partial linearizations captured) and
    write a self-contained HTML timeline; returns the path.  A
    pre-computed ``verdict`` only overrides the banner — the evidence
    is always recomputed verbosely."""
    v, info = check_operations_verbose(model, history, timeout=timeout)
    return visualize_info(model, info, path, verdict or v, title=title)


def assert_linearizable(
    model: Model,
    history: List[Operation],
    timeout: Optional[float] = None,
    name: str = "history",
) -> CheckResult:
    """Assert a history is linearizable; on failure, dump the partial-
    linearization viz and point at it from the assertion message — the
    reference harnesses' behavior (kvraft/test_test.go:365-381).
    Returns the verdict (UNKNOWN passes, as in the reference)."""
    import os
    import re
    import tempfile

    from .checker import check_operations

    res = check_operations(model, history, timeout=timeout)
    if res is CheckResult.ILLEGAL:
        safe = re.sub(r"[^\w.-]", "_", name)
        path = os.path.join(
            tempfile.gettempdir(), f"linearizability_{safe}.html"
        )
        try:
            # Evidence pass: re-check verbosely (bounded) and render.
            v, info = check_operations_verbose(model, history, timeout=30.0)
            visualize_info(model, info, path, v, title=name)
            where = f"; viz dumped to {path}"
        except Exception as exc:  # pragma: no cover - viz must not mask
            where = f"; viz dump failed: {exc!r}"
        raise AssertionError(f"{name} is not linearizable{where}")
    return res
