"""Interactive HTML timeline for operation histories — the visualizer
component (reference: porcupine/visualization.go:89-109, which emits a
self-contained HTML/JS page; this is a clean-room equivalent, not a
port of its template).

``visualize(model, history, path)`` writes one self-contained HTML file:
each client is a row, each operation a bar spanning [call, ret] on the
virtual-time axis, grouped per partition, with the operation description
(from ``model.describe_operation``) on hover and a pass/fail banner from
the checker verdict.  Used by the kvraft/shardkv harnesses to dump
failing histories (reference: kvraft/test_test.go:365-381 dumps
visualization on porcupine failure).
"""

from __future__ import annotations

import html
import json
from typing import List, Optional

from .checker import CheckResult, check_operations
from .model import Model, Operation

__all__ = ["visualize"]

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>history: {title}</title>
<style>
 body {{ font: 13px system-ui, sans-serif; margin: 20px; background: #fafafa; }}
 .banner {{ padding: 8px 12px; border-radius: 6px; display: inline-block;
           margin-bottom: 14px; color: #fff; font-weight: 600; }}
 .ok {{ background: #2e7d32; }} .illegal {{ background: #c62828; }}
 .unknown {{ background: #ef6c00; }}
 .partition {{ margin: 18px 0 6px; font-weight: 600; color: #333; }}
 svg {{ background: #fff; border: 1px solid #ddd; border-radius: 4px; }}
 .op {{ cursor: pointer; }}
 .op rect {{ fill: #90caf9; stroke: #1565c0; }}
 .op:hover rect {{ fill: #ffe082; }}
 .op text {{ font-size: 10px; pointer-events: none; }}
 #tip {{ position: fixed; background: #212121; color: #fff; padding: 4px 8px;
        border-radius: 4px; font-size: 12px; display: none; z-index: 10; }}
</style></head><body>
<h2>Operation history</h2>
<div class="banner {verdict_class}">{verdict}</div>
<div id="tip"></div>
<div id="content"></div>
<script>
const DATA = {data};
const tip = document.getElementById('tip');
const content = document.getElementById('content');
for (const part of DATA.partitions) {{
  const div = document.createElement('div');
  div.className = 'partition';
  div.textContent = 'partition: ' + part.name + ' (' + part.ops.length + ' ops)';
  content.appendChild(div);
  const clients = [...new Set(part.ops.map(o => o.client))].sort((a,b)=>a-b);
  const rowH = 26, pad = 44, width = 1100;
  const t0 = Math.min(...part.ops.map(o => o.call));
  const t1 = Math.max(...part.ops.map(o => o.ret));
  const scale = (width - pad - 10) / Math.max(t1 - t0, 1e-9);
  const svgNS = 'http://www.w3.org/2000/svg';
  const svg = document.createElementNS(svgNS, 'svg');
  svg.setAttribute('width', width);
  svg.setAttribute('height', clients.length * rowH + 24);
  clients.forEach((c, row) => {{
    const label = document.createElementNS(svgNS, 'text');
    label.textContent = 'client ' + c;
    label.setAttribute('x', 2); label.setAttribute('y', row * rowH + 17);
    label.setAttribute('font-size', '11'); svg.appendChild(label);
  }});
  for (const op of part.ops) {{
    const row = clients.indexOf(op.client);
    const g = document.createElementNS(svgNS, 'g');
    g.setAttribute('class', 'op');
    const r = document.createElementNS(svgNS, 'rect');
    const x = pad + (op.call - t0) * scale;
    const w = Math.max((op.ret - op.call) * scale, 3);
    r.setAttribute('x', x); r.setAttribute('y', row * rowH + 4);
    r.setAttribute('width', w); r.setAttribute('height', rowH - 10);
    r.setAttribute('rx', 3);
    g.appendChild(r);
    const t = document.createElementNS(svgNS, 'text');
    t.textContent = op.desc.slice(0, Math.max(w / 6, 4));
    t.setAttribute('x', x + 3); t.setAttribute('y', row * rowH + 16);
    g.appendChild(t);
    g.addEventListener('mousemove', ev => {{
      tip.style.display = 'block';
      tip.style.left = (ev.clientX + 12) + 'px';
      tip.style.top = (ev.clientY + 12) + 'px';
      tip.textContent = op.desc + '  [' + op.call.toFixed(6) + ', '
                        + op.ret.toFixed(6) + ']';
    }});
    g.addEventListener('mouseleave', () => tip.style.display = 'none');
    svg.appendChild(g);
  }}
  content.appendChild(svg);
}}
</script></body></html>
"""


def _describe(model: Model, op: Operation) -> str:
    if model.describe_operation is not None:
        return model.describe_operation(op.input, op.output)
    return f"{op.input!r} -> {op.output!r}"


def visualize(
    model: Model,
    history: List[Operation],
    path: str,
    verdict: Optional[CheckResult] = None,
    title: str = "history",
) -> str:
    """Write a self-contained HTML timeline; returns the path."""
    if verdict is None:
        verdict = check_operations(model, history, timeout=1.0)
    partitions = []
    for i, part in enumerate(model.partitions(history)):
        name = getattr(part[0].input, "key", str(i)) if part else str(i)
        partitions.append(
            {
                "name": str(name),
                "ops": [
                    {
                        "client": op.client_id,
                        "call": op.call,
                        "ret": op.ret,
                        "desc": _describe(model, op),
                    }
                    for op in part
                ],
            }
        )
    verdict_class = {
        CheckResult.OK: "ok",
        CheckResult.ILLEGAL: "illegal",
        CheckResult.UNKNOWN: "unknown",
    }[verdict]
    page = _PAGE.format(
        title=html.escape(title),
        verdict=f"linearizability: {verdict.value}",
        verdict_class=verdict_class,
        data=json.dumps({"partitions": partitions}),
    )
    with open(path, "w") as f:
        f.write(page)
    return path
