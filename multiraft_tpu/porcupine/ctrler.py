"""Shard-controller specification model.

Linearizability spec for shardctrler histories — a second NON-KV model
(reference contract: porcupine/model.go:5-49; the reference ships only
the KV instance, models/kv.go, and never linearizability-checks its
controller — this model closes that gap the same way the service
itself exceeds the reference's empty shardkv skeleton).

The automaton state is the full CONFIG SEQUENCE (the controller is an
append-only log of configs: Query(num) reads history, so the state
cannot be just the latest config).  Join/Leave/Move append a new
config derived with the SAME pure :func:`..services.shardctrler.
rebalance` the replicated service applies — the spec and the
implementation share one rebalancing core, so they cannot drift.

States are tuples-of-tuples (hashable but large); the model rides the
model-generic compiled DFS (:mod:`.checker`), which interns each
distinct state to an int id once — exactly the shape where the
compiled search pays off over the Python DFS re-hashing the whole
config history every step.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

from ..services.shardctrler import NSHARDS, Config, rebalance
from .model import Model, Operation

__all__ = [
    "CtrlerOpInput",
    "CtrlerOpOutput",
    "ctrler_model",
    "ctrler_model_py",
    "freeze_config",
    "CTRL_QUERY",
    "CTRL_JOIN",
    "CTRL_LEAVE",
    "CTRL_MOVE",
]

CTRL_QUERY = "query"
CTRL_JOIN = "join"
CTRL_LEAVE = "leave"
CTRL_MOVE = "move"

# A frozen config: (num, shards tuple, ((gid, (server, ...)), ...)
# sorted by gid).  Hashable, order-canonical.
FrozenConfig = Tuple[int, Tuple[int, ...], Tuple[Tuple[int, Tuple[str, ...]], ...]]


def freeze_config(cfg: Config) -> FrozenConfig:
    return (
        cfg.num,
        tuple(cfg.shards),
        tuple(sorted((g, tuple(s)) for g, s in cfg.groups.items())),
    )


@dataclasses.dataclass(frozen=True)
class CtrlerOpInput:
    op: str = CTRL_QUERY
    # join: ((gid, (server, ...)), ...); leave: (gid, ...)
    servers: Tuple[Tuple[int, Tuple[str, ...]], ...] = ()
    gids: Tuple[int, ...] = ()
    shard: int = 0
    gid: int = 0
    num: int = -1


@dataclasses.dataclass(frozen=True)
class CtrlerOpOutput:
    config: FrozenConfig = (0, (0,) * NSHARDS, ())


def _init() -> Tuple[FrozenConfig, ...]:
    # Config 0: every shard owned by gid 0 (reference:
    # shardctrler/common.go — the zeroth config).
    return ((0, (0,) * NSHARDS, ()),)


def _next_config(
    state: Tuple[FrozenConfig, ...], inp: CtrlerOpInput
) -> FrozenConfig:
    num, shards, groups_t = state[-1]
    groups = {g: list(s) for g, s in groups_t}
    shards = list(shards)
    if inp.op == CTRL_JOIN:
        for g, srvs in inp.servers:
            groups[g] = list(srvs)
        shards = rebalance(shards, groups)
    elif inp.op == CTRL_LEAVE:
        for g in inp.gids:
            groups.pop(g, None)
        shards = rebalance(shards, groups)
    else:  # move: explicit assignment, no rebalance
        shards[inp.shard] = inp.gid
    return (
        num + 1,
        tuple(shards),
        tuple(sorted((g, tuple(s)) for g, s in groups.items())),
    )


def _step(state, inp: CtrlerOpInput, out: CtrlerOpOutput):
    """(mirrors the service apply path, services/shardctrler.py;
    reference: shardctrler/server.go:124-162)"""
    if inp.op == CTRL_QUERY:
        n = inp.num
        want = state[n] if 0 <= n < len(state) else state[-1]
        return out.config == want, state
    return True, state + (_next_config(state, inp),)


def _describe(inp: CtrlerOpInput, out: CtrlerOpOutput) -> str:
    if inp.op == CTRL_QUERY:
        return f"query({inp.num}) -> cfg#{out.config[0]}"
    if inp.op == CTRL_JOIN:
        return f"join({[g for g, _ in inp.servers]})"
    if inp.op == CTRL_LEAVE:
        return f"leave({list(inp.gids)})"
    return f"move(shard {inp.shard} -> gid {inp.gid})"


ctrler_model = Model(
    init=_init,
    step=_step,
    describe_operation=_describe,
)

# Pure-Python oracle for differential tests of the generic native DFS.
ctrler_model_py = dataclasses.replace(ctrler_model, native_generic=False)
