"""Sim↔engine differential conformance — the golden-parity rig.

SURVEY §7.2 step 5 demands golden parity between the two consensus
backends: the event-driven :class:`~multiraft_tpu.raft.node.RaftNode`
simulator is the correctness oracle, and the batched tensor engine
(:mod:`multiraft_tpu.engine`) must agree with it.  This module drives
BOTH backends through the *same* seeded scenario script — a timed
schedule of crashes, restarts, partitions, message loss, reordering,
and a serialized client pump — and asserts:

* **Identical committed command streams.**  Clients propose commands
  ``0..N-1`` serially (command *k+1* only after *k* is observed
  committed), so on every backend the committed log, deduplicated by
  first occurrence, must be exactly ``[0, 1, ..., N-1]`` — the same
  sequence, in the same order.  This is the state-machine equivalence
  the services above consume: the applied state is a pure function of
  this stream.  (Terms and absolute indices are NOT compared across
  backends: virtual-seconds futures vs synchronous ticks elect leaders
  at different terms by construction.  Each backend's own
  ``(index, term)`` stream is instead checked for internal safety —
  see below.)
* **Per-tick / per-apply safety.**  The sim runs under the harness's
  cross-server invariant appliers (reference: raft/config.go:144-186);
  the engine runs under :class:`InvariantMonitor`, which asserts
  election safety, committed-term durability, log matching, and
  monotonicity after every tick.
* **Convergence.**  After the script's heal point, both backends must
  commit all N commands within a bounded drain window and converge to
  matching logs.

Timing map: one engine tick = :data:`TICK_S` = 10 ms of sim virtual
time, under which the engine's default timers (HB_TICKS=9,
ELECT_MIN/MAX=30/60) equal the sim's (90 ms heartbeat, 300–600 ms
election window; reference: raft/raft.go:42-50).

Fuzz mode: :func:`random_scenario` generates a seeded random fault
script; tests/test_conformance.py runs a fixed scenario battery plus
fuzz seeds on both backends.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

__all__ = [
    "Event",
    "Scenario",
    "ConformanceError",
    "run_sim",
    "run_engine",
    "run_both",
    "random_scenario",
    "SCENARIOS",
    "TICK_S",
]

TICK_S = 0.01  # one engine tick == 10 ms of sim virtual time

# Client pump pacing: a proposed-but-uncommitted command is re-proposed
# after this long (covers leader loss / truncated entries on both
# backends; duplicates are deduplicated by the stream comparison).
RETRY_S = 1.0

# Drain window after heal-all within which every command must commit.
DRAIN_S = 40.0

# Post-heal flush command (filtered from streams): forces commit
# rediscovery after a full restart, where the current-term guard blocks
# commit advance until a fresh entry commits.
SENTINEL = -1


class ConformanceError(AssertionError):
    pass


@dataclasses.dataclass(frozen=True)
class Event:
    """One timed fault-script action.

    ``kind`` ∈ {crash, restart, crash_leader, restart_all, cut, heal,
    cut_leader, heal_all, drop, reorder}; ``arg`` is a replica id for
    the targeted kinds, a probability for ``drop`` (0 disables; the sim
    maps any nonzero onto labrpc's unreliable mode), a bool for
    ``reorder``.
    """

    time_s: float
    kind: str
    arg: Any = None


@dataclasses.dataclass(frozen=True)
class Scenario:
    """A backend-agnostic conformance scenario."""

    name: str
    n_cmds: int = 25
    P: int = 3
    events: Tuple[Event, ...] = ()
    heal_at_s: float = 3.0  # when heal-all fires; drain phase follows
    # burst > 1 pipelines proposals; ordering across a burst is not
    # defined (backlog re-queues scramble it), so ordered=False relaxes
    # the stream assert to completeness + per-backend safety.
    burst: int = 1
    ordered: bool = True
    engine_L: int = 48  # ring capacity (small values force compaction)
    sim_snapshot: bool = False  # sim-side service snapshots every 10


# ---------------------------------------------------------------------------
# Sim backend runner
# ---------------------------------------------------------------------------


def _dedup(stream: List[int]) -> List[int]:
    seen: Set[int] = set()
    out = []
    for v in stream:
        if v not in seen:
            seen.add(v)
            out.append(v)
    return out


def run_sim(sc: Scenario, seed: int = 0) -> List[int]:
    """Run ``sc`` on the RaftNode simulator; return the deduplicated
    committed command stream.  Safety is enforced continuously by the
    harness invariant appliers; raises on timeout or violation."""
    from .harness.raft_harness import RaftHarness

    h = RaftHarness(sc.P, unreliable=False, snapshot=sc.sim_snapshot, seed=seed)
    try:
        return _run_sim_inner(h, sc)
    finally:
        h.cleanup()


def _sim_leader(h) -> Optional[int]:
    best, best_term = None, -1
    for i in range(h.n):
        r = h.rafts[i]
        if r is not None and h.connected[i]:
            term, is_leader = r.get_state()
            if is_leader and term > best_term:
                best, best_term = i, term
    return best


def _run_sim_inner(h, sc: Scenario) -> List[int]:
    events = sorted(sc.events, key=lambda e: e.time_s)
    ei = 0
    inflight: Dict[int, float] = {}  # cmd -> last propose time
    next_cmd = 0
    committed: Set[int] = set()
    crashed: Set[int] = set()
    cut: Set[int] = set()
    healed = False
    deadline = sc.heal_at_s + DRAIN_S
    real_cmds = set(range(sc.n_cmds))
    sentinel_at = float("-inf")

    def fire(ev: Event) -> None:
        nonlocal healed
        kind, a = ev.kind, ev.arg
        if kind in ("crash_leader", "cut_leader"):
            a = _sim_leader(h)
            if a is None:
                return
            kind = "crash" if kind == "crash_leader" else "cut"
        if kind == "crash":
            if a not in crashed:
                h.crash1(a)
                crashed.add(a)
                cut.discard(a)
        elif kind == "restart":
            if a in crashed:
                h.start1(a)
                h.connect(a)
                crashed.discard(a)
        elif kind == "restart_all":
            for i in sorted(crashed):
                h.start1(i)
                h.connect(i)
            crashed.clear()
        elif kind == "cut":
            if a not in crashed and a not in cut:
                h.disconnect(a)
                cut.add(a)
        elif kind == "heal":
            if a in cut:
                h.connect(a)
                cut.discard(a)
        elif kind == "heal_all":
            for i in sorted(crashed):
                h.start1(i)
            crashed.clear()
            for i in range(h.n):
                h.connect(i)
            cut.clear()
            h.net.set_reliable(True)
            h.net.set_long_reordering(False)
            healed = True
        elif kind == "drop":
            h.net.set_reliable(not a)
        elif kind == "reorder":
            h.net.set_long_reordering(bool(a))
        else:  # pragma: no cover - scenario author error
            raise ValueError(f"unknown event kind {kind!r}")

    while h.sched.now < deadline:
        now = h.sched.now
        while ei < len(events) and events[ei].time_s <= now:
            fire(events[ei])
            ei += 1
        if not healed and now >= sc.heal_at_s:
            fire(Event(now, "heal_all"))
        # Observe commits (any replica applying a value proves commit).
        visible: Set[int] = set()
        for log in h.logs:
            visible.update(log.values())
        for v in visible:
            if v not in committed:
                committed.add(v)
                inflight.pop(v, None)
        # Done only when the CURRENT logs cover every command: a
        # crash-restart wipes the harness apply record, and commit is
        # only rediscovered once a current-term entry commits (the
        # current-term guard), so after healing we must drive a fresh
        # sentinel until the whole prefix re-applies — the reference's
        # post-heal one() does the same (raft/config.go:569-619).
        if healed and len(visible & real_cmds) == sc.n_cmds:
            break
        if (
            healed
            and next_cmd >= sc.n_cmds
            and now - sentinel_at >= RETRY_S
        ):
            lead = _sim_leader(h)
            if lead is not None:
                _, _, ok = h.rafts[lead].start(SENTINEL)
                if ok:
                    sentinel_at = now
        # Pump: keep up to ``burst`` uncommitted proposals in flight.
        stale = [c for c, t0 in inflight.items() if now - t0 >= RETRY_S]
        want_new = sc.burst - len(inflight)
        for c in stale + [None] * max(0, want_new):
            if c is None:
                if next_cmd >= sc.n_cmds:
                    continue
                c, is_new = next_cmd, True
            else:
                is_new = False
            lead = _sim_leader(h)
            if lead is None:
                break
            _, _, ok = h.rafts[lead].start(c)
            if ok:
                inflight[c] = now
                if is_new:
                    next_cmd += 1
            else:
                break
        h.sched.run_for(2 * TICK_S)

    n_visible = len(
        real_cmds & set().union(*[set(l.values()) for l in h.logs])
    )
    if n_visible != sc.n_cmds:
        raise ConformanceError(
            f"sim[{sc.name}]: only {n_visible}/{sc.n_cmds} commands "
            f"applied by t={h.sched.now:.1f}s "
            f"({len(committed)} ever observed committed)"
        )
    # Let replication quiesce, then extract the stream by index order.
    h.sched.run_for(1.0)
    idx2cmd: Dict[int, int] = {}
    for log in h.logs:
        idx2cmd.update(log)  # cross-server consistency enforced by appliers
    stream = [
        v for v in _dedup([idx2cmd[i] for i in sorted(idx2cmd)]) if v >= 0
    ]
    if h.apply_err:
        raise ConformanceError(f"sim[{sc.name}]: {h.apply_err}")
    return stream


# ---------------------------------------------------------------------------
# Engine backend runner
# ---------------------------------------------------------------------------


def run_engine(sc: Scenario, seed: int = 0, groups: int = 2) -> List[List[int]]:
    """Run ``sc`` on the batched engine with ``groups`` independent
    lanes (each lane experiences the same fault schedule but draws its
    own timer jitter) under the per-tick :class:`InvariantMonitor`.
    Returns one deduplicated committed command stream per group."""
    from .engine.core import EngineConfig
    from .engine.host import EngineDriver
    from .engine.invariants import InvariantMonitor

    cfg = EngineConfig(
        G=groups,
        P=sc.P,
        L=sc.engine_L,
        E=4,
        INGEST=max(4, sc.burst),
    )
    d = EngineDriver(cfg, seed=seed)
    mon = InvariantMonitor(d)
    G = groups

    events = sorted(sc.events, key=lambda e: e.time_s)
    ei = 0
    heal_tick = int(round(sc.heal_at_s / TICK_S))
    deadline = heal_tick + int(round(DRAIN_S / TICK_S))
    retry_ticks = int(round(RETRY_S / TICK_S))

    inflight: List[Dict[int, int]] = [dict() for _ in range(G)]
    next_cmd = [0] * G
    committed: List[Set[int]] = [set() for _ in range(G)]
    raw_stream: List[List[int]] = [[] for _ in range(G)]
    read_upto = [0] * G
    crashed: Set[Tuple[int, int]] = set()
    cut: Set[Tuple[int, int]] = set()
    healed = False
    # (g, abs index) -> term of the accepting leader: disambiguates a
    # stale payload binding from the entry that actually committed.
    bind_term: Dict[Tuple[int, int], int] = {}
    # Indices bound more than once at distinct terms: the one case
    # where the payload dict may misreport the committed value when no
    # ring still covers the index (see the frontier read below).
    suspect: Set[Tuple[int, int]] = set()

    def evicted(payload: Any) -> None:
        # The bound entry lost its slot: mark it immediately stale so
        # the pump re-proposes it next tick (unless it committed).
        g, c = payload
        if c not in committed[g] and c in inflight[g]:
            inflight[g][c] = -(10**9)

    d.on_payload_evicted = evicted

    def fire(ev: Event) -> None:
        nonlocal healed
        kind, a = ev.kind, ev.arg
        if kind == "drop":
            d.drop_prob = float(a)
            return
        if kind == "reorder":
            d.set_reorder(2.0 / 3.0 if a else 0.0, 2, 10)
            return
        for g in range(G):
            k, p = kind, a
            if k in ("crash_leader", "cut_leader"):
                p = d.leader_of(g)
                if p is None:
                    continue
                k = "crash" if k == "crash_leader" else "cut"
            if k == "crash":
                if (g, p) not in crashed:
                    # A crash supersedes a live partition (the sim's
                    # crash1 drops the cut; start1+connect reconnects),
                    # so heal the edges — they're inert while dead.
                    if (g, p) in cut:
                        d.partition_replica(g, p, True)
                        cut.discard((g, p))
                    d.set_alive(g, p, False)
                    crashed.add((g, p))
            elif k == "restart":
                if (g, p) in crashed:
                    d.restart_replica(g, p)
                    mon.note_restart(g, p)
                    crashed.discard((g, p))
            elif k == "restart_all":
                for gg, pp in sorted(crashed):
                    if gg == g:
                        d.restart_replica(gg, pp)
                        mon.note_restart(gg, pp)
                crashed.difference_update({c for c in list(crashed) if c[0] == g})
            elif k == "cut":
                if (g, p) not in cut:
                    d.partition_replica(g, p, False)
                    cut.add((g, p))
            elif k == "heal":
                if (g, p) in cut:
                    d.partition_replica(g, p, True)
                    cut.discard((g, p))
            elif k == "heal_all":
                pass  # handled once below
            else:  # pragma: no cover - scenario author error
                raise ValueError(f"unknown event kind {kind!r}")
        if kind == "heal_all":
            for g, p in sorted(crashed):
                d.restart_replica(g, p)
                mon.note_restart(g, p)
            crashed.clear()
            for g, p in sorted(cut):
                d.partition_replica(g, p, True)
            cut.clear()
            d.drop_prob = 0.0
            d.set_reorder(0.0)
            healed = True

    while d.tick < deadline:
        now_s = d.tick * TICK_S
        while ei < len(events) and events[ei].time_s <= now_s:
            fire(events[ei])
            ei += 1
        if not healed and d.tick >= heal_tick:
            fire(Event(now_s, "heal_all"))
        # Pump each group.
        for g in range(G):
            stale = [
                c for c, t0 in inflight[g].items()
                if d.tick - t0 >= retry_ticks
            ]
            want_new = sc.burst - len(inflight[g])
            for c in stale:
                d.start(g, (g, c))
                inflight[g][c] = d.tick
            for _ in range(max(0, want_new)):
                if next_cmd[g] >= sc.n_cmds:
                    break
                c = next_cmd[g]
                d.start(g, (g, c))
                inflight[g][c] = d.tick
                next_cmd[g] += 1
        metrics = d.step()
        st = d.np_state()
        mon.observe(st)
        # Bind fresh acceptances to the term they carry (stamped
        # device-side by the tick, metrics["accept_term"]); a re-bind
        # at a different term marks the index ambiguous.
        accepted = np.asarray(metrics["accepted"])
        starts = np.asarray(metrics["start_index"])
        accept_terms = np.asarray(metrics["accept_term"])
        for g in np.nonzero(accepted)[0]:
            gi = int(g)
            t_acc = int(accept_terms[g])
            for off in range(int(accepted[g])):
                slot = (gi, int(starts[g]) + 1 + off)
                old_t = bind_term.get(slot)
                if old_t is not None and old_t != t_acc:
                    suspect.add(slot)
                bind_term[slot] = t_acc
        # Advance the committed-stream read frontier.
        commit_max = st["commit"].max(axis=1)
        for g in range(G):
            c = int(commit_max[g])
            for i in range(read_upto[g] + 1, c + 1):
                payload = d.payloads.get((g, i))
                if payload is None:
                    continue  # index never bound (cannot happen in practice)
                # Verify the binding against the committed term where
                # any replica's ring still covers index i; a mismatch
                # means the binding is from a later, uncommitted
                # acceptance at i (revived-branch race) — skip it and
                # let the retry path settle the command.  When no ring
                # covers i (compacted the tick it committed), the
                # binding is still exact unless the index was ever
                # bound at two distinct terms (``suspect``): with a
                # single acceptance, the committed entry can only be
                # that acceptance.
                bt = bind_term.get((g, i))
                ok = True
                if bt is not None:
                    covered = False
                    for p in range(sc.P):
                        base = int(st["base"][g, p])
                        last = base + int(st["log_len"][g, p])
                        if base < i <= last:
                            covered = True
                            ok = int(st["log_term"][g, p][i % cfg.L]) == bt
                            break
                    if not covered and (g, i) in suspect:
                        ok = False
                if not ok:
                    continue
                _, cval = payload
                raw_stream[g].append(cval)
                if cval not in committed[g]:
                    committed[g].add(cval)
                    inflight[g].pop(cval, None)
            read_upto[g] = max(read_upto[g], c)
        if healed and all(len(committed[g]) == sc.n_cmds for g in range(G)):
            break

    for g in range(G):
        if len(committed[g]) != sc.n_cmds:
            raise ConformanceError(
                f"engine[{sc.name}] group {g}: only {len(committed[g])}/"
                f"{sc.n_cmds} commands committed by tick {d.tick}"
            )
        d.check_log_matching(g)
    return [_dedup(s) for s in raw_stream]


# ---------------------------------------------------------------------------
# Differential assertion + scenario battery
# ---------------------------------------------------------------------------


def run_both(sc: Scenario, seed: int = 0) -> None:
    """Run ``sc`` on both backends and assert the committed command
    streams are identical (and, for ordered scenarios, in proposal
    order)."""
    expect = list(range(sc.n_cmds))
    sim_stream = run_sim(sc, seed=seed)
    engine_streams = run_engine(sc, seed=seed)
    if sc.ordered:
        if sim_stream != expect:
            raise ConformanceError(
                f"sim[{sc.name}]: committed stream {sim_stream} != {expect}"
            )
        for g, s in enumerate(engine_streams):
            if s != expect:
                raise ConformanceError(
                    f"engine[{sc.name}] group {g}: stream {s} != {expect}"
                )
        assert all(s == sim_stream for s in engine_streams)
    else:
        if sorted(sim_stream) != expect:
            raise ConformanceError(
                f"sim[{sc.name}]: committed set {sorted(sim_stream)} != {expect}"
            )
        for g, s in enumerate(engine_streams):
            if sorted(s) != expect:
                raise ConformanceError(
                    f"engine[{sc.name}] group {g}: set {sorted(s)} != {expect}"
                )


def random_scenario(seed: int) -> Scenario:
    """Fuzz mode: a seeded random fault script, safe-by-construction
    (faults stop at heal_at_s; the drain phase completes the pump)."""
    rng = random.Random(seed)
    P = rng.choice([3, 3, 5])
    heal_at = rng.uniform(3.0, 5.0)
    events: List[Event] = []
    t = 0.5
    max_down = (P - 1) // 2
    n_down = 0  # crashes + cuts currently outstanding (leader kinds count)
    cut_now: List[int] = []
    while t < heal_at - 0.5:
        roll = rng.random()
        if roll < 0.3 and n_down < max_down:
            kind = rng.choice(["crash", "cut", "crash_leader"])
            p = None if kind == "crash_leader" else rng.randrange(P)
            if p is not None and p in cut_now:
                pass  # already cut; skip this beat
            else:
                events.append(Event(t, kind, p))
                n_down += 1
                if kind == "cut":
                    cut_now.append(p)
        elif roll < 0.5 and n_down:
            # Revive everything at once (restart crashes, heal cuts) —
            # the coarse heal keeps bookkeeping backend-agnostic.
            events.append(Event(t, "restart_all"))
            for p in cut_now:
                events.append(Event(t, "heal", p))
            cut_now.clear()
            n_down = 0
        elif roll < 0.7:
            events.append(Event(t, "drop", rng.choice([0.0, 0.1, 0.2])))
        elif roll < 0.8:
            events.append(Event(t, "reorder", rng.random() < 0.5))
        t += rng.uniform(0.3, 0.8)
    return Scenario(
        name=f"fuzz-{seed}",
        n_cmds=20,
        P=P,
        events=tuple(events),
        heal_at_s=heal_at,
    )


SCENARIOS: Dict[str, Scenario] = {
    s.name: s
    for s in [
        Scenario(name="quiet", n_cmds=30, heal_at_s=0.5),
        Scenario(
            name="leader_crash",
            events=(
                Event(1.0, "crash_leader"),
                Event(2.2, "restart_all"),
            ),
        ),
        Scenario(
            name="follower_crash",
            events=(Event(1.0, "crash", 2), Event(2.2, "restart", 2)),
        ),
        Scenario(
            name="rolling_leader_crashes",
            heal_at_s=5.0,
            events=(
                Event(1.0, "crash_leader"),
                Event(1.8, "restart_all"),
                Event(2.4, "crash_leader"),
                Event(3.2, "restart_all"),
                Event(3.8, "crash_leader"),
                Event(4.6, "restart_all"),
            ),
        ),
        Scenario(
            name="partition_leader",
            events=(Event(1.0, "cut_leader"), Event(2.2, "heal_all")),
        ),
        Scenario(
            name="partition_cycle",
            heal_at_s=4.5,
            events=(
                Event(1.0, "cut", 0),
                Event(1.8, "heal", 0),
                Event(2.0, "cut", 1),
                Event(2.8, "heal", 1),
                Event(3.0, "cut", 2),
                Event(3.8, "heal", 2),
            ),
        ),
        Scenario(
            name="unreliable",
            n_cmds=20,
            heal_at_s=4.0,
            events=(Event(0.0, "drop", 0.1),),
        ),
        Scenario(
            name="reorder",
            n_cmds=20,
            heal_at_s=4.0,
            events=(Event(0.0, "reorder", True),),
        ),
        Scenario(
            name="snapshot_pressure",
            n_cmds=60,
            burst=6,
            ordered=False,
            engine_L=24,
            sim_snapshot=True,
            heal_at_s=4.0,
            events=(Event(1.0, "cut", 1), Event(3.0, "heal", 1)),
        ),
        Scenario(
            name="cocktail",
            n_cmds=20,
            heal_at_s=5.0,
            events=(
                Event(0.0, "drop", 0.1),
                Event(1.0, "cut", 0),
                Event(2.0, "heal", 0),
                Event(2.2, "crash_leader"),
                Event(3.2, "restart_all"),
                Event(3.5, "reorder", True),
            ),
        ),
        Scenario(
            name="total_outage",
            events=(
                Event(1.0, "crash", 0),
                Event(1.05, "crash", 1),
                Event(1.1, "crash", 2),
                Event(2.0, "restart_all"),
            ),
        ),
        Scenario(
            name="five_peers_two_down",
            P=5,
            events=(
                Event(1.0, "crash", 1),
                Event(1.2, "crash", 3),
                Event(2.4, "restart_all"),
            ),
        ),
    ]
}
