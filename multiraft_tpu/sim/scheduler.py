"""Virtual-time discrete-event scheduler — the framework's host runtime.

The reference runs each Raft peer as 3+2(n-1) goroutines on wall-clock
timers (reference: raft/raft.go:51-87, labrpc/labrpc.go:153-165).  The
TPU-native design inverts that: every node, client, and network delivery is
an *event* on one deterministic virtual clock.  This gives

  * determinism — a seeded run replays bit-for-bit (no data races by
    construction, replacing ``go test -race``),
  * speed — a "5 second" fault-injection scenario executes in milliseconds
    of real time because sleeps cost nothing,
  * a direct path to the batched engine — the engine's tick loop is this
    scheduler with a fixed tick quantum and a dense mailbox.

Blocking control flow (clerk retry loops, server wait-channels —
reference: kvraft/client.go:47-71, kvraft/server.go:56-96) is expressed as
generator coroutines that ``yield`` :class:`Future` objects.
"""

from __future__ import annotations

import heapq
import types
from typing import Any, Callable, Generator, Optional

__all__ = [
    "Future",
    "Scheduler",
    "Timer",
    "TIMEOUT",
    "DeadlockError",
]


class _TimeoutSentinel:
    """Unique sentinel distinguishing a timeout from any RPC reply."""

    _instance: "_TimeoutSentinel | None" = None

    def __new__(cls) -> "_TimeoutSentinel":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return "<TIMEOUT>"

    def __bool__(self) -> bool:
        return False


TIMEOUT = _TimeoutSentinel()


class DeadlockError(RuntimeError):
    """Raised when the event loop runs dry while a caller still waits."""


class Future:
    """A one-shot value container resolved by the scheduler.

    Coroutines ``yield`` a Future to suspend until it resolves.  Unlike
    asyncio futures there is no exception transport — failures are encoded
    as values (``None`` for a dropped RPC, :data:`TIMEOUT` for a timer
    race), mirroring labrpc's boolean ``ok`` result
    (reference: labrpc/labrpc.go:87-126).
    """

    __slots__ = ("done", "value", "_callbacks")

    def __init__(self) -> None:
        self.done = False
        self.value: Any = None
        self._callbacks: list[Callable[["Future"], None]] = []

    def resolve(self, value: Any = None) -> None:
        if self.done:
            return
        self.done = True
        self.value = value
        callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            cb(self)

    def add_done_callback(self, cb: Callable[["Future"], None]) -> None:
        if self.done:
            cb(self)
        else:
            self._callbacks.append(cb)


class Timer:
    """Cancellable handle for a scheduled callback."""

    __slots__ = ("when", "cancelled", "_fn", "_args")

    def __init__(self, when: float, fn: Callable, args: tuple) -> None:
        self.when = when
        self.cancelled = False
        self._fn = fn
        self._args = args

    def cancel(self) -> None:
        self.cancelled = True
        self._fn = None
        self._args = ()


class Scheduler:
    """Deterministic virtual-time event loop.

    All timestamps are virtual seconds.  Events at equal timestamps fire in
    scheduling order (a monotone sequence number breaks ties), so a seeded
    simulation is fully reproducible.
    """

    def __init__(self) -> None:
        self.now: float = 0.0
        self._heap: list[tuple[float, int, Timer]] = []
        self._seq = 0
        # Count of live (uncancelled, unfired) events, kept so tests can
        # detect runaway simulations cheaply.
        self.fired_events = 0

    # -- scheduling -------------------------------------------------------

    def call_at(self, when: float, fn: Callable, *args: Any) -> Timer:
        if when < self.now:
            when = self.now
        self._seq += 1
        timer = Timer(when, fn, args)
        heapq.heappush(self._heap, (when, self._seq, timer))
        return timer

    def call_after(self, delay: float, fn: Callable, *args: Any) -> Timer:
        return self.call_at(self.now + delay, fn, *args)

    def call_soon(self, fn: Callable, *args: Any) -> Timer:
        return self.call_at(self.now, fn, *args)

    # -- futures / coroutines --------------------------------------------

    def sleep(self, delay: float) -> Future:
        fut = Future()
        self.call_after(delay, fut.resolve, None)
        return fut

    def with_timeout(self, fut: Future, timeout: float) -> Future:
        """A future resolving to ``fut.value``, or :data:`TIMEOUT` if the
        timer wins — the clerk's 100 ms retry pattern
        (reference: kvraft/client.go:57-69)."""
        out = Future()
        timer = self.call_after(timeout, out.resolve, TIMEOUT)

        def _done(f: Future) -> None:
            timer.cancel()
            out.resolve(f.value)

        fut.add_done_callback(_done)
        return out

    def spawn(self, gen: Generator) -> Future:
        """Drive a generator coroutine; the returned future resolves with
        the generator's return value.

        Resolving the returned future externally *cancels* the coroutine:
        the next step notices and closes the generator instead of driving
        it further.  ``BlockingClerk`` uses this to abandon retry loops
        whose caller timed out."""
        result = Future()
        if not isinstance(gen, types.GeneratorType):
            # Allow plain functions that return a value immediately.
            result.resolve(gen)
            return result

        def step(send_value: Any) -> None:
            if result.done:  # cancelled from outside
                gen.close()
                return
            try:
                waited = gen.send(send_value)
            except StopIteration as stop:
                result.resolve(stop.value)
                return
            if isinstance(waited, Future):
                waited.add_done_callback(lambda f: step(f.value))
            elif isinstance(waited, (int, float)):
                # ``yield seconds`` sleeps.
                self.call_after(float(waited), step, None)
            else:  # pragma: no cover - defensive
                raise TypeError(f"coroutine yielded {waited!r}")

        self.call_soon(step, None)
        return result

    # -- running ----------------------------------------------------------

    def _pop(self) -> Optional[Timer]:
        while self._heap:
            _, _, timer = heapq.heappop(self._heap)
            if not timer.cancelled:
                return timer
        return None

    def run_until(
        self,
        fut: Optional[Future] = None,
        deadline: Optional[float] = None,
        max_events: Optional[int] = None,
    ) -> Any:
        """Run events until ``fut`` resolves (returning its value), the
        virtual ``deadline`` passes, or the heap drains.

        With a future and no deadline, a drained heap means no event can
        ever resolve it — that is a deadlock, reported loudly rather than
        hung (the reference relies on the 2-minute wall-clock test cap for
        this, raft/config.go:342-347).
        """
        budget = max_events
        while True:
            if fut is not None and fut.done:
                return fut.value
            if budget is not None and budget <= 0:
                raise RuntimeError("scheduler exceeded max_events budget")
            timer = self._pop()
            if timer is None:
                if fut is not None:
                    raise DeadlockError(
                        f"event loop drained at t={self.now:.6f} with an "
                        "unresolved future — simulated deadlock"
                    )
                if deadline is not None and deadline > self.now:
                    self.now = deadline
                return None
            if deadline is not None and timer.when > deadline:
                # Put it back; the caller only wanted time advanced so far.
                self._seq += 1
                heapq.heappush(self._heap, (timer.when, self._seq, timer))
                self.now = deadline
                return fut.value if (fut is not None and fut.done) else None
            self.now = timer.when
            fn, args = timer._fn, timer._args
            timer._fn, timer._args = None, ()
            self.fired_events += 1
            if budget is not None:
                budget -= 1
            fn(*args)

    def run_for(self, duration: float) -> None:
        """Advance virtual time by ``duration``, firing due events."""
        self.run_until(deadline=self.now + duration)

    def pending_events(self) -> int:
        return sum(1 for _, _, t in self._heap if not t.cancelled)
