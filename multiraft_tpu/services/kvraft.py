"""Linearizable replicated KV service (reference: src/kvraft).

Architecture mirrors the reference: a unified ``Command`` RPC feeds ops
through the Raft log; a per-client dup table gives at-most-once apply;
per-request wait continuations match apply-loop completions back to
blocked RPC handlers; the service snapshots its state machine when the
raft state grows (reference: kvraft/server.go:40-183).

Event-driven differences from the Go original: the RPC handler is a
generator coroutine suspended on a future instead of a goroutine on a
channel, and the apply "loop" is the Raft node's apply callback.

Documented divergences (SURVEY §7.5): the snapshot trigger really fires
at 0.8×maxraftstate (the reference's integer division makes its 0.8
threshold effectively 1.0, kvraft/server.go:151); ``ErrTimeout`` has no
leading space (kvraft/rpc.go:7); the legacy unused Get/PutAppend RPC
types are not reproduced.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..raft.messages import ApplyMsg
from ..raft.node import RaftNode
from ..raft.persister import Persister
from ..sim.scheduler import Future, Scheduler, TIMEOUT
from ..transport import codec
from ..transport.network import ClientEnd

__all__ = [
    "OK",
    "ERR_NO_KEY",
    "ERR_WRONG_LEADER",
    "ERR_TIMEOUT",
    "GET",
    "PUT",
    "APPEND",
    "CommandArgs",
    "CommandReply",
    "MemoryKV",
    "KVServer",
    "Clerk",
]

# Error strings (reference: kvraft/rpc.go:3-12).
OK = "OK"
ERR_NO_KEY = "ErrNoKey"
ERR_WRONG_LEADER = "ErrWrongLeader"
ERR_TIMEOUT = "ErrTimeout"

GET = "Get"
PUT = "Put"
APPEND = "Append"

# Server-side wait before giving up on a started op
# (reference: kvraft/server.go:80 — 99 ms) and clerk per-attempt
# timeout before rotating servers (reference: kvraft/client.go:57 —
# 100 ms), both from the config system (MULTIRAFT_SERVER_WAIT /
# MULTIRAFT_CLERK_RETRY).
from ..utils.config import settings as _settings

SERVER_WAIT = _settings().service.server_wait
CLERK_RETRY = _settings().service.clerk_retry

# Pause after a full failed sweep of all servers before retrying
# (reference analog: shardctrler/client.go:52-62's 100 ms inter-sweep
# sleep); kept short so post-election client latency stays low.
SWEEP_BACKOFF = 0.02


@codec.registered
@dataclasses.dataclass
class CommandArgs:
    """(reference: kvraft/rpc.go CommandArgs)"""

    key: str = ""
    value: str = ""
    op: str = GET
    client_id: int = 0
    command_id: int = 0


@codec.registered
@dataclasses.dataclass
class CommandReply:
    err: str = OK
    value: str = ""


@codec.registered
@dataclasses.dataclass
class Op:
    """The entry actually replicated through Raft."""

    key: str = ""
    value: str = ""
    op: str = GET
    client_id: int = 0
    command_id: int = 0


class MemoryKV:
    """(reference: kvraft/memoryKV.go:3-36)"""

    def __init__(self) -> None:
        self.data: Dict[str, str] = {}

    def get(self, key: str) -> tuple[str, str]:
        if key in self.data:
            return self.data[key], OK
        return "", ERR_NO_KEY

    def put(self, key: str, value: str) -> str:
        self.data[key] = value
        return OK

    def append(self, key: str, value: str) -> str:
        self.data[key] = self.data.get(key, "") + value
        return OK


class KVServer:
    """Replicated KV server (reference: kvraft/server.go).

    RPC surface: ``KVServer.command``.  Construct one per peer; it owns
    its RaftNode."""

    def __init__(
        self,
        sched: Scheduler,
        ends: List[ClientEnd],
        me: int,
        persister: Persister,
        maxraftstate: int = -1,
        seed: int = 0,
    ) -> None:
        self.sched = sched
        self.me = me
        self.maxraftstate = maxraftstate
        self.kv = MemoryKV()
        # client_id -> highest applied command_id (dup table,
        # reference: kvraft/server.go:145-148).
        self.latest: Dict[int, int] = {}
        # (client_id, command_id) -> Future resolved by the apply loop
        # (wait-channel pattern, reference: kvraft/server.go:130-143;
        # keyed deterministically instead of by random Seq).
        self._waiters: Dict[tuple, Future] = {}
        self._killed = False
        self.rf = RaftNode(
            sched, ends, me, persister, self._on_apply, seed=seed
        )
        self._install_snapshot(persister.read_snapshot())

    # -- RPC handler (reference: kvraft/server.go:56-96) -----------------

    def command(self, args: CommandArgs):
        if self._killed:
            return CommandReply(err=ERR_WRONG_LEADER)
        # Duplicate write: already applied, answer immediately
        # (reference: kvraft/server.go:66-69; reads go through the log
        # for linearizability — no lease/read-index shortcut).
        if args.op != GET and self.latest.get(args.client_id, -1) >= args.command_id:
            return CommandReply(err=OK)
        op = Op(
            key=args.key,
            value=args.value,
            op=args.op,
            client_id=args.client_id,
            command_id=args.command_id,
        )
        index, term, is_leader = self.rf.start(op)
        if not is_leader:
            return CommandReply(err=ERR_WRONG_LEADER)
        fut = Future()
        key = (args.client_id, args.command_id, index)
        self._waiters[key] = fut
        result = yield self.sched.with_timeout(fut, SERVER_WAIT)
        self._waiters.pop(key, None)
        if result is TIMEOUT:
            return CommandReply(err=ERR_TIMEOUT)
        return result

    # -- apply loop (reference: kvraft/server.go:98-128) -----------------

    def _on_apply(self, msg: ApplyMsg) -> None:
        if self._killed:
            return
        if msg.snapshot_valid:
            self._install_snapshot(msg.snapshot)
            return
        if not msg.command_valid:
            return
        op: Op = msg.command
        if self.latest.get(op.client_id, -1) >= op.command_id:
            # Duplicate already applied; a re-proposed Get answers with a
            # fresh read, a re-proposed write just acks (SURVEY §7.5 #8).
            reply = self._read_reply(op) if op.op == GET else CommandReply(err=OK)
        else:
            reply = self._apply_op(op)
            self.latest[op.client_id] = op.command_id
        waiter = self._waiters.get((op.client_id, op.command_id, msg.command_index))
        if waiter is not None:
            term, is_leader = self.rf.get_state()
            if is_leader and term == msg.command_term:
                waiter.resolve(reply)
        self._maybe_snapshot(msg.command_index)

    def _apply_op(self, op: Op) -> CommandReply:
        if op.op == GET:
            return self._read_reply(op)
        if op.op == PUT:
            return CommandReply(err=self.kv.put(op.key, op.value))
        return CommandReply(err=self.kv.append(op.key, op.value))

    def _read_reply(self, op: Op) -> CommandReply:
        value, err = self.kv.get(op.key)
        return CommandReply(err=err, value=value)

    # -- snapshots (reference: kvraft/server.go:150-183) -----------------

    def _maybe_snapshot(self, index: int) -> None:
        if self.maxraftstate < 0:
            return
        # Trigger at the documented 0.8 threshold (divergence: the
        # reference's integer division makes its check effectively 1.0×,
        # kvraft/server.go:151).
        if self.rf.raft_state_size() >= (
            _settings().service.snapshot_threshold * self.maxraftstate
        ):
            blob = codec.encode(
                {"data": dict(self.kv.data), "latest": dict(self.latest)}
            )
            self.rf.snapshot(index, blob)

    def _install_snapshot(self, data: bytes) -> None:
        if not data:
            return
        blob = codec.decode(data)
        self.kv.data = dict(blob["data"])
        self.latest = dict(blob["latest"])

    # -- lifecycle -------------------------------------------------------

    def kill(self) -> None:
        self._killed = True
        self.rf.kill()


class Clerk:
    """KV client (reference: kvraft/client.go).

    Caches the last known leader, stamps ops with (client_id,
    monotonically increasing command_id), retries with a per-attempt
    timeout, rotating servers on failure."""

    _next_client_id = 0

    def __init__(self, sched: Scheduler, ends: List[ClientEnd]) -> None:
        from ..utils.ids import unique_client_id

        self.sched = sched
        self.ends = ends
        self.leader = 0
        Clerk._next_client_id += 1
        # Nonce-qualified: the class counter is only unique within one
        # process, but the distributed deployment runs clerks in many
        # (every server process owns internal clerks) — a collision
        # makes dedup tables swallow another client's commands.
        self.client_id = unique_client_id(Clerk._next_client_id)
        self.command_id = 0

    def _command(self, op: str, key: str, value: str):
        """Generator coroutine (reference: kvraft/client.go:47-71)."""
        self.command_id += 1
        args = CommandArgs(
            key=key,
            value=value,
            op=op,
            client_id=self.client_id,
            command_id=self.command_id,
        )
        failures = 0
        while True:
            fut = self.ends[self.leader].call("KVServer.command", args)
            reply = yield self.sched.with_timeout(fut, CLERK_RETRY)
            if (
                reply is TIMEOUT
                or reply is None
                or reply.err in (ERR_WRONG_LEADER, ERR_TIMEOUT)
            ):
                self.leader = (self.leader + 1) % len(self.ends)
                failures += 1
                if failures % len(self.ends) == 0:
                    # A full sweep failed (leaderless / partitioned): pause
                    # before sweeping again so fast-failing RPCs (real TCP
                    # connection-refused) don't busy-spin the loop — the
                    # reference paces the same way between sweeps
                    # (reference: shardctrler/client.go:52-62).
                    yield self.sched.sleep(SWEEP_BACKOFF)
                continue
            return reply.value if reply.err != ERR_NO_KEY else ""

    def get(self, key: str):
        return self._command(GET, key, "")

    def put(self, key: str, value: str):
        return self._command(PUT, key, value)

    def append(self, key: str, value: str):
        return self._command(APPEND, key, value)
