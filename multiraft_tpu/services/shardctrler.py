"""Shard controller — the replicated configuration service
(reference: src/shardctrler).

A second Raft-backed state machine mapping ``NSHARDS`` shards to replica
groups.  JOIN/LEAVE trigger the minimal-movement rebalancer; MOVE pins a
shard; QUERY reads any historical config (configs are never mutated in
place, so history is queryable forever —
reference: shardctrler/common.go:27-31, shardctrler/server.go:48-162).

The rebalancer is a pure, deterministic function: it runs inside the
replicated apply path, so every replica MUST compute the identical
assignment (reference: shardctrler/common.go:87-132 sorts map keys for
exactly this reason).

In the batched TPU engine the shard→group table is a small device array
indexed by the services layer (the expert-routing analog, SURVEY §2.1).

Documented divergence (SURVEY §7.5 #9): replies carry an explicit
``OK`` instead of the reference's zero-value success string, and QUERY
reads happen inside the apply path rather than after the wait-channel
fires.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..raft.messages import ApplyMsg
from ..raft.node import RaftNode
from ..raft.persister import Persister
from ..sim.scheduler import Future, Scheduler, TIMEOUT
from ..transport import codec
from ..transport.network import ClientEnd

__all__ = [
    "NSHARDS",
    "Config",
    "ShardCtrler",
    "CtrlerClerk",
    "rebalance",
    "rebalance_weighted",
    "QUERY",
    "JOIN",
    "LEAVE",
    "MOVE",
]

from ..utils.config import settings as _settings

# (reference: shardctrler/common.go:23; MULTIRAFT_NSHARDS overrides)
NSHARDS = _settings().nshards

QUERY = "Query"
JOIN = "Join"
LEAVE = "Leave"
MOVE = "Move"

OK = "OK"
ERR_WRONG_LEADER = "ErrWrongLeader"
ERR_TIMEOUT = "ErrTimeout"

SERVER_WAIT = _settings().service.server_wait  # (reference: shardctrler/server.go:19)


@codec.registered
@dataclasses.dataclass
class Config:
    """(reference: shardctrler/common.go:27-31)"""

    num: int = 0
    shards: List[int] = dataclasses.field(
        default_factory=lambda: [0] * NSHARDS
    )
    groups: Dict[int, List[str]] = dataclasses.field(default_factory=dict)

    def clone(self) -> "Config":
        return Config(
            num=self.num,
            shards=list(self.shards),
            groups={g: list(s) for g, s in self.groups.items()},
        )


def rebalance(shards: List[int], groups: Dict[int, List[str]]) -> List[int]:
    """Minimal-movement shard rebalance
    (reference: shardctrler/common.go:53-132).

    1. Shards owned by departed/unknown groups go to the least-loaded
       group.
    2. While the load spread exceeds 1, move one shard from the most-
       to the least-loaded group.

    Deterministic tie-breaks (sorted gids) because this runs inside the
    replicated apply path on every replica."""
    if not groups:
        return [0] * NSHARDS
    counts = {gid: 0 for gid in sorted(groups)}
    out = list(shards)
    for s, g in enumerate(out):
        if g in counts:
            counts[g] += 1
        else:
            out[s] = 0

    def min_gid() -> int:
        return min(counts, key=lambda g: (counts[g], g))

    def max_gid() -> int:
        return max(counts, key=lambda g: (counts[g], -g))

    for s in range(NSHARDS):
        if out[s] == 0:
            g = min_gid()
            out[s] = g
            counts[g] += 1
    while True:
        mx, mn = max_gid(), min_gid()
        if counts[mx] - counts[mn] <= 1:
            break
        for s in range(NSHARDS):
            if out[s] == mx:
                out[s] = mn
                counts[mx] -= 1
                counts[mn] += 1
                break
    return out


def rebalance_weighted(
    assign: Dict[int, Optional[int]],
    weights: Dict[int, float],
    bins: List[int],
):
    """Weighted generalization of :func:`rebalance` for the fleet
    placement controller: ``assign`` maps item (raft group id) to its
    current bin (mesh process index, or ``None``/a departed bin for
    orphans), ``weights`` gives each item's load, ``bins`` is the live
    bin set.  Returns ``(new_assign, moves)`` with ``moves`` a list of
    ``(item, src_bin, dst_bin)``.

    Same shape as the unweighted rebalancer, so the minimal-movement
    character carries over:

    1. every item stays where it is if its bin is still live;
    2. orphans go to the lightest bin;
    3. while it strictly helps, move the heaviest movable item from the
       heaviest to the lightest bin — "movable" means ``w < max - min``,
       which keeps both bins inside the old (min, max) interval, so the
       potential ``sum(load**2)`` strictly decreases and the loop
       terminates.

    With uniform weights the movable condition degenerates to
    ``max - min >= 2`` — exactly the unweighted loop — so the move
    count never exceeds the unweighted minimal-movement bound (the
    property test in tests/test_placement.py pins this).

    Deterministic (sorted tie-breaks throughout): it runs inside the
    controller's replicated apply path, where every replica must plan
    the identical move set."""
    bins = sorted(set(bins))
    if not bins:
        return dict(assign), []
    live = set(bins)
    load = {b: 0.0 for b in bins}
    out: Dict[int, int] = {}
    moves = []
    orphans = []
    for item in sorted(assign):
        b = assign[item]
        if b in live:
            out[item] = b
            load[b] += weights.get(item, 0.0)
        else:
            orphans.append(item)

    def lightest() -> int:
        return min(bins, key=lambda b: (load[b], b))

    def heaviest() -> int:
        return max(bins, key=lambda b: (load[b], -b))

    for item in orphans:
        b = lightest()
        out[item] = b
        load[b] += weights.get(item, 0.0)
        moves.append((item, assign[item], b))

    # Each move strictly shrinks sum(load**2); the cap is a defensive
    # bound, not the expected exit.
    for _ in range(4 * len(out) + 16):
        hi, lo = heaviest(), lightest()
        gap = load[hi] - load[lo]
        best = None
        for item in sorted(out):
            if out[item] != hi:
                continue
            w = weights.get(item, 0.0)
            # w > 0: moving a zero-weight item changes no load — churn.
            if 0 < w < gap and (best is None or w > weights.get(best, 0.0)):
                best = item
        if best is None:
            break
        out[best] = lo
        w = weights.get(best, 0.0)
        load[hi] -= w
        load[lo] += w
        moves.append((best, hi, lo))
    return out, moves


@codec.registered
@dataclasses.dataclass
class CtrlerArgs:
    """Unified op args (reference: shardctrler/server.go Command)."""

    op: str = QUERY
    servers: Dict[int, List[str]] = dataclasses.field(default_factory=dict)
    gids: List[int] = dataclasses.field(default_factory=list)
    shard: int = 0
    gid: int = 0
    num: int = -1
    client_id: int = 0
    command_id: int = 0


@codec.registered
@dataclasses.dataclass
class CtrlerReply:
    err: str = OK
    config: Optional[Config] = None


class ShardCtrler:
    """Controller server (reference: shardctrler/server.go:164-182).
    RPC surface: ``ShardCtrler.command``."""

    def __init__(
        self,
        sched: Scheduler,
        ends: List[ClientEnd],
        me: int,
        persister: Persister,
        maxraftstate: int = -1,
        seed: int = 0,
    ) -> None:
        self.sched = sched
        self.me = me
        self.maxraftstate = maxraftstate
        self.configs: List[Config] = [Config()]  # config 0: all shards -> gid 0
        self.latest: Dict[int, int] = {}
        self._waiters: Dict[tuple, Future] = {}
        self._killed = False
        self.rf = RaftNode(sched, ends, me, persister, self._on_apply, seed=seed)
        self._install_snapshot(persister.read_snapshot())

    # -- RPC (reference: shardctrler/server.go:48-100) -------------------

    def command(self, args: CtrlerArgs):
        if self._killed:
            return CtrlerReply(err=ERR_WRONG_LEADER)
        if args.op != QUERY and self.latest.get(args.client_id, -1) >= args.command_id:
            return CtrlerReply(err=OK)
        index, term, is_leader = self.rf.start(args)
        if not is_leader:
            return CtrlerReply(err=ERR_WRONG_LEADER)
        fut = Future()
        key = (args.client_id, args.command_id, index)
        self._waiters[key] = fut
        result = yield self.sched.with_timeout(fut, SERVER_WAIT)
        self._waiters.pop(key, None)
        if result is TIMEOUT:
            return CtrlerReply(err=ERR_TIMEOUT)
        return result

    # -- apply (reference: shardctrler/server.go:124-162) ----------------

    def _on_apply(self, msg: ApplyMsg) -> None:
        if self._killed:
            return
        if msg.snapshot_valid:
            self._install_snapshot(msg.snapshot)
            return
        if not msg.command_valid:
            return
        args: CtrlerArgs = msg.command
        reply = CtrlerReply(err=OK)
        is_dup = self.latest.get(args.client_id, -1) >= args.command_id
        if args.op == QUERY:
            reply.config = self._query(args.num)
        elif not is_dup:
            if args.op == JOIN:
                self._join(args.servers)
            elif args.op == LEAVE:
                self._leave(args.gids)
            elif args.op == MOVE:
                self._move(args.shard, args.gid)
        if not is_dup:
            self.latest[args.client_id] = args.command_id
        waiter = self._waiters.get(
            (args.client_id, args.command_id, msg.command_index)
        )
        if waiter is not None:
            term, is_leader = self.rf.get_state()
            if is_leader and term == msg.command_term:
                waiter.resolve(reply)
        self._maybe_snapshot(msg.command_index)

    def _query(self, num: int) -> Config:
        if num < 0 or num >= len(self.configs):
            return self.configs[-1].clone()
        return self.configs[num].clone()

    def _join(self, servers: Dict[int, List[str]]) -> None:
        """(reference: shardctrler/server.go JOIN + ReAllocGID)"""
        cfg = self.configs[-1].clone()
        cfg.num += 1
        cfg.groups.update({g: list(s) for g, s in servers.items()})
        cfg.shards = rebalance(cfg.shards, cfg.groups)
        self.configs.append(cfg)

    def _leave(self, gids: List[int]) -> None:
        cfg = self.configs[-1].clone()
        cfg.num += 1
        for g in gids:
            cfg.groups.pop(g, None)
        cfg.shards = rebalance(cfg.shards, cfg.groups)
        self.configs.append(cfg)

    def _move(self, shard: int, gid: int) -> None:
        cfg = self.configs[-1].clone()
        cfg.num += 1
        cfg.shards[shard] = gid
        self.configs.append(cfg)

    # -- snapshots --------------------------------------------------------

    def _maybe_snapshot(self, index: int) -> None:
        if self.maxraftstate < 0:
            return
        if self.rf.raft_state_size() >= (
            _settings().service.snapshot_threshold * self.maxraftstate
        ):
            blob = codec.encode(
                {"configs": self.configs, "latest": dict(self.latest)}
            )
            self.rf.snapshot(index, blob)

    def _install_snapshot(self, data: bytes) -> None:
        if not data:
            return
        blob = codec.decode(data)
        self.configs = blob["configs"]
        self.latest = dict(blob["latest"])

    def kill(self) -> None:
        self._killed = True
        self.rf.kill()


class CtrlerClerk:
    """Controller client (reference: shardctrler/client.go:41-79)."""

    _next_client_id = 1 << 20  # distinct from KV clerks

    def __init__(self, sched: Scheduler, ends: List[ClientEnd]) -> None:
        self.sched = sched
        self.ends = ends
        self.leader = 0
        from ..utils.ids import unique_client_id

        CtrlerClerk._next_client_id += 1
        # Nonce-qualified for cross-process uniqueness (see utils/ids.py).
        self.client_id = unique_client_id(CtrlerClerk._next_client_id)
        self.command_id = 0

    def _command(self, args: CtrlerArgs):
        args.client_id = self.client_id
        self.command_id += 1
        args.command_id = self.command_id
        while True:
            fut = self.ends[self.leader].call("ShardCtrler.command", args)
            reply = yield self.sched.with_timeout(fut, 0.1)
            if (
                reply is TIMEOUT
                or reply is None
                or reply.err in (ERR_WRONG_LEADER, ERR_TIMEOUT)
            ):
                self.leader = (self.leader + 1) % len(self.ends)
                continue
            return reply

    def query(self, num: int = -1):
        reply = yield from self._command(CtrlerArgs(op=QUERY, num=num))
        return reply.config

    def join(self, servers: Dict[int, List[str]]):
        yield from self._command(CtrlerArgs(op=JOIN, servers=servers))

    def leave(self, gids: List[int]):
        yield from self._command(CtrlerArgs(op=LEAVE, gids=gids))

    def move(self, shard: int, gid: int):
        yield from self._command(CtrlerArgs(op=MOVE, shard=shard, gid=gid))
