"""Sharded multi-group KV service.

The reference ships only a skeleton server (shardkv/server.go:30-36 —
empty Get/PutAppend bodies); the behavior implemented here is the one
its test suite demands (SURVEY §4.4, shardkv/test_test.go), including
both challenges: shard deletion with bounded storage (Challenge 1,
shardkv/test_test.go:738-817) and serving unaffected / partially-
migrated shards during config changes (Challenge 2,
shardkv/test_test.go:824-948).

Design — per-shard state machines with a pull-based migration pipeline,
all transitions replicated through the group's own Raft log:

* Shard states: SERVING → (config change) → PULLING (new owner fetching)
  / BEPULLING (old owner, frozen until fetched) → GCING (new owner
  serving, old copy not yet deleted) → SERVING.
* A leader config ticker polls the controller for config num+1 and
  proposes it only when no migration is in flight, so configs apply in
  order, exactly one transition outstanding per group.
* A pull ticker fetches PULLING shards (data + per-shard dup table)
  from the previous owner and proposes InsertShard; the shard serves as
  soon as that applies — before sibling shards finish (Challenge 2).
* A GC ticker asks the previous owner to delete BEPULLING shards
  (bounding storage, Challenge 1) and then confirms GCING → SERVING.
* Client ops are gated per shard: ErrWrongGroup unless this group owns
  the shard in the current config AND its state is SERVING/GCING
  (reference: shardkv/common.go:12-18 error contract).

Dup tables are per-shard so exactly-once survives migration.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from ..raft.messages import ApplyMsg
from ..raft.node import RaftNode
from ..raft.persister import Persister
from ..sim.scheduler import Future, Scheduler, TIMEOUT
from ..transport import codec
from ..transport.network import ClientEnd
from .shardctrler import NSHARDS, Config, CtrlerClerk

__all__ = [
    "ShardKVServer",
    "ShardClerk",
    "key2shard",
    "OK",
    "ERR_NO_KEY",
    "ERR_WRONG_GROUP",
    "ERR_WRONG_LEADER",
    "ERR_TIMEOUT",
    "ERR_NOT_READY",
]

OK = "OK"
ERR_NO_KEY = "ErrNoKey"
ERR_WRONG_GROUP = "ErrWrongGroup"  # (reference: shardkv/common.go:12-18)
ERR_WRONG_LEADER = "ErrWrongLeader"
ERR_TIMEOUT = "ErrTimeout"
ERR_NOT_READY = "ErrNotReady"

GET = "Get"
PUT = "Put"
APPEND = "Append"

from ..utils.config import settings as _settings

SERVER_WAIT = _settings().service.server_wait
# Leader ticker cadences (reference polls the controller every 100 ms,
# shardkv hint; staggered to avoid lockstep).  CONFIG_POLL comes from
# the config system (MULTIRAFT_CONFIG_POLL).
CONFIG_POLL = _settings().service.config_poll
PULL_INTERVAL = 0.06
GC_INTERVAL = 0.07

# Shard states.
SERVING = 0
PULLING = 1
BEPULLING = 2
GCING = 3


def key2shard(key: str) -> int:
    """(reference: shardkv/client.go:22-29 — first byte mod NSHARDS)"""
    return (ord(key[0]) if key else 0) % NSHARDS


@codec.registered
@dataclasses.dataclass
class Shard:
    state: int = SERVING
    data: Dict[str, str] = dataclasses.field(default_factory=dict)
    latest: Dict[int, int] = dataclasses.field(default_factory=dict)


@codec.registered
@dataclasses.dataclass
class ShardArgs:
    key: str = ""
    value: str = ""
    op: str = GET
    client_id: int = 0
    command_id: int = 0


@codec.registered
@dataclasses.dataclass
class ShardReply:
    err: str = OK
    value: str = ""


@codec.registered
@dataclasses.dataclass
class ClientOp:
    key: str = ""
    value: str = ""
    op: str = GET
    client_id: int = 0
    command_id: int = 0


@codec.registered
@dataclasses.dataclass
class ConfigOp:
    config: Config = None


@codec.registered
@dataclasses.dataclass
class InsertShardOp:
    config_num: int = 0
    shard: int = 0
    data: Dict[str, str] = dataclasses.field(default_factory=dict)
    latest: Dict[int, int] = dataclasses.field(default_factory=dict)


@codec.registered
@dataclasses.dataclass
class DeleteShardOp:
    config_num: int = 0
    shard: int = 0


@codec.registered
@dataclasses.dataclass
class ConfirmGCOp:
    config_num: int = 0
    shard: int = 0


@codec.registered
@dataclasses.dataclass
class PullArgs:
    config_num: int = 0
    shard: int = 0


@codec.registered
@dataclasses.dataclass
class PullReply:
    err: str = OK
    data: Dict[str, str] = dataclasses.field(default_factory=dict)
    latest: Dict[int, int] = dataclasses.field(default_factory=dict)


@codec.registered
@dataclasses.dataclass
class DeleteArgs:
    config_num: int = 0
    shard: int = 0


@codec.registered
@dataclasses.dataclass
class DeleteReply:
    err: str = OK


class ShardKVServer:
    """One replica of one group (reference: shardkv/server.go:77-98
    StartServer wiring: raft + controller clerk + make_end).

    RPC surface: ``ShardKV.command``, ``ShardKV.pull_shard``,
    ``ShardKV.delete_shard``."""

    def __init__(
        self,
        sched: Scheduler,
        ends: List[ClientEnd],
        me: int,
        persister: Persister,
        gid: int,
        ctrler_ends: List[ClientEnd],
        make_end: Callable[[Any], ClientEnd],
        maxraftstate: int = -1,
        seed: int = 0,
    ) -> None:
        self.sched = sched
        self.me = me
        self.gid = gid
        self.maxraftstate = maxraftstate
        self.make_end = make_end
        self._peer_ends: Dict[Any, ClientEnd] = {}
        self.ctrler = CtrlerClerk(sched, ctrler_ends)
        self.cur = Config()  # current config
        self.prev = Config()  # previous config (migration source map)
        self.shards: Dict[int, Shard] = {s: Shard() for s in range(NSHARDS)}
        self._waiters: Dict[tuple, Future] = {}
        # In-flight guards: one outstanding pull/GC exchange per shard,
        # so ticker overlap can't propose duplicate full-payload
        # InsertShard entries into the log (storage-bound hygiene).
        self._pulling_now: set = set()
        self._gcing_now: set = set()
        self._killed = False
        self.rf = RaftNode(sched, ends, me, persister, self._on_apply, seed=seed)
        self._install_snapshot(persister.read_snapshot())
        sched.spawn(self._config_ticker())
        sched.spawn(self._pull_ticker())
        sched.spawn(self._gc_ticker())

    # ------------------------------------------------------------------
    # Client ops
    # ------------------------------------------------------------------

    def _can_serve(self, shard: int) -> bool:
        """Challenge 2: GCING shards serve while their old copy awaits
        deletion; PULLING shards don't serve yet; unaffected shards are
        SERVING throughout a migration."""
        return self.cur.shards[shard] == self.gid and self.shards[shard].state in (
            SERVING,
            GCING,
        )

    def command(self, args: ShardArgs):
        if self._killed:
            return ShardReply(err=ERR_WRONG_LEADER)
        shard = key2shard(args.key)
        if not self._can_serve(shard):
            return ShardReply(err=ERR_WRONG_GROUP)
        sh = self.shards[shard]
        if args.op != GET and sh.latest.get(args.client_id, -1) >= args.command_id:
            return ShardReply(err=OK)
        op = ClientOp(
            key=args.key,
            value=args.value,
            op=args.op,
            client_id=args.client_id,
            command_id=args.command_id,
        )
        index, term, is_leader = self.rf.start(op)
        if not is_leader:
            return ShardReply(err=ERR_WRONG_LEADER)
        fut = Future()
        key = (args.client_id, args.command_id, index)
        self._waiters[key] = fut
        result = yield self.sched.with_timeout(fut, SERVER_WAIT)
        self._waiters.pop(key, None)
        if result is TIMEOUT:
            return ShardReply(err=ERR_TIMEOUT)
        return result

    # ------------------------------------------------------------------
    # Migration RPCs (served leader-side on the *source* group)
    # ------------------------------------------------------------------

    def pull_shard(self, args: PullArgs) -> PullReply:
        """New owner fetches a shard's data + dup table."""
        _, is_leader = self.rf.get_state()
        if self._killed or not is_leader:
            return PullReply(err=ERR_WRONG_LEADER)
        if self.cur.num < args.config_num:
            # We haven't seen the config that moves this shard yet.
            return PullReply(err=ERR_NOT_READY)
        sh = self.shards[args.shard]
        return PullReply(
            err=OK, data=dict(sh.data), latest=dict(sh.latest)
        )

    def delete_shard(self, args: DeleteArgs):
        """New owner confirms receipt; we may delete our frozen copy
        (Challenge 1)."""
        _, is_leader = self.rf.get_state()
        if self._killed or not is_leader:
            return DeleteReply(err=ERR_WRONG_LEADER)
        if self.cur.num > args.config_num:
            # Already deleted and moved on: idempotent success.
            return DeleteReply(err=OK)
        index, term, is_leader = self.rf.start(
            DeleteShardOp(config_num=args.config_num, shard=args.shard)
        )
        if not is_leader:
            return DeleteReply(err=ERR_WRONG_LEADER)
        fut = Future()
        key = ("del", args.config_num, args.shard, index)
        self._waiters[key] = fut
        result = yield self.sched.with_timeout(fut, SERVER_WAIT)
        self._waiters.pop(key, None)
        if result is TIMEOUT:
            return DeleteReply(err=ERR_TIMEOUT)
        return result

    # ------------------------------------------------------------------
    # Leader tickers
    # ------------------------------------------------------------------

    def _is_leader(self) -> bool:
        _, is_leader = self.rf.get_state()
        return is_leader

    def _config_ticker(self):
        """Poll for the next config; propose it when no migration is in
        flight so configs apply strictly in order."""
        while not self._killed:
            yield CONFIG_POLL
            if self._killed or not self._is_leader():
                continue
            if any(
                sh.state != SERVING for sh in self.shards.values()
            ):
                continue  # migration in flight; finish it first
            nxt = yield from self.ctrler.query(self.cur.num + 1)
            if nxt is not None and nxt.num == self.cur.num + 1:
                self.rf.start(ConfigOp(config=nxt))

    def _pull_ticker(self):
        while not self._killed:
            yield PULL_INTERVAL
            if self._killed or not self._is_leader():
                continue
            for s in range(NSHARDS):
                if self.shards[s].state == PULLING and s not in self._pulling_now:
                    # In-flight dedup set: ≤ NSHARDS entries, discarded
                    # when _pull_one completes.
                    self._pulling_now.add(s)  # graftlint: disable=unbounded-queue
                    self.sched.spawn(self._pull_one(s, self.cur.num))

    def _pull_one(self, shard: int, config_num: int):
        try:
            src_gid = self.prev.shards[shard]
            servers = self.prev.groups.get(src_gid, [])
            args = PullArgs(config_num=config_num, shard=shard)
            for name in servers:
                if self._killed or self.cur.num != config_num:
                    return
                if self.shards[shard].state != PULLING:
                    return
                end = self._end_to(name)
                reply = yield self.sched.with_timeout(
                    end.call("ShardKV.pull_shard", args), 0.1
                )
                if reply is TIMEOUT or reply is None or reply.err != OK:
                    continue
                if self.shards[shard].state != PULLING or self.cur.num != config_num:
                    return
                self.rf.start(
                    InsertShardOp(
                        config_num=config_num,
                        shard=shard,
                        data=reply.data,
                        latest=reply.latest,
                    )
                )
                return
        finally:
            self._pulling_now.discard(shard)

    def _gc_ticker(self):
        while not self._killed:
            yield GC_INTERVAL
            if self._killed or not self._is_leader():
                continue
            for s in range(NSHARDS):
                if self.shards[s].state == GCING and s not in self._gcing_now:
                    # In-flight dedup set: ≤ NSHARDS entries, discarded
                    # when _gc_one completes.
                    self._gcing_now.add(s)  # graftlint: disable=unbounded-queue
                    self.sched.spawn(self._gc_one(s, self.cur.num))

    def _gc_one(self, shard: int, config_num: int):
        try:
            src_gid = self.prev.shards[shard]
            servers = self.prev.groups.get(src_gid, [])
            args = DeleteArgs(config_num=config_num, shard=shard)
            for name in servers:
                if self._killed or self.cur.num != config_num:
                    return
                if self.shards[shard].state != GCING:
                    return
                end = self._end_to(name)
                reply = yield self.sched.with_timeout(
                    end.call("ShardKV.delete_shard", args), 0.1
                )
                if reply is TIMEOUT or reply is None or reply.err != OK:
                    continue
                if self.shards[shard].state == GCING and self.cur.num == config_num:
                    self.rf.start(
                        ConfirmGCOp(config_num=config_num, shard=shard)
                    )
                return
        finally:
            self._gcing_now.discard(shard)

    def _end_to(self, servername: Any) -> ClientEnd:
        if servername not in self._peer_ends:
            self._peer_ends[servername] = self.make_end(servername)
        return self._peer_ends[servername]

    # ------------------------------------------------------------------
    # Replicated apply path
    # ------------------------------------------------------------------

    def _on_apply(self, msg: ApplyMsg) -> None:
        if self._killed:
            return
        if msg.snapshot_valid:
            self._install_snapshot(msg.snapshot)
            return
        if not msg.command_valid:
            return
        op = msg.command
        reply: Any = None
        if isinstance(op, ClientOp):
            reply = self._apply_client_op(op, msg)
        elif isinstance(op, ConfigOp):
            self._apply_config(op.config)
        elif isinstance(op, InsertShardOp):
            self._apply_insert(op)
        elif isinstance(op, DeleteShardOp):
            reply = self._apply_delete(op, msg)
        elif isinstance(op, ConfirmGCOp):
            self._apply_confirm_gc(op)
        self._maybe_snapshot(msg.command_index)

    def _apply_client_op(self, op: ClientOp, msg: ApplyMsg) -> None:
        shard_id = key2shard(op.key)
        sh = self.shards[shard_id]
        # Re-check ownership at apply time: the config may have changed
        # between Start() and commit.
        if not self._can_serve(shard_id):
            reply = ShardReply(err=ERR_WRONG_GROUP)
        elif op.op != GET and sh.latest.get(op.client_id, -1) >= op.command_id:
            reply = ShardReply(err=OK)
        else:
            if op.op == GET:
                if op.key in sh.data:
                    reply = ShardReply(err=OK, value=sh.data[op.key])
                else:
                    reply = ShardReply(err=ERR_NO_KEY)
            elif op.op == PUT:
                sh.data[op.key] = op.value
                reply = ShardReply(err=OK)
            else:
                sh.data[op.key] = sh.data.get(op.key, "") + op.value
                reply = ShardReply(err=OK)
            if op.op != GET:
                sh.latest[op.client_id] = op.command_id
        waiter = self._waiters.get((op.client_id, op.command_id, msg.command_index))
        if waiter is not None:
            term, is_leader = self.rf.get_state()
            if is_leader and term == msg.command_term:
                waiter.resolve(reply)

    def _apply_config(self, cfg: Config) -> None:
        """One config step: set per-shard migration states."""
        if cfg.num != self.cur.num + 1:
            return  # stale or out-of-order proposal
        if any(sh.state != SERVING for sh in self.shards.values()):
            return  # defensive: never start a new migration mid-flight
        self.prev = self.cur
        self.cur = cfg
        for s in range(NSHARDS):
            was_mine = self.prev.shards[s] == self.gid
            is_mine = cfg.shards[s] == self.gid
            if is_mine and not was_mine:
                if self.prev.shards[s] == 0:
                    self.shards[s].state = SERVING  # fresh shard, no data
                else:
                    self.shards[s].state = PULLING
            elif was_mine and not is_mine:
                self.shards[s].state = BEPULLING

    def _apply_insert(self, op: InsertShardOp) -> None:
        if op.config_num != self.cur.num:
            return
        sh = self.shards[op.shard]
        if sh.state != PULLING:
            return  # duplicate insert
        sh.data = dict(op.data)
        sh.latest = dict(op.latest)
        sh.state = GCING  # serve immediately; old copy not yet deleted

    def _apply_delete(self, op: DeleteShardOp, msg: ApplyMsg):
        reply = DeleteReply(err=OK)
        if op.config_num == self.cur.num:
            sh = self.shards[op.shard]
            if sh.state == BEPULLING:
                self.shards[op.shard] = Shard(state=SERVING)
        # config_num < cur.num: already gone — idempotent OK.
        waiter = self._waiters.get(
            ("del", op.config_num, op.shard, msg.command_index)
        )
        if waiter is not None:
            term, is_leader = self.rf.get_state()
            if is_leader and term == msg.command_term:
                waiter.resolve(reply)
        return reply

    def _apply_confirm_gc(self, op: ConfirmGCOp) -> None:
        if op.config_num != self.cur.num:
            return
        sh = self.shards[op.shard]
        if sh.state == GCING:
            sh.state = SERVING

    # ------------------------------------------------------------------
    # Snapshots
    # ------------------------------------------------------------------

    def _maybe_snapshot(self, index: int) -> None:
        if self.maxraftstate < 0:
            return
        if self.rf.raft_state_size() >= (
            _settings().service.snapshot_threshold * self.maxraftstate
        ):
            blob = codec.encode(
                {
                    "cur": self.cur,
                    "prev": self.prev,
                    "shards": self.shards,
                }
            )
            self.rf.snapshot(index, blob)

    def _install_snapshot(self, data: bytes) -> None:
        if not data:
            return
        blob = codec.decode(data)
        self.cur = blob["cur"]
        self.prev = blob["prev"]
        self.shards = blob["shards"]

    def kill(self) -> None:
        self._killed = True
        self.rf.kill()


class ShardClerk:
    """Sharded KV client (reference: shardkv/client.go:68-129).

    Routes by ``key2shard`` through the latest known config; re-queries
    the controller on ErrWrongGroup or exhausted retries."""

    _next_client_id = 1 << 22  # distinct range from KV/ctrler clerks

    def __init__(
        self,
        sched: Scheduler,
        ctrler_ends: List[ClientEnd],
        make_end: Callable[[Any], ClientEnd],
    ) -> None:
        self.sched = sched
        self.ctrler = CtrlerClerk(sched, ctrler_ends)
        self.make_end = make_end
        self._ends: Dict[Any, ClientEnd] = {}
        self.config = Config()
        from ..utils.ids import unique_client_id

        ShardClerk._next_client_id += 1
        # Nonce-qualified for cross-process uniqueness (see utils/ids.py).
        self.client_id = unique_client_id(ShardClerk._next_client_id)
        self.command_id = 0

    def _end_to(self, servername: Any) -> ClientEnd:
        if servername not in self._ends:
            self._ends[servername] = self.make_end(servername)
        return self._ends[servername]

    def _command(self, op: str, key: str, value: str):
        self.command_id += 1
        args = ShardArgs(
            key=key,
            value=value,
            op=op,
            client_id=self.client_id,
            command_id=self.command_id,
        )
        shard = key2shard(key)
        while True:
            gid = self.config.shards[shard]
            servers = self.config.groups.get(gid, [])
            for name in servers:
                reply = yield self.sched.with_timeout(
                    self._end_to(name).call("ShardKV.command", args), 0.1
                )
                if reply is TIMEOUT or reply is None:
                    continue
                if reply.err in (OK, ERR_NO_KEY):
                    return reply.value if reply.err == OK else ""
                if reply.err == ERR_WRONG_GROUP:
                    break  # re-query config
                # ErrWrongLeader / ErrTimeout: try next server.
            yield 0.1  # (reference: shardkv/client.go 100 ms between sweeps)
            self.config = yield from self.ctrler.query(-1)

    def get(self, key: str):
        return self._command(GET, key, "")

    def put(self, key: str, value: str):
        return self._command(PUT, key, value)

    def append(self, key: str, value: str):
        return self._command(APPEND, key, value)
