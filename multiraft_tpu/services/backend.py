"""The ConsensusEngine interface — services over pluggable backends.

SURVEY §7.1: services consume a consensus interface with two backends —
the event-driven simulation (:class:`~multiraft_tpu.raft.node.RaftNode`,
the correctness oracle, default for the fault-injection test pyramid)
and the batched TPU engine (:mod:`multiraft_tpu.engine`, the throughput
path).  This module pins down that contract.

Two styles exist because the backends have different latency models:

* **Synchronous proposal** (sim backend): ``start()`` returns
  ``(index, term, is_leader)`` immediately — the service can key its
  wait-continuation on the index (kvraft/shardctrler/shardkv do this).
* **Deferred proposal** (batched engine): proposals are accepted by the
  next device tick; ``start()`` hands back a ticket resolved with the
  assigned index when the tick's acceptance readback lands.  Services
  written against :class:`DeferredConsensus` (see
  ``multiraft_tpu.engine.kv.BatchedKV``) work on both, treating the sim
  backend as a zero-tick device.

Apply-path contract (both backends): committed commands are delivered
exactly once, in index order, via the apply callback, interleaved with
snapshot installs that always respect the ordering guarantee
(reference: raft/raft_snapshot.go:51-53).
"""

from __future__ import annotations

from typing import Any, Protocol, Tuple, runtime_checkable

__all__ = ["SyncConsensus", "DeferredConsensus"]


@runtime_checkable
class SyncConsensus(Protocol):
    """What kvraft/shardctrler/shardkv require of their consensus
    instance.  ``RaftNode`` conforms (raft/node.py)."""

    def start(self, command: Any) -> Tuple[int, int, bool]:
        """Propose; returns (index, term, is_leader)."""
        ...

    def get_state(self) -> Tuple[int, bool]:
        ...

    def snapshot(self, index: int, snapshot: bytes) -> None:
        ...

    def raft_state_size(self) -> int:
        ...

    def kill(self) -> None:
        ...


@runtime_checkable
class DeferredConsensus(Protocol):
    """Batch-friendly proposal surface: the engine accepts proposals at
    tick granularity.  ``EngineDriver`` + ``BatchedKV`` implement this
    shape (engine/host.py, engine/kv.py)."""

    def submit(self, group: int, command: Any) -> Any:
        """Queue a proposal for ``group``; returns a ticket whose
        completion carries the applied result."""
        ...

    def pump(self, n_ticks: int = 1) -> None:
        """Advance consensus and deliver apply callbacks."""
        ...
