"""Affinity-aware CPU counting for the single-core accommodations."""

from __future__ import annotations

import os

__all__ = ["usable_cpus"]


def usable_cpus() -> int:
    """CPUs actually available to THIS process — the affinity mask
    (cgroup/taskset-aware), not the host core count: a process pinned
    to one core of a 64-core host must take the single-CPU paths
    (transport spin off, hot pump off) or it steals its co-located
    peers' only core."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):  # non-Linux fallback
        return os.cpu_count() or 1
