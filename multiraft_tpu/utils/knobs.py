"""Typed registry of every ``MRT_*`` environment knob.

Every runtime tunable the deployment plane reads from the environment
is declared ONCE here — name, type, default, owning module and a doc
line — and read through the typed accessors (:func:`knob_str`,
:func:`knob_int`, :func:`knob_float`, :func:`knob_bool`).  graftlint's
``env-knob`` rule makes a raw ``os.environ`` read of an ``MRT_*`` name
anywhere else in the package a finding, and an accessor call with an
undeclared name a finding, so a knob cannot ship half-registered: the
table is what generates ``docs/KNOBS.md`` and what the CI drift gate
checks doc/workflow mentions against.

Semantics (canonical across every knob — historical call sites had
four different bool spellings, now unified):

* ``bool`` — set-and-not-falsey is ON; ``"" / 0 / false / no / off``
  (case-insensitive) are OFF; unset means the declared default.
* ``int`` / ``float`` — parsed; unset, empty or unparsable values fall
  back to the default (a typo'd knob must not crash a server at
  import, matching the old ``_env_f`` helpers).
* ``str`` — unset or empty means the default (``None`` for "feature
  off" path knobs like ``MRT_TRACE_DIR``).

A default of ``None`` marks a DYNAMIC knob: the declared default
depends on the host (CPU count, sibling knob) and the call site must
pass ``default=``.

CLI:

    python -m multiraft_tpu.utils.knobs --write   # regenerate docs/KNOBS.md
    python -m multiraft_tpu.utils.knobs --check   # CI drift gate
"""

from __future__ import annotations

import os
import re
import sys
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional, Tuple

__all__ = [
    "Knob",
    "KNOBS",
    "knob_str",
    "knob_int",
    "knob_float",
    "knob_bool",
    "render_doc",
    "doc_drift",
]


@dataclass(frozen=True)
class Knob:
    """One declared environment knob."""

    name: str
    type: str  # "str" | "int" | "float" | "bool"
    default: object  # None = dynamic (call site supplies default=)
    module: str  # owning module, dotted under multiraft_tpu
    doc: str  # one-line effect description


# The whole registry.  Keep sorted by (module, name) — the generated
# doc table follows this order and the round-trip test pins it.
KNOBS: Tuple[Knob, ...] = (
    # -- analysis.postmortem ------------------------------------------------
    Knob("MRT_CPUSAT_PERMILLE", "int", 850, "analysis.postmortem",
         "PROF breadcrumb CPU-busy permille at/above which the doctor "
         "calls a stall window cpu_saturation rather than "
         "queueing_collapse."),
    # -- distributed.admission ---------------------------------------------
    Knob("MRT_ADMISSION", "bool", True, "distributed.admission",
         "Kill switch for token-bucket admission control at the "
         "dispatch layer (off = every request admitted)."),
    Knob("MRT_ADMIT_BURST", "float", None, "distributed.admission",
         "Admission bucket burst size in ops (dynamic default: "
         "MRT_ADMIT_RATE / 8, ~125 ms of headroom at the rate)."),
    Knob("MRT_ADMIT_INFLIGHT", "int", 512, "distributed.admission",
         "Per-connection cap on dispatched-but-unreplied requests "
         "before admission sheds with retry_after_s."),
    Knob("MRT_ADMIT_RATE", "float", 1600.0, "distributed.admission",
         "Global admitted ops/s for the node's token bucket (~0.8x "
         "the measured knee of the load curve)."),
    Knob("MRT_ADMIT_RETRY_S", "float", 0.05, "distributed.admission",
         "Base retry-after hint handed to shed clients (scaled up "
         "with brownout level)."),
    Knob("MRT_ADMIT_SESSION_RATE", "float", None, "distributed.admission",
         "Per-session admitted ops/s (dynamic default: MRT_ADMIT_RATE, "
         "i.e. no per-session throttling below the global bucket)."),
    Knob("MRT_BROWNOUT_FACTORS", "str", "", "distributed.admission",
         "CSV of shed factors per brownout state overriding the "
         "built-in healthy/shedding/brownout ladder."),
    # -- distributed.cluster ------------------------------------------------
    Knob("MRT_DEBUG", "bool", False, "distributed.cluster",
         "Verbose harness/cluster debug logging to stderr."),
    # -- distributed.engine_cluster ------------------------------------------
    Knob("MRT_ENGINE_PLATFORM", "str", "cpu", "distributed.engine_cluster",
         "JAX platform the engine server process initializes "
         "(cpu/tpu); engine-cluster launches pin it per child."),
    # -- distributed.engine_pump ---------------------------------------------
    Knob("MRT_PIPELINE_DEPTH", "int", 2, "distributed.engine_pump",
         "In-flight fused tick batches the pipelined pump keeps "
         "dispatched (overlaps host bookkeeping with device compute); "
         "durable servers pin it to 1 so every checkpoint sees a "
         "drained pipeline."),
    Knob("MRT_PUMP_IDLE_S", "float", 0.002, "distributed.engine_pump",
         "Idle engine-pump cadence in seconds (the adaptive cadence's "
         "slow interval when no traffic is flowing)."),
    Knob("MRT_PUMP_TICKS", "int", 0, "distributed.engine_pump",
         "Fused device ticks per dispatched pipeline batch (0 = the "
         "server's ticks_per_pump)."),
    # -- distributed.flightrec ----------------------------------------------
    Knob("MRT_FLIGHTREC_DIR", "str", None, "distributed.flightrec",
         "Directory for the crash-safe flight-recorder rings; unset "
         "disables the recorder entirely."),
    Knob("MRT_FLIGHTREC_SLOTS", "int", 8192, "distributed.flightrec",
         "Ring capacity in fixed-width event slots per process."),
    # -- distributed.launch -------------------------------------------------
    Knob("MRT_SERVER_LOG_DIR", "str", None, "distributed.launch",
         "Directory for per-server-child stdout/stderr capture files; "
         "unset inherits the parent's streams."),
    # -- distributed.observe ------------------------------------------------
    Knob("MRT_OBS_MAX_EVENTS", "int", 50000, "distributed.observe",
         "Bound on buffered observability events per process before "
         "the oldest are dropped."),
    Knob("MRT_STAGECLOCK", "bool", True, "distributed.observe",
         "Per-stage serving-path CPU segment accounting (the "
         "cpu_*_us_per_op loadcurve columns); off removes the clocks."),
    # -- distributed.overload -----------------------------------------------
    Knob("MRT_BROWNOUT_DOWN", "int", 8, "distributed.overload",
         "Consecutive clean overload-watch ticks required to "
         "de-escalate one brownout level."),
    Knob("MRT_BROWNOUT_UP", "int", 2, "distributed.overload",
         "Consecutive tripping overload-watch ticks required to "
         "escalate one brownout level."),
    Knob("MRT_OVERLOAD_BACKLOG", "float", 4096.0, "distributed.overload",
         "Engine dispatch backlog depth the overload watch treats as "
         "a trip."),
    Knob("MRT_OVERLOAD_INTERVAL", "float", 0.25, "distributed.overload",
         "Overload watch period in seconds."),
    Knob("MRT_OVERLOAD_P99_MS", "float", 100.0, "distributed.overload",
         "Per-stage p99 latency bound in ms; a window past it trips "
         "the brownout machine."),
    Knob("MRT_OVERLOAD_REPLYQ", "float", 1024.0, "distributed.overload",
         "Queued-replies depth the overload watch treats as a trip."),
    Knob("MRT_OVERLOAD_WAL", "float", 4096.0, "distributed.overload",
         "Appended-but-unsynced WAL record count the overload watch "
         "treats as a trip."),
    Knob("MRT_OVERLOAD_WATCH", "bool", True, "distributed.overload",
         "Kill switch for the overload watch / brownout controller."),
    # -- distributed.placement ----------------------------------------------
    Knob("MRT_PLACE_COOLDOWN_S", "float", 5.0, "distributed.placement",
         "Minimum seconds between planner migration decisions "
         "(anti-thrash)."),
    Knob("MRT_PLACE_DEAD_S", "float", 3.0, "distributed.placement",
         "Seconds without a heartbeat scrape before a process is "
         "declared dead and its groups re-placed."),
    Knob("MRT_PLACE_MAX_MOVES", "int", 1, "distributed.placement",
         "Max group migrations per planner decision."),
    Knob("MRT_PLACE_MIN_GAIN", "float", 0.25, "distributed.placement",
         "Minimum fractional load-spread improvement before the "
         "planner bothers moving a group."),
    Knob("MRT_PLACE_REPLACE", "bool", True, "distributed.placement",
         "Kill switch for automated dead-voter replacement via joint "
         "consensus."),
    Knob("MRT_PLACE_REPLACE_DEADLINE_S", "float", 30.0,
         "distributed.placement",
         "Seconds a group may sit on a reduced quorum before the "
         "doctor flags the replacement leg as stuck."),
    Knob("MRT_PLACE_SCRAPE_S", "float", 0.5, "distributed.placement",
         "Placement controller metric-scrape period in seconds."),
    # -- distributed.profile ------------------------------------------------
    Knob("MRT_PROFILE", "bool", True, "distributed.profile",
         "Continuous stack-sampling profiler, default on within its "
         "measured <2% budget."),
    Knob("MRT_PROFILE_DEPTH", "int", 48, "distributed.profile",
         "Max frames kept per sampled stack."),
    Knob("MRT_PROFILE_HZ", "float", None, "distributed.profile",
         "Sampling rate override (dynamic default: 67 Hz with spare "
         "cores, 19 Hz on a 1-CPU host)."),
    Knob("MRT_PROFILE_MAX_STACKS", "int", 5000, "distributed.profile",
         "Distinct-stack table cap; overflow folds into a sentinel "
         "frame."),
    # -- distributed.realtime -----------------------------------------------
    Knob("MRT_PUMP_HOT", "bool", None, "distributed.realtime",
         "Hot engine pump (spin between ticks instead of sleeping); "
         "dynamic default: on with spare cores, off on a 1-CPU host."),
    # -- distributed.sanitize -----------------------------------------------
    Knob("MRT_SANITIZE", "bool", False, "distributed.sanitize",
         "Runtime invariant sanitizer (deep frame/state checks on the "
         "serving path); default off for speed."),
    Knob("MRT_SANITIZE_CB_BUDGET_MS", "float", 250.0,
         "distributed.sanitize",
         "Callback wall-clock budget in ms before the sanitizer "
         "records an overrun."),
    Knob("MRT_SANITIZE_STRICT", "bool", False, "distributed.sanitize",
         "Escalate sanitizer findings from flight-record events to "
         "raised exceptions."),
    # -- distributed.stateplane ---------------------------------------------
    Knob("MRT_SHIP_SYNC", "bool", False, "distributed.stateplane",
         "Acks gate on state shipment (zero acknowledged-write loss; "
         "the durable chaos gate runs with this on)."),
    Knob("MRT_SHIP_TAIL_CAP", "int", 512, "distributed.stateplane",
         "Re-snapshot early once the unshipped tail exceeds this many "
         "records (bounds standby replay time)."),
    Knob("MRT_SHIP_WINDOW_S", "float", 5.0, "distributed.stateplane",
         "Snapshot shipment cadence; the bound on data loss when "
         "async shipping races a death."),
    # -- distributed.tail ---------------------------------------------------
    Knob("MRT_TAIL", "bool", True, "distributed.tail",
         "Per-request lifecycle exemplars with tail-based sampling "
         "(the Obs.tail plane); off removes the per-request record."),
    Knob("MRT_TAIL_RESERVOIR", "int", 64, "distributed.tail",
         "Reservoir size for NORMAL (under-SLO) request exemplars "
         "kept per drain window."),
    Knob("MRT_TAIL_SLO_CAP", "int", 4096, "distributed.tail",
         "Hard bound on guaranteed over-SLO exemplars held between "
         "drains; overflow is counted, not stored."),
    Knob("MRT_TAIL_SLO_MS", "float", 250.0, "distributed.tail",
         "Total-latency SLO in ms; every request over it is retained "
         "verbatim (up to MRT_TAIL_SLO_CAP) until the next Obs.tail "
         "drain and breadcrumbed into the flight ring."),
    Knob("MRT_TAIL_TOPK", "int", 16, "distributed.tail",
         "Windowed top-k: the k slowest requests since the last drain "
         "are retained even when under the SLO."),
    # -- distributed.tcp ----------------------------------------------------
    Knob("MRT_DEBUG_RPC", "bool", False, "distributed.tcp",
         "Per-frame RPC debug logging on the wire path."),
    Knob("MRT_REPLY_Q_CAP", "int", 4096, "distributed.tcp",
         "Bound on queued unsent replies per connection before "
         "backpressure engages."),
    Knob("MRT_SPIN_US", "int", None, "distributed.tcp",
         "Epoll busy-poll spin budget in microseconds (dynamic "
         "default: CPU-count dependent)."),
    Knob("MRT_TRACE_DIR", "str", None, "distributed.tcp",
         "Directory for per-node Chrome-trace span capture; unset "
         "disables tracing."),
    Knob("MRT_WIRE_LEGACY", "bool", False, "distributed.tcp",
         "Speak the pre-capability legacy wire dialect (no hello "
         "capability negotiation) for interop tests."),
    # -- distributed.wedge --------------------------------------------------
    Knob("MRT_WEDGE_INTERVAL", "float", 0.25, "distributed.wedge",
         "Wedge watchdog check period in seconds."),
    Knob("MRT_WEDGE_TICKS", "int", 8, "distributed.wedge",
         "Consecutive no-progress checks before a group is declared "
         "wedged and flight-recorded."),
    Knob("MRT_WEDGE_WATCH", "bool", True, "distributed.wedge",
         "Kill switch for the wedge watchdog."),
    # -- engine.core --------------------------------------------------------
    Knob("MRT_CHECK_QUORUM", "bool", True, "engine.core",
         "Check-quorum leader self-demotion (kill switch, paired "
         "with MRT_PREVOTE for the CI A/B matrix)."),
    Knob("MRT_MEMBERSHIP", "bool", True, "engine.core",
         "Joint-consensus membership change support (kill switch)."),
    Knob("MRT_PREVOTE", "bool", True, "engine.core",
         "PreVote election mode (kill switch for the legacy CI arm)."),
    # -- engine.host --------------------------------------------------------
    Knob("MRT_ENGINE_PIPELINE", "bool", True, "engine.host",
         "Asynchronous engine pipeline: fused multi-tick device scan "
         "plus a dedicated pump thread; 0 restores the serial per-tick "
         "step and the synchronous pump loop for clean A/B."),
    # -- harness.nemesis ----------------------------------------------------
    Knob("MRT_POSTMORTEM_DIR", "str", None, "harness.nemesis",
         "Directory where a failed chaos run drops its evidence "
         "bundle for the postmortem doctor."),
)

_BY_NAME = {k.name: k for k in KNOBS}

# Canonical falsey spellings for bool knobs (case-insensitive).
_FALSEY = ("", "0", "false", "no", "off")


def _declared(name: str, expect: str) -> Knob:
    k = _BY_NAME.get(name)
    if k is None:
        raise KeyError(
            f"undeclared env knob {name!r}: add it to KNOBS in "
            f"multiraft_tpu/utils/knobs.py"
        )
    if k.type != expect:
        raise TypeError(
            f"env knob {name} is declared {k.type!r}, read as {expect!r}"
        )
    return k


def knob_str(name: str, default: Optional[str] = None) -> Optional[str]:
    """Declared string knob; unset/empty → ``default`` if given, else
    the declared default."""
    k = _declared(name, "str")
    raw = os.environ.get(name)
    if raw:
        return raw
    return default if default is not None else k.default  # type: ignore[return-value]


def knob_int(name: str, default: Optional[int] = None) -> int:
    k = _declared(name, "int")
    fallback = default if default is not None else k.default
    if fallback is None:
        raise TypeError(f"dynamic knob {name} needs an explicit default=")
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else int(fallback)  # type: ignore[arg-type]
    except ValueError:
        return int(fallback)  # type: ignore[arg-type]


def knob_float(name: str, default: Optional[float] = None) -> float:
    k = _declared(name, "float")
    fallback = default if default is not None else k.default
    if fallback is None:
        raise TypeError(f"dynamic knob {name} needs an explicit default=")
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else float(fallback)  # type: ignore[arg-type]
    except ValueError:
        return float(fallback)  # type: ignore[arg-type]


def knob_bool(name: str, default: Optional[bool] = None) -> bool:
    k = _declared(name, "bool")
    raw = os.environ.get(name)
    if raw is None:
        if default is not None:
            return bool(default)
        return bool(k.default)
    return raw.strip().lower() not in _FALSEY


# ---------------------------------------------------------------------------
# docs/KNOBS.md generation + drift gate
# ---------------------------------------------------------------------------

_REPO_ROOT = Path(__file__).resolve().parents[2]
_DOC_PATH = _REPO_ROOT / "docs" / "KNOBS.md"

# Surfaces the drift gate scans for MRT_* mentions: every token must
# name a declared knob (a trailing-underscore token like MRT_PLACE_*
# is a prefix mention and must match at least one declared knob).
_SCAN_GLOBS = ("README.md", "docs/*.md", ".github/workflows/*.yml")
_TOKEN = re.compile(r"MRT_[A-Z0-9_]+")


def _fmt_default(k: Knob) -> str:
    if k.default is None:
        return "(dynamic)" if k.type != "str" else "(unset)"
    if k.type == "bool":
        return "on" if k.default else "off"
    if k.type == "str":
        return f'`"{k.default}"`' if k.default != "" else '`""`'
    return f"`{k.default}`"


def render_doc() -> str:
    """The full docs/KNOBS.md content from the declared table."""
    lines = [
        "# Environment knobs",
        "",
        "Every `MRT_*` runtime tunable, generated from the declared",
        "registry in `multiraft_tpu/utils/knobs.py` — do not edit by",
        "hand; regenerate with `python -m multiraft_tpu.utils.knobs",
        "--write`.  CI (`scripts/check.py`) fails when this file is",
        "stale or when a doc/workflow mentions an undeclared knob.",
        "",
        "Bool knobs: set-and-not-falsey is on; `\"\"`/`0`/`false`/`no`/",
        "`off` are off; unset means the default.  `(dynamic)` defaults",
        "depend on the host (CPU count or a sibling knob) — the doc",
        "line says which.",
        "",
        "| Knob | Type | Default | Owning module | Effect |",
        "|---|---|---|---|---|",
    ]
    for k in KNOBS:
        lines.append(
            f"| `{k.name}` | {k.type} | {_fmt_default(k)} | "
            f"`multiraft_tpu/{k.module.replace('.', '/')}.py` | {k.doc} |"
        )
    lines.append("")
    return "\n".join(lines)


def doc_drift(repo_root: Optional[Path] = None) -> List[str]:
    """Drift problems: stale generated doc, or an MRT_* token in a doc
    or workflow surface that names no declared knob."""
    root = Path(repo_root) if repo_root is not None else _REPO_ROOT
    problems: List[str] = []
    doc = root / "docs" / "KNOBS.md"
    if not doc.exists():
        problems.append(f"{doc}: missing (run --write)")
    elif doc.read_text(encoding="utf-8") != render_doc():
        problems.append(f"{doc}: stale vs. the declared KNOBS table "
                        f"(run --write)")
    declared = set(_BY_NAME)
    for pattern in _SCAN_GLOBS:
        for f in sorted(root.glob(pattern)):
            for i, line in enumerate(
                f.read_text(encoding="utf-8").splitlines(), start=1
            ):
                for tok in _TOKEN.findall(line):
                    if tok in declared:
                        continue
                    if tok.endswith("_"):
                        # Prefix mention ("MRT_PLACE_*"): fine while
                        # at least one declared knob carries it.
                        if any(n.startswith(tok) for n in declared):
                            continue
                    problems.append(
                        f"{f.relative_to(root)}:{i}: mentions "
                        f"undeclared knob {tok}"
                    )
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="multiraft_tpu.utils.knobs")
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--write", action="store_true",
                   help="regenerate docs/KNOBS.md")
    g.add_argument("--check", action="store_true",
                   help="fail on generated-doc staleness or undeclared "
                        "knob mentions")
    ns = ap.parse_args(argv)
    if ns.write:
        _DOC_PATH.parent.mkdir(parents=True, exist_ok=True)
        _DOC_PATH.write_text(render_doc(), encoding="utf-8")
        print(f"knobs: wrote {_DOC_PATH} ({len(KNOBS)} knobs)")
        return 0
    problems = doc_drift()
    for p in problems:
        print(f"knobs: {p}", file=sys.stderr)
    if problems:
        print(f"knobs: {len(problems)} drift problem(s)", file=sys.stderr)
        return 1
    print(f"knobs: clean ({len(KNOBS)} knobs declared)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
