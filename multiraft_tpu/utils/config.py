"""Central configuration — the "real config system" the reference lacks
(SURVEY §5.6: its knobs are constants scattered through the code:
heartbeat 90 ms raft/raft.go:42-44, election 300–600 ms raft/raft.go:
46-50, NShards=10 shardctrler/common.go:23, 99/100 ms service timeouts
kvraft/server.go:80 + kvraft/client.go:57, SnapShotInterval=10
raft/config.go:215, with ``maxraftstate`` the only runtime knob).

Everything is a frozen dataclass; ``Settings.default()`` reproduces the
reference's timing exactly, and the engine's tick-domain equivalents
live in :class:`multiraft_tpu.engine.core.EngineConfig`.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Tuple

__all__ = ["RaftTiming", "ServiceTiming", "FaultModel", "Settings"]


@dataclasses.dataclass(frozen=True)
class RaftTiming:
    heartbeat: float = 0.09  # (reference: raft/raft.go:42-44)
    election: Tuple[float, float] = (0.3, 0.6)  # (raft/raft.go:46-50)


@dataclasses.dataclass(frozen=True)
class ServiceTiming:
    server_wait: float = 0.099  # (reference: kvraft/server.go:80)
    clerk_retry: float = 0.1  # (reference: kvraft/client.go:57)
    config_poll: float = 0.08  # shardkv controller poll cadence
    snapshot_threshold: float = 0.8  # fraction of maxraftstate (fixed
    # from the reference's integer-division quirk, SURVEY §7.5 #1)


@dataclasses.dataclass(frozen=True)
class FaultModel:
    """The labrpc fault constants (reference: labrpc/labrpc.go:221-312)."""

    drop_request: float = 0.1
    drop_reply: float = 0.1
    unreliable_delay: float = 0.026
    reorder_fraction: float = 2.0 / 3.0
    reorder_delay: Tuple[float, float] = (0.2, 2.6)
    dead_timeout: float = 0.1
    long_dead_timeout: float = 7.0


@dataclasses.dataclass(frozen=True)
class Settings:
    raft: RaftTiming = RaftTiming()
    service: ServiceTiming = ServiceTiming()
    faults: FaultModel = FaultModel()
    nshards: int = 10  # (reference: shardctrler/common.go:23)

    @staticmethod
    def default() -> "Settings":
        return Settings()

    @staticmethod
    def from_env(prefix: str = "MULTIRAFT_") -> "Settings":
        """Override timing via environment, e.g. MULTIRAFT_HEARTBEAT=0.05."""
        s = Settings()
        hb = os.environ.get(prefix + "HEARTBEAT")
        if hb:
            s = dataclasses.replace(s, raft=dataclasses.replace(s.raft, heartbeat=float(hb)))
        return s
