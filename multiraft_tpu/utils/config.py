"""Central configuration — the "real config system" the reference lacks
(SURVEY §5.6: its knobs are constants scattered through the code:
heartbeat 90 ms raft/raft.go:42-44, election 300–600 ms raft/raft.go:
46-50, NShards=10 shardctrler/common.go:23, 99/100 ms service timeouts
kvraft/server.go:80 + kvraft/client.go:57, SnapShotInterval=10
raft/config.go:215, with ``maxraftstate`` the only runtime knob).

Everything is a frozen dataclass; ``Settings.default()`` reproduces the
reference's timing exactly.  The process-wide instance is
:func:`settings` (parsed once from ``MULTIRAFT_*`` environment
variables) — it is what the consumers actually read:

* ``raft.node`` takes its heartbeat/election timing from it,
* ``services.kvraft`` / ``shardctrler`` / ``shardkv`` take their
  server-wait, clerk-retry, config-poll, and snapshot thresholds,
* ``transport.network`` takes the whole labrpc fault model,
* :meth:`Settings.engine_config` derives the tick-domain
  :class:`~multiraft_tpu.engine.core.EngineConfig` timing from the
  same wall-clock knobs (10 ms/tick).

Tests that need custom timing should pass explicit values or set the
environment before import; the cached instance keeps every layer's view
consistent within a process.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import TYPE_CHECKING, Any, Tuple

if TYPE_CHECKING:  # circular at runtime: engine.core reads settings()
    from ..engine.core import EngineConfig

__all__ = ["RaftTiming", "ServiceTiming", "FaultModel", "Settings", "settings"]


@dataclasses.dataclass(frozen=True)
class RaftTiming:
    heartbeat: float = 0.09  # (reference: raft/raft.go:42-44)
    election: Tuple[float, float] = (0.3, 0.6)  # (raft/raft.go:46-50)


@dataclasses.dataclass(frozen=True)
class ServiceTiming:
    server_wait: float = 0.099  # (reference: kvraft/server.go:80)
    clerk_retry: float = 0.1  # (reference: kvraft/client.go:57)
    config_poll: float = 0.08  # shardkv controller poll cadence
    snapshot_threshold: float = 0.8  # fraction of maxraftstate (fixed
    # from the reference's integer-division quirk, SURVEY §7.5 #1)


@dataclasses.dataclass(frozen=True)
class FaultModel:
    """The labrpc fault constants (reference: labrpc/labrpc.go:221-312)."""

    drop_request: float = 0.1
    drop_reply: float = 0.1
    unreliable_delay: float = 0.026
    reorder_fraction: float = 2.0 / 3.0
    reorder_delay: Tuple[float, float] = (0.2, 2.6)
    dead_timeout: float = 0.1
    long_dead_timeout: float = 7.0


@dataclasses.dataclass(frozen=True)
class Settings:
    raft: RaftTiming = RaftTiming()
    service: ServiceTiming = ServiceTiming()
    faults: FaultModel = FaultModel()
    nshards: int = 10  # (reference: shardctrler/common.go:23)

    @staticmethod
    def default() -> "Settings":
        return Settings()

    @staticmethod
    def from_env(prefix: str = "MULTIRAFT_") -> "Settings":
        """Build Settings with every wall-clock/topology knob
        overridable from the environment:

        ========================  =================================
        MULTIRAFT_HEARTBEAT       raft heartbeat seconds
        MULTIRAFT_ELECTION_MIN    election timeout lower bound
        MULTIRAFT_ELECTION_MAX    election timeout upper bound
        MULTIRAFT_SERVER_WAIT     service wait-channel timeout
        MULTIRAFT_CLERK_RETRY     clerk per-RPC retry timeout
        MULTIRAFT_CONFIG_POLL     shardkv config poll cadence
        MULTIRAFT_SNAP_THRESHOLD  snapshot trigger fraction
        MULTIRAFT_NSHARDS         shard count
        ========================  =================================
        """

        def f(name: str, cur: float) -> float:
            v = os.environ.get(prefix + name)
            return float(v) if v else cur

        s = Settings()
        raft = RaftTiming(
            heartbeat=f("HEARTBEAT", s.raft.heartbeat),
            election=(
                f("ELECTION_MIN", s.raft.election[0]),
                f("ELECTION_MAX", s.raft.election[1]),
            ),
        )
        service = ServiceTiming(
            server_wait=f("SERVER_WAIT", s.service.server_wait),
            clerk_retry=f("CLERK_RETRY", s.service.clerk_retry),
            config_poll=f("CONFIG_POLL", s.service.config_poll),
            snapshot_threshold=f(
                "SNAP_THRESHOLD", s.service.snapshot_threshold
            ),
        )
        return dataclasses.replace(
            s,
            raft=raft,
            service=service,
            nshards=int(f("NSHARDS", s.nshards)),
        )

    def engine_config(self, tick_s: float = 0.01, **overrides: Any) -> "EngineConfig":
        """Derive the batched engine's tick-domain timing from these
        wall-clock knobs (SURVEY §2.2's 10 ms/tick mapping), keeping
        the two backends' timing in one place.  ``overrides`` pass
        through to :class:`~multiraft_tpu.engine.core.EngineConfig`
        (shapes, pallas flags, prevote, ...)."""
        from ..engine.core import EngineConfig

        timing = dict(
            HB_TICKS=max(1, round(self.raft.heartbeat / tick_s)),
            ELECT_MIN=max(2, round(self.raft.election[0] / tick_s)),
            ELECT_MAX=max(3, round(self.raft.election[1] / tick_s)),
        )
        timing.update(overrides)
        return EngineConfig(**timing)


@functools.lru_cache(maxsize=None)
def settings() -> Settings:
    """The process-wide Settings instance (parsed from the environment
    once; every consumer layer reads this so their views agree)."""
    return Settings.from_env()
