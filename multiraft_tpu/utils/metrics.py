"""Lightweight metrics registry + gated tracing.

The reference's observability is wall-clock begin/end printers plus
RPC/byte counters and two debug-printf gates (SURVEY §5.1/§5.5:
raft/config.go:624-651, labrpc/labrpc.go:375-383, raft/utility.go:55-72).
This module gives the framework a real registry: named counters,
gauges, and histogram-ish timers.  Live consumers:

* ``transport.network.Network`` — its RPC/byte accounting IS a Metrics
  registry (``get_total_count``/``get_total_bytes`` read through it);
* ``harness.raft_harness.RaftHarness`` — shares the network's registry
  and records ``one()`` agreement counts + virtual-time latency;
* ``engine.host.EngineDriver`` — tick counter, plus wall-clock per-tick
  latency samples under the tracer;
* ``bench.py`` — percentile computation over run samples.

``trace`` is the DPrintf equivalent (reference: raft/utility.go:55-72),
gated by ``MULTIRAFT_DEBUG`` and wired into RaftNode's leadership
transitions.
"""

from __future__ import annotations

import math
import os
import random
import sys
import time
from collections import defaultdict
from typing import Dict, List, Optional

__all__ = ["Hist", "Metrics", "global_metrics", "trace", "DEBUG"]


class Hist:
    """Fixed log-bucket streaming histogram for latency metrics.

    Algorithm-R reservoirs estimate the *whole-stream* distribution, which
    is the wrong tool for latency under sustained load: once the reservoir
    fills, each new sample lands with probability ``cap/seen`` — after a
    million observations a queueing-collapse tail is a 0.4% lottery, so
    the reported p99 lags reality by minutes.  A fixed log-bucket
    histogram has none of that: every sample always lands in its bucket,
    memory is a constant 128 ints, two histograms merge exactly by
    elementwise addition (the property the fleet scraper and the windowed
    diff both rely on), and percentile error is bounded by the bucket
    width (±~9% with 4 sub-buckets per octave).

    Buckets are geometric: bucket ``i`` covers
    ``[MIN * 2**(i/SUB), MIN * 2**((i+1)/SUB))`` with ``MIN`` = 1 µs and
    ``SUB`` = 4 sub-buckets per octave; 128 buckets span 1 µs → ~4300 s.
    Values below 1 µs clamp into bucket 0, values above the top clamp
    into the last bucket; exact ``vmin``/``vmax`` are tracked so the
    extremes stay honest.
    """

    SUB = 4
    NBUCKETS = 128
    MIN = 1e-6

    __slots__ = ("counts", "count", "total", "vmin", "vmax")

    def __init__(self) -> None:
        self.counts: List[int] = [0] * Hist.NBUCKETS
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    @staticmethod
    def bucket_index(value: float) -> int:
        if value <= Hist.MIN:
            return 0
        i = int(math.log2(value / Hist.MIN) * Hist.SUB)
        return min(max(i, 0), Hist.NBUCKETS - 1)

    @staticmethod
    def bucket_mid(i: int) -> float:
        """Geometric midpoint of bucket ``i`` (the percentile estimate)."""
        return float(Hist.MIN * 2.0 ** ((i + 0.5) / Hist.SUB))

    def observe(self, value: float) -> None:
        self.counts[Hist.bucket_index(value)] += 1
        self.count += 1
        self.total += value
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value

    def percentile(self, q: float) -> Optional[float]:
        if self.count == 0:
            return None
        rank = min(int(q * self.count), self.count - 1)
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen > rank:
                # Clamp to the exact extremes so q=0/q=1 never report a
                # bucket midpoint outside the observed range.
                return min(max(Hist.bucket_mid(i), self.vmin), self.vmax)
        return self.vmax

    def merge(self, other: "Hist") -> None:
        """Exact merge: elementwise bucket addition."""
        for i, c in enumerate(other.counts):
            if c:
                self.counts[i] += c
        self.count += other.count
        self.total += other.total
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)

    def dump(self) -> Dict[str, object]:
        """Compact wire form: sparse non-zero buckets + exact extremes.

        Cumulative (never reset by a scrape), so two dumps taken at
        different times diff into the window between them (``sub``).
        """
        return {
            "n": self.count,
            "sum": self.total,
            "min": self.vmin if self.count else None,
            "max": self.vmax if self.count else None,
            "b": {i: c for i, c in enumerate(self.counts) if c},
        }

    @classmethod
    def from_dump(cls, d: Dict[str, object]) -> "Hist":
        h = cls()
        h.count = int(d["n"])  # type: ignore[arg-type]
        h.total = float(d["sum"])  # type: ignore[arg-type]
        h.vmin = float(d["min"]) if d.get("min") is not None else math.inf  # type: ignore[arg-type]
        h.vmax = float(d["max"]) if d.get("max") is not None else -math.inf  # type: ignore[arg-type]
        buckets = d.get("b") or {}
        if isinstance(buckets, dict):
            for i, c in buckets.items():
                h.counts[int(i)] = int(c)
        return h

    @classmethod
    def sub(cls, now: "Hist", then: "Hist") -> "Hist":
        """Windowed view: counts accumulated strictly after ``then``.

        Both arguments must be cumulative dumps of the *same* histogram;
        the result's extremes are the cumulative ones (bucket counts are
        exactly diffable, min/max are not).
        """
        h = cls()
        for i in range(cls.NBUCKETS):
            h.counts[i] = max(now.counts[i] - then.counts[i], 0)
        h.count = max(now.count - then.count, 0)
        h.total = now.total - then.total
        h.vmin = now.vmin
        h.vmax = now.vmax
        return h

DEBUG = os.environ.get("MULTIRAFT_DEBUG", "") not in ("", "0")


def trace(fmt: str, *args: object) -> None:
    """Gated debug printf (reference: DPrintf, raft/utility.go:55-72)."""
    if DEBUG:
        print(fmt % args if args else fmt, file=sys.stderr)


class Metrics:
    """Named counters, gauges, and bounded sample reservoirs.

    Sample lists are capped at ``max_samples`` per name (long nemesis and
    bench runs observe millions of latencies).  Below the cap every value
    is kept and percentiles are exact; above it the list becomes a uniform
    reservoir (Vitter's algorithm R): each new value replaces a random
    slot with probability ``cap/seen``, so percentiles are unbiased
    estimates over the whole stream rather than a recency window.  The
    RNG is seeded per-registry, keeping runs reproducible.
    """

    def __init__(self, max_samples: int = 4096) -> None:
        self.counters: Dict[str, int] = defaultdict(int)
        self.gauges: Dict[str, float] = {}
        self.samples: Dict[str, List[float]] = defaultdict(list)
        self.hists: Dict[str, Hist] = {}
        self.max_samples = max_samples
        self.seen: Dict[str, int] = defaultdict(int)
        self._rng = random.Random(0x0B5)

    def inc(self, name: str, by: int = 1) -> None:
        self.counters[name] += by

    def set(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        # Latency metrics (the repo-wide ``*_s`` seconds suffix) go to
        # log-bucket histograms: every sample always lands, so a load
        # spike moves the p99 immediately instead of winning a
        # cap/seen reservoir lottery.  Everything else (batch sizes,
        # frames-per-flush, ...) keeps the whole-stream reservoir.
        if name.endswith("_s"):
            h = self.hists.get(name)
            if h is None:
                h = self.hists[name] = Hist()
            h.observe(value)
            return
        self.seen[name] += 1
        xs = self.samples[name]
        if len(xs) < self.max_samples:
            xs.append(value)
            return
        j = self._rng.randrange(self.seen[name])
        if j < self.max_samples:
            xs[j] = value

    def hist(self, name: str) -> Optional[Hist]:
        return self.hists.get(name)

    def percentile(self, name: str, q: float) -> Optional[float]:
        h = self.hists.get(name)
        if h is not None:
            return h.percentile(q)
        xs = sorted(self.samples.get(name, []))
        if not xs:
            return None
        i = min(int(q * len(xs)), len(xs) - 1)
        return xs[i]

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = dict(self.counters)
        out.update(self.gauges)
        for name in self.samples:
            p50 = self.percentile(name, 0.50)
            p99 = self.percentile(name, 0.99)
            if p50 is not None:
                out[name + "_p50"] = p50
                out[name + "_p99"] = p99
        for hname, h in self.hists.items():
            hp50 = h.percentile(0.50)
            hp99 = h.percentile(0.99)
            if hp50 is not None and hp99 is not None:
                out[hname + "_p50"] = hp50
                out[hname + "_p99"] = hp99
                out[hname + "_count"] = float(h.count)
        return out

    def hist_dumps(self) -> Dict[str, Dict[str, object]]:
        """All histograms in mergeable wire form (for ``Obs.hist``)."""
        return {name: h.dump() for name, h in self.hists.items()}

    def reset(self) -> None:
        self.counters.clear()
        self.gauges.clear()
        self.samples.clear()
        self.hists.clear()
        self.seen.clear()

    class _Timer:
        def __init__(self, m: "Metrics", name: str) -> None:
            self.m, self.name = m, name

        def __enter__(self) -> "Metrics._Timer":
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc: object) -> None:
            self.m.observe(self.name, time.perf_counter() - self.t0)

    def timer(self, name: str) -> "_Timer":
        return Metrics._Timer(self, name)


global_metrics = Metrics()
