"""Lightweight metrics registry + gated tracing.

The reference's observability is wall-clock begin/end printers plus
RPC/byte counters and two debug-printf gates (SURVEY §5.1/§5.5:
raft/config.go:624-651, labrpc/labrpc.go:375-383, raft/utility.go:55-72).
This module gives the framework a real registry: named counters,
gauges, and histogram-ish timers.  Live consumers:

* ``transport.network.Network`` — its RPC/byte accounting IS a Metrics
  registry (``get_total_count``/``get_total_bytes`` read through it);
* ``harness.raft_harness.RaftHarness`` — shares the network's registry
  and records ``one()`` agreement counts + virtual-time latency;
* ``engine.host.EngineDriver`` — tick counter, plus wall-clock per-tick
  latency samples under the tracer;
* ``bench.py`` — percentile computation over run samples.

``trace`` is the DPrintf equivalent (reference: raft/utility.go:55-72),
gated by ``MULTIRAFT_DEBUG`` and wired into RaftNode's leadership
transitions.
"""

from __future__ import annotations

import os
import random
import sys
import time
from collections import defaultdict
from typing import Dict, List, Optional

__all__ = ["Metrics", "global_metrics", "trace", "DEBUG"]

DEBUG = os.environ.get("MULTIRAFT_DEBUG", "") not in ("", "0")


def trace(fmt: str, *args: object) -> None:
    """Gated debug printf (reference: DPrintf, raft/utility.go:55-72)."""
    if DEBUG:
        print(fmt % args if args else fmt, file=sys.stderr)


class Metrics:
    """Named counters, gauges, and bounded sample reservoirs.

    Sample lists are capped at ``max_samples`` per name (long nemesis and
    bench runs observe millions of latencies).  Below the cap every value
    is kept and percentiles are exact; above it the list becomes a uniform
    reservoir (Vitter's algorithm R): each new value replaces a random
    slot with probability ``cap/seen``, so percentiles are unbiased
    estimates over the whole stream rather than a recency window.  The
    RNG is seeded per-registry, keeping runs reproducible.
    """

    def __init__(self, max_samples: int = 4096) -> None:
        self.counters: Dict[str, int] = defaultdict(int)
        self.gauges: Dict[str, float] = {}
        self.samples: Dict[str, List[float]] = defaultdict(list)
        self.max_samples = max_samples
        self.seen: Dict[str, int] = defaultdict(int)
        self._rng = random.Random(0x0B5)

    def inc(self, name: str, by: int = 1) -> None:
        self.counters[name] += by

    def set(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        self.seen[name] += 1
        xs = self.samples[name]
        if len(xs) < self.max_samples:
            xs.append(value)
            return
        j = self._rng.randrange(self.seen[name])
        if j < self.max_samples:
            xs[j] = value

    def percentile(self, name: str, q: float) -> Optional[float]:
        xs = sorted(self.samples.get(name, []))
        if not xs:
            return None
        i = min(int(q * len(xs)), len(xs) - 1)
        return xs[i]

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = dict(self.counters)
        out.update(self.gauges)
        for name in self.samples:
            p50 = self.percentile(name, 0.50)
            p99 = self.percentile(name, 0.99)
            if p50 is not None:
                out[name + "_p50"] = p50
                out[name + "_p99"] = p99
        return out

    def reset(self) -> None:
        self.counters.clear()
        self.gauges.clear()
        self.samples.clear()
        self.seen.clear()

    class _Timer:
        def __init__(self, m: "Metrics", name: str) -> None:
            self.m, self.name = m, name

        def __enter__(self) -> "Metrics._Timer":
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc: object) -> None:
            self.m.observe(self.name, time.perf_counter() - self.t0)

    def timer(self, name: str) -> "_Timer":
        return Metrics._Timer(self, name)


global_metrics = Metrics()
