"""Structured tracing: Chrome-trace (catapult JSON) event capture.

The reference's only observability is per-test counters and gated
printf (SURVEY §5.1 — `raft/config.go:624-651`, `raft/utility.go:55-72`);
this subsystem goes beyond it: attach a :class:`Tracer` to the simulated
:class:`~multiraft_tpu.transport.network.Network` and every RPC becomes
a span tagged with its outcome (ok/timeout/drop/suppressed), or to
an :class:`~multiraft_tpu.engine.host.EngineDriver` and every device
tick becomes a span carrying its metrics. Export with :meth:`Tracer.save`
and open in ``chrome://tracing`` / Perfetto.

Timestamps are microseconds. The sim uses virtual-time seconds
(×1e6); the engine driver uses wall-clock ticks.
"""

from __future__ import annotations

import gzip
import json
from typing import Any, Dict, List, Tuple

__all__ = ["Tracer"]


class Tracer:
    """Bounded in-memory event buffer in Chrome trace-event format.

    ``max_events`` guards long runs: once full, new events are dropped
    and :attr:`dropped` counts them (a trace that silently self-truncates
    is worse than one that says so).
    """

    def __init__(self, max_events: int = 200_000) -> None:
        self.events: List[Dict[str, Any]] = []
        self.max_events = max_events
        self.dropped = 0

    # -- recording --------------------------------------------------------

    def _emit(self, ev: Dict[str, Any]) -> None:
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        self.events.append(ev)

    def span(
        self,
        name: str,
        ts_us: float,
        dur_us: float,
        track: str = "main",
        pid: int = 0,
        **args: Any,
    ) -> None:
        """A complete event: ``[ts, ts+dur]`` on ``track``."""
        self._emit(
            {
                "ph": "X",
                "name": name,
                "ts": ts_us,
                "dur": max(dur_us, 0.0),
                "pid": pid,
                "tid": track,
                "args": args,
            }
        )

    def instant(
        self, name: str, ts_us: float, track: str = "main", pid: int = 0, **args: Any
    ) -> None:
        self._emit(
            {
                "ph": "i",
                "s": "t",
                "name": name,
                "ts": ts_us,
                "pid": pid,
                "tid": track,
                "args": args,
            }
        )

    def counter(
        self,
        name: str,
        ts_us: float,
        values: Dict[str, float],
        pid: int = 0,
        track: str = "counters",
    ) -> None:
        """A counter sample (renders as a stacked area in the viewer).

        ``track`` becomes the event's ``tid`` — without one, Perfetto
        lumps every counter onto thread 0 of the process.
        """
        self._emit(
            {
                "ph": "C",
                "name": name,
                "ts": ts_us,
                "pid": pid,
                "tid": track,
                "args": values,
            }
        )

    def process_name(self, pid: int, name: str) -> None:
        """Metadata event: labels ``pid``'s row in the viewer."""
        self._emit(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": name},
            }
        )

    def drain(self) -> Tuple[List[Dict[str, Any]], int]:
        """Hand off the buffered events (and drop count) and reset the
        buffer — the scrape protocol: repeated drains never duplicate."""
        evs, dropped = self.events, self.dropped
        self.events, self.dropped = [], 0
        return evs, dropped

    # -- export -----------------------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        meta: Dict[str, Any] = {"displayTimeUnit": "ms"}
        if self.dropped:
            meta["otherData"] = {"dropped_events": self.dropped}
        return {"traceEvents": self.events, **meta}

    def save(self, path: str) -> str:
        """Write catapult JSON; a ``.gz`` suffix selects gzip transport
        (Perfetto opens either, and fleet traces compress ~20x)."""
        if path.endswith(".gz"):
            with gzip.open(path, "wt", encoding="utf-8") as f:
                json.dump(self.to_json(), f)
        else:
            with open(path, "w") as f:
                json.dump(self.to_json(), f)
        return path

    @staticmethod
    def load(path: str) -> Dict[str, Any]:
        """Round-trip loader for :meth:`save` output (either transport).

        Transport is sniffed from the gzip magic bytes, not trusted
        from the suffix — a ``.gz``-named file that is actually plain
        JSON (or vice versa: a crash between rename and write) should
        parse or fail on its CONTENT, with json/gzip's own diagnostic,
        rather than on its name."""
        with open(path, "rb") as fb:
            head = fb.read(2)
        if head == b"\x1f\x8b":
            with gzip.open(path, "rt", encoding="utf-8") as f:
                return json.load(f)
        with open(path, "r") as f:
            return json.load(f)
