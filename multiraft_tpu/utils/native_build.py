"""Shared build-and-load helper for the C++ extensions.

Both native components (the porcupine DFS checker and the TCP
transport) ship as a single .cpp compiled with g++ on first use — no
pybind11 in this image, plain C ABI via ctypes.  This helper owns the
one tricky part: concurrent processes (cluster children, parallel
pytest) must never dlopen a half-written .so, so the compile goes to a
process-unique temp name and is published with an atomic rename.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Sequence

__all__ = ["build_and_load"]


def build_and_load(src: str, so: str, extra_flags: Sequence[str] = ()) -> ctypes.CDLL:
    """Compile ``src`` → ``so`` if missing/stale and dlopen it.

    Raises on compile or load failure — callers decide whether to fall
    back to a Python implementation or to hard-fail.
    """
    if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
        tmp = f"{so}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", *extra_flags,
             src, "-o", tmp],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, so)
    return ctypes.CDLL(so)
