"""Crash-safe persistent-compilation-cache shim.

jax 0.4.x's file-system cache writes entries IN PLACE
(``LRUCache.put`` → ``Path.write_bytes``): a process SIGKILLed
mid-write leaves a truncated serialized executable under the final
name, and a concurrent reader can observe the same torn state while a
sibling writes.  Deserializing a truncated executable does not fail
cleanly — it SEGFAULTS the process (observed: a chaos-restarted engine
server dying with SIGSEGV inside its first cached tick dispatch,
tests/test_chaos.py).  Multi-process engine fleets hit both windows:
several servers share one cache dir, and the nemesis kills them at
arbitrary points.

:func:`harden_persistent_cache` swaps the write for the standard
crash-safe idiom — temp file in the same directory, then an atomic
``os.replace`` — so the final name only ever points at a complete
entry.  Call it before the first jit in any process that shares a
cache dir with processes that may die (server children do, via
cluster._server_main; the test parent does, via conftest)."""

from __future__ import annotations

import os
import time
import warnings

__all__ = ["harden_persistent_cache"]


def harden_persistent_cache() -> bool:
    """Make jax's on-disk compilation-cache writes atomic.  Returns
    True when the patch is in place (or already was), False when this
    jax build has no file-system LRU cache to patch (nothing to do —
    the cache, and therefore the hazard, is absent)."""
    try:
        from jax._src import lru_cache as _m
    except Exception:  # pragma: no cover - jax layout changed
        return False
    cls = getattr(_m, "LRUCache", None)
    if cls is None or not hasattr(cls, "put"):  # pragma: no cover
        return False
    if getattr(cls, "_mrt_atomic_put", False):
        return True

    cache_sfx = getattr(_m, "_CACHE_SUFFIX", "-cache")
    atime_sfx = getattr(_m, "_ATIME_SUFFIX", "-atime")

    def put(self, key: str, val: bytes) -> None:
        if not key:
            raise ValueError("key cannot be empty")
        if self.eviction_enabled and len(val) > self.max_size:
            warnings.warn(
                f"Cache value for key {key!r} of size {len(val)} bytes "
                f"exceeds the maximum cache size of {self.max_size} bytes"
            )
            return
        cache_path = self.path / f"{key}{cache_sfx}"
        atime_path = self.path / f"{key}{atime_sfx}"
        if self.eviction_enabled:
            self.lock.acquire(timeout=self.lock_timeout_secs)
        try:
            if cache_path.exists():
                return
            self._evict_if_needed(additional_size=len(val))
            # The one behavioral change vs upstream: write to a
            # pid-unique temp name, publish with an atomic rename.  A
            # crash mid-write strands a temp file; it never produces a
            # truncated entry under the final name.
            tmp = cache_path.with_name(f"{cache_path.name}.tmp{os.getpid()}")
            try:
                tmp.write_bytes(val)
                os.replace(tmp, cache_path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            timestamp = time.time_ns().to_bytes(8, "little")
            atime_path.write_bytes(timestamp)
        finally:
            if self.eviction_enabled:
                self.lock.release()

    cls.put = put
    cls._mrt_atomic_put = True
    return True
