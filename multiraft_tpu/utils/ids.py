"""Cross-process-unique client identity.

Clerk dedup tables are keyed by ``client_id`` and persist in snapshots
(and migrate between shard groups), so IDs must be unique across every
process that ever talks to a cluster — a per-process class counter (the
sim's original scheme) collides the moment two OS processes each create
their "first" clerk, and a PID qualifier collides again when the OS
recycles PIDs. A per-process random nonce has no such lifetime: 40 bits
of entropy per process, 24 bits of counter space within it.
"""

from __future__ import annotations

import secrets

__all__ = ["unique_client_id"]

_PROC_NONCE = secrets.randbits(40)


def unique_client_id(counter: int) -> int:
    """Globally unique clerk id from a process-local counter (< 2^24)."""
    return (_PROC_NONCE << 24) | counter
